"""In-loop QEC decoders over syndrome histories.

Pure-``jnp`` decoders the rounds-scan entry point
(:func:`~..sim.interpreter.simulate_rounds`) invokes INSIDE the same
jit as the R-round execution scan, so R rounds of syndrome extraction
plus the logical decode are one dispatch (docs/PERF.md "Streaming
QEC").  Everything here is shape-polymorphic over leading batch axes
and engine-invariant by construction: the inputs are integer bit
planes and every op is an elementwise/reduction composition with no
data-dependent control flow.

Two schemes, matching the two workload layouts in ``models/qec.py``:

* ``'majority'`` — repetition-code rounds where every DATA core
  measures its own qubit each round: a per-qubit majority vote over
  the round axis denoises the readout stream, then the pattern
  majority picks the correction (the vectorized equivalent of the
  ``majority_lut`` table the fproc fabric applies per round).
* ``'matching'`` — surface-code-cycle-shaped rounds where ANCILLA
  cores measure the syndrome: a per-ancilla majority over rounds
  denoises measurement errors, then an exact minimum-weight matching
  on the repetition chain (the "union-find-lite" decoder — on a chain
  graph the union-find and MWPM decoders coincide and have a closed
  form) produces the data-qubit correction.

The NumPy ``*_np`` twins are the host-side oracles: brute-force
min-weight search for the chain decoder and the literal LUT-table
walk for the majority decoder, pitted against the ``jnp`` decoders by
the seeded fuzz in tests/test_qec_stream.py.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import jax.numpy as jnp

DECODE_SCHEMES = ('majority', 'matching')


@dataclass(frozen=True)
class DecodeSpec:
    """Static description of the in-loop decode: which cores' injected
    measurement bits form the syndrome history and how to decode it.
    Frozen/hashable so it rides the jit cache key as a static argument
    (same contract as :class:`~..sim.interpreter.InterpreterConfig`).

    ``scheme``: one of :data:`DECODE_SCHEMES`.
    ``cores``: tuple of core indices whose bits are the history
    (data cores for 'majority', ancilla cores for 'matching').
    ``slot``: which per-round measurement slot to read (the round
    programs in ``models/qec.py`` measure once per round -> slot 0).
    """
    scheme: str
    cores: tuple
    slot: int = 0

    def __post_init__(self):
        if self.scheme not in DECODE_SCHEMES:
            raise ValueError(f'decode scheme must be one of '
                             f'{DECODE_SCHEMES}; got {self.scheme!r}')
        if not self.cores:
            raise ValueError('DecodeSpec.cores must name >= 1 core')
        object.__setattr__(self, 'cores',
                           tuple(int(c) for c in self.cores))


def as_decode_spec(decode) -> DecodeSpec:
    """Coerce a :class:`DecodeSpec`, ``(scheme, cores, slot)`` tuple,
    or mapping into a validated :class:`DecodeSpec`."""
    if decode is None:
        raise ValueError('decode is None')
    if isinstance(decode, DecodeSpec):
        return decode
    if isinstance(decode, dict):
        return DecodeSpec(**decode)
    return DecodeSpec(*decode)


def majority_vote(hist):
    """Per-position majority over the round axis: ``hist``
    ``[..., R, K]`` -> ``[..., K]``.  Strict majority (``2*count > R``,
    ties -> 0), the same convention as
    :func:`~..models.repetition.majority_lut`."""
    hist = jnp.asarray(hist, jnp.int32)
    return (2 * jnp.sum(hist, axis=-2) > hist.shape[-2]) \
        .astype(jnp.int32)


def bit_majority_correction(bits):
    """Pattern-majority correction: ``bits`` ``[..., K]`` ->
    ``[..., K]`` with bit i set iff position i disagrees with the
    majority of the pattern — the vectorized ``majority_lut`` entry."""
    bits = jnp.asarray(bits, jnp.int32)
    maj = (2 * jnp.sum(bits, axis=-1, keepdims=True)
           > bits.shape[-1]).astype(jnp.int32)
    return (bits != maj).astype(jnp.int32)


def chain_matching(synd):
    """Exact minimum-weight matching on the repetition chain:
    ``synd`` ``[..., A]`` (ancilla i checks data qubits i and i+1) ->
    correction ``[..., A+1]``.

    Any error pattern ``e`` on the chain with ``s_i = e_i ^ e_{i+1}``
    is determined by its first bit: ``e_{i+1} = e_0 ^ (s_0^...^s_i)``.
    So there are exactly TWO syndrome-consistent candidates — the
    prefix-parity pattern anchored at ``e_0 = 0`` and its complement —
    and min-weight decoding picks the lighter one (ties -> the
    ``e_0 = 0`` branch, the same anchor :func:`chain_matching_np`'s
    enumeration order tie-breaks to).  This closed form IS the
    union-find/MWPM
    decoder on a chain, with no iteration to port into the jit."""
    synd = jnp.asarray(synd, jnp.int32)
    prefix = jnp.cumsum(synd, axis=-1) % 2
    e0 = jnp.concatenate(
        [jnp.zeros(synd.shape[:-1] + (1,), jnp.int32), prefix], axis=-1)
    e1 = 1 - e0
    lighter0 = jnp.sum(e0, axis=-1, keepdims=True) \
        <= jnp.sum(e1, axis=-1, keepdims=True)
    return jnp.where(lighter0, e0, e1).astype(jnp.int32)


def decode_history(hist, scheme: str):
    """Decode a syndrome history ``[..., R, K]`` under ``scheme``.

    ``'majority'``: per-qubit round-majority then pattern-majority
    correction -> ``[..., K]`` (K data qubits).
    ``'matching'``: per-ancilla round-majority then chain matching ->
    ``[..., K+1]`` (K ancillas check K+1 data qubits).
    """
    if scheme == 'majority':
        return bit_majority_correction(majority_vote(hist))
    if scheme == 'matching':
        return chain_matching(majority_vote(hist))
    raise ValueError(f'decode scheme must be one of {DECODE_SCHEMES}; '
                     f'got {scheme!r}')


# ---------------------------------------------------------------------------
# NumPy oracles (host-side; the fuzz reference + LUT table builders)
# ---------------------------------------------------------------------------

def chain_matching_np(synd) -> np.ndarray:
    """Brute-force oracle for :func:`chain_matching` on ONE syndrome
    ``[A]``: search all ``2^(A+1)`` error patterns for the minimum
    weight one consistent with the syndrome.  Patterns are enumerated
    with data qubit 0 in the high bit, so the first min-weight hit —
    the tie-break — is the candidate with qubit 0 clear, the same
    anchor the closed form picks.  Exponential on purpose — it shares
    no structure with the closed form it checks."""
    synd = np.asarray(synd, np.int32)
    n = synd.shape[-1] + 1
    best, best_w = None, n + 1
    for pattern in range(1 << n):
        e = np.array([(pattern >> (n - 1 - i)) & 1 for i in range(n)],
                     np.int32)
        if np.array_equal(e[:-1] ^ e[1:], synd):
            w = int(e.sum())
            if w < best_w:
                best, best_w = e, w
    return best


def majority_correction_np(bits) -> np.ndarray:
    """LUT-walk oracle for :func:`bit_majority_correction` on ONE
    pattern ``[K]``: index the literal
    :func:`~..models.repetition.majority_lut` table — the exact entry
    the fproc fabric serves per round."""
    from ..models.repetition import majority_lut
    bits = np.asarray(bits, np.int32)
    k = bits.shape[-1]
    addr = int(sum(int(b) << i for i, b in enumerate(bits)))
    entry = majority_lut(k)[addr]
    return np.array([(entry >> i) & 1 for i in range(k)], np.int32)
