"""distributed_processor_tpu: a TPU-native (JAX/XLA/Pallas) framework with
the capabilities of the QubiC distributed-processor stack.

Layers:

* :mod:`.isa` — the 128-bit distributed-processor ISA (encode / decode /
  structure-of-arrays programs)
* :mod:`.qchip`, :mod:`.hwconfig`, :mod:`.elements`, :mod:`.envelopes` —
  calibration + hardware configuration
* :mod:`.ir`, :mod:`.compiler`, :mod:`.assembler`, :mod:`.decoder` — the
  compiler stack (CFG IR, 12-pass pipeline, machine-code assembly)
* :mod:`.sim` (planned this layer up) — the JAX lax.scan ISA interpreter
  (per-qubit cores, measurement feedback, sync barriers) batched over shots
* :mod:`.ops` — DSP kernels: pulse synthesis, readout demod, state
  discrimination (Pallas on TPU)
* :mod:`.parallel` — shot/sweep sharding over the TPU mesh
* :mod:`.models` — canned experiments (randomized benchmarking, sweeps)
* :mod:`.serve` — continuous-batching execution service: async
  submission, shape-bucketed coalescing, per-request futures (imported
  explicitly — it pulls in jax)
* :mod:`.compilecache` — multi-tenant compile front door: a
  content-addressed source->MachineProgram cache with singleflight,
  persistence and calibration-epoch invalidation
"""

__version__ = '0.1.0'

from . import isa
from . import hwconfig
from . import envelopes
from . import elements
from . import qchip
from . import ir
from . import compiler
from . import assembler
from . import decoder
from . import compilecache

from .hwconfig import FPGAConfig, ChannelConfig, FPROCChannel, load_channel_configs
from .elements import TPUElementConfig
from .qchip import QChip
from .compiler import Compiler, CompiledProgram, CompilerFlags, get_passes, \
    load_compiled_program
from .assembler import SingleCoreAssembler, GlobalAssembler
from .decoder import (decode_assembled_program, MachineProgram,
                      make_init_regs)

# experiment-curve fitting lives in .analysis (imported explicitly —
# it pulls in jax, which the compile stack above does not need)
