"""Minimal format-string pattern matching.

Channel and core groupings are declared with format patterns like
``'{qubit}.qdrv'`` (reference: python/distproc/compiler.py:141-142).  This
implements the inverse operation — matching a concrete string against the
pattern and extracting the named fields — without the third-party ``parse``
dependency.
"""

from __future__ import annotations

import re
from functools import lru_cache

_FIELD_RE = re.compile(r'\{(\w+)\}')


@lru_cache(maxsize=None)
def _compile(pattern: str) -> re.Pattern:
    out = []
    pos = 0
    for m in _FIELD_RE.finditer(pattern):
        out.append(re.escape(pattern[pos:m.start()]))
        out.append(f'(?P<{m.group(1)}>.+?)')
        pos = m.end()
    out.append(re.escape(pattern[pos:]))
    return re.compile('^' + ''.join(out) + '$')


def match_pattern(pattern: str, string: str) -> dict | None:
    """Match ``string`` against a ``{field}`` format pattern.

    Returns the dict of captured fields, or None if there is no match.
    ``match_pattern('{qubit}.qdrv', 'Q0.qdrv') == {'qubit': 'Q0'}``.
    """
    m = _compile(pattern).match(string)
    return m.groupdict() if m else None


def format_pattern(pattern: str, fields: dict) -> str:
    return pattern.format(**fields)
