"""Profiling helpers: the software replacement for RTL waveform dumps.

The reference profiles by Verilator tracing (`--trace` in every cocotb
Makefile); here the analogs are (a) the interpreter's ``trace=True``
instruction trace and (b) the JAX/XLA device profiler wrapped below.
"""

from __future__ import annotations

import contextlib
import time

import jax


@contextlib.contextmanager
def device_profile(logdir: str):
    """Capture an XLA device profile (view with TensorBoard/Perfetto)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StageTimer:
    """Wall-clock stage timing with device synchronisation.

    Example::

        t = StageTimer()
        out = t.stage('simulate', lambda: simulate_batch(mp, bits))
        print(t.report())
    """

    def __init__(self):
        self.times: dict[str, float] = {}

    def stage(self, name: str, fn):
        t0 = time.perf_counter()
        out = jax.block_until_ready(fn())
        self.times[name] = self.times.get(name, 0.0) \
            + (time.perf_counter() - t0)
        return out

    def report(self) -> str:
        total = sum(self.times.values()) or 1.0
        lines = [f'{name:20s} {dt * 1000:10.1f} ms  {dt / total:6.1%}'
                 for name, dt in sorted(self.times.items(),
                                        key=lambda kv: -kv[1])]
        return '\n'.join(lines)
