"""Profiling helpers: the software replacement for RTL waveform dumps.

The reference profiles by Verilator tracing (`--trace` in every cocotb
Makefile); here the analogs are (a) the interpreter's ``trace=True``
instruction trace and (b) the JAX/XLA device profiler wrapped below.
"""

from __future__ import annotations

import contextlib
import time

import numpy as np

import jax


# ---------------------------------------------------------------------------
# named counters: one process-wide registry for trace/step probes
# ---------------------------------------------------------------------------
# The interpreter's retrace probes (multi_trace_count / span_trace_count /
# block_trace_count) were separate module globals; they now live in the
# typed metrics registry (obs/metrics.py) so tests and bench rows can
# snapshot every probe uniformly and export the lot as Prometheus text.
# Counters are ints incremented at Python (trace) time — NOT inside traced
# code — so they count host events (jit cache misses, dispatches), which
# is exactly what the retrace-contract tests assert on.
#
# The registry is thread-safe: the serving runtime (serve/) increments
# from its dispatcher thread while submitters read snapshots, and a bare
# dict read-modify-write would drop increments under that interleaving
# (and let trace-count asserts misfire on torn snapshots).
#
# These functions are the stable facade — every pre-existing counter name
# (`serve.*`, `aot_*`, `*_trace`) keeps working unchanged; gauges and
# histograms are reached through `registry()`.

from ..obs.metrics import default_registry as _default_registry


def registry():
    """The process-wide typed metrics registry backing these counters."""
    return _default_registry()


def counter_inc(name: str, amount: int = 1) -> int:
    """Increment (and return) the named counter."""
    return _default_registry().inc(name, amount)


def counter_get(name: str) -> int:
    """Current value of the named counter (0 if never incremented)."""
    return _default_registry().get(name)


def counters() -> dict:
    """Consistent snapshot of every named counter."""
    return _default_registry().counters()


def registry_snapshot() -> dict:
    """Deep snapshot of the whole registry (counters + gauges +
    histograms) — pair with :func:`registry_restore` to isolate
    counter-asserting tests from execution order."""
    return _default_registry().snapshot()


def registry_restore(snap: dict) -> None:
    """Restore a :func:`registry_snapshot`."""
    return _default_registry().restore(snap)


def prometheus_text() -> str:
    """Prometheus text-format exposition of every registered metric."""
    return _default_registry().prometheus_text()


@contextlib.contextmanager
def device_profile(logdir: str):
    """Capture an XLA device profile (view with TensorBoard/Perfetto)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class StageTimer:
    """Wall-clock stage timing with device synchronisation.

    Example::

        t = StageTimer()
        out = t.stage('simulate', lambda: simulate_batch(mp, bits))
        print(t.report())
    """

    def __init__(self):
        self.times: dict[str, float] = {}

    def stage(self, name: str, fn):
        t0 = time.perf_counter()
        out = jax.block_until_ready(fn())
        self.times[name] = self.times.get(name, 0.0) \
            + (time.perf_counter() - t0)
        return out

    def report(self) -> str:
        total = sum(self.times.values()) or 1.0
        lines = [f'{name:20s} {dt * 1000:10.1f} ms  {dt / total:6.1%}'
                 for name, dt in sorted(self.times.items(),
                                        key=lambda kv: -kv[1])]
        return '\n'.join(lines)


class DispatchTimer:
    """Per-step wall-clock split into the three host-visible phases of
    an asynchronously dispatched device step: DISPATCH (the traced call
    returning its futures — trace/cache lookup + enqueue, where tunnel
    round-trip latency lives), DEVICE (``block_until_ready`` on those
    futures), TRANSFER (``np.asarray`` of every output leaf).  A
    dispatch-bound loop shows the first segment dominating while the
    device sits idle — the diagnosis that motivates folding batches
    into one dispatch (``parallel.sweep.run_spanned``).

    Example::

        t = DispatchTimer()
        for k in keys:
            stats = t.step(lambda: jitted_step(k))
        print(t.breakdown())
    """

    def __init__(self):
        self.dispatch_s = 0.0
        self.device_s = 0.0
        self.transfer_s = 0.0
        self.steps = 0

    def step(self, fn):
        """Run ``fn() -> pytree of device arrays``; returns the host
        numpy pytree, charging each phase to its counter."""
        t0 = time.perf_counter()
        out = fn()
        t1 = time.perf_counter()
        out = jax.block_until_ready(out)
        t2 = time.perf_counter()
        host = jax.tree.map(np.asarray, out)
        t3 = time.perf_counter()
        self.dispatch_s += t1 - t0
        self.device_s += t2 - t1
        self.transfer_s += t3 - t2
        self.steps += 1
        return host

    def breakdown(self) -> dict:
        """Totals + per-step means in ms, JSON-able for bench rows."""
        n = max(self.steps, 1)
        out = {'steps': self.steps}
        for name in ('dispatch', 'device', 'transfer'):
            s = getattr(self, name + '_s')
            out[name + '_s'] = round(s, 6)
            out[name + '_ms_per_step'] = round(1e3 * s / n, 4)
        return out
