"""Restricted evaluation of numeric expressions found in gate configs.

Gate-library JSON files express phases symbolically (e.g. ``"np.pi/2"``,
``"-numpy.pi/2.0"`` — see the reference fixture python/test/qubitcfg.json).
This evaluates such strings against a numpy-only namespace, rejecting
anything with attribute access outside numpy or names outside a small
whitelist.
"""

from __future__ import annotations

import ast
import numpy as np

_ALLOWED_NODES = (
    ast.Expression, ast.BinOp, ast.UnaryOp, ast.Constant, ast.Name,
    ast.Attribute, ast.Add, ast.Sub, ast.Mult, ast.Div, ast.Pow,
    ast.USub, ast.UAdd, ast.Mod, ast.Load,
)
_ALLOWED_NAMES = {'np': np, 'numpy': np, 'pi': np.pi, 'e': np.e}


def eval_numeric(expr):
    """Evaluate a numeric literal or numpy constant expression.

    Non-strings pass through unchanged; strings must be pure arithmetic over
    numbers and numpy constants (``np.pi`` etc.).
    """
    if not isinstance(expr, str):
        return expr
    tree = ast.parse(expr, mode='eval')
    for node in ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise ValueError(f'disallowed element {type(node).__name__} in {expr!r}')
        if isinstance(node, ast.Name) and node.id not in _ALLOWED_NAMES:
            raise ValueError(f'unknown name {node.id!r} in {expr!r}')
    return float(eval(compile(tree, '<config>', 'eval'), {'__builtins__': {}}, _ALLOWED_NAMES))
