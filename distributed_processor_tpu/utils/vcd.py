"""VCD (Value Change Dump) export of an instruction-traced run.

The reference's debug artifact is an RTL waveform: every cocotb
testbench compiles with ``--trace --trace-structs`` and inspection
happens in GTKWave (reference: cocotb/proc/Makefile EXTRA_ARGS,
hdl/proc.sv:159-165 commented $dumpvars block).  The TPU build records
the equivalent state trace as scan outputs (``trace=True`` →
``trace_pc``/``trace_time``) plus the pulse records; this module turns
one shot of that into a standard VCD file so the same waveform tooling
works on simulated runs.

Per core the dump carries:

- ``pc[15:0]``   — program counter at each retired step
- ``qclk[31:0]`` — the qclk value (time - offset), exact at every step
  via the per-step offset trace (``trace_off``); a legacy trace without
  it dumps the final-offset approximation under the name ``qclk_approx``
- ``done``       — end-of-program flag
- per element (one sub-scope per element that fired, mirroring the
  reference's per-element ``pulse_iface``): ``cstrobe`` — one-cycle
  pulse at every trigger time — and ``amp[15:0]``, ``phase[16:0]``,
  ``freq[8:0]``, ``env[23:0]`` latched at each cstrobe
  (reference: hdl/pulse_iface.sv widths)

Timestamps are picoseconds (``$timescale 1 ps`` — the spec only allows
1/10/100 multipliers), one FPGA clock = ``clk_period_ns`` (2 ns
default — reference: hwconfig.py fpga_clk_period).
"""

from __future__ import annotations

import numpy as np

_PULSE_VARS = (('amp', 16, 'rec_amp'), ('phase', 17, 'rec_phase'),
               ('freq', 9, 'rec_freq'), ('env', 24, 'rec_env'))


def _ident(i: int) -> str:
    """Short VCD identifier (printable ASCII 33..126)."""
    chars = []
    i += 1
    while i:
        i, r = divmod(i, 94)
        chars.append(chr(33 + r))
    return ''.join(chars)


def _bits(value: int, width: int) -> str:
    return format(int(value) & ((1 << width) - 1), f'0{width}b')


def write_vcd(path: str, out: dict, clk_period_ns: float = 2.0,
              shot: int = None, cores=None, core_labels=None) -> int:
    """Write one shot of a traced run (``trace=True``) as a VCD file.

    ``out``: the result dict of ``simulate``/``Simulator.run`` — must
    carry ``trace_pc``/``trace_time`` and the ``rec_*`` pulse records.
    ``shot`` selects a shot from a batched run.  ``cores``: positional
    core indices to dump (default all); ``core_labels``: display name
    per positional core (e.g. the compiled program's ``core_inds`` —
    defaults to the position).  Returns the number of value-change
    events written.
    """
    if 'trace_pc' not in out:
        raise ValueError('run has no instruction trace: execute with '
                         'trace=True')
    if 'rec_gtime' not in out:
        raise ValueError('run has no pulse records: execute with '
                         'record_pulses=True')
    batched = np.asarray(out['n_pulses']).ndim == 2
    if batched and shot is None:
        raise ValueError('batched run: pass shot= to select one shot')
    sel = (lambda a: np.asarray(a)[shot]) if batched \
        else (lambda a: np.asarray(a))

    # one host conversion per array, not per extracted scalar
    trace_pc = sel(out['trace_pc'])
    trace_t = sel(out['trace_time'])
    trace_off = sel(out['trace_off']) if 'trace_off' in out else None
    n_pulses = sel(out['n_pulses'])
    gtime = sel(out['rec_gtime'])
    elem_rec = sel(out['rec_elem'])
    pulse_rec = {name: sel(out[key]) for name, _, key in _PULSE_VARS}
    qclk_fin = sel(out['qclk'])
    time_fin = sel(out['time']) if 'time' in out else None
    done_fin = sel(out['done'])

    n_cores = trace_pc.shape[0]
    steps = int(np.asarray(out['steps']))
    cores = list(range(n_cores)) if cores is None else list(cores)
    if core_labels is None:
        core_labels = cores
    tick = int(round(clk_period_ns * 1000))       # ps per FPGA clock

    events = []          # (time_ps, order, ident, width, value)
    k = 0

    def new_ident():
        nonlocal k
        s = _ident(k)
        k += 1
        return s

    # with the per-step offset trace the dumped qclk is exact at every
    # timestamp; a legacy trace (no trace_off) falls back to the final
    # offset and is honestly named qclk_approx (sync/inc_qclk offset
    # changes appear as retroactive ramps there)
    qclk_name = 'qclk' if trace_off is not None else 'qclk_approx'
    header = []          # (label, [(name, width, ident)], {elem: [...]})
    for c, label in zip(cores, core_labels):
        v_pc, v_qclk, v_done = new_ident(), new_ident(), new_ident()
        core_vars = [('pc', 16, v_pc), (qclk_name, 32, v_qclk),
                     ('done', 1, v_done)]

        # pc at each retired step (dedupe repeats after done)
        prev = None
        for s in range(steps):
            t = int(trace_t[c, s])
            pc = int(trace_pc[c, s])
            if prev is not None and (t, pc) == prev:
                continue
            prev = (t, pc)
            events.append((t * tick, 0, v_pc, 16, pc))
        if trace_off is not None:
            # exact: qclk = time - offset with the offset AS OF the step
            last = None
            for s in range(steps):
                t = int(trace_t[c, s])
                q = t - int(trace_off[c, s])
                if (t, q) == last:
                    continue
                last = (t, q)
                events.append((t * tick, 1, v_qclk, 32, q))
        elif time_fin is not None:
            off = int(time_fin[c]) - int(qclk_fin[c])
            seen = set()
            for s in range(steps):
                t = int(trace_t[c, s])
                if t in seen:
                    continue
                seen.add(t)
                events.append((t * tick, 1, v_qclk, 32, t - off))

        # pulse events at their trigger times, one sub-scope per element
        # (two elements triggering at the same time stay distinct, as on
        # the hardware's per-element pulse_iface)
        n = int(n_pulses[c])
        elems = sorted({int(elem_rec[c, p]) for p in range(n)})
        elem_vars = {}
        for e in elems:
            ids = {name: new_ident() for name, _, _ in _PULSE_VARS}
            ids['cstrobe'] = new_ident()
            elem_vars[e] = ids
        for p in range(n):
            t = int(gtime[c, p])
            ids = elem_vars[int(elem_rec[c, p])]
            for name, width, _ in _PULSE_VARS:
                events.append((t * tick, 2, ids[name], width,
                               int(pulse_rec[name][c, p])))
            events.append((t * tick, 3, ids['cstrobe'], 1, 1))
            events.append(((t + 1) * tick, 0, ids['cstrobe'], 1, 0))

        if bool(done_fin[c]):
            t_done = int(trace_t[c, steps - 1]) if steps else 0
            events.append((t_done * tick, 4, v_done, 1, 1))
        header.append((label, core_vars, elem_vars))

    events.sort(key=lambda e: (e[0], e[1]))

    # ---- emit ----------------------------------------------------------
    def var_line(name, width, ident):
        rng = f' [{width - 1}:0]' if width > 1 else ''
        return f'$var wire {width} {ident} {name}{rng} $end'

    lines = ['$date generated by distributed_processor_tpu $end',
             '$timescale 1 ps $end',
             '$scope module dproc $end']
    init = []
    for label, core_vars, elem_vars in header:
        lines.append(f'$scope module core{label} $end')
        for name, width, ident in core_vars:
            lines.append(var_line(name, width, ident))
            init.append((width, ident))
        for e, ids in sorted(elem_vars.items()):
            lines.append(f'$scope module elem{e} $end')
            for name, width, _ in _PULSE_VARS:
                lines.append(var_line(name, width, ids[name]))
                init.append((width, ids[name]))
            lines.append(var_line('cstrobe', 1, ids['cstrobe']))
            init.append((1, ids['cstrobe']))
            lines.append('$upscope $end')
        lines.append('$upscope $end')
    lines.append('$upscope $end')
    lines.append('$enddefinitions $end')

    lines.append('$dumpvars')
    for width, ident in init:
        lines.append(f'b{_bits(0, width)} {ident}' if width > 1
                     else f'0{ident}')
    lines.append('$end')

    cur_t = None
    n_changes = 0
    for t, _, ident, width, value in events:
        if t != cur_t:
            lines.append(f'#{max(t, 0)}')
            cur_t = t
        lines.append(f'b{_bits(value, width)} {ident}' if width > 1
                     else f'{int(bool(value))}{ident}')
        n_changes += 1

    with open(path, 'w') as f:
        f.write('\n'.join(lines) + '\n')
    return n_changes
