from .patterns import match_pattern, format_pattern
from .safe_eval import eval_numeric
from .results import save_results, load_results, SweepAccumulator
from .profiling import device_profile, DispatchTimer, StageTimer
