from .patterns import match_pattern, format_pattern
from .safe_eval import eval_numeric
