"""Sweep-result checkpointing.

The reference has no result persistence (CompiledProgram.save is
stubbed upstream; results live on the host); long sharded sweeps here
need resumable accumulation.  Results are stored as compressed npz
archives with a manifest, written atomically so an interrupted sweep
never leaves a torn checkpoint.
"""

from __future__ import annotations

import json
import os
import zipfile
import zlib

import numpy as np


def save_results(path: str, results: dict, meta: dict = None) -> None:
    """Atomically save a dict of arrays (+ JSON-able metadata)."""
    arrays = {}
    for k, v in results.items():
        if k.startswith('_'):
            continue
        arrays[k] = np.asarray(v)
    if meta is not None:
        arrays['__meta__'] = np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8)
    tmp = path + '.tmp'
    with open(tmp, 'wb') as f:
        np.savez_compressed(f, **arrays)
    os.replace(tmp, path)


def load_results(path: str) -> tuple[dict, dict]:
    """Load a checkpoint -> (arrays dict, metadata dict)."""
    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files if k != '__meta__'}
        meta = {}
        if '__meta__' in z.files:
            meta = json.loads(bytes(z['__meta__']).decode())
    return arrays, meta


def quarantine_checkpoint(path: str) -> str:
    """Move an unreadable checkpoint aside as ``<path>.corrupt-<n>``.

    The rename keeps the evidence (for post-mortem CRC inspection)
    while freeing ``path`` for a clean restart; ``<n>`` counts up so
    repeated corruption never overwrites an earlier specimen.
    """
    n = 0
    while os.path.exists(f'{path}.corrupt-{n}'):
        n += 1
    dest = f'{path}.corrupt-{n}'
    os.replace(path, dest)
    return dest


class SweepAccumulator:
    """Accumulate per-batch sweep statistics with periodic checkpoints.

    ``add`` sums array leaves across batches (counts, histograms);
    ``checkpoint_every`` batches a checkpoint is written; ``resume``
    picks up the accumulated state + next batch index.
    """

    def __init__(self, path: str = None, checkpoint_every: int = 0,
                 meta: dict = None):
        self.path = path
        self.checkpoint_every = checkpoint_every
        self.state: dict = {}
        self.n_batches = 0
        # caller-defined identity (batch size, keys, program fingerprint
        # ...) persisted with the checkpoint so a resume can validate it
        self.meta = dict(meta or {})

    def add(self, batch_stats: dict) -> None:
        self.add_span(batch_stats, 1)

    def add_span(self, span_stats: dict, n_batches: int) -> None:
        """Fold an already-summed span of ``n_batches`` batches.

        ``checkpoint_every`` stays in BATCH units; with spans the write
        happens when the accumulated batch count CROSSES a multiple of
        it (checkpoints snap to span edges).  For ``n_batches == 1``
        this is exactly ``add``'s write-on-multiple behavior.
        """
        if n_batches < 1:
            raise ValueError(f'span must cover >= 1 batches, '
                             f'got {n_batches}')
        for k, v in span_stats.items():
            v = np.asarray(v)
            self.state[k] = self.state.get(k, 0) + v
        prev = self.n_batches
        self.n_batches += n_batches
        if self.path and self.checkpoint_every and \
                self.n_batches // self.checkpoint_every \
                > prev // self.checkpoint_every:
            self.save()

    def save(self) -> None:
        save_results(self.path, self.state,
                     meta={'n_batches': self.n_batches, **self.meta})

    @classmethod
    def resume(cls, path: str, checkpoint_every: int = 0,
               meta: dict = None, strict: bool = False) -> 'SweepAccumulator':
        """Load the checkpoint at ``path`` (fresh accumulator if absent).

        With ``meta`` given, a checkpoint whose stored identity differs
        raises — field by field, naming exactly what diverged — instead
        of silently mixing incompatible accumulations.  A checkpoint
        with *no* stored identity (written before fingerprinting, or by
        an older fingerprint version) is treated as legacy: accepted
        with a warning rather than rejected, since there is nothing to
        compare against.  ``strict=True`` upgrades both legacy paths to
        hard errors — no identity and no version skew are tolerated, so
        fields whose representation changed between fingerprint versions
        (and would otherwise be skipped with a warning) can never smuggle
        a different sweep past validation.

        A checkpoint that cannot be PARSED at all (truncated zip,
        bit-flipped npz member, mangled manifest) is quarantined: the
        file is renamed to ``<path>.corrupt-<n>`` and a fresh
        accumulator is returned with a warning, so a long campaign
        restarts cleanly instead of crashing on unreadable state.
        ``strict=True`` raises instead (nothing is renamed).
        """
        if strict and meta is None:
            raise ValueError(
                'strict=True requires meta (the identity to validate '
                'against) — without it strict resume would be a silent '
                'no-op')
        acc = cls(path, checkpoint_every, meta=meta)
        if os.path.exists(path):
            try:
                arrays, stored = load_results(path)
            except (zipfile.BadZipFile, zlib.error, ValueError, KeyError,
                    OSError, EOFError, json.JSONDecodeError) as e:
                # torn/bit-flipped checkpoint (atomic writes make this
                # rare — disk corruption, not interruption): losing the
                # accumulated batches is recoverable, crashing a
                # million-shot campaign on an unreadable file is not
                if strict:
                    raise ValueError(
                        f'strict resume: checkpoint {path} is unreadable '
                        f'({type(e).__name__}: {e})') from e
                import warnings
                dest = quarantine_checkpoint(path)
                warnings.warn(
                    f'checkpoint {path} is unreadable '
                    f'({type(e).__name__}: {e}); quarantined to {dest} '
                    f'and restarting the sweep from batch 0',
                    stacklevel=2)
                return acc
            acc.state = dict(arrays)
            acc.n_batches = int(stored.pop('n_batches', 0))
            if meta is not None:
                import warnings
                want_ver = acc.meta.get('fingerprint_version')
                have_ver = stored.get('fingerprint_version')
                if strict and (not stored or have_ver != want_ver):
                    raise ValueError(
                        f'strict resume: checkpoint {path} has '
                        f'fingerprint version {have_ver if stored else None}'
                        f' but this sweep requires {want_ver} — '
                        f'version-skewed/unfingerprinted checkpoints are '
                        f'rejected under strict=True')
                if not stored:
                    warnings.warn(
                        f'checkpoint {path} carries no identity — '
                        f'resuming without validation', stacklevel=2)
                    diff = []
                elif have_ver != want_ver:
                    # version skew: still validate the overlap whose
                    # representation is format-stable (same JSON type in
                    # both versions — batch/key/crcs survive any version;
                    # a field whose format changed, e.g. repr-string ->
                    # dict, is skipped with a warning, not failed)
                    shared = (set(stored) & set(acc.meta)) \
                        - {'fingerprint_version'}
                    comparable = {k for k in shared
                                  if type(stored[k]) is type(acc.meta[k])}
                    skipped = sorted((set(stored) ^ set(acc.meta)
                                      | (shared - comparable))
                                     - {'fingerprint_version'})
                    warnings.warn(
                        f'checkpoint {path} has fingerprint version '
                        f'{have_ver} (current {want_ver}); fields '
                        f'{skipped or "(none)"} not validated',
                        stacklevel=2)
                    diff = [k for k in sorted(comparable)
                            if stored[k] != acc.meta[k]]
                else:
                    diff = sorted(set(stored) ^ set(acc.meta)) + \
                        [k for k in sorted(set(stored) & set(acc.meta))
                         if stored[k] != acc.meta[k]]
                if diff:
                    detail = {k: (stored.get(k, '<absent>'),
                                  acc.meta.get(k, '<absent>'))
                              for k in diff}
                    raise ValueError(
                        f'checkpoint {path} was written by a '
                        f'different sweep; differing fields '
                        f'(stored, requested): {detail}')
        return acc
