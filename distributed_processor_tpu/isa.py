"""128-bit distributed-processor ISA: encoders, decoders, and the
structure-of-arrays (SoA) decoded form consumed by the JAX interpreter.

The word layout is the binary contract shared with the QubiC gateware
(reference: hdl/instr_params.vh:4-28, hdl/proc.sv:89-103, hdl/pulse_reg.sv:10-12,
python/distproc/command_gen.py:16-48).  Everything else in this module —
the vectorised decoder, the SoA program representation, and the
numpy packing helpers — is designed for the TPU execution path: the
interpreter never touches 128-bit integers, it gathers from the int32
field arrays produced by :func:`decode_soa`.

Command word anatomy (bit positions are LSB-indexed into the 128-bit word):

* ALU-family ops use an 8-bit opcode ``cmd[127:120]`` =
  ``(op5 << 3) | alu_op3`` where bit 3 of the byte (``op5 & 1``) selects
  register (1) vs immediate (0) for ALU input 0.
* Pulse-family ops use only the top 5 bits ``cmd[127:123]``.
* Field positions::

      imm (alu in0, 32b two's complement)  @ 88
      alu in0 reg addr (4b)                @ 116
      alu in1 reg addr (4b)                @ 84
      reg write addr (4b)                  @ 80
      jump addr (8b)                       @ 68
      fproc func id (8b)                   @ 52
      sync barrier id (8b)                 @ 112
      pulse: cmd_time(32b)@5, cfg(4b+1)@37, amp(16b+2)@42,
             freq(9b+2)@60, phase(17b+2)@71, env(24b+2)@90,
             pulse reg addr(4b)@116

  Each pulse parameter carries control bits directly above its value
  field: ``{write_enable, use_register}`` (cfg has only write_enable).
"""

from __future__ import annotations

import numpy as np
from dataclasses import dataclass

# ---------------------------------------------------------------------------
# opcode tables
# ---------------------------------------------------------------------------

ALU_OPS = {
    'id0': 0b000,
    'add': 0b001,
    'sub': 0b010,
    'eq': 0b011,
    'le': 0b100,
    'ge': 0b101,
    'id1': 0b110,
    'zero': 0b111,
}

# 5-bit primary opcodes (cmd[127:123]); for ALU-family ops the LSB of the
# 5-bit code selects register (1) / immediate (0) input 0.
OPCODES = {
    'pulse_write': 0b10000,
    'pulse_write_trig': 0b10010,
    'reg_alu_i': 0b00010,
    'reg_alu': 0b00011,
    'jump_i': 0b00100,
    'jump_cond_i': 0b00110,
    'jump_cond': 0b00111,
    'alu_fproc_i': 0b01000,
    'alu_fproc': 0b01001,
    'jump_fproc_i': 0b01010,
    'jump_fproc': 0b01011,
    'inc_qclk_i': 0b01100,
    'inc_qclk': 0b01101,
    'sync': 0b01110,
    'done': 0b10100,
    'pulse_reset': 0b10110,
    'idle': 0b11000,
}

CMD_BYTES = 16  # 128-bit commands
N_REGS = 16
REG_BITS = 4

# pulse parameter field widths / positions
PULSE_FIELDS = ('cmd_time', 'cfg', 'amp', 'freq', 'phase', 'env_word')
PULSE_WIDTH = {
    'cmd_time': 32, 'cfg': 4, 'amp': 16, 'freq': 9, 'phase': 17, 'env_word': 24,
}
# each param is followed by its control bits (1 for cfg, 2 for the rest)
PULSE_POS = {'cmd_time': 5}
PULSE_POS['cfg'] = PULSE_POS['cmd_time'] + PULSE_WIDTH['cmd_time']        # 37
PULSE_POS['amp'] = PULSE_POS['cfg'] + PULSE_WIDTH['cfg'] + 1              # 42
PULSE_POS['freq'] = PULSE_POS['amp'] + PULSE_WIDTH['amp'] + 2             # 60
PULSE_POS['phase'] = PULSE_POS['freq'] + PULSE_WIDTH['freq'] + 2          # 71
PULSE_POS['env_word'] = PULSE_POS['phase'] + PULSE_WIDTH['phase'] + 2     # 90

IMM_POS = 88
IN0_REG_POS = 116
IN1_REG_POS = 84
WRITE_REG_POS = 80
JUMP_ADDR_POS = 68
FUNC_ID_POS = 52
BARRIER_ID_POS = 112
PULSE_REG_POS = 116


def twos_complement(value, nbits: int = 32):
    """Two's complement encoding of a signed python int / array of ints."""
    arr = np.asarray(value, dtype=np.int64)
    if np.any((arr > 2 ** (nbits - 1) - 1) | (arr < -(2 ** (nbits - 1)))):
        raise ValueError(f'{value} out of range for {nbits}-bit signed')
    enc = np.where(arr < 0, arr + (1 << nbits), arr)
    if np.isscalar(value) or np.ndim(value) == 0:
        return int(enc)
    return enc


def from_twos_complement(word, nbits: int = 32):
    """Inverse of :func:`twos_complement`."""
    arr = np.asarray(word, dtype=np.int64)
    dec = np.where(arr >= (1 << (nbits - 1)), arr - (1 << nbits), arr)
    if np.isscalar(word) or np.ndim(word) == 0:
        return int(dec)
    return dec


# ---------------------------------------------------------------------------
# encoders
# ---------------------------------------------------------------------------

def pulse_cmd(freq_word=None, freq_regaddr=None, phase_word=None, phase_regaddr=None,
              amp_word=None, amp_regaddr=None, cfg_word=None,
              env_word=None, env_regaddr=None, cmd_time=None) -> int:
    """Encode a pulse command.

    Loads any subset of the five pulse-register parameters (at most one of
    them sourced from a processor register), and — iff ``cmd_time`` is given —
    schedules a trigger at that qclk timestamp (``pulse_write_trig``),
    otherwise only writes the parameters (``pulse_write``).
    """
    cmd = 0
    regaddr = None
    for name, word, reg in (('cfg', cfg_word, None),
                            ('amp', amp_word, amp_regaddr),
                            ('freq', freq_word, freq_regaddr),
                            ('phase', phase_word, phase_regaddr),
                            ('env_word', env_word, env_regaddr)):
        pos, width = PULSE_POS[name], PULSE_WIDTH[name]
        # control bits above the value field: {write_enable, use_register}
        # for amp/freq/phase/env (write_enable is the high bit); cfg has a
        # single write_enable bit
        wen_bit = width if name == 'cfg' else width + 1
        if word is not None:
            if reg is not None:
                raise ValueError(f'{name}: immediate and register are exclusive')
            if not 0 <= int(word) < (1 << width):
                raise ValueError(f'{name} word {word} out of range ({width} bits)')
            cmd += (int(word) + (1 << wen_bit)) << pos
        elif reg is not None:
            if regaddr is not None:
                raise ValueError('at most one pulse parameter may come from a register')
            if not 0 <= int(reg) < N_REGS:
                raise ValueError(f'{name} reg addr {reg} out of range')
            regaddr = int(reg)
            cmd += 0b11 << (pos + width)   # use_register + write_enable
    if regaddr is not None:
        cmd += regaddr << PULSE_REG_POS

    if cmd_time is not None:
        if not 0 <= int(cmd_time) < (1 << 32):
            raise ValueError(f'cmd_time {cmd_time} out of range')
        cmd += int(cmd_time) << PULSE_POS['cmd_time']
        opcode = OPCODES['pulse_write_trig']
    else:
        opcode = OPCODES['pulse_write']
    return cmd + (opcode << 123)


def alu_cmd(optype: str, im_or_reg: str, alu_in0, alu_op: str = None, alu_in1: int = 0,
            write_reg_addr: int = None, jump_cmd_ptr: int = None, func_id: int = None) -> int:
    """Encode any ALU-family command.

    ``optype`` in {reg_alu, jump_cond, alu_fproc, jump_fproc, inc_qclk};
    ``im_or_reg`` 'i' (``alu_in0`` is an immediate) or 'r' (register address).
    """
    cmd = 0
    if optype in ('reg_alu', 'jump_cond'):
        cmd += int(alu_in1) << IN1_REG_POS
    if optype in ('alu_fproc', 'jump_fproc') and func_id is not None:
        cmd += int(func_id) << FUNC_ID_POS
    if optype in ('jump_cond', 'jump_fproc'):
        cmd += int(jump_cmd_ptr) << JUMP_ADDR_POS
    if optype in ('reg_alu', 'alu_fproc'):
        cmd += int(write_reg_addr) << WRITE_REG_POS
    if optype == 'inc_qclk':
        if alu_op not in (None, 'add'):
            raise ValueError('inc_qclk only supports the add ALU op')
        alu_op = 'add'

    if im_or_reg == 'i':
        opkey = optype + '_i'
        cmd += twos_complement(int(alu_in0)) << IMM_POS
    elif im_or_reg == 'r':
        opkey = optype
        cmd += int(alu_in0) << IN0_REG_POS
    else:
        raise ValueError(f"im_or_reg must be 'i' or 'r', got {im_or_reg}")

    opcode = (OPCODES[opkey] << 3) + ALU_OPS[alu_op]
    return cmd + (opcode << 120)


def jump_i(instr_ptr_addr: int) -> int:
    return ((OPCODES['jump_i'] << 3) << 120) + (int(instr_ptr_addr) << JUMP_ADDR_POS)


def idle(cmd_time: int) -> int:
    if not 0 <= int(cmd_time) < (1 << 32):
        raise ValueError(f'idle end time {cmd_time} out of range')
    return (OPCODES['idle'] << 123) + (int(cmd_time) << PULSE_POS['cmd_time'])


def done_cmd() -> int:
    return OPCODES['done'] << 123


def pulse_reset() -> int:
    return OPCODES['pulse_reset'] << 123


def sync(barrier_id: int) -> int:
    return (OPCODES['sync'] << 123) + (int(barrier_id) << BARRIER_ID_POS)


def read_fproc(func_id: int, write_reg_addr: int) -> int:
    """Store the fproc result for ``func_id`` in a register (alu_fproc id1)."""
    return alu_cmd('alu_fproc', 'i', 0, 'id1', write_reg_addr=write_reg_addr,
                   func_id=func_id)


def cmds_to_bytes(cmds) -> bytes:
    """Serialise 128-bit command ints little-endian, 16 bytes each."""
    return b''.join(int(c).to_bytes(CMD_BYTES, 'little') for c in cmds)


def bytes_to_cmds(buf: bytes) -> list[int]:
    if len(buf) % CMD_BYTES:
        raise ValueError('command buffer length must be a multiple of 16 bytes')
    return [int.from_bytes(buf[i:i + CMD_BYTES], 'little')
            for i in range(0, len(buf), CMD_BYTES)]


# ---------------------------------------------------------------------------
# decoder → structure-of-arrays program (interpreter input)
# ---------------------------------------------------------------------------

# instruction kinds for the interpreter's lax.switch
K_PULSE_WRITE = 0
K_PULSE_TRIG = 1
K_REG_ALU = 2
K_JUMP_I = 3
K_JUMP_COND = 4
K_ALU_FPROC = 5
K_JUMP_FPROC = 6
K_INC_QCLK = 7
K_SYNC = 8
K_DONE = 9
K_PULSE_RESET = 10
K_IDLE = 11

N_KINDS = 12

_OP5_TO_KIND = {
    OPCODES['pulse_write']: K_PULSE_WRITE,
    OPCODES['pulse_write_trig']: K_PULSE_TRIG,
    OPCODES['reg_alu_i']: K_REG_ALU,
    OPCODES['reg_alu']: K_REG_ALU,
    OPCODES['jump_i']: K_JUMP_I,
    OPCODES['jump_cond_i']: K_JUMP_COND,
    OPCODES['jump_cond']: K_JUMP_COND,
    OPCODES['alu_fproc_i']: K_ALU_FPROC,
    OPCODES['alu_fproc']: K_ALU_FPROC,
    OPCODES['jump_fproc_i']: K_JUMP_FPROC,
    OPCODES['jump_fproc']: K_JUMP_FPROC,
    OPCODES['inc_qclk_i']: K_INC_QCLK,
    OPCODES['inc_qclk']: K_INC_QCLK,
    OPCODES['sync']: K_SYNC,
    OPCODES['done']: K_DONE,
    OPCODES['pulse_reset']: K_PULSE_RESET,
    OPCODES['idle']: K_IDLE,
    0: K_DONE,  # an all-zero opcode halts the core, like DONE (ctrl.v:382)
}

SOA_FIELDS = (
    'kind', 'alu_op', 'in0_is_reg', 'imm', 'in0_reg', 'in1_reg', 'out_reg',
    'jump_addr', 'func_id', 'barrier', 'cmd_time',
    'p_env', 'p_phase', 'p_freq', 'p_amp', 'p_cfg',
    'p_wen', 'p_regsel', 'p_reg',
)

# bit order of the per-parameter write-enable / register-select masks
PULSE_PARAM_ORDER = ('env', 'phase', 'freq', 'amp', 'cfg')


@dataclass
class SoAProgram:
    """Decoded machine program as parallel int32 field arrays.

    Every field has shape ``[..., n_instr]`` (a leading core axis is added by
    :func:`stack_soa`).  This is the representation the JAX interpreter
    gathers from each step; it never re-decodes bits at trace time.
    """
    kind: np.ndarray
    alu_op: np.ndarray
    in0_is_reg: np.ndarray
    imm: np.ndarray          # signed int32 (two's complement decoded)
    in0_reg: np.ndarray
    in1_reg: np.ndarray
    out_reg: np.ndarray
    jump_addr: np.ndarray
    func_id: np.ndarray
    barrier: np.ndarray
    cmd_time: np.ndarray     # uint32 bit pattern stored in int32
    p_env: np.ndarray
    p_phase: np.ndarray
    p_freq: np.ndarray
    p_amp: np.ndarray
    p_cfg: np.ndarray
    p_wen: np.ndarray        # 5-bit write-enable mask, PULSE_PARAM_ORDER
    p_regsel: np.ndarray     # 5-bit from-register mask
    p_reg: np.ndarray        # source register for the (single) reg param

    @property
    def n_instr(self) -> int:
        return self.kind.shape[-1]

    def asdict(self) -> dict[str, np.ndarray]:
        return {f: getattr(self, f) for f in SOA_FIELDS}


def _bits(word: int, pos: int, width: int) -> int:
    return (word >> pos) & ((1 << width) - 1)


def decode_soa(cmds, use_native: bool = True) -> SoAProgram:
    """Decode a command buffer (bytes or list of 128-bit ints) into SoA form.

    Uses the native C++ codec when available (bit-exact with the Python
    path below; see distributed_processor_tpu/native/)."""
    if isinstance(cmds, (bytes, bytearray)) and use_native:
        from . import native
        if native.available():
            fields_arr = native.decode_soa_fields(bytes(cmds))
            return SoAProgram(**{f: np.ascontiguousarray(fields_arr[i])
                                 for i, f in enumerate(SOA_FIELDS)})
    if isinstance(cmds, (bytes, bytearray)):
        cmds = bytes_to_cmds(bytes(cmds))
    n = len(cmds)
    fields = {f: np.zeros(n, dtype=np.int32) for f in SOA_FIELDS}
    for i, cmd in enumerate(cmds):
        cmd = int(cmd)
        op5 = _bits(cmd, 123, 5)
        if op5 not in _OP5_TO_KIND:
            raise ValueError(f'instruction {i}: unknown opcode {op5:05b}')
        kind = _OP5_TO_KIND[op5]
        fields['kind'][i] = kind
        fields['alu_op'][i] = _bits(cmd, 120, 3)
        fields['in0_is_reg'][i] = op5 & 1 if kind in (
            K_REG_ALU, K_JUMP_COND, K_ALU_FPROC, K_JUMP_FPROC, K_INC_QCLK) else 0
        fields['imm'][i] = from_twos_complement(_bits(cmd, IMM_POS, 32))
        fields['in0_reg'][i] = _bits(cmd, IN0_REG_POS, REG_BITS)
        fields['in1_reg'][i] = _bits(cmd, IN1_REG_POS, REG_BITS)
        fields['out_reg'][i] = _bits(cmd, WRITE_REG_POS, REG_BITS)
        fields['jump_addr'][i] = _bits(cmd, JUMP_ADDR_POS, 8)
        fields['func_id'][i] = _bits(cmd, FUNC_ID_POS, 8)
        fields['barrier'][i] = _bits(cmd, BARRIER_ID_POS, 8)
        # cmd_time doubles as the idle end-time; keep the raw uint32 bit pattern
        fields['cmd_time'][i] = np.uint32(_bits(cmd, PULSE_POS['cmd_time'], 32)).view(np.int32)
        if kind in (K_PULSE_WRITE, K_PULSE_TRIG):
            wen = regsel = 0
            for b, name in enumerate(PULSE_PARAM_ORDER):
                pos, width = PULSE_POS[name if name != 'env' else 'env_word'], \
                    PULSE_WIDTH[name if name != 'env' else 'env_word']
                fields['p_' + name][i] = _bits(cmd, pos, width)
                if name == 'cfg':
                    w, r = _bits(cmd, pos + width, 1), 0
                else:
                    # {write_enable (high), use_register (low)}
                    ctl = _bits(cmd, pos + width, 2)
                    w, r = (ctl >> 1) & 1, ctl & 1
                wen |= w << b
                regsel |= r << b
            fields['p_wen'][i] = wen
            fields['p_regsel'][i] = regsel
            fields['p_reg'][i] = _bits(cmd, PULSE_REG_POS, REG_BITS)
    return SoAProgram(**fields)


def stack_soa(programs: list[SoAProgram], pad_to: int = None) -> SoAProgram:
    """Stack per-core SoA programs into ``[n_cores, n_instr]`` arrays.

    Shorter programs are padded with DONE instructions so a core that runs
    off the end simply halts — same behavior as all-zero command memory.
    """
    n = max(p.n_instr for p in programs)
    if pad_to is not None:
        n = max(n, pad_to)
    out = {f: np.zeros((len(programs), n), dtype=np.int32) for f in SOA_FIELDS}
    out['kind'][:] = K_DONE
    for c, prog in enumerate(programs):
        for f in SOA_FIELDS:
            out[f][c, :prog.n_instr] = getattr(prog, f)
    return SoAProgram(**out)


def shape_bucket(n_instr: int, min_size: int = 8) -> int:
    """Pad target for the multi-program path: ``n_instr`` rounded up to
    the next power of two (floored at ``min_size``).

    The multi-program executor keys its jit cache on array SHAPES, so
    every ensemble padded into the same bucket shares one compiled
    executable — all RB sequences of a depth band, say — and fresh
    random sequences of the same shape never retrace.
    """
    if n_instr <= 0:
        raise ValueError(f'n_instr must be positive, got {n_instr}')
    return max(min_size, 1 << (n_instr - 1).bit_length())


def stack_soa_multi(programs: list[SoAProgram],
                    pad_to: int = None) -> SoAProgram:
    """Stack already-stacked ``[n_cores, n_instr]`` SoA programs into
    ``[n_progs, n_cores, n_instr]`` arrays — the program-as-data tensor
    the multi-program executor vmaps over.

    Shorter programs pad with DONE exactly like :func:`stack_soa`: a
    padded core halts at its original DONE and the trailing rows never
    execute, so padding is semantically invisible.  Every program must
    share one ``n_cores``.
    """
    if not programs:
        raise ValueError('need at least one program to stack')
    n_cores = programs[0].kind.shape[0]
    for p in programs:
        if p.kind.ndim != 2 or p.kind.shape[0] != n_cores:
            raise ValueError(
                f'every program must be [n_cores={n_cores}, n_instr]; '
                f'got shape {p.kind.shape}')
    n = max(p.n_instr for p in programs)
    if pad_to is not None:
        n = max(n, pad_to)
    out = {f: np.zeros((len(programs), n_cores, n), dtype=np.int32)
           for f in SOA_FIELDS}
    out['kind'][:] = K_DONE
    for i, prog in enumerate(programs):
        for f in SOA_FIELDS:
            out[f][i, :, :prog.n_instr] = getattr(prog, f)
    return SoAProgram(**out)


# ---------------------------------------------------------------------------
# CFG block table (the block-compiled interpreter engine's program layout)
# ---------------------------------------------------------------------------

# Kinds that END a straight-line block: anything that branches, blocks on
# another core (fproc read / sync barrier), or otherwise needs the generic
# engine's dynamic dispatch.  K_ALU_FPROC / K_JUMP_FPROC here is what
# makes the block engine sound under EVERY fproc fabric — lut included:
# a read is always served at a boundary step by the generic fabric step
# with gathered producer state (and, under lut, the time-indexed
# meas_time plane), never from inside a superinstruction body
# (sim.interpreter.block_ineligible documents the per-fabric argument).
# DONE is deliberately NOT here: a halted core simply stops executing,
# so DONE rows are handled inline by the block bodies — otherwise the
# DONE padding that equalizes per-core program lengths (stack_soa)
# would shatter every block of a heterogeneous-length program.
BLOCK_TERMINATORS = frozenset(
    {K_JUMP_I, K_JUMP_COND, K_ALU_FPROC, K_JUMP_FPROC, K_SYNC})

# kinds a block body knows how to execute (everything else is a terminator)
BLOCK_BODY_KINDS = frozenset(
    {K_PULSE_WRITE, K_PULSE_TRIG, K_REG_ALU, K_INC_QCLK, K_PULSE_RESET,
     K_IDLE, K_DONE})

# below this, a block saves nothing over the generic boundary step but
# still costs a specialized trace — leave it to the generic engine
BLOCK_MIN_LEN = 2


def build_block_table(soa_or_fields, min_len: int = BLOCK_MIN_LEN):
    """Union-refined block table over a stacked ``[n_cores, n_instr]``
    program: the runtime layout of the block-compiled engine
    (``sim.interpreter._exec_blocks``).

    Block intervals live in the GLOBAL instruction-index space, shared
    by every core (cores of one lane sit at independent ``pc`` values,
    so a per-core table would need a per-core dispatch; a shared table
    needs one).  Boundaries are the union over cores of (a) every
    :data:`BLOCK_TERMINATORS` position and (b) every jump target — so
    no body interval contains, on ANY core, an instruction the body
    cannot execute, and no jump can land mid-body.

    Bodies with identical instruction content (every field, every core)
    are DEDUPLICATED: the engine traces one specialized body per
    distinct content and dispatches lanes onto it by block id, so the
    compile cost scales with the deduped total length, not the program
    length.

    ``soa_or_fields``: a :class:`SoAProgram` (or anything with
    ``.asdict()``) or a ``{field: [n_cores, n_instr] array}`` dict —
    at minimum ``kind`` and ``jump_addr``; ALL supplied fields enter
    the dedup key.

    Returns ``(bid_at, bodies)``: ``bid_at`` int32 ``[n_instr]`` maps a
    body-interval START to its deduplicated body id (−1 everywhere
    else); ``bodies`` is ``[(start, length)]`` per body id, ``start``
    being the representative interval whose rows define the body.
    """
    fields = soa_or_fields.asdict() if hasattr(soa_or_fields, 'asdict') \
        else dict(soa_or_fields)
    kind = np.asarray(fields['kind'])
    jump_addr = np.asarray(fields['jump_addr'])
    if kind.ndim != 2:
        raise ValueError(f'need stacked [n_cores, n_instr] fields; '
                         f'kind has shape {kind.shape}')
    C, N = kind.shape
    term_any = np.zeros(N, dtype=bool)
    for k in BLOCK_TERMINATORS:
        term_any |= np.any(kind == k, axis=0)
    jmask = (kind == K_JUMP_I) | (kind == K_JUMP_COND) \
        | (kind == K_JUMP_FPROC)
    leaders = {0}
    leaders.update(int(t) for t in jump_addr[jmask] if 0 <= int(t) < N)
    leaders.update(int(i) + 1 for i in np.nonzero(term_any)[0]
                   if int(i) + 1 < N)
    bounds = sorted(leaders) + [N]
    names = sorted(fields)
    bid_at = np.full(N, -1, dtype=np.int32)
    bodies: list = []
    index: dict = {}
    for s, e in zip(bounds, bounds[1:]):
        # a terminator position is always the LAST of its segment (its
        # successor is a leader), so the body is the segment minus at
        # most that one trailing instruction
        be = e - 1 if term_any[e - 1] else e
        if be - s < min_len:
            continue
        key = b''.join(
            np.ascontiguousarray(np.asarray(fields[f])[:, s:be]).tobytes()
            for f in names)
        bid = index.get(key)
        if bid is None:
            bid = len(bodies)
            index[key] = bid
            bodies.append((s, be - s))
        bid_at[s] = bid
    return bid_at, bodies


# ---------------------------------------------------------------------------
# human-readable disassembly (debugging / golden tests)
# ---------------------------------------------------------------------------

_KIND_NAMES = {
    K_PULSE_WRITE: 'pulse_write', K_PULSE_TRIG: 'pulse_write_trig',
    K_REG_ALU: 'reg_alu', K_JUMP_I: 'jump_i', K_JUMP_COND: 'jump_cond',
    K_ALU_FPROC: 'alu_fproc', K_JUMP_FPROC: 'jump_fproc',
    K_INC_QCLK: 'inc_qclk', K_SYNC: 'sync', K_DONE: 'done',
    K_PULSE_RESET: 'pulse_reset', K_IDLE: 'idle',
}
_ALU_NAMES = {v: k for k, v in ALU_OPS.items()}


def disassemble(cmds) -> list[dict]:
    """Decode a command buffer into a list of readable instruction dicts."""
    soa = decode_soa(cmds)
    out = []
    for i in range(soa.n_instr):
        kind = int(soa.kind[i])
        d = {'op': _KIND_NAMES[kind]}
        if kind in (K_PULSE_WRITE, K_PULSE_TRIG):
            wen, regsel = int(soa.p_wen[i]), int(soa.p_regsel[i])
            for b, name in enumerate(PULSE_PARAM_ORDER):
                if wen >> b & 1:
                    if regsel >> b & 1:
                        d[name] = ('reg', int(soa.p_reg[i]))
                    else:
                        d[name] = int(getattr(soa, 'p_' + name)[i])
            if kind == K_PULSE_TRIG:
                d['cmd_time'] = int(np.int32(soa.cmd_time[i]).view(np.uint32))
            env = d.pop('env', None)
            if env is not None:
                d['env_word'] = env
                if isinstance(env, int):
                    d['env_start'] = env & 0xfff
                    d['env_length'] = (env >> 12) & 0xfff
        elif kind == K_REG_ALU:
            d.update(alu_op=_ALU_NAMES[int(soa.alu_op[i])],
                     in0=('reg', int(soa.in0_reg[i])) if soa.in0_is_reg[i] else int(soa.imm[i]),
                     in1_reg=int(soa.in1_reg[i]), out_reg=int(soa.out_reg[i]))
        elif kind == K_JUMP_COND:
            d.update(alu_op=_ALU_NAMES[int(soa.alu_op[i])],
                     in0=('reg', int(soa.in0_reg[i])) if soa.in0_is_reg[i] else int(soa.imm[i]),
                     in1_reg=int(soa.in1_reg[i]), jump_addr=int(soa.jump_addr[i]))
        elif kind in (K_ALU_FPROC, K_JUMP_FPROC):
            d.update(alu_op=_ALU_NAMES[int(soa.alu_op[i])],
                     in0=('reg', int(soa.in0_reg[i])) if soa.in0_is_reg[i] else int(soa.imm[i]),
                     func_id=int(soa.func_id[i]))
            if kind == K_JUMP_FPROC:
                d['jump_addr'] = int(soa.jump_addr[i])
            else:
                d['out_reg'] = int(soa.out_reg[i])
        elif kind == K_JUMP_I:
            d['jump_addr'] = int(soa.jump_addr[i])
        elif kind == K_INC_QCLK:
            d['in0'] = ('reg', int(soa.in0_reg[i])) if soa.in0_is_reg[i] else int(soa.imm[i])
        elif kind == K_SYNC:
            d['barrier'] = int(soa.barrier[i])
        elif kind == K_IDLE:
            d['end_time'] = int(np.int32(soa.cmd_time[i]).view(np.uint32))
        out.append(d)
    return out
