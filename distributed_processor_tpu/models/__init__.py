from .channels import make_channel_config, make_channel_configs
from .experiments import (active_reset, rabi_program, t1_program,
                          ramsey_program, loop_shots_program, ghz_program,
                          t2_echo_program)
from .rb import rb_program, rb_sequence, rb_ensemble, clifford_table
from .rb2q import (rb2q_program, rb2q_sequence, clifford2_table,
                   rb2q_interleaved_program, element_index,
                   depol2_survival, count_cz)
from .coupling import couplings_from_qchip
from .readout import sample_meas_bits, apply_assignment_error, IQReadoutModel
from .default_qchip import make_default_qchip, make_default_qchip_dict
from .repetition import (repetition_round_machine_program, repetition_config,
                         repetition_round_program,
                         repetition_physics_kwargs, repetition_logical_program,
                         correlated_noise_stage, independent_noise_stage,
                         majority_lut, corrected_counts)
from .calibration import (fit_centroids, assignment_matrix,
                          readout_fidelity, calibrate_readout)
