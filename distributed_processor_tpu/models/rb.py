"""Single-qubit Clifford randomized benchmarking sequences.

The reference has no experiment library (RB programs are authored by
hand against the compiler's input format); this module generates them:
each Clifford is realised in the virtual-Z style the compiler optimises
for — ``Z(a) X90 Z(b) X90 Z(c)`` with angles in multiples of pi/2, so a
Clifford costs exactly two physical pulses and three frame updates
(which the ResolveVirtualZ pass folds into pulse phases).

The 24-element group table is built numerically at import time and the
recovery Clifford is found by projective unitary comparison.
"""

from __future__ import annotations

import functools

import numpy as np

_X90 = np.array([[1, -1j], [-1j, 1]]) / np.sqrt(2)


def _rz(k: int) -> np.ndarray:
    """Rz by k * pi/2."""
    a = k * np.pi / 2
    return np.array([[np.exp(-1j * a / 2), 0], [0, np.exp(1j * a / 2)]])


def _proj_eq(u: np.ndarray, v: np.ndarray) -> bool:
    return abs(abs(np.trace(u.conj().T @ v)) - 2) < 1e-9


@functools.lru_cache()
def clifford_table():
    """The 24 single-qubit Cliffords as (a, b, c) Euler triples (units of
    pi/2) with their unitaries: ``U = Rz(c) @ X90 @ Rz(b) @ X90 @ Rz(a)``
    (program order: Z(a), X90, Z(b), X90, Z(c))."""
    triples, unitaries = [], []
    for a in range(4):
        for b in range(4):
            for c in range(4):
                u = _rz(c) @ _X90 @ _rz(b) @ _X90 @ _rz(a)
                if not any(_proj_eq(u, v) for v in unitaries):
                    triples.append((a, b, c))
                    unitaries.append(u)
    assert len(triples) == 24, f'expected 24 Cliffords, got {len(triples)}'
    return triples, np.array(unitaries)


def inverse_index(net: np.ndarray) -> int:
    """Table index of the Clifford inverting ``net`` (projectively)."""
    _, unitaries = clifford_table()
    for i, u in enumerate(unitaries):
        if _proj_eq(u @ net, np.eye(2)):
            return i
    raise ValueError('net unitary is not a Clifford')


def rb_sequence(rng, depth: int) -> list[int]:
    """Random Clifford indices of length ``depth`` plus the recovery."""
    _, unitaries = clifford_table()
    seq = [int(rng.integers(24)) for _ in range(depth)]
    net = np.eye(2)
    for i in seq:
        net = unitaries[i] @ net
    seq.append(inverse_index(net))
    return seq


def clifford_instructions(qubit: str, index: int) -> list[dict]:
    """One Clifford as compiler-input instructions (2 pulses + 3 vz)."""
    triples, _ = clifford_table()
    a, b, c = triples[index]
    out = []
    for k, is_pulse in ((a, False), (None, True), (b, False), (None, True),
                        (c, False)):
        if is_pulse:
            out.append({'name': 'X90', 'qubit': [qubit]})
        elif k:
            out.append({'name': 'virtual_z', 'qubit': [qubit],
                        'phase': k * np.pi / 2})
    return out


def rb_program(qubits, depth: int, rng=None, seed: int = 0,
               delay_before: float = 500e-9) -> list[dict]:
    """Simultaneous per-qubit RB: independent random sequences on every
    qubit, aligned with a barrier, ending in a read on each qubit."""
    rng = rng or np.random.default_rng(seed)
    program = [{'name': 'delay', 't': delay_before}]
    seqs = {q: rb_sequence(rng, depth) for q in qubits}
    for q, seq in seqs.items():
        for idx in seq:
            program.extend(clifford_instructions(q, idx))
    program.append({'name': 'barrier', 'qubit': list(qubits)})
    for q in qubits:
        program.append({'name': 'read', 'qubit': [q]})
    return program


def rb_ensemble(qubits, depth: int, n_seqs: int, seed: int = 0,
                delay_before: float = 500e-9) -> list[list[dict]]:
    """``n_seqs`` independent random RB programs of one depth — the
    multi-sequence ensemble an RB experiment actually averages over
    (a single fixed sequence measures that sequence, not the gate set).

    Every Clifford costs exactly two physical pulses regardless of the
    random draw, so all members of an ensemble compile to the same
    instruction-count band and share one shape bucket — execute them in
    one compile via ``sim.interpreter.simulate_multi_batch``.

    Sequence ``s`` seeds its own generator from ``(seed, s)``:
    ensembles are reproducible, and growing ``n_seqs`` extends an
    existing ensemble without re-randomizing the earlier members.
    """
    if n_seqs <= 0:
        raise ValueError(f'need n_seqs >= 1, got {n_seqs}')
    return [rb_program(qubits, depth,
                       rng=np.random.default_rng([seed, s]),
                       delay_before=delay_before)
            for s in range(n_seqs)]
