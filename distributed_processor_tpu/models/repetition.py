"""Repetition-code syndrome round on the LUT measurement fabric.

Flagship demo of the fproc_lut path (reference: hdl/fproc_lut.sv +
meas_lut.sv): every data core measures, the fabric forms the syndrome
address from all data bits, and each core receives its own correction
bit from a majority-vote table — the distributed-feedback pattern the
gateware hard-codes, here generated for any code distance.
"""

from __future__ import annotations

import numpy as np

from .. import isa
from ..decoder import machine_program_from_cmds
from ..sim.interpreter import InterpreterConfig


def majority_lut(n_data: int) -> tuple:
    """LUT table: entry ``addr`` has bit i set iff data bit i disagrees
    with the majority of the measured pattern (i.e. core i needs an X
    correction to restore the codeword)."""
    table = []
    for addr in range(1 << n_data):
        bits = [(addr >> i) & 1 for i in range(n_data)]
        maj = 1 if sum(bits) * 2 > n_data else 0
        table.append(sum((1 << i) for i, b in enumerate(bits) if b != maj))
    return tuple(table)


def repetition_round_machine_program(n_data: int = 3,
                                     meas_time: int = 10,
                                     correct_time: int = 400):
    """One syndrome-measurement + correction round, one core per data
    qubit: measure (rdlo), read own correction bit from the LUT
    (func_id=1), conditionally flip (two X90 = X), halt."""
    cores = []
    for _ in range(n_data):
        cmds = [
            isa.pulse_cmd(freq_word=1, cfg_word=2, env_word=(2 << 12) | 0,
                          cmd_time=meas_time),
            isa.alu_cmd('jump_fproc', 'i', 1, 'eq', jump_cmd_ptr=3,
                        func_id=1),
            isa.jump_i(5),
            isa.pulse_cmd(freq_word=2, cfg_word=0, env_word=(2 << 12) | 0,
                          cmd_time=correct_time),
            isa.pulse_cmd(cmd_time=correct_time + 20),
            isa.done_cmd(),
        ]
        cores.append(cmds)
    return machine_program_from_cmds(cores)


def _lut_fabric_kwargs(n_data: int) -> dict:
    """The LUT-fabric wiring every repetition path shares: all data
    cores masked into the syndrome address, majority table loaded."""
    return dict(fabric='lut', lut_mask=(True,) * n_data,
                lut_table=majority_lut(n_data))


def repetition_config(n_data: int, **kw) -> InterpreterConfig:
    defaults = dict(max_steps=64, max_pulses=8, max_meas=2, max_resets=1,
                    **_lut_fabric_kwargs(n_data))
    defaults.update(kw)
    return InterpreterConfig(**defaults)


def repetition_round_program(n_data: int = 3,
                             slack_s: float = 3e-6) -> list[dict]:
    """Gate-level (compiled-path) repetition round, for physics-closed
    execution: every data qubit measures, branches on its own
    majority-vote correction bit from the syndrome LUT (``func_id=1``),
    and conditionally flips (two X90 = X).

    ``slack_s``: delay at the head of the correction branch — the LUT
    read blocks until every masked core's window demodulates (readout
    window + demod hold), a wait the static scheduler cannot see; the
    slack keeps the correction pulses' trigger times ahead of it.

    Run with ``repetition_physics_kwargs(n_data)`` as the interpreter
    configuration.
    """
    program = []
    for i in range(n_data):
        q = f'Q{i}'
        program += [
            {'name': 'read', 'qubit': [q]},
            {'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
             'func_id': 1, 'scope': [q],
             'true': [{'name': 'delay', 't': slack_s, 'qubit': [q]},
                      {'name': 'X90', 'qubit': [q]},
                      {'name': 'X90', 'qubit': [q]}],
             'false': []},
        ]
    return program


def repetition_physics_kwargs(n_data: int) -> dict:
    """Interpreter-config kwargs for the physics-closed compiled round
    (pass to ``run_physics_batch``): the shared LUT wiring plus budgets
    sized for the gate-level program (more pulses per core than the
    hand-assembled machine round)."""
    return dict(max_pulses=16, max_meas=2, **_lut_fabric_kwargs(n_data))


def corrected_counts(out, n_data: int) -> np.ndarray:
    """Per-core correction count from a run's pulse records: cores that
    fired the 2-pulse flip after the readout."""
    n = np.asarray(out['n_pulses'])
    return (n - 1) // 2      # readout pulse + optionally 2 X90s
