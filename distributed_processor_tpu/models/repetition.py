"""Repetition-code syndrome round on the LUT measurement fabric.

Flagship demo of the fproc_lut path (reference: hdl/fproc_lut.sv +
meas_lut.sv): every data core measures, the fabric forms the syndrome
address from all data bits, and each core receives its own correction
bit from a majority-vote table — the distributed-feedback pattern the
gateware hard-codes, here generated for any code distance.
"""

from __future__ import annotations

import numpy as np

from .. import isa
from ..decoder import machine_program_from_cmds
from ..sim.interpreter import InterpreterConfig


def majority_lut(n_data: int) -> tuple:
    """LUT table: entry ``addr`` has bit i set iff data bit i disagrees
    with the majority of the measured pattern (i.e. core i needs an X
    correction to restore the codeword)."""
    table = []
    for addr in range(1 << n_data):
        bits = [(addr >> i) & 1 for i in range(n_data)]
        maj = 1 if sum(bits) * 2 > n_data else 0
        table.append(sum((1 << i) for i, b in enumerate(bits) if b != maj))
    return tuple(table)


def repetition_round_machine_program(n_data: int = 3,
                                     meas_time: int = 10,
                                     correct_time: int = 400):
    """One syndrome-measurement + correction round, one core per data
    qubit: measure (rdlo), read own correction bit from the LUT
    (func_id=1), conditionally flip (two X90 = X), halt."""
    cores = []
    for _ in range(n_data):
        cmds = [
            isa.pulse_cmd(freq_word=1, cfg_word=2, env_word=(2 << 12) | 0,
                          cmd_time=meas_time),
            isa.alu_cmd('jump_fproc', 'i', 1, 'eq', jump_cmd_ptr=3,
                        func_id=1),
            isa.jump_i(5),
            isa.pulse_cmd(freq_word=2, cfg_word=0, env_word=(2 << 12) | 0,
                          cmd_time=correct_time),
            isa.pulse_cmd(cmd_time=correct_time + 20),
            isa.done_cmd(),
        ]
        cores.append(cmds)
    return machine_program_from_cmds(cores)


def _lut_fabric_kwargs(n_data: int) -> dict:
    """The LUT-fabric wiring every repetition path shares: all data
    cores masked into the syndrome address, majority table loaded."""
    return dict(fabric='lut', lut_mask=(True,) * n_data,
                lut_table=majority_lut(n_data))


def repetition_config(n_data: int, **kw) -> InterpreterConfig:
    defaults = dict(max_steps=64, max_pulses=8, max_meas=2, max_resets=1,
                    **_lut_fabric_kwargs(n_data))
    defaults.update(kw)
    return InterpreterConfig(**defaults)


def repetition_round_program(n_data: int = 3,
                             slack_s: float = 3e-6) -> list[dict]:
    """Gate-level (compiled-path) repetition round, for physics-closed
    execution: every data qubit measures, branches on its own
    majority-vote correction bit from the syndrome LUT (``func_id=1``),
    and conditionally flips (two X90 = X).

    ``slack_s``: delay at the head of the correction branch — the LUT
    read blocks until every masked core's window demodulates (readout
    window + demod hold), a wait the static scheduler cannot see; the
    slack keeps the correction pulses' trigger times ahead of it.

    Run with ``repetition_physics_kwargs(n_data)`` as the interpreter
    configuration.
    """
    program = []
    for i in range(n_data):
        q = f'Q{i}'
        program += [
            {'name': 'read', 'qubit': [q]},
            {'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
             'func_id': 1, 'scope': [q],
             'true': [{'name': 'delay', 't': slack_s, 'qubit': [q]},
                      {'name': 'X90', 'qubit': [q]},
                      {'name': 'X90', 'qubit': [q]}],
             'false': []},
        ]
    return program


def repetition_physics_kwargs(n_data: int) -> dict:
    """Interpreter-config kwargs for the physics-closed compiled round
    (pass to ``run_physics_batch``): the shared LUT wiring plus budgets
    sized for the gate-level program (more pulses per core than the
    hand-assembled machine round)."""
    return dict(max_pulses=16, max_meas=2, **_lut_fabric_kwargs(n_data))


def _zero_amp_pulse(dest_q: int, freq_q: int, qchip=None) -> dict:
    """A zero-amplitude drive pulse on ``Q<dest_q>.qdrv`` at qubit
    ``freq_q``'s frequency: rotates nothing, but gives the statevec
    device's stochastic error channels a pulse to fire on (1q depol
    when freq_q == dest_q, the 2q coupling channel otherwise).

    The frequency is resolved from ``qchip`` — it must match the target
    qubit's drive frequency exactly or the coupling map never fires and
    the 'noise' silently injects nothing (models/coupling.py matches by
    frequency value)."""
    if qchip is None:
        from .default_qchip import make_default_qchip
        qchip = make_default_qchip(max(dest_q, freq_q) + 1)
    return {'name': 'pulse', 'dest': f'Q{dest_q}.qdrv',
            'freq': qchip.get_qubit_freq(f'Q{freq_q}.freq'),
            'phase': 0.0, 'amp': 0.0, 'twidth': 24e-9,
            'env': {'env_func': 'square', 'paradict': {}}}


def correlated_noise_stage(pairs, qchip=None) -> list[dict]:
    """Pairwise-correlated error injection: one zero-amplitude
    cross-resonance pulse per (control, target) pair.  With
    ``DeviceModel.depol2_per_pulse = p``, each pair suffers one of the
    15 two-qubit Paulis with probability p — including the both-flip
    errors (4/15 of them) that defeat a distance-3 majority vote with a
    SINGLE event, which is what makes correlated noise strictly worse
    for the repetition code than independent noise of equal marginal
    strength (tests/test_repetition_correlated.py)."""
    out = []
    qubits = sorted({q for ab in pairs for q in ab})
    if qchip is None and pairs:
        from .default_qchip import make_default_qchip
        qchip = make_default_qchip(max(qubits) + 1)
    for a, b in pairs:
        out.append({'name': 'barrier',
                    'qubit': [f'Q{q}' for q in qubits]})
        out.append(_zero_amp_pulse(a, b, qchip))
    return out


def independent_noise_stage(qubits, qchip=None) -> list[dict]:
    """Per-qubit independent error injection: one zero-amplitude 1q
    drive pulse per qubit; ``DeviceModel.depol_per_pulse = p`` then
    flips each qubit independently with probability 2p/3."""
    qubits = list(qubits)
    if qchip is None and qubits:
        from .default_qchip import make_default_qchip
        qchip = make_default_qchip(max(qubits) + 1)
    return [_zero_amp_pulse(q, q, qchip) for q in qubits]


def repetition_logical_program(n_data: int = 3, noise: list = None,
                               slack_s: float = 3e-6) -> list[dict]:
    """Noise stage + one full syndrome round + verification readout:
    inject errors, measure every data qubit, apply the LUT
    majority-vote correction, then read again — the second-round
    majority is the logical state after correction.  Run with
    ``repetition_physics_kwargs(n_data)``."""
    qubits = [f'Q{i}' for i in range(n_data)]
    program = list(noise or [])
    program.append({'name': 'barrier', 'qubit': qubits})
    program += repetition_round_program(n_data, slack_s)
    program.append({'name': 'barrier', 'qubit': qubits})
    for q in qubits:
        program.append({'name': 'read', 'qubit': [q]})
    return program


def corrected_counts(out, n_data: int) -> np.ndarray:
    """Per-core correction count from a run's pulse records: cores that
    fired the 2-pulse flip after the readout."""
    n = np.asarray(out['n_pulses'])
    return (n - 1) // 2      # readout pulse + optionally 2 X90s
