"""Canonical experiment program builders.

Programs in the compiler's dict input format (same surface as the
reference's — reference: python/distproc/compiler.py:1-106): measurement
feedback via ``branch_fproc``, frame updates via ``virtual_z``, gate
parameter overrides via ``modi``.  These are the "model families" of the
framework — the programs users actually sweep and run.
"""

from __future__ import annotations

import numpy as np


def active_reset(qubits, n_rounds: int = 1) -> list[dict]:
    """Measurement-conditioned reset: read, flip if |1> (the idiom the
    reference's OpenQASM frontend emits for QuantumReset — reference:
    python/distproc/openqasm/visitor.py:86-92)."""
    program = []
    for _ in range(n_rounds):
        for q in qubits:
            program.append({'name': 'read', 'qubit': [q]})
            program.append({
                'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
                'func_id': f'{q}.meas', 'scope': [q],
                'true': [{'name': 'X90', 'qubit': [q]},
                         {'name': 'X90', 'qubit': [q]}],
                'false': []})
    return program


def rabi_program(qubit: str, amplitude: float, pulse_name: str = 'X90') -> list[dict]:
    """Amplitude-Rabi point: drive at overridden amplitude, then read."""
    return [
        {'name': pulse_name, 'qubit': [qubit],
         'modi': {(0, 'amp'): float(amplitude)}},
        {'name': 'read', 'qubit': [qubit]},
    ]


def t1_program(qubit: str, delay_s: float) -> list[dict]:
    """T1 point: pi pulse (2x X90), wait, read."""
    return [
        {'name': 'X90', 'qubit': [qubit]},
        {'name': 'X90', 'qubit': [qubit]},
        {'name': 'delay', 't': float(delay_s), 'qubit': [qubit]},
        {'name': 'read', 'qubit': [qubit]},
    ]


def ramsey_program(qubit: str, delay_s: float,
                   detuning_phase: float = 0.0) -> list[dict]:
    """Ramsey point: X90, wait (+ optional frame advance), X90, read."""
    out = [
        {'name': 'X90', 'qubit': [qubit]},
        {'name': 'delay', 't': float(delay_s), 'qubit': [qubit]},
    ]
    if detuning_phase:
        out.append({'name': 'virtual_z', 'qubit': [qubit],
                    'phase': float(detuning_phase)})
    out += [
        {'name': 'X90', 'qubit': [qubit]},
        {'name': 'read', 'qubit': [qubit]},
    ]
    return out


def t2_echo_program(qubit: str, delay_s: float) -> list[dict]:
    """Hahn echo point: X90 - wait/2 - X (echo) - wait/2 - X90, read."""
    half = {'name': 'delay', 't': float(delay_s) / 2, 'qubit': [qubit]}
    return [
        {'name': 'X90', 'qubit': [qubit]},
        dict(half),
        {'name': 'X90', 'qubit': [qubit]},
        {'name': 'X90', 'qubit': [qubit]},
        dict(half),
        {'name': 'X90', 'qubit': [qubit]},
        {'name': 'read', 'qubit': [qubit]},
    ]


def ghz_program(qubits) -> list[dict]:
    """GHZ-state preparation + readout: H on the first qubit, a CNOT
    chain, barrier, read all (uses the CNOT calibrations the default
    qchip defines for adjacent pairs).

    Every CNOT is fenced with a barrier over all qubits — on hardware
    (and in the schedule the statevec device's discrete-event gate
    replays in time order) this keeps a deep chain's drives from
    overlapping the neighbour's CR tone."""
    q0 = qubits[0]
    prog = [
        {'name': 'virtual_z', 'qubit': [q0], 'phase': np.pi / 2},
        {'name': 'X90', 'qubit': [q0]},
        {'name': 'virtual_z', 'qubit': [q0], 'phase': np.pi / 2},
    ]
    for a, b in zip(qubits, qubits[1:]):
        prog.append({'name': 'barrier', 'qubit': list(qubits)})
        prog.append({'name': 'CNOT', 'qubit': [a, b]})
    prog.append({'name': 'barrier', 'qubit': list(qubits)})
    for q in qubits:
        prog.append({'name': 'read', 'qubit': [q]})
    return prog


def loop_shots_program(body: list[dict], n_shots: int, scope) -> list[dict]:
    """Wrap a program body in an on-device shot loop (the reference's
    loop instruction with a var counter — qclk rewind keeps per-iteration
    schedules identical; reference: compiler.py:322-324)."""
    return [
        {'name': 'declare', 'var': 'shotcnt', 'dtype': 'int', 'scope': scope},
        {'name': 'set_var', 'var': 'shotcnt', 'value': 0},
        {'name': 'loop', 'cond_lhs': int(n_shots), 'alu_cond': 'ge',
         'cond_rhs': 'shotcnt', 'scope': scope,
         'body': list(body) + [
             {'name': 'alu', 'lhs': 1, 'op': 'add', 'rhs': 'shotcnt',
              'out': 'shotcnt'}]},
    ]
