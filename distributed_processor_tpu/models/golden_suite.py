"""Self-contained golden-test suite: programs + canonical outputs.

The reference pins its compiler with golden files generated from an
external calibration JSON (reference: python/test/test_compiler.py
golden tests against test_outputs/*.txt); those oracle comparisons need
the reference checkout mounted.  This module is the repo's *own*
equivalent: a fixed set of programs compiled against the built-in
default qchip (models/default_qchip.py), with canonical JSON renderings
of both the per-core assembly and the assembled byte buffers.  The
committed goldens live in tests/goldens/ (regenerate with
``python -m distributed_processor_tpu.models.golden_suite``), and
tests/test_goldens_self.py compares fresh compilations against them in
any checkout — no reference needed.
"""

from __future__ import annotations

import json
import numpy as np

from ..hwconfig import FPGAConfig
from ..elements import TPUElementConfig
from ..assembler import GlobalAssembler
from .channels import make_channel_configs
from .default_qchip import make_default_qchip
from .experiments import active_reset, ghz_program, t2_echo_program
from .rb import rb_program


def _linear():
    return [{'name': 'X90', 'qubit': ['Q0']},
            {'name': 'X90', 'qubit': ['Q1']},
            {'name': 'read', 'qubit': ['Q0']}]


def _pulse_sequence():
    return [
        {'name': 'pulse', 'dest': 'Q0.qdrv', 'freq': 4.2e9, 'phase': 0.0,
         'amp': 0.5, 'twidth': 32e-9,
         'env': {'env_func': 'cos_edge_square',
                 'paradict': {'ramp_fraction': 0.25}}},
        {'name': 'barrier', 'qubit': ['Q0', 'Q1']},
        {'name': 'pulse', 'dest': 'Q1.qdrv', 'freq': 4.31e9,
         'phase': np.pi / 4, 'amp': 0.25, 'twidth': 24e-9,
         'env': {'env_func': 'square', 'paradict': {}}},
        {'name': 'delay', 't': 100e-9, 'qubit': ['Q0']},
        {'name': 'X90', 'qubit': ['Q0']},
        {'name': 'read', 'qubit': ['Q1']},
    ]


def _fproc_hold():
    return [{'name': 'read', 'qubit': ['Q0']},
            {'name': 'branch_fproc', 'alu_cond': 'eq', 'cond_lhs': 1,
             'func_id': 'Q0.meas', 'scope': ['Q0'],
             'true': [{'name': 'X90', 'qubit': ['Q0']},
                      {'name': 'X90', 'qubit': ['Q0']}],
             'false': [{'name': 'Z90', 'qubit': ['Q0']}]}]


def _simple_loop():
    return [{'name': 'X90', 'qubit': ['Q0']},
            {'name': 'declare', 'var': 'loopind', 'dtype': 'int',
             'scope': ['Q0']},
            {'name': 'loop', 'cond_lhs': 10, 'cond_rhs': 'loopind',
             'alu_cond': 'ge', 'scope': ['Q0'],
             'body': [{'name': 'X90', 'qubit': ['Q0']},
                      {'name': 'X90', 'qubit': ['Q0']}]},
            {'name': 'read', 'qubit': ['Q0']}]


def _nested_loop():
    return [{'name': 'declare', 'var': 'i', 'dtype': 'int', 'scope': ['Q0']},
            {'name': 'declare', 'var': 'j', 'dtype': 'int', 'scope': ['Q0']},
            {'name': 'loop', 'cond_lhs': 3, 'cond_rhs': 'i',
             'alu_cond': 'ge', 'scope': ['Q0'],
             'body': [{'name': 'X90', 'qubit': ['Q0']},
                      {'name': 'loop', 'cond_lhs': 2, 'cond_rhs': 'j',
                       'alu_cond': 'ge', 'scope': ['Q0'],
                       'body': [{'name': 'X90', 'qubit': ['Q0']}]}]},
            {'name': 'read', 'qubit': ['Q0']}]


def _hw_virtualz():
    return [{'name': 'declare', 'var': 'q0_phase', 'scope': ['Q0'],
             'dtype': 'phase'},
            {'name': 'bind_phase', 'var': 'q0_phase', 'freq': 'Q0.freq'},
            {'name': 'X90', 'qubit': ['Q0']},
            {'name': 'X90', 'qubit': ['Q1']},
            {'name': 'virtual_z', 'qubit': 'Q0', 'phase': np.pi / 2},
            {'name': 'X90', 'qubit': ['Q0']},
            {'name': 'read', 'qubit': ['Q0']}]


def _sw_virtualz():
    return [{'name': 'X90', 'qubit': ['Q0']},
            {'name': 'virtual_z', 'qubit': 'Q0', 'phase': np.pi / 2},
            {'name': 'X90', 'qubit': ['Q0']},
            {'name': 'virtual_z', 'qubit': 'Q0', 'phase': -np.pi / 4},
            {'name': 'X90', 'qubit': ['Q0']},
            {'name': 'read', 'qubit': ['Q0']}]


# name -> (n_qubits, program thunk); every entry compiles with the
# default qchip and default FPGAConfig — fully self-contained
GOLDEN_PROGRAMS = {
    'linear_x90_read': (2, _linear),
    'pulse_sequence': (2, _pulse_sequence),
    'active_reset_2q': (2, lambda: active_reset(['Q0', 'Q1'])),
    'fproc_hold': (1, _fproc_hold),
    'simple_loop': (1, _simple_loop),
    'nested_loop': (1, _nested_loop),
    'hw_virtualz': (2, _hw_virtualz),
    'sw_virtualz': (1, _sw_virtualz),
    'ghz_3q': (3, lambda: ghz_program(['Q0', 'Q1', 'Q2'])),
    't2_echo': (1, lambda: t2_echo_program('Q0', 1e-6)),
    'rb_2q_depth3': (2, lambda: rb_program(['Q0', 'Q1'], 3, seed=99)),
}


def compile_golden(name: str) -> dict:
    """Compile one golden program; returns the canonical JSON-safe dict
    {'asm': CompiledProgram.to_dict(), 'assembled': {core: hex bufs}}."""
    from ..pipeline import compile_program
    n_qubits, thunk = GOLDEN_PROGRAMS[name]
    qchip = make_default_qchip(max(n_qubits, 2))
    prog = compile_program(thunk(), qchip, FPGAConfig())
    asm = GlobalAssembler(prog, make_channel_configs(n_qubits),
                          TPUElementConfig)
    assembled = asm.get_assembled_program()
    return {
        'asm': prog.to_dict(),
        'assembled': {
            str(core): {
                'cmd_buf': bufs['cmd_buf'].hex(),
                'env_buffers': [b.hex() for b in bufs['env_buffers']],
                'freq_buffers': [b.hex() for b in bufs['freq_buffers']],
            } for core, bufs in assembled.items()},
    }


def canonical_json(obj) -> str:
    return json.dumps(obj, indent=1, sort_keys=True)


def main():
    """Regenerate tests/goldens/*.json from the current compiler."""
    import os
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    outdir = os.path.join(here, 'tests', 'goldens')
    os.makedirs(outdir, exist_ok=True)
    for name in GOLDEN_PROGRAMS:
        path = os.path.join(outdir, name + '.json')
        with open(path, 'w') as f:
            f.write(canonical_json(compile_golden(name)) + '\n')
        print('wrote', path)


if __name__ == '__main__':
    main()
