"""Built-in N-qubit calibration: a self-contained QChip gate library.

The reference requires an external calibration JSON (the out-of-repo
``qubitconfig`` package's qubitcfg.json); this generates an equivalent
library programmatically — per-qubit X90 (DRAG), Z90 (virtual), read
(flat-top rdrv + square rdlo) — so benchmarks and demos run without any
external files.  Schema matches :class:`~..qchip.QChip`.
"""

from __future__ import annotations

from ..qchip import QChip


def make_default_qchip_dict(n_qubits: int = 8) -> dict:
    qubits, gates = {}, {}
    for i in range(n_qubits):
        q = f'Q{i}'
        qubits[q] = {
            'freq': 4.2e9 + 0.11e9 * i,
            'freq_ef': 4.0e9 + 0.11e9 * i,
            'readfreq': 6.4e9 + 0.08e9 * i,
        }
        gates[q + 'X90'] = [{
            'dest': q + '.qdrv', 'freq': q + '.freq', 'phase': 0.0,
            'amp': 0.48, 't0': 0.0, 'twidth': 24e-9,
            'env': {'env_func': 'DRAG',
                    'paradict': {'alpha': 0.4, 'sigmas': 3,
                                 'delta': -270e6}},
        }]
        gates[q + 'Z90'] = [{'gate': 'virtualz', 'freq': q + '.freq',
                             'phase': 1.5707963267948966}]
        gates[q + 'read'] = [
            {'dest': q + '.rdrv', 'freq': q + '.readfreq', 'phase': 0.0,
             'amp': 0.25, 't0': 0.0, 'twidth': 512e-9,
             'env': {'env_func': 'cos_edge_square',
                     'paradict': {'ramp_fraction': 0.25}}},
            {'dest': q + '.rdlo', 'freq': q + '.readfreq', 'phase': 0.0,
             'amp': 1.0, 't0': 0.0, 'twidth': 512e-9,
             'env': {'env_func': 'square', 'paradict': {'phase': 0.0,
                                                        'amplitude': 1.0}}},
        ]
    # two-qubit gates for adjacent pairs: a cross-resonance-style CNOT
    # (drive on the control at the target frequency + echo) and a CZ
    for i in range(n_qubits - 1):
        c, t = f'Q{i}', f'Q{i+1}'
        cr = {'env_func': 'cos_edge_square', 'paradict': {'ramp_fraction': 0.3}}
        gates[c + t + 'CNOT'] = [
            {'gate': 'virtualz', 'freq': c + '.freq', 'phase': -1.5707963267948966},
            {'dest': c + '.qdrv', 'freq': t + '.freq', 'phase': 0.0,
             'amp': 0.35, 't0': 0.0, 'twidth': 120e-9, 'env': cr},
            {'gate': c + 'X90', 't0': 120e-9},
            {'dest': c + '.qdrv', 'freq': t + '.freq',
             'phase': 3.141592653589793, 'amp': 0.35, 't0': 144e-9,
             'twidth': 120e-9, 'env': cr},
            {'gate': c + 'X90', 't0': 264e-9},
        ]
        gates[c + t + 'CZ'] = [
            {'dest': c + '.qdrv', 'freq': c + '.freq_ef', 'phase': 0.0,
             'amp': 0.42, 't0': 0.0, 'twidth': 80e-9, 'env': cr},
            {'gate': 'virtualz', 'freq': c + '.freq', 'phase': 0.7853981633974483},
            {'gate': 'virtualz', 'freq': t + '.freq', 'phase': 0.7853981633974483},
        ]
    return {'Qubits': qubits, 'Gates': gates}


def make_default_qchip(n_qubits: int = 8) -> QChip:
    return QChip(make_default_qchip_dict(n_qubits))
