"""Built-in N-qubit calibration: a self-contained QChip gate library.

The reference requires an external calibration JSON (the out-of-repo
``qubitconfig`` package's qubitcfg.json); this generates an equivalent
library programmatically — per-qubit X90 (DRAG), Z90 (virtual), read
(flat-top rdrv + square rdlo) — so benchmarks and demos run without any
external files.  Schema matches :class:`~..qchip.QChip`.
"""

from __future__ import annotations

from ..qchip import QChip

# cross-resonance / ef-drive reference amplitudes: a full-amplitude CR
# pulse is a pi/2 ZX rotation (sim/device.py ZX90_AMP_DEFAULT =
# round(CR_AMP * 0xffff)), the CZ ef drive a pi/2 ZZ rotation
CR_AMP = 0.35
CZ_AMP = 0.42


def make_default_qchip_dict(n_qubits: int = 8) -> dict:
    qubits, gates = {}, {}
    for i in range(n_qubits):
        q = f'Q{i}'
        qubits[q] = {
            'freq': 4.2e9 + 0.11e9 * i,
            'freq_ef': 4.0e9 + 0.11e9 * i,
            'readfreq': 6.4e9 + 0.08e9 * i,
        }
        gates[q + 'X90'] = [{
            'dest': q + '.qdrv', 'freq': q + '.freq', 'phase': 0.0,
            'amp': 0.48, 't0': 0.0, 'twidth': 24e-9,
            'env': {'env_func': 'DRAG',
                    'paradict': {'alpha': 0.4, 'sigmas': 3,
                                 'delta': -270e6}},
        }]
        gates[q + 'Z90'] = [{'gate': 'virtualz', 'freq': q + '.freq',
                             'phase': 1.5707963267948966}]
        gates[q + 'read'] = [
            {'dest': q + '.rdrv', 'freq': q + '.readfreq', 'phase': 0.0,
             'amp': 0.25, 't0': 0.0, 'twidth': 512e-9,
             'env': {'env_func': 'cos_edge_square',
                     'paradict': {'ramp_fraction': 0.25}}},
            {'dest': q + '.rdlo', 'freq': q + '.readfreq', 'phase': 0.0,
             'amp': 1.0, 't0': 0.0, 'twidth': 512e-9,
             'env': {'env_func': 'square', 'paradict': {'phase': 0.0,
                                                        'amplitude': 1.0}}},
        ]
    # Two-qubit gates for adjacent pairs, designed to compose EXACTLY to
    # CNOT / CZ under the statevec device model's interaction semantics
    # (sim/device.py: a drive on the control at the target's frequency
    # is exp(-i th/2 Z_c X_t^phi) with th = (pi/2) * amp / zx90_amp; an
    # ef-frequency drive is exp(-i th/2 Z_c Z_t)); pinned by
    # tests/test_device_statevec.py.
    #
    # CNOT = e^{i pi/4} Rz_c(pi/2) Rx_t(pi/2) R_zx(-pi/2): the R_zx via
    # an echoed cross-resonance pair — CR(pi/4, phase pi), X180_c,
    # CR(pi/4, phase 0), X180_c == R_zx(-pi/2) about any folded control
    # frame — then X90 on the target and virtual-z on the control
    # (virtual_z(p) realizes Rz(-p) for Z-measured circuits).
    for i in range(n_qubits - 1):
        c, t = f'Q{i}', f'Q{i+1}'
        cr = {'env_func': 'cos_edge_square', 'paradict': {'ramp_fraction': 0.3}}
        half_cr = CR_AMP / 2
        gates[c + t + 'CNOT'] = [
            {'dest': c + '.qdrv', 'freq': t + '.freq',
             'phase': 3.141592653589793, 'amp': half_cr, 't0': 0.0,
             'twidth': 120e-9, 'env': cr},
            {'gate': c + 'X90', 't0': 120e-9},
            {'gate': c + 'X90', 't0': 144e-9},
            {'dest': c + '.qdrv', 'freq': t + '.freq', 'phase': 0.0,
             'amp': half_cr, 't0': 168e-9, 'twidth': 120e-9, 'env': cr},
            {'gate': c + 'X90', 't0': 288e-9},
            {'gate': c + 'X90', 't0': 312e-9},
            {'gate': t + 'X90', 't0': 336e-9},
            {'gate': 'virtualz', 'freq': c + '.freq',
             'phase': -1.5707963267948966},
        ]
        # CZ = e^{-i pi/4} Rz_c(-pi/2) Rz_t(-pi/2) R_zz(pi/2): one
        # ef drive (th_zz = pi/2 at amp = CZ_AMP = zz90_amp) plus
        # virtual-z pi/2 on both frames (Rz(-pi/2) each)
        gates[c + t + 'CZ'] = [
            {'dest': c + '.qdrv', 'freq': c + '.freq_ef', 'phase': 0.0,
             'amp': CZ_AMP, 't0': 0.0, 'twidth': 80e-9, 'env': cr},
            {'gate': 'virtualz', 'freq': c + '.freq',
             'phase': 1.5707963267948966},
            {'gate': 'virtualz', 'freq': t + '.freq',
             'phase': 1.5707963267948966},
        ]
    return {'Qubits': qubits, 'Gates': gates}


def make_default_qchip(n_qubits: int = 8) -> QChip:
    return QChip(make_default_qchip_dict(n_qubits))
