"""Derive the statevec device's two-qubit coupling map from a compiled
program and its gate library.

The statevec model (sim/device.py) identifies entangling pulses by
``(core, frequency-word)``: a drive pulse whose frequency table entry is
another qubit's drive frequency is a cross-resonance (ZX) interaction,
and one at the control's own ef transition is a ZZ (CZ-style) drive.
The mapping from frequency *values* to per-core table *indices* is a
property of the compiled machine program (the assembler builds each
core's frequency table from the pulses the program actually plays,
assembler.py add_freq), so the coupling map is derived per-program here
and handed to :class:`~..sim.device.DeviceModel` as static
configuration.

The reference treats two-qubit calibrations as first-class gate-library
entries (reference: python/test/qubitcfg.json:1152 Q5Q4CNOT) but models
no physics for them — hardware entangles; this map is what lets the
TPU build's closed loop entangle in-sim.
"""

from __future__ import annotations

import re

import numpy as np

from ..qchip import QChip, GatePulse

_GATE_RE = re.compile(r'(Q\d+)(Q\d+)(CNOT|CZ)')


def couplings_from_qchip(mp, qchip: QChip, drive_elem: int = 0) -> tuple:
    """Coupling entries ``(ctrl_core, freq_idx, target_core, kind)`` for
    every two-qubit gate in ``qchip`` whose interaction frequency the
    compiled program ``mp`` actually uses.

    Qubit ``Qn`` maps to core ``n`` (the models/channels.py layout).  A
    CNOT's CR pulses (control driven at the target's frequency) become
    ``'zx'`` entries; a CZ's ef drive becomes ``'zz'``.  The control's
    own-frame echo pulses are excluded by frequency.
    """
    out = set()
    for name in qchip.gates:
        m = _GATE_RE.fullmatch(name)
        if not m:
            continue
        ctrl_q, tgt_q, gname = m.group(1), m.group(2), m.group(3)
        ctrl, tgt = int(ctrl_q[1:]), int(tgt_q[1:])
        kind = 'zx' if gname == 'CNOT' else 'zz'
        own_freq = qchip.get_qubit_freq(f'{ctrl_q}.freq')
        gate = qchip.get_gate(name)
        for p in gate.contents:
            if not (isinstance(p, GatePulse)
                    and p.dest == f'{ctrl_q}.qdrv'):
                continue
            if np.isclose(p.freq, own_freq, rtol=1e-12):
                continue                      # own-frame echo pulse: 1q
            if ctrl >= len(mp.tables) or tgt >= len(mp.tables):
                continue
            freq_tabs = mp.tables[ctrl].freqs
            if drive_elem >= len(freq_tabs):
                continue
            freqs = np.asarray(freq_tabs[drive_elem]['freq'], np.float64)
            for i in np.nonzero(np.isclose(freqs, p.freq, rtol=1e-12,
                                           atol=1.0))[0]:
                out.add((ctrl, int(i), tgt, kind))
    return tuple(sorted(out))
