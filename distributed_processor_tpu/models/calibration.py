"""Readout calibration: centroid fitting and fidelity estimation.

The reference delegates calibration to external tooling (the
``qubitconfig`` ecosystem); this closes the loop in-framework: run
prepared-|0> and prepared-|1> calibration batches through the IQ
readout path, fit per-channel centroids, and report assignment
fidelities — producing the ``centers0/centers1`` consumed by
:func:`..ops.demod.discriminate`.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..ops.demod import discriminate


def fit_centroids(iq0, iq1):
    """Mean IQ per channel from labelled calibration shots.

    ``iq0``/``iq1``: ``[shots, channels, 2]`` I/Q points measured with
    the qubit prepared in |0> / |1>.  Returns ``(c0, c1)`` as
    ``[channels, 2]`` float32 arrays.
    """
    c0 = jnp.mean(jnp.asarray(iq0, jnp.float32), axis=0)
    c1 = jnp.mean(jnp.asarray(iq1, jnp.float32), axis=0)
    return c0, c1


def assignment_matrix(iq0, iq1, c0=None, c1=None):
    """Per-channel assignment probabilities ``[channels, 2, 2]``:
    entry ``[c, prepared, measured]``.  Fits centroids from the data
    unless provided."""
    if c0 is None or c1 is None:
        c0, c1 = fit_centroids(iq0, iq1)
    m0 = np.asarray(discriminate(iq0, c0, c1))     # [S, C]
    m1 = np.asarray(discriminate(iq1, c0, c1))
    n_chan = m0.shape[1]
    out = np.zeros((n_chan, 2, 2))
    out[:, 0, 1] = m0.mean(axis=0)
    out[:, 0, 0] = 1 - out[:, 0, 1]
    out[:, 1, 1] = m1.mean(axis=0)
    out[:, 1, 0] = 1 - out[:, 1, 1]
    return out


def readout_fidelity(iq0, iq1, c0=None, c1=None) -> np.ndarray:
    """Per-channel assignment fidelity 1 - (P(1|0) + P(0|1))/2."""
    a = assignment_matrix(iq0, iq1, c0, c1)
    return 1 - (a[:, 0, 1] + a[:, 1, 0]) / 2


def calibrate_readout(model, key, shots: int = 1024):
    """Run |0>/|1> calibration batches against an
    :class:`~.readout.IQReadoutModel`; returns (c0, c1, fidelity)."""
    import jax
    k0, k1 = jax.random.split(key)
    n = len(model.c0)
    iq0 = model.sample_iq(k0, jnp.zeros((shots, n), jnp.int32))
    iq1 = model.sample_iq(k1, jnp.ones((shots, n), jnp.int32))
    c0, c1 = fit_centroids(iq0, iq1)
    return c0, c1, readout_fidelity(iq0, iq1, c0, c1)
