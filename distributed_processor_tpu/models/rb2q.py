"""Two-qubit Clifford randomized benchmarking sequences.

The single-qubit module (models/rb.py) realises the 24-element C1 group
as virtual-Z Euler sequences; this module provides genuine *two-qubit*
RB over the full 11,520-element two-qubit Clifford group C2, with the
entangling content supplied by the calibrated CZ gate (exact under the
statevec device model — sim/device.py).

Rather than transcribing a literature coset decomposition, the group is
generated numerically: a breadth-first closure over the generator set
{24 C1 on qubit a, 24 C1 on qubit b, CZ} with projective deduplication.
Each element is stored with its generator word, so sequence emission,
inverse lookup (the recovery Clifford), and exact survival predictions
all come from the same table.  BFS from these generators provably
reaches all of C2 (C1 x C1 and CZ generate it); the 11,520 count is
asserted at build time.

Survival under a pure two-qubit depolarizing channel of probability p
per CZ (DeviceModel.depol2_per_pulse) is EXACTLY
``P = 1/4 + 3/4 * (1 - 16 p / 15)^n_cz`` for a sequence with ``n_cz``
CZ pulses — global depolarization commutes with every Clifford — which
is what tests/test_rb2q.py pins the trajectory engine against.
"""

from __future__ import annotations

import functools

import numpy as np

from .rb import clifford_table, clifford_instructions

_CZ = np.diag([1.0, 1.0, 1.0, -1.0]).astype(complex)
N_CLIFFORD2 = 11520


def _canon_keys(us: np.ndarray) -> list[bytes]:
    """Projective canonical byte keys for a batch of unitaries [N,4,4]:
    divide out the phase of the first above-threshold entry, round."""
    flat = us.reshape(len(us), 16)
    first = np.argmax(np.abs(flat) > 0.25, axis=1)   # |entries| of a 4x4
    pivot = flat[np.arange(len(us)), first]          # unitary: max >= 1/2
    canon = flat / (pivot / np.abs(pivot))[:, None]
    canon = np.round(canon, 8) + (0.0 + 0.0j)        # kill -0.0 (re AND im)
    return [c.tobytes() for c in canon]


@functools.lru_cache()
def clifford2_table():
    """The two-qubit Clifford group as ``(words, unitaries, index)``:
    ``words[i]`` is a tuple of generator ids (0..23 = C1 on qubit a,
    24..47 = C1 on qubit b, 48 = CZ), ``unitaries[i]`` the 4x4 matrix
    (qubit a = MSB), ``index`` the canonical-key -> i lookup."""
    _, c1 = clifford_table()
    gens = np.concatenate([
        np.stack([np.kron(u, np.eye(2)) for u in c1]),
        np.stack([np.kron(np.eye(2), u) for u in c1]),
        _CZ[None]])                                   # [49, 4, 4]
    words = [()]
    unitaries = [np.eye(4, dtype=complex)]
    index = {_canon_keys(np.eye(4)[None])[0]: 0}
    frontier = [0]
    while frontier:
        fu = np.stack([unitaries[i] for i in frontier])
        prod = np.einsum('gxy,fyz->fgxz', gens, fu)   # gen AFTER element
        keys = _canon_keys(prod.reshape(-1, 4, 4))
        nxt = []
        for fi, i in enumerate(frontier):
            for g in range(len(gens)):
                k = keys[fi * len(gens) + g]
                if k not in index:
                    index[k] = len(words)
                    words.append(words[i] + (g,))
                    unitaries.append(prod[fi, g])
                    nxt.append(index[k])
        frontier = nxt
    assert len(words) == N_CLIFFORD2, len(words)
    return words, np.stack(unitaries), index


def inverse2_index(net: np.ndarray) -> int:
    """Table index of the Clifford inverting ``net`` (projectively)."""
    return element_index(np.asarray(net).conj().T)


def rb2q_sequence(rng, depth: int) -> list[int]:
    """Uniform random C2 indices of length ``depth`` plus the recovery."""
    words, unitaries, _ = clifford2_table()
    seq = [int(rng.integers(N_CLIFFORD2)) for _ in range(depth)]
    net = np.eye(4, dtype=complex)
    for i in seq:
        net = unitaries[i] @ net
    seq.append(inverse2_index(net))
    return seq


def clifford2_instructions(qa: str, qb: str, index: int) -> list[dict]:
    """One C2 element as compiler-input instructions.  Every CZ is
    fenced with barriers so the *schedule* (the physical ground truth
    the statevec engine replays in time order) serializes the
    entangler against both qubits' single-qubit pulses."""
    words, _, _ = clifford2_table()
    out = []
    for g in words[index]:
        if g < 24:
            out += clifford_instructions(qa, g)
        elif g < 48:
            out += clifford_instructions(qb, g - 24)
        else:
            out += [{'name': 'barrier', 'qubit': [qa, qb]},
                    {'name': 'CZ', 'qubit': [qa, qb]},
                    {'name': 'barrier', 'qubit': [qa, qb]}]
    return out


def count_cz(indices) -> int:
    """Total CZ pulses a sequence of C2 indices compiles to — the
    exponent of the exact depol2 survival prediction."""
    words, _, _ = clifford2_table()
    return sum(1 for i in indices for g in words[i] if g == 48)


def rb2q_program(qa: str, qb: str, depth: int, rng=None, seed: int = 0,
                 delay_before: float = 500e-9) -> tuple[list[dict], dict]:
    """A full two-qubit RB program: ``depth`` random C2 Cliffords plus
    the recovery, ending in a read on both qubits.  Returns
    ``(program, info)`` with ``info['n_cz']`` (for exact survival
    predictions) and ``info['indices']``."""
    rng = rng or np.random.default_rng(seed)
    seq = rb2q_sequence(rng, depth)
    return _emit_program(qa, qb, seq, delay_before)


def depol2_survival(p2: float, n_cz: int) -> float:
    """Exact |00> survival under depol2-only errors (see module doc)."""
    return 0.25 + 0.75 * (1.0 - 16.0 * p2 / 15.0) ** n_cz


def element_index(u: np.ndarray) -> int:
    """Table index of the C2 element projectively equal to ``u``."""
    _, _, index = clifford2_table()
    key = _canon_keys(np.asarray(u, complex)[None])[0]
    try:
        return index[key]
    except KeyError:
        raise ValueError('not a two-qubit Clifford')


def _emit_program(qa: str, qb: str, seq, delay_before: float
                  ) -> tuple[list[dict], dict]:
    """Shared emission tail: instructions for ``seq``, barrier, reads,
    and the info dict both RB program builders return."""
    program = [{'name': 'delay', 't': delay_before}]
    for i in seq:
        program += clifford2_instructions(qa, qb, i)
    program.append({'name': 'barrier', 'qubit': [qa, qb]})
    program += [{'name': 'read', 'qubit': [qa]},
                {'name': 'read', 'qubit': [qb]}]
    return program, {'indices': seq, 'n_cz': count_cz(seq)}


def rb2q_interleaved_program(qa: str, qb: str, depth: int, rng=None,
                             seed: int = 0,
                             delay_before: float = 500e-9
                             ) -> tuple[list[dict], dict]:
    """Interleaved two-qubit RB with the calibrated CZ as the target
    gate: each random C2 Clifford is followed by a bare CZ, and the
    recovery inverts the FULL product (C2 is a group, so the net is
    still an element and the recovery is exact).  Comparing the decay
    against the reference curve (:func:`rb2q_program` with the same
    depths) isolates the interleaved gate's error:
    ``alpha_CZ = alpha_int / alpha_ref``, ``EPC_CZ = 3/4 (1 - alpha_CZ)``
    — the standard interleaved-RB estimator, exact here for
    depolarizing errors.  Returns ``(program, info)`` with
    ``info['n_cz']`` counting every CZ pulse (random Cliffords' own
    plus the ``depth`` interleaves plus the recovery's)."""
    rng = rng or np.random.default_rng(seed)
    words, unitaries, _ = clifford2_table()
    cz_idx = element_index(_CZ)
    seq = []
    net = np.eye(4, dtype=complex)
    for _ in range(depth):
        i = int(rng.integers(N_CLIFFORD2))
        seq += [i, cz_idx]
        net = _CZ @ unitaries[i] @ net
    seq.append(inverse2_index(net))
    return _emit_program(qa, qb, seq, delay_before)
