"""Statistical readout models: measurement-bit sources for the simulator.

The reference never models readout — real hardware (or the cocotb
testbench) supplies the ``meas`` bits (reference: hdl/fproc_meas.sv
inputs; cocotb/proc/test_proc.py:441-446).  For closed-loop simulation
the framework needs a bit source; two are provided:

* :func:`sample_meas_bits` — Bernoulli bits per (shot, core, index) with
  optional assignment error (fast path for large sweeps; measurement
  outcomes independent per index);
* :class:`IQReadoutModel` — full-physics path: state-dependent IQ
  clouds, demodulated and discriminated through :mod:`..ops.demod`, so
  readout infidelity emerges from the noise model rather than being
  injected.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..ops.demod import discriminate


def sample_meas_bits(key, p1, n_shots: int, n_meas: int):
    """Bernoulli measurement bits ``[n_shots, n_cores, n_meas]``.

    ``p1``: per-core probability of reading |1> (array ``[n_cores]``).
    """
    p1 = jnp.asarray(p1, jnp.float32)
    n_cores = p1.shape[0]
    u = jax.random.uniform(key, (n_shots, n_cores, n_meas))
    return (u < p1[None, :, None]).astype(jnp.int32)


def apply_assignment_error(key, bits, p01: float, p10: float):
    """Flip bits with asymmetric assignment-error probabilities."""
    u = jax.random.uniform(key, bits.shape)
    p_flip = jnp.where(bits == 0, p01, p10)
    return jnp.where(u < p_flip, 1 - bits, bits)


class IQReadoutModel:
    """Gaussian IQ-cloud readout: state -> IQ point -> discriminated bit.

    ``centers0``/``centers1``: complex ``[n_cores]`` cloud centres;
    ``sigma``: cloud standard deviation (same units).
    """

    def __init__(self, centers0, centers1, sigma: float):
        self.c0 = np.asarray(centers0, complex)
        self.c1 = np.asarray(centers1, complex)
        self.sigma = float(sigma)

    def sample_iq(self, key, states):
        """states ``[S, C]`` (0/1) -> IQ points ``[S, C, 2]`` float32."""
        states = jnp.asarray(states)
        c0 = jnp.asarray(
            np.stack([self.c0.real, self.c0.imag], -1), jnp.float32)
        c1 = jnp.asarray(
            np.stack([self.c1.real, self.c1.imag], -1), jnp.float32)
        mean = jnp.where(states[..., None] == 1, c1[None], c0[None])
        noise = self.sigma * jax.random.normal(key, mean.shape)
        return mean + noise

    def measure(self, key, states):
        """states ``[S, C]`` -> (bits ``[S, C]``, iq ``[S, C, 2]``)."""
        iq = self.sample_iq(key, states)
        bits = discriminate(iq, self.c0, self.c1)
        return bits, iq
