"""Channel-map generation for N-qubit systems.

The reference ships a hand-written 2-core ``channel_config.json``
(reference: python/test/channel_config.json); scaling to N qubits there
means editing JSON.  Here the standard per-qubit channel triple
(qdrv/rdrv/rdlo with the ZCU216 sample geometry) is generated
programmatically.
"""

from __future__ import annotations

from ..hwconfig import load_channel_configs

# (elem_ind, samples_per_clk, interp_ratio) per channel role — the ZCU216
# geometry from the reference test fixture (channel_config.json:8-35)
CHANNEL_ROLES = {
    'qdrv': (0, 16, 1),
    'rdrv': (1, 16, 16),
    'rdlo': (2, 4, 4),
}


def make_channel_config(n_qubits: int = 8,
                        fpga_clk_freq: float = 500e6) -> dict:
    """Build the raw channel-config dict for ``n_qubits`` qubit cores."""
    cfg = {'fpga_clk_freq': fpga_clk_freq}
    for q in range(n_qubits):
        for role, (elem, spc, interp) in CHANNEL_ROLES.items():
            cfg[f'Q{q}.{role}'] = {
                'core_ind': q,
                'elem_ind': elem,
                'elem_params': {'samples_per_clk': spc,
                                'interp_ratio': interp},
                'env_mem_name': f'{role}env{{core_ind}}',
                'freq_mem_name': f'{role}freq{{core_ind}}',
                'acc_mem_name': 'accbuf{core_ind}',
            }
    return cfg


def make_channel_configs(n_qubits: int = 8, fpga_clk_freq: float = 500e6):
    """Loaded :class:`~..hwconfig.ChannelConfig` objects for N qubits."""
    return load_channel_configs(make_channel_config(n_qubits, fpga_clk_freq))
