"""QEC workloads on the LUT measurement fabric: repetition rounds and
surface-code-cycle-shaped programs.

Grows ``models/repetition.py`` (one majority-LUT syndrome round) into
the continuous syndrome-extraction model zoo the streaming traffic
class serves (docs/SERVING.md "Streaming sessions", docs/PERF.md
"Streaming QEC"):

* :func:`qec_round_machine_program` — ONE syndrome round (the
  repetition round re-exported): the unit program
  :func:`~..sim.interpreter.simulate_rounds` scans R times in one
  dispatch with per-round injected bits.
* :func:`qec_multiround_machine_program` — the R-round EMITTER: R
  measure -> fproc-LUT-correct rounds unrolled into one instruction
  stream, eligible for the content-keyed fast engines
  (``engine='block'``/``'pallas'`` via the PR 17 timestamped fabric)
  and the ``('dp', 'cores')`` mesh.
* :func:`surface_cycle_machine_program` — the distance-d
  surface-code-cycle-shaped variant: d data cores + d-1 ancilla
  cores, ancillas measure the syndrome, data cores read their own
  correction from a chain-matching LUT (:func:`chain_lut`).

Every program follows the proven measure-then-read shape of the
single-round repetition program, so the PR 17 dispatch-granularity
invariance (and with it fast-engine/mesh eligibility) carries over
unchanged.
"""

from __future__ import annotations

import numpy as np

from .. import isa
from ..decoder import machine_program_from_cmds
from ..ops.decode import DecodeSpec, chain_matching_np
from ..sim.interpreter import InterpreterConfig
from .repetition import (majority_lut, _lut_fabric_kwargs,  # noqa: F401
                         repetition_config,
                         repetition_round_machine_program)

# the single-round unit program the rounds scan executes R times
qec_round_machine_program = repetition_round_machine_program


def qec_config(n_data: int, rounds: int = 1, **kw) -> InterpreterConfig:
    """Interpreter config for the repetition-code QEC programs:
    majority-LUT fabric over the ``n_data`` cores, budgets sized for
    ``rounds`` unrolled rounds (``rounds=1`` covers the scanned
    single-round program — pass the scan's round count via
    ``simulate_rounds`` / ``cfg.rounds``, not here)."""
    defaults = dict(max_steps=16 * rounds + 48, max_pulses=3 * rounds + 2,
                    max_meas=max(rounds, 2), max_resets=1,
                    **_lut_fabric_kwargs(n_data))
    defaults.update(kw)
    return InterpreterConfig(**defaults)


def qec_multiround_machine_program(n_data: int = 3, rounds: int = 4,
                                   meas_time: int = 10,
                                   correct_time: int = 400,
                                   round_period: int = 1000):
    """R rounds of measure -> majority-LUT correction unrolled into
    one machine program, one core per data qubit.  Round r occupies
    absolute clocks ``[r*round_period, (r+1)*round_period)``: measure
    at ``+meas_time``, read the own-core correction bit from the LUT
    (``func_id=1``), conditionally flip (two X90 = X) at
    ``+correct_time``.  Branch targets are intra-round skips, so the
    CFG is a chain of R identical diamonds — block-engine eligible,
    and the timestamped fabric keeps every LUT read
    dispatch-granularity-invariant (round r's read serves round r's
    bits: earlier rounds' production clocks are below the read time,
    later rounds' above it).  Run with ``qec_config(n_data, rounds)``.
    """
    if rounds < 1:
        raise ValueError(f'rounds must be >= 1; got {rounds}')
    cores = []
    for _ in range(n_data):
        cmds = []
        for r in range(rounds):
            t0 = round_period * r
            base = len(cmds)
            cmds += [
                isa.pulse_cmd(freq_word=1, cfg_word=2,
                              env_word=(2 << 12) | 0,
                              cmd_time=t0 + meas_time),
                isa.alu_cmd('jump_fproc', 'i', 1, 'eq',
                            jump_cmd_ptr=base + 3, func_id=1),
                isa.jump_i(base + 5),
                isa.pulse_cmd(freq_word=2, cfg_word=0,
                              env_word=(2 << 12) | 0,
                              cmd_time=t0 + correct_time),
                isa.pulse_cmd(cmd_time=t0 + correct_time + 20),
            ]
        cmds.append(isa.done_cmd())
        cores.append(cmds)
    return machine_program_from_cmds(cores)


def chain_lut(distance: int) -> tuple:
    """Chain-matching LUT for the distance-``distance`` repetition
    chain: entry ``addr`` (ancilla syndrome bits, LSB = ancilla 0 =
    the check between data qubits 0 and 1) has bit i set iff data
    qubit i takes an X correction under exact min-weight matching
    (:func:`~..ops.decode.chain_matching_np` — the brute-force oracle
    builds the table, the closed-form decoder is what gets fuzzed
    against it)."""
    if distance < 2:
        raise ValueError(f'distance must be >= 2; got {distance}')
    table = []
    for addr in range(1 << (distance - 1)):
        synd = [(addr >> i) & 1 for i in range(distance - 1)]
        corr = chain_matching_np(np.array(synd, np.int32))
        table.append(int(sum(1 << i for i, b in enumerate(corr) if b)))
    return tuple(table)


def surface_cycle_machine_program(distance: int = 3,
                                  meas_time: int = 10,
                                  correct_time: int = 400):
    """Distance-d surface-code-cycle-shaped round: cores ``0..d-1``
    are data, cores ``d..2d-2`` are ancillas.  Every core measures at
    ``meas_time`` (ancillas produce the syndrome the LUT address is
    formed from; the data readout doubles as the logical verification
    measurement), then each DATA core reads its own chain-matching
    correction bit from the fabric (``func_id=1``) and conditionally
    flips.  Ancilla LUT outputs are zero by construction
    (:func:`chain_lut` sets bits only at data positions), so ancilla
    cores halt after measuring.  Run with
    ``surface_cycle_config(distance)``; the matching decode spec is
    :func:`surface_decode_spec`."""
    if distance < 2:
        raise ValueError(f'distance must be >= 2; got {distance}')
    data = [
        isa.pulse_cmd(freq_word=1, cfg_word=2, env_word=(2 << 12) | 0,
                      cmd_time=meas_time),
        isa.alu_cmd('jump_fproc', 'i', 1, 'eq', jump_cmd_ptr=3,
                    func_id=1),
        isa.jump_i(5),
        isa.pulse_cmd(freq_word=2, cfg_word=0, env_word=(2 << 12) | 0,
                      cmd_time=correct_time),
        isa.pulse_cmd(cmd_time=correct_time + 20),
        isa.done_cmd(),
    ]
    ancilla = [
        isa.pulse_cmd(freq_word=1, cfg_word=2, env_word=(2 << 12) | 0,
                      cmd_time=meas_time),
        isa.done_cmd(),
    ]
    cores = [list(data) for _ in range(distance)] \
        + [list(ancilla) for _ in range(distance - 1)]
    return machine_program_from_cmds(cores)


def surface_cycle_config(distance: int, **kw) -> InterpreterConfig:
    """Config for :func:`surface_cycle_machine_program`: only the
    ancilla cores feed the LUT address; the table is the exact
    min-weight chain matching."""
    mask = (False,) * distance + (True,) * (distance - 1)
    defaults = dict(max_steps=64, max_pulses=8, max_meas=2,
                    max_resets=1, fabric='lut', lut_mask=mask,
                    lut_table=chain_lut(distance))
    defaults.update(kw)
    return InterpreterConfig(**defaults)


def repetition_decode_spec(n_data: int, slot: int = 0) -> DecodeSpec:
    """Decode spec for the repetition-round programs: every data
    core's per-round readout, majority-decoded."""
    return DecodeSpec('majority', tuple(range(n_data)), slot)


def surface_decode_spec(distance: int, slot: int = 0) -> DecodeSpec:
    """Decode spec for :func:`surface_cycle_machine_program`: the
    ancilla cores' syndrome stream, chain-matching-decoded into a
    data-qubit correction."""
    return DecodeSpec('matching',
                      tuple(range(distance, 2 * distance - 1)), slot)
