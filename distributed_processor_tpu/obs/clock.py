"""Cross-process monotonic-clock alignment for fleet observability.

Every process in a fleet keeps time with its own ``time.monotonic()``
— the clocks share no epoch, so a replica-side span timestamp is
meaningless in the router's timeline until it is shifted by that
replica's clock offset.  The router estimates the offset from the
request/response pairs it already has: the gossip heartbeat
(docs/FLEET.md) is a natural NTP-style probe, sent at local ``t_send``,
answered with the replica's ``t_remote``, received at local ``t_recv``.

The classic bound applies: assuming the remote timestamp was taken
somewhere inside the round trip, the offset

    offset = t_remote - (t_send + t_recv) / 2

is wrong by at most half the round-trip time — so the estimator keeps a
sliding window of samples and reports the one with the SMALLEST RTT,
whose error bound ``rtt/2`` is the tightest available
(tests/test_fleet_obs.py pins the bound on synthetic samples).

Offsets are defined as ``remote - local``: ``to_local`` maps a
replica-clock timestamp into the router's clock by subtracting the
offset.  Gossip runs every ~25 ms, so the window refreshes fast enough
that monotonic-clock drift (ppm-scale) never dominates the RTT bound.
"""

from __future__ import annotations

import threading
from collections import deque


class ClockOffsetEstimator:
    """Min-RTT offset estimate between one remote clock and ours."""

    def __init__(self, window: int = 64):
        self._samples = deque(maxlen=int(window))   # (rtt, offset)
        self._lock = threading.Lock()

    def add_sample(self, t_send: float, t_remote: float,
                   t_recv: float) -> None:
        """One probe: local send/receive timestamps bracketing the
        remote timestamp they carried back."""
        rtt = max(0.0, t_recv - t_send)
        offset = t_remote - 0.5 * (t_send + t_recv)
        with self._lock:
            self._samples.append((rtt, offset))

    @property
    def n(self) -> int:
        return len(self._samples)

    def _best(self):
        with self._lock:
            if not self._samples:
                return None
            return min(self._samples)

    @property
    def offset(self) -> float:
        """Estimated ``remote - local`` offset in seconds (0.0 before
        the first sample)."""
        best = self._best()
        return 0.0 if best is None else best[1]

    @property
    def uncertainty_s(self) -> float:
        """Worst-case estimate error: half the RTT of the sample the
        offset came from (``inf`` before the first sample)."""
        best = self._best()
        return float('inf') if best is None else 0.5 * best[0]

    def to_local(self, t_remote: float) -> float:
        """Map a remote-clock timestamp onto the local clock."""
        return t_remote - self.offset

    def to_remote(self, t_local: float) -> float:
        """Map a local-clock timestamp onto the remote clock."""
        return t_local + self.offset
