"""Per-request tracing: typed lifecycle spans, Chrome Trace export.

A sampled request carries one :class:`TraceContext` on its
:class:`~..serve.request.RequestHandle` from ``submit``/``submit_source``
to resolution; the serving layers append spans as the request moves
through the pipeline.  The span taxonomy (docs/OBSERVABILITY.md):

duration spans (``t0``..``t1``)
    ``compile``         submit_source front door (args: hit/disk/miss/wait)
    ``queued``          submit (or requeue) → claimed by a dispatcher
    ``coalesce.ripen``  oldest batch member's wait → batch pop
    ``dispatch``        claim → simulate entry (args: device, bucket,
                        cold/warm/aot classification, engine, occupancy)
    ``execute``         the whole ``_run_batch`` window (chaos included)
    ``demux``           per-request result split + fulfil

instant events (hops; ``t1`` is None)
    ``submit`` ``submit_source`` ``park`` ``unpark`` ``steal``
    ``migrate`` ``retry`` ``retry_exhausted`` ``requeue`` ``chaos``
    ``shed`` ``batch_error`` ``done``

A retried request simply accumulates another ``queued``/``dispatch``/
``execute`` run joined by ``retry``/``requeue`` instants — the
multi-hop chain the chaos tests assert on.

Export is Chrome Trace Event JSON (``{"traceEvents": [...]}``), one
``tid`` row per request, loadable in Perfetto / chrome://tracing.
Times are ``time.monotonic()`` seconds internally, rebased to
microseconds at export.

Cost discipline: with sampling off the per-request footprint is the
``None`` context slot already present on every handle — ``maybe_start``
returns ``None`` without allocating, and every emission site guards on
``handle._trace is not None``.
"""

from __future__ import annotations

import itertools
import json
import os
import time
from collections import deque

# canonical stage order for waterfall-style summaries (tools/traceview).
# The fleet stages interleave with the service stages when a request
# crosses the wire (docs/OBSERVABILITY.md "Fleet observability"):
# `route` and `wire.send` are router-side, the replica stages (queued..
# demux) land inside the `wire.await` window after clock alignment.
STAGE_ORDER = ('submit', 'submit_source', 'route', 'wire.send',
               'compile', 'queued', 'coalesce.ripen', 'dispatch',
               'execute', 'demux', 'wire.await')


def _period_of(sample: float) -> int:
    if sample <= 0.0:
        return 0
    if sample >= 1.0:
        return 1
    return max(1, int(round(1.0 / sample)))


class TraceContext:
    """Span accumulator for one sampled request.

    Appends come from the submitter thread, dispatcher threads, and the
    supervisor; ``list.append`` is atomic under the GIL and spans are
    immutable once appended, so no lock is needed.  ``last_claim``
    carries the batch-claim timestamp from the dispatch loop to the
    ``dispatch`` span recorded inside the batch runner.
    """

    __slots__ = ('trace_id', 'spans', 'last_claim')

    def __init__(self, trace_id: int):
        self.trace_id = trace_id
        self.spans = []
        self.last_claim = None

    def span(self, name: str, t0: float, t1: float, **args) -> None:
        """Record a completed duration span."""
        self.spans.append({'name': name, 't0': t0, 't1': t1,
                           'args': args})

    def instant(self, name: str, t: float = None, **args) -> None:
        """Record an instant (zero-duration hop) event."""
        self.spans.append({'name': name,
                           't0': time.monotonic() if t is None else t,
                           't1': None, 'args': args})


class Tracer:
    """Sampling front door + bounded retention of sampled contexts.

    ``sample`` is the fraction of submissions traced: ``0`` disables
    tracing entirely (``maybe_start`` returns ``None`` with no
    allocation), ``>= 1`` traces everything, and intermediate values
    sample deterministically every ``round(1/sample)``-th submission —
    deterministic so tests and repeated bench runs see the same set.
    """

    def __init__(self, sample: float = 0.0, keep: int = 1024):
        self.sample = float(sample)
        self._period = _period_of(self.sample)
        self._seq = itertools.count()
        self._kept = deque(maxlen=keep)

    @property
    def enabled(self) -> bool:
        return self._period > 0

    def set_sample(self, sample: float) -> None:
        """Retune the sampling rate in place, keeping the id sequence
        and retained contexts (bench sweeps use this to compare trace
        cost without rebuilding retention)."""
        self.sample = float(sample)
        self._period = _period_of(self.sample)

    def sampled(self, trace_id: int) -> bool:
        """The sampling decision as a pure function of the trace id —
        deterministic, so two processes holding the same rate agree on
        the same ids (the fleet router and its replicas)."""
        return self._period > 0 and trace_id % self._period == 0

    def maybe_start(self) -> TraceContext | None:
        """Sampling decision for one submission: a fresh context when
        sampled (retained for later export), else ``None``."""
        if not self._period:
            return None
        n = next(self._seq)
        if not self.sampled(n):
            return None
        return self.start(n)

    def start(self, trace_id: int) -> TraceContext:
        """Open a context for an externally-made sampling decision —
        the fleet wire carries the ROUTER's decision to the replica,
        which must trace exactly those requests regardless of its own
        sampling rate.  Retained like locally sampled contexts."""
        ctx = TraceContext(int(trace_id))
        self._kept.append(ctx)
        return ctx

    def contexts(self) -> list:
        """Snapshot of retained contexts, oldest first."""
        return list(self._kept)


def chrome_trace_events(contexts, pid: str = 'serve') -> list:
    """Flatten trace contexts into Chrome Trace Event dicts.

    Duration spans become complete events (``ph: "X"``), instants
    become thread-scoped instant events (``ph: "i"``); each request is
    its own ``tid`` row so Perfetto renders a per-request waterfall.
    Timestamps are rebased to the earliest span and expressed in µs.
    """
    t_base = None
    for ctx in contexts:
        for s in ctx.spans:
            if t_base is None or s['t0'] < t_base:
                t_base = s['t0']
    if t_base is None:
        return []
    events = []
    for ctx in contexts:
        tid = f'req-{ctx.trace_id}'
        for s in ctx.spans:
            ev = {'name': s['name'], 'cat': 'serve', 'pid': pid,
                  'tid': tid,
                  'ts': round((s['t0'] - t_base) * 1e6, 3)}
            if s['t1'] is not None:
                ev['ph'] = 'X'
                ev['dur'] = round(max(0.0, s['t1'] - s['t0']) * 1e6, 3)
            else:
                ev['ph'] = 'i'
                ev['s'] = 't'
            if s['args']:
                ev['args'] = s['args']
            events.append(ev)
    return events


def write_chrome_trace(path: str, contexts, pid: str = 'serve') -> int:
    """Write a Perfetto-loadable trace file; returns the event count.

    Atomic (tmp + rename) so a reader never sees a torn file.
    """
    events = chrome_trace_events(contexts, pid=pid)
    doc = {'traceEvents': events, 'displayTimeUnit': 'ms'}
    tmp = f'{path}.tmp.{os.getpid()}'
    with open(tmp, 'w') as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return len(events)
