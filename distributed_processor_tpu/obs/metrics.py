"""Typed metrics registry: counters, gauges, fixed-bucket histograms.

The flat named-counter dict in ``utils/profiling.py`` grew organically
from the interpreter's retrace probes into the serving tier's whole
metrics surface.  This module is the typed replacement it delegates to:
one process-wide :class:`MetricsRegistry` holding

``counter``    monotone int (the existing ``counter_inc`` namespace —
               every ``serve.*`` / ``*_trace`` / ``aot_*`` name lands
               here unchanged)
``gauge``      last-write-wins float (queue depths, cache sizes)
``histogram``  fixed-bucket counts + sum/count for exposition, plus a
               bounded window of raw samples so existing exact-
               percentile ``stats()`` fields stay byte-compatible

with a Prometheus-style text exposition (:meth:`prometheus_text`) and a
snapshot/restore API that the test suite uses to isolate counter
asserts from execution order (tests/conftest.py).

Deliberately stdlib-only and import-cheap: the serve dispatcher
increments counters on its hot path and the tracing layer must be
importable without jax.
"""

from __future__ import annotations

import bisect
import math
import re
import threading
from collections import deque

# latency-flavoured default bucket ladder (milliseconds); the +inf
# bucket is implicit — Prometheus convention, cumulative on exposition
DEFAULT_BUCKETS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                   250.0, 500.0, 1000.0, 2500.0, 5000.0)

_NAME_RE = re.compile(r'[^a-zA-Z0-9_:]')


def _prom_name(name: str) -> str:
    """Sanitize a dotted counter name into a Prometheus metric name."""
    out = _NAME_RE.sub('_', name)
    if out and out[0].isdigit():
        out = '_' + out
    return out


class Histogram:
    """Fixed-bucket histogram with a bounded exact-sample window.

    The buckets feed the Prometheus exposition; the window keeps the
    raw samples (newest ``window`` of them) so callers that previously
    ran ``np.percentile`` over a deque — the service's latency
    percentiles, the compile cache's compile-time percentiles — keep
    producing the exact same numbers after migrating onto the registry.
    """

    def __init__(self, name: str, buckets=None, window: int = 4096):
        self.name = name
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._counts = [0] * (len(self.buckets) + 1)   # +1 = +inf
        self._sum = 0.0
        self._n = 0
        self._window = deque(maxlen=window)
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._n += 1
        # deque.append is atomic; keeping it outside the lock keeps the
        # hot path to one short critical section
        self._window.append(value)

    @property
    def count(self) -> int:
        return self._n

    @property
    def sum(self) -> float:
        return self._sum

    def values(self) -> list:
        """Snapshot of the retained raw-sample window (newest last)."""
        return list(self._window)

    def percentile(self, p: float):
        """Exact percentile over the retained window (linear
        interpolation, numpy-compatible); None when empty."""
        vals = sorted(self._window)
        if not vals:
            return None
        if len(vals) == 1:
            return vals[0]
        rank = (p / 100.0) * (len(vals) - 1)
        lo = int(math.floor(rank))
        hi = min(lo + 1, len(vals) - 1)
        frac = rank - lo
        return vals[lo] * (1.0 - frac) + vals[hi] * frac

    def state(self) -> dict:
        with self._lock:
            return {'buckets': self.buckets,
                    'counts': list(self._counts),
                    'sum': self._sum, 'n': self._n,
                    'window': list(self._window),
                    'maxlen': self._window.maxlen}

    @classmethod
    def from_state(cls, name: str, st: dict) -> 'Histogram':
        h = cls(name, buckets=st['buckets'], window=st['maxlen'])
        h._counts = list(st['counts'])
        h._sum = st['sum']
        h._n = st['n']
        h._window.extend(st['window'])
        return h


class MetricsRegistry:
    """One process-wide home for every counter, gauge, and histogram."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict = {}
        self._gauges: dict = {}
        self._histograms: dict = {}

    # -- counters (the utils.profiling namespace) -----------------------

    def inc(self, name: str, amount: int = 1) -> int:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + amount
            return self._counters[name]

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def counters(self) -> dict:
        with self._lock:
            return dict(self._counters)

    # -- gauges ---------------------------------------------------------

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def gauge(self, name: str, default=0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    def gauges(self) -> dict:
        with self._lock:
            return dict(self._gauges)

    # -- histograms -----------------------------------------------------

    def histogram(self, name: str, buckets=None,
                  window: int = 4096) -> Histogram:
        """Get-or-create the named histogram (first caller fixes the
        bucket ladder and window size)."""
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = Histogram(name, buckets=buckets, window=window)
                self._histograms[name] = h
            return h

    def observe(self, name: str, value: float, buckets=None) -> None:
        self.histogram(name, buckets=buckets).observe(value)

    def histograms(self) -> dict:
        with self._lock:
            return dict(self._histograms)

    # -- snapshot / restore (test isolation) ----------------------------

    def snapshot(self) -> dict:
        """Deep-copyable state of every metric, for later ``restore``."""
        with self._lock:
            return {
                'counters': dict(self._counters),
                'gauges': dict(self._gauges),
                'histograms': {n: h.state()
                               for n, h in self._histograms.items()},
            }

    def restore(self, snap: dict) -> None:
        """Reset the registry to a prior ``snapshot``.  Histogram
        objects handed out before the snapshot keep working (they are
        rebuilt fresh in the registry, so post-restore observations via
        ``observe(name, ...)`` land in the restored instance)."""
        with self._lock:
            self._counters = dict(snap.get('counters', {}))
            self._gauges = dict(snap.get('gauges', {}))
            self._histograms = {
                n: Histogram.from_state(n, st)
                for n, st in snap.get('histograms', {}).items()}

    def reset(self) -> None:
        self.restore({'counters': {}, 'gauges': {}, 'histograms': {}})

    # -- exposition -----------------------------------------------------

    def prometheus_text(self) -> str:
        """Prometheus text-format exposition of every metric.

        Dotted names are sanitized (``serve.compile.cold`` →
        ``serve_compile_cold``); histogram buckets are cumulative with
        the conventional ``le`` label and trailing ``+Inf``.
        """
        lines = []
        for name, val in sorted(self.counters().items()):
            pn = _prom_name(name)
            lines.append(f'# TYPE {pn} counter')
            lines.append(f'{pn} {val}')
        for name, val in sorted(self.gauges().items()):
            pn = _prom_name(name)
            lines.append(f'# TYPE {pn} gauge')
            lines.append(f'{pn} {val}')
        for name, h in sorted(self.histograms().items()):
            pn = _prom_name(name)
            st = h.state()
            lines.append(f'# TYPE {pn} histogram')
            cum = 0
            for edge, c in zip(st['buckets'], st['counts']):
                cum += c
                lines.append(f'{pn}_bucket{{le="{edge}"}} {cum}')
            cum += st['counts'][-1]
            lines.append(f'{pn}_bucket{{le="+Inf"}} {cum}')
            lines.append(f'{pn}_sum {st["sum"]}')
            lines.append(f'{pn}_count {st["n"]}')
        return '\n'.join(lines) + ('\n' if lines else '')


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry ``utils.profiling`` delegates to."""
    return _DEFAULT
