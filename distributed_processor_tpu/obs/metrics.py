"""Typed metrics registry: counters, gauges, fixed-bucket histograms.

The flat named-counter dict in ``utils/profiling.py`` grew organically
from the interpreter's retrace probes into the serving tier's whole
metrics surface.  This module is the typed replacement it delegates to:
one process-wide :class:`MetricsRegistry` holding

``counter``    monotone int (the existing ``counter_inc`` namespace —
               every ``serve.*`` / ``*_trace`` / ``aot_*`` name lands
               here unchanged)
``gauge``      last-write-wins float (queue depths, cache sizes)
``histogram``  fixed-bucket counts + sum/count for exposition, plus a
               bounded window of raw samples so existing exact-
               percentile ``stats()`` fields stay byte-compatible

with a Prometheus-style text exposition (:meth:`prometheus_text`) and a
snapshot/restore API that the test suite uses to isolate counter
asserts from execution order (tests/conftest.py).

Deliberately stdlib-only and import-cheap: the serve dispatcher
increments counters on its hot path and the tracing layer must be
importable without jax.
"""

from __future__ import annotations

import bisect
import math
import re
import threading
from collections import deque

# latency-flavoured default bucket ladder (milliseconds); the +inf
# bucket is implicit — Prometheus convention, cumulative on exposition
DEFAULT_BUCKETS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
                   250.0, 500.0, 1000.0, 2500.0, 5000.0)

_NAME_RE = re.compile(r'[^a-zA-Z0-9_:]')


def _prom_name(name: str) -> str:
    """Sanitize a dotted counter name into a Prometheus metric name."""
    out = _NAME_RE.sub('_', name)
    if out and out[0].isdigit():
        out = '_' + out
    return out


def escape_label_value(value) -> str:
    """Escape a label value per the Prometheus text-format spec:
    backslash, double-quote, and newline must be backslash-escaped.
    Replica ids and bucket-spec labels flow through here on the fleet
    exposition path."""
    return (str(value).replace('\\', '\\\\').replace('"', '\\"')
            .replace('\n', '\\n'))


def _format_labels(labels: dict) -> str:
    """``{k="v",...}`` with keys sorted, values escaped; '' if empty."""
    if not labels:
        return ''
    body = ','.join(f'{k}="{escape_label_value(v)}"'
                    for k, v in sorted(labels.items()))
    return '{' + body + '}'


def prometheus_snapshot_lines(snap: dict, labels: dict = None,
                              type_lines: bool = True) -> list:
    """Render a :meth:`MetricsRegistry.snapshot` dict as Prometheus
    text-format lines, optionally stamping constant ``labels`` onto
    every series — the fleet router re-exposes each replica's snapshot
    with a ``replica`` label this way (docs/FLEET.md)."""
    labels = dict(labels or {})
    lab = _format_labels(labels)
    lines = []
    for name, val in sorted(snap.get('counters', {}).items()):
        pn = _prom_name(name)
        if type_lines:
            lines.append(f'# TYPE {pn} counter')
        lines.append(f'{pn}{lab} {val}')
    for name, val in sorted(snap.get('gauges', {}).items()):
        pn = _prom_name(name)
        if type_lines:
            lines.append(f'# TYPE {pn} gauge')
        lines.append(f'{pn}{lab} {val}')
    for name, st in sorted(snap.get('histograms', {}).items()):
        pn = _prom_name(name)
        if type_lines:
            lines.append(f'# TYPE {pn} histogram')
        lines.extend(_histogram_lines(pn, st, labels))
    return lines


def _histogram_lines(pn: str, st: dict, labels: dict) -> list:
    lines = []
    cum = 0
    for edge, c in zip(st['buckets'], st['counts']):
        cum += c
        lines.append(
            f'{pn}_bucket{_format_labels({**labels, "le": edge})} '
            f'{cum}')
    cum += st['counts'][-1]
    lines.append(
        f'{pn}_bucket{_format_labels({**labels, "le": "+Inf"})} {cum}')
    lab = _format_labels(labels)
    lines.append(f'{pn}_sum{lab} {st["sum"]}')
    lines.append(f'{pn}_count{lab} {st["n"]}')
    return lines


def merged_prometheus_text(snapshots: dict, label: str = 'replica'
                           ) -> list:
    """Merge per-process registry snapshots into one labeled
    exposition: for every metric name, one ``# TYPE`` line, a
    fleet-level ROLLUP series (counters: sum; histograms: summed
    buckets when the ladders agree), then one ``{label="<id>"}``
    series per process.  ``snapshots`` maps process id (replica id) →
    :meth:`MetricsRegistry.snapshot` dict; returns text lines."""
    lines = []
    names = sorted({n for s in snapshots.values()
                    for n in s.get('counters', {})})
    for name in names:
        pn = _prom_name(name)
        lines.append(f'# TYPE {pn} counter')
        lines.append(f'{pn} ' + str(sum(
            s.get('counters', {}).get(name, 0)
            for s in snapshots.values())))
        for rid in sorted(snapshots):
            val = snapshots[rid].get('counters', {}).get(name)
            if val is not None:
                lines.append(f'{pn}{_format_labels({label: rid})} '
                             f'{val}')
    names = sorted({n for s in snapshots.values()
                    for n in s.get('gauges', {})})
    for name in names:
        pn = _prom_name(name)
        lines.append(f'# TYPE {pn} gauge')
        for rid in sorted(snapshots):
            val = snapshots[rid].get('gauges', {}).get(name)
            if val is not None:
                lines.append(f'{pn}{_format_labels({label: rid})} '
                             f'{val}')
    names = sorted({n for s in snapshots.values()
                    for n in s.get('histograms', {})})
    for name in names:
        pn = _prom_name(name)
        lines.append(f'# TYPE {pn} histogram')
        sts = {rid: snapshots[rid]['histograms'][name]
               for rid in sorted(snapshots)
               if name in snapshots[rid].get('histograms', {})}
        ladders = {tuple(st['buckets']) for st in sts.values()}
        if len(ladders) == 1:
            roll = {'buckets': next(iter(ladders)),
                    'counts': [sum(c) for c in zip(
                        *(st['counts'] for st in sts.values()))],
                    'sum': sum(st['sum'] for st in sts.values()),
                    'n': sum(st['n'] for st in sts.values())}
            lines.extend(_histogram_lines(pn, roll, {}))
        for rid, st in sts.items():
            lines.extend(_histogram_lines(pn, st, {label: rid}))
    return lines


# billing-grade per-tenant meter suffixes: the ``tenant.<name>.<meter>``
# counter family the serving tier emits (docs/SERVING.md "Tenants").
# Fixed set so tenant names containing dots still parse unambiguously —
# the meter is always the LAST dotted segment and always one of these.
TENANT_METERS = ('submitted', 'completed', 'failed', 'shed',
                 'quota_rejected', 'shots', 'device_ms', 'compile_ms',
                 'bytes_wire')


def tenant_usage(snap: dict) -> dict:
    """Fold the ``tenant.<name>.<meter>`` counter family out of a
    registry :meth:`MetricsRegistry.snapshot` (or a bare counters dict)
    into ``{tenant: {meter: value}}`` usage rows, zero-filled over
    :data:`TENANT_METERS`.  Fleet tooling sums these rows across
    replica snapshots to get fleet-level billing totals — counters are
    monotone, so summation is exact."""
    counters = snap.get('counters', snap) if isinstance(snap, dict) \
        else {}
    out = {}
    for name, val in counters.items():
        if not isinstance(name, str) or not name.startswith('tenant.'):
            continue
        tenant, sep, meter = name[len('tenant.'):].rpartition('.')
        if not sep or meter not in TENANT_METERS:
            continue
        row = out.setdefault(tenant, {m: 0 for m in TENANT_METERS})
        row[meter] = val
    return out


def merge_tenant_usage(per_process: dict) -> dict:
    """Sum :func:`tenant_usage` rows across processes: maps
    ``{process_id: usage_rows}`` → one fleet-level ``{tenant:
    {meter: total}}`` rollup."""
    out = {}
    for rows in per_process.values():
        for tenant, row in rows.items():
            agg = out.setdefault(tenant,
                                 {m: 0 for m in TENANT_METERS})
            for m in TENANT_METERS:
                agg[m] += row.get(m, 0)
    return out


class Histogram:
    """Fixed-bucket histogram with a bounded exact-sample window.

    The buckets feed the Prometheus exposition; the window keeps the
    raw samples (newest ``window`` of them) so callers that previously
    ran ``np.percentile`` over a deque — the service's latency
    percentiles, the compile cache's compile-time percentiles — keep
    producing the exact same numbers after migrating onto the registry.
    """

    def __init__(self, name: str, buckets=None, window: int = 4096):
        self.name = name
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._counts = [0] * (len(self.buckets) + 1)   # +1 = +inf
        self._sum = 0.0
        self._n = 0
        self._window = deque(maxlen=window)
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._n += 1
        # deque.append is atomic; keeping it outside the lock keeps the
        # hot path to one short critical section
        self._window.append(value)

    @property
    def count(self) -> int:
        return self._n

    @property
    def sum(self) -> float:
        return self._sum

    def values(self) -> list:
        """Snapshot of the retained raw-sample window (newest last)."""
        return list(self._window)

    def percentile(self, p: float):
        """Exact percentile over the retained window (linear
        interpolation, numpy-compatible); None when empty."""
        vals = sorted(self._window)
        if not vals:
            return None
        if len(vals) == 1:
            return vals[0]
        rank = (p / 100.0) * (len(vals) - 1)
        lo = int(math.floor(rank))
        hi = min(lo + 1, len(vals) - 1)
        frac = rank - lo
        return vals[lo] * (1.0 - frac) + vals[hi] * frac

    def state(self) -> dict:
        with self._lock:
            return {'buckets': self.buckets,
                    'counts': list(self._counts),
                    'sum': self._sum, 'n': self._n,
                    'window': list(self._window),
                    'maxlen': self._window.maxlen}

    @classmethod
    def from_state(cls, name: str, st: dict) -> 'Histogram':
        h = cls(name, buckets=st['buckets'], window=st['maxlen'])
        h._counts = list(st['counts'])
        h._sum = st['sum']
        h._n = st['n']
        h._window.extend(st['window'])
        return h


class MetricsRegistry:
    """One process-wide home for every counter, gauge, and histogram."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict = {}
        self._gauges: dict = {}
        self._histograms: dict = {}

    # -- counters (the utils.profiling namespace) -----------------------

    def inc(self, name: str, amount: int = 1) -> int:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + amount
            return self._counters[name]

    def get(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def counters(self) -> dict:
        with self._lock:
            return dict(self._counters)

    # -- gauges ---------------------------------------------------------

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def gauge(self, name: str, default=0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    def gauges(self) -> dict:
        with self._lock:
            return dict(self._gauges)

    # -- histograms -----------------------------------------------------

    def histogram(self, name: str, buckets=None,
                  window: int = 4096) -> Histogram:
        """Get-or-create the named histogram (first caller fixes the
        bucket ladder and window size)."""
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = Histogram(name, buckets=buckets, window=window)
                self._histograms[name] = h
            return h

    def observe(self, name: str, value: float, buckets=None) -> None:
        self.histogram(name, buckets=buckets).observe(value)

    def histograms(self) -> dict:
        with self._lock:
            return dict(self._histograms)

    # -- snapshot / restore (test isolation) ----------------------------

    def snapshot(self) -> dict:
        """Deep-copyable state of every metric, for later ``restore``."""
        with self._lock:
            return {
                'counters': dict(self._counters),
                'gauges': dict(self._gauges),
                'histograms': {n: h.state()
                               for n, h in self._histograms.items()},
            }

    def restore(self, snap: dict) -> None:
        """Reset the registry to a prior ``snapshot``.  Histogram
        objects handed out before the snapshot keep working (they are
        rebuilt fresh in the registry, so post-restore observations via
        ``observe(name, ...)`` land in the restored instance)."""
        with self._lock:
            self._counters = dict(snap.get('counters', {}))
            self._gauges = dict(snap.get('gauges', {}))
            self._histograms = {
                n: Histogram.from_state(n, st)
                for n, st in snap.get('histograms', {}).items()}

    def reset(self) -> None:
        self.restore({'counters': {}, 'gauges': {}, 'histograms': {}})

    # -- exposition -----------------------------------------------------

    def prometheus_text(self) -> str:
        """Prometheus text-format exposition of every metric.

        Dotted names are sanitized (``serve.compile.cold`` →
        ``serve_compile_cold``); histogram buckets are cumulative with
        the conventional ``le`` label and trailing ``+Inf``; label
        values are escaped per the text-format spec
        (:func:`escape_label_value`).
        """
        lines = prometheus_snapshot_lines(self.snapshot())
        return '\n'.join(lines) + ('\n' if lines else '')


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry ``utils.profiling`` delegates to."""
    return _DEFAULT
