"""Flight-deck observability for the serving stack (docs/OBSERVABILITY.md).

Three stdlib-only, jax-free pieces the serve / compilecache / sim
layers emit into:

:mod:`.trace`     per-request lifecycle spans + Chrome Trace export
:mod:`.metrics`   typed registry (counters / gauges / histograms) with
                  Prometheus text exposition — the backing store for
                  ``utils.profiling``'s counter namespace
:mod:`.recorder`  flight recorder — lock-cheap ring buffer of
                  supervision / chaos events
"""

from .metrics import (DEFAULT_BUCKETS, Histogram, MetricsRegistry,
                      default_registry)
from .recorder import FlightRecorder
from .trace import (STAGE_ORDER, TraceContext, Tracer,
                    chrome_trace_events, write_chrome_trace)

__all__ = [
    'DEFAULT_BUCKETS',
    'Histogram',
    'MetricsRegistry',
    'default_registry',
    'FlightRecorder',
    'STAGE_ORDER',
    'TraceContext',
    'Tracer',
    'chrome_trace_events',
    'write_chrome_trace',
]
