"""Flight-deck observability for the serving stack (docs/OBSERVABILITY.md).

Four stdlib-only, jax-free pieces the serve / compilecache / sim
layers emit into:

:mod:`.trace`     per-request lifecycle spans + Chrome Trace export
:mod:`.metrics`   typed registry (counters / gauges / histograms) with
                  Prometheus text exposition — the backing store for
                  ``utils.profiling``'s counter namespace
:mod:`.recorder`  flight recorder — lock-cheap ring buffer of
                  supervision / chaos events
:mod:`.clock`     cross-process monotonic-clock offset estimation —
                  aligns replica-side spans and flight events into the
                  fleet router's timeline (docs/FLEET.md)
"""

from .clock import ClockOffsetEstimator
from .metrics import (DEFAULT_BUCKETS, TENANT_METERS, Histogram,
                      MetricsRegistry, default_registry,
                      escape_label_value, merge_tenant_usage,
                      merged_prometheus_text,
                      prometheus_snapshot_lines, tenant_usage)
from .recorder import FlightRecorder
from .trace import (STAGE_ORDER, TraceContext, Tracer,
                    chrome_trace_events, write_chrome_trace)

__all__ = [
    'ClockOffsetEstimator',
    'DEFAULT_BUCKETS',
    'Histogram',
    'MetricsRegistry',
    'default_registry',
    'escape_label_value',
    'merge_tenant_usage',
    'merged_prometheus_text',
    'prometheus_snapshot_lines',
    'TENANT_METERS',
    'tenant_usage',
    'FlightRecorder',
    'STAGE_ORDER',
    'TraceContext',
    'Tracer',
    'chrome_trace_events',
    'write_chrome_trace',
]
