"""Flight recorder: a lock-cheap ring buffer of structured events.

When a chaos soak ends with a tripped breaker or a dead executor, the
counters say *that* it happened; the flight recorder says *what led up
to it* — the last N supervision events in order, each a small JSON-able
dict.  Event taxonomy (docs/OBSERVABILITY.md):

``breaker_trip``     executor quarantined (consecutive infra failures)
``canary``           canary probe result (``ok`` bool)
``readmission``      quarantined executor re-admitted after canary pass
``executor_death``   dispatcher thread found dead by the supervisor
``respawn``          dead dispatcher re-spawned
``hang``             dispatch exceeded the hang watchdog
``shed``             overload eviction of a queued request
``overload_reject``  admission-time overload rejection
``retry``            batch failure re-queued under the retry policy
``retry_exhausted``  retry budget exhausted, request failed
``batch_failure``    a batch raised (infra or program class)
``chaos_inject``     ChaosMonkey injected a non-ok outcome
``cache_invalidate`` compile-cache calibration-epoch invalidation
``integrity_violation`` audit or digest mismatch (edge-triggered per
                     executor: one event per clean->bad transition)
``scrubber_fail``    background scrubber canary mismatched golden ref

Cost discipline: ``record`` is one dict build + ``deque.append``
(atomic under the GIL) + an ``itertools.count`` draw — no lock, safe
from any thread.  The ring holds the newest ``capacity`` events;
``recorded`` counts everything ever seen so truncation is visible.

``ExecutionService`` owns one recorder per service and dumps it
automatically on supervisor-detected failures when ``flight_dump_dir``
(or ``$DPROC_FLIGHT_DIR``) is set; ``tools/servechaos.py`` attaches the
recorder to its exit report.
"""

from __future__ import annotations

import itertools
import json
import os
import time
from collections import Counter, deque


class FlightRecorder:
    """Bounded in-memory ring of structured events."""

    def __init__(self, capacity: int = 512):
        self.capacity = int(capacity)
        self._ring = deque(maxlen=self.capacity)
        self._seq = itertools.count()

    def record(self, kind: str, **data) -> None:
        """Append one event; ``data`` values must be JSON-able.  The
        ``seq``/``t``/``mono``/``kind`` fields are the recorder's own —
        a colliding payload key is overwritten, never the envelope."""
        ev = dict(data)
        ev.update(seq=next(self._seq), t=time.time(),
                  mono=time.monotonic(), kind=kind)
        self._ring.append(ev)

    @property
    def recorded(self) -> int:
        """Total events ever recorded (>= len(events()) once the ring
        wraps)."""
        # itertools.count has no peek; its pickle form carries the
        # next value to be drawn
        return self._seq.__reduce__()[1][0]

    @property
    def dropped(self) -> int:
        """Events overwritten by ring wrap: a nonzero value means the
        dump is a TRUNCATED incident timeline, not a quiet one — the
        federated fleet report surfaces it per replica."""
        return max(0, self.recorded - len(self._ring))

    def events(self, kind: str = None) -> list:
        """Snapshot of retained events, oldest first; optionally
        filtered by ``kind``."""
        evs = list(self._ring)
        if kind is not None:
            evs = [e for e in evs if e['kind'] == kind]
        return evs

    def counts(self) -> dict:
        """Retained event counts by kind."""
        return dict(Counter(e['kind'] for e in self._ring))

    def to_json(self) -> dict:
        return {'capacity': self.capacity, 'recorded': self.recorded,
                'dropped': self.dropped, 'counts': self.counts(),
                'events': self.events()}

    def dump(self, path: str) -> int:
        """Atomically write the ring to ``path``; returns the retained
        event count."""
        doc = self.to_json()
        tmp = f'{path}.tmp.{os.getpid()}'
        with open(tmp, 'w') as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
        return len(doc['events'])
