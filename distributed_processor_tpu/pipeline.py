"""End-to-end convenience pipeline: dict program -> MachineProgram.

Chains the same stages as the reference's main entry path (reference:
Compiler -> GlobalAssembler, python/distproc/compiler.py:177 /
assembler.py:542) and continues where the reference stops at the FPGA
BRAM boundary: the assembled buffers are decoded into the tensorised
machine program the JAX interpreter executes.
"""

from __future__ import annotations

from .hwconfig import FPGAConfig
from .compiler import Compiler, get_passes, CompilerFlags
from .assembler import GlobalAssembler
from .elements import TPUElementConfig
from .decoder import decode_assembled_program, MachineProgram
from .models.channels import make_channel_configs


def compile_program(program, qchip, fpga_config: FPGAConfig = None,
                    compiler_flags: CompilerFlags = None,
                    proc_grouping=None):
    """Dict program -> CompiledProgram (per-core asm)."""
    fpga_config = fpga_config or FPGAConfig()
    kw = {}
    if proc_grouping is not None:
        kw['proc_grouping'] = proc_grouping
    compiler = Compiler(program, **kw)
    compiler.run_ir_passes(get_passes(fpga_config, qchip,
                                      compiler_flags=compiler_flags))
    return compiler.compile()


def compile_to_machine(program, qchip, channel_configs=None,
                       fpga_config: FPGAConfig = None,
                       compiler_flags: CompilerFlags = None,
                       n_qubits: int = 8, pad_to: int = None,
                       element_cls=TPUElementConfig) -> MachineProgram:
    """Full pipeline: compile, assemble, and decode for the simulator."""
    if channel_configs is None:
        channel_configs = make_channel_configs(n_qubits)
    if fpga_config is None:
        # size the auto-generated 'Qn.meas' fproc channels to the system
        # (the Simulator facade does the same)
        fpga_config = FPGAConfig(n_cores=n_qubits)
    prog = compile_program(program, qchip, fpga_config, compiler_flags)
    asm = GlobalAssembler(prog, channel_configs, element_cls)
    assembled = asm.get_assembled_program()
    return decode_assembled_program(assembled, channel_configs, pad_to=pad_to,
                                    reg_maps=asm.register_maps)


def cached_compile_to_machine(program, qchip, channel_configs=None,
                              fpga_config: FPGAConfig = None,
                              compiler_flags: CompilerFlags = None,
                              n_qubits: int = 8, pad_to: int = None,
                              element_cls=TPUElementConfig,
                              cache=None) -> MachineProgram:
    """:func:`compile_to_machine` through the content-addressed compile
    cache (process-wide default, or an explicit :class:`CompileCache`).
    Accepts OpenQASM 3 text as well as dict-instruction programs; a warm
    hit for identical source + calibration costs a dict lookup.
    """
    from .compilecache import default_cache
    if cache is None:
        cache = default_cache()
    mp, _status, _key = cache.get_or_compile(
        program, qchip, channel_configs=channel_configs,
        fpga_config=fpga_config, compiler_flags=compiler_flags,
        n_qubits=n_qubits, pad_to=pad_to, element_cls=element_cls)
    return mp
