"""Hardware parameterisation shared by the compiler, assembler and simulator.

Mirrors the reference's configuration surface (reference:
python/distproc/hwconfig.py) with plain dataclasses:

* :class:`FPGAConfig` — the processor timing model.  These constants are the
  cycle-exactness contract between the scheduler, the schedule linter and
  the JAX interpreter.
* :class:`FPROCChannel` — named measurement-feedback channels.
* :class:`ChannelConfig` / :func:`load_channel_configs` — wiring of pulse
  destination channels to (core, element) indices, loaded from JSON.
* :class:`ElementConfig` — abstract per-element word-encoding interface
  (phase/amp/env/freq/cfg words, env + freq buffers); the TPU signal
  element lives in :mod:`distributed_processor_tpu.elements`.
"""

from __future__ import annotations

import json
from abc import ABC, abstractmethod
from dataclasses import dataclass, field

FPROC_MEAS_CLKS = 64   # clks after rdlo pulse end until the meas bit is valid
N_CORES = 8


@dataclass
class FPROCChannel:
    """A named measurement-feedback (fproc) channel.

    ``id``: either the numeric fproc function id, or a ``(channel_name,
    attribute)`` tuple resolved at assembly time against the channel
    configs — e.g. ``('Q0.rdlo', 'core_ind')``.

    ``hold_after_chans`` / ``hold_nclks``: fproc reads on this channel must
    execute at least ``hold_nclks`` after the end of the most recent pulse
    on any of the listed channels (the compiler inserts a Hold).
    """
    id: int | tuple
    hold_after_chans: list = field(default_factory=list)
    hold_nclks: int = 0


@dataclass
class FPGAConfig:
    """Distributed-processor timing model (units: FPGA clocks, 2 ns)."""
    fpga_clk_period: float = 2.e-9
    alu_instr_clks: int = 5
    jump_cond_clks: int = 5
    jump_fproc_clks: int = 8   # conservative; covers the fproc_meas handshake
    pulse_regwrite_clks: int = 3
    pulse_load_clks: int = 3   # min clks between pulses on the same core
    fproc_channels: dict = None
    # how many 'Qn.meas' channels to auto-generate (the reference
    # hard-codes N_CORES=8, hwconfig.py:112-115; here it follows the
    # system size — Simulator passes its n_qubits)
    n_cores: int = N_CORES
    # syndrome-LUT fabric contents (ops/fabric.py MeasLUT and the
    # interpreter's fabric='lut' path).  The gateware hard-codes these
    # (reference: hdl/meas_lut.sv:16-20, TODO "make these writable");
    # here they are hardware configuration like every timing constant
    # above.  ``meas_lut_mask``: bool per core — which cores' bits form
    # the table address (LSB = lowest masked core).  ``meas_lut_table``:
    # 2^popcount(mask) entries, bit c of an entry = output bit for core
    # c.  Empty (the default) = no LUT configured.
    meas_lut_mask: tuple = ()
    meas_lut_table: tuple = ()

    def __post_init__(self):
        # normalize JSON-borne lists to the hashable tuples the
        # interpreter config requires, and validate the pair early —
        # a mis-sized table should fail at configuration time, not at
        # first simulated fproc read
        self.meas_lut_mask = tuple(bool(b) for b in self.meas_lut_mask)
        self.meas_lut_table = tuple(int(e) for e in self.meas_lut_table)
        if self.meas_lut_mask or self.meas_lut_table:
            k = sum(self.meas_lut_mask)
            if len(self.meas_lut_table) != 1 << k:
                raise ValueError(
                    f'meas_lut_table must have 2^{k} entries for a '
                    f'{k}-input mask, got {len(self.meas_lut_table)}')
        if self.fproc_channels is None:
            # default: one 'Qn.meas' channel per qubit, served by the rdlo
            # demod chain on that qubit's core
            self.fproc_channels = {
                f'Q{i}.meas': FPROCChannel(
                    id=(f'Q{i}.rdlo', 'core_ind'),
                    hold_after_chans=[f'Q{i}.rdlo'],
                    hold_nclks=FPROC_MEAS_CLKS)
                for i in range(self.n_cores)}

    @property
    def fpga_clk_freq(self) -> float:
        return 1 / self.fpga_clk_period

    def to_dict(self) -> dict:
        d = {'fpga_clk_period': self.fpga_clk_period,
             'alu_instr_clks': self.alu_instr_clks,
             'jump_cond_clks': self.jump_cond_clks,
             'jump_fproc_clks': self.jump_fproc_clks,
             'pulse_regwrite_clks': self.pulse_regwrite_clks,
             'pulse_load_clks': self.pulse_load_clks,
             'n_cores': self.n_cores}
        if self.meas_lut_mask:
            # only when configured: serialized CompiledPrograms (and the
            # golden files pinning them) predate these fields
            d['meas_lut_mask'] = list(self.meas_lut_mask)
            d['meas_lut_table'] = list(self.meas_lut_table)
        return d


@dataclass
class ChannelConfig:
    """Wiring of one pulse destination channel (e.g. ``Q0.qdrv``)."""
    core_ind: int
    elem_ind: int
    elem_params: dict
    env_mem_name: str = ''
    freq_mem_name: str = ''
    acc_mem_name: str = ''

    def _fmt(self, name):
        return name.format(core_ind=self.core_ind)

    @property
    def env_mem(self) -> str:
        return self._fmt(self.env_mem_name)

    @property
    def freq_mem(self) -> str:
        return self._fmt(self.freq_mem_name)

    @property
    def acc_mem(self) -> str:
        return self._fmt(self.acc_mem_name)


def load_channel_configs(config: dict | str) -> dict:
    """Load a channel-config dict (or JSON file path).

    Returns a dict mapping channel name -> :class:`ChannelConfig`, with
    scalar entries (e.g. ``fpga_clk_freq``) passed through.
    """
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if 'fpga_clk_freq' not in config:
        raise ValueError("channel config must define 'fpga_clk_freq'")
    out = {}
    for key, value in config.items():
        if isinstance(value, dict):
            out[key] = ChannelConfig(**value)
        else:
            out[key] = value
    return out


class ElementConfig(ABC):
    """Per-element word encodings: how pulse parameters map to machine words.

    One instance per signal-generator element (qdrv/rdrv/rdlo).  The
    assembler uses it to encode pulse commands and build envelope/frequency
    buffers; the simulator uses the same instance to decode them, which
    keeps encode/decode bit-consistent by construction.
    """

    def __init__(self, fpga_clk_period: float, samples_per_clk: int):
        self.fpga_clk_period = fpga_clk_period
        self.samples_per_clk = samples_per_clk

    @property
    def sample_period(self) -> float:
        return self.fpga_clk_period / self.samples_per_clk

    @property
    def sample_freq(self) -> float:
        return 1 / self.sample_period

    @property
    def fpga_clk_freq(self) -> float:
        return 1 / self.fpga_clk_period

    @abstractmethod
    def get_phase_word(self, phase: float) -> int: ...

    @abstractmethod
    def get_amp_word(self, amplitude: float) -> int: ...

    @abstractmethod
    def get_env_word(self, env_start_ind: int, env_length: int) -> int: ...

    @abstractmethod
    def get_cw_env_word(self, env_start_ind: int) -> int: ...

    @abstractmethod
    def get_env_buffer(self, env) -> 'np.ndarray': ...

    @abstractmethod
    def get_freq_buffer(self, freqs) -> 'np.ndarray': ...

    @abstractmethod
    def get_freq_addr(self, freq_ind: int) -> int: ...

    @abstractmethod
    def get_cfg_word(self, elem_ind: int, mode_bits: int | None) -> int: ...

    @abstractmethod
    def length_nclks(self, tlength: float) -> int: ...
