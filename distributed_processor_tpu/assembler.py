"""Assembler: per-core assembly dialect → 128-bit machine code + element
envelope/frequency buffers.

Assembly dialect (parity with the reference asm format,
python/distproc/assembler.py:1-47):

* ``{'op': 'declare_reg', 'name', 'dtype': ('int',) | ('phase', elem) | ('amp', elem)}``
* ``{'op': 'declare_freq', 'freq', 'elem_ind', ['freq_ind']}``
* ``{'op': 'pulse', 'freq', 'env', 'phase', 'amp', 'start_time', 'elem_ind',
  ['label'], ['tag']}`` — freq/phase/amp may be register names (at most one
  per machine instruction; multi-register pulses split automatically)
* ``{'op': 'reg_alu', 'in0', 'alu_op', 'in1_reg', 'out_reg', ['label']}``
* ``{'op': 'inc_qclk', 'in0'}``, ``{'op': 'jump_cond', ...}``,
  ``{'op': 'jump_fproc', ...}``, ``{'op': 'alu_fproc', ...}``
* ``{'op': 'jump_i', 'jump_label'}``, ``{'op': 'jump_label', 'dest_label'}``
* ``{'op': 'phase_reset'}``, ``{'op': 'done_stb'}``, ``{'op': 'idle', 'end_time'}``

:class:`GlobalAssembler` consumes a CompiledProgram, resolves pulse
destinations and named fproc channels against the channel configs, and
assembles every core.
"""

from __future__ import annotations

import copy
import json
import logging
import warnings

import numpy as np

from . import isa
from . import hwconfig as hw

logger = logging.getLogger(__name__)

N_MAX_REGS = isa.N_REGS


class SingleCoreAssembler:
    """Assemble one core's program against its element configs.

    ``elem_cfgs``: ordered list of :class:`ElementConfig` — one per signal
    element attached to this core (element index = list position).
    """

    def __init__(self, elem_cfgs: list):
        self.n_element = len(elem_cfgs)
        self._elem_cfgs = elem_cfgs
        self._env_dicts = [dict() for _ in range(self.n_element)]
        self._freq_lists: list[list] = [[] for _ in range(self.n_element)]
        self._program: list[dict] = []
        self._regs: dict[str, dict] = {}

    # -- program construction -------------------------------------------

    def from_list(self, cmd_list: list[dict]):
        cmd_list = [dict(c) for c in cmd_list]   # do not mutate caller's program
        pending_labels = []
        for cmd in cmd_list:
            op = cmd['op']
            # declare_* emit no machine instruction: labels pending at a
            # declaration bind to the next real instruction (e.g. a loop
            # label whose block starts with a declare).  Several labels
            # may accumulate (label, declares, label); all alias the
            # same instruction address.
            if pending_labels and op not in ('declare_reg', 'declare_freq',
                                             'jump_label'):
                cmd = {**cmd, 'label': pending_labels[0]
                       if len(pending_labels) == 1 else tuple(pending_labels)}
                pending_labels = []
            args = {k: v for k, v in cmd.items() if k != 'op'}
            if op == 'pulse':
                n_reg_params = sum(isinstance(cmd.get(k), str)
                                   for k in ('freq', 'amp', 'phase'))
                if n_reg_params > 1:
                    warnings.warn(
                        f'{cmd} will be split into multiple instructions, '
                        'which may cause timing problems')
                self.add_pulse(**args)
            elif op in ('reg_alu', 'jump_cond', 'alu_fproc', 'jump_fproc'):
                self.add_alu_cmd(op, **args)
            elif op == 'inc_qclk':
                self.add_inc_qclk(**args)
            elif op == 'reg_write':
                self.add_reg_write(**args)
            elif op == 'phase_reset':
                self.add_phase_reset(**args)
            elif op == 'done_stb':
                self.add_done_stb(**args)
            elif op == 'declare_freq':
                self.add_freq(**args)
            elif op == 'declare_reg':
                self.declare_reg(**args)
            elif op == 'idle':
                self.add_idle(**args)
            elif op == 'jump_i':
                self.add_jump_i(**args)
            elif op == 'jump_label':
                pending_labels.append(args['dest_label'])
            else:
                raise ValueError(f'unsupported assembly op: {cmd}')
        if pending_labels:
            raise ValueError(
                f'jump label(s) {pending_labels} at end of program')

    @property
    def register_map(self) -> dict:
        """Declared variables: ``{name: {'index': i, 'dtype': (...)}}``."""
        return {n: dict(index=r['index'], dtype=tuple(r['dtype']))
                for n, r in self._regs.items()}

    def declare_reg(self, name: str, dtype=('int',)):
        if name in self._regs:
            raise ValueError(f'register {name} already declared')
        used = {r['index'] for r in self._regs.values()}
        index = next(i for i in range(N_MAX_REGS + 1) if i not in used)
        if index >= N_MAX_REGS:
            raise ValueError(f'out of registers (max {N_MAX_REGS})')
        if isinstance(dtype, str):
            dtype = (dtype,)
        self._regs[name] = {'index': index, 'dtype': tuple(dtype)}

    def add_alu_cmd(self, op: str, in0, alu_op: str, in1_reg: str = None,
                    out_reg: str = None, jump_label: str = None,
                    func_id=None, label: str = None):
        if op not in ('reg_alu', 'jump_cond', 'alu_fproc', 'jump_fproc', 'inc_qclk'):
            raise ValueError(f'bad alu op {op}')
        if in1_reg is not None and in1_reg not in self._regs:
            raise ValueError(f'undeclared register {in1_reg}')
        if isinstance(in0, str) and in0 not in self._regs:
            raise ValueError(f'undeclared register {in0}')

        cmd = {'op': op, 'in0': in0, 'alu_op': alu_op}
        if op in ('reg_alu', 'jump_cond'):
            assert in1_reg is not None and func_id is None
            if isinstance(in0, str):
                assert self._regs[in0]['dtype'] == self._regs[in1_reg]['dtype']
            cmd['in1_reg'] = in1_reg
        else:
            assert in1_reg is None
        if op in ('reg_alu', 'alu_fproc'):
            assert out_reg is not None
            if isinstance(in0, str):
                assert self._regs[in0]['dtype'] == self._regs[out_reg]['dtype']
            if in1_reg is not None:
                assert self._regs[in1_reg]['dtype'] == self._regs[out_reg]['dtype']
            cmd['out_reg'] = out_reg
        else:
            assert out_reg is None
        if op in ('jump_cond', 'jump_fproc'):
            assert jump_label is not None
            cmd['jump_label'] = jump_label
        if op in ('alu_fproc', 'jump_fproc'):
            cmd['func_id'] = func_id
        else:
            assert func_id is None
        if label is not None:
            cmd['label'] = label
        self._program.append(cmd)

    def add_reg_alu(self, in0, alu_op, in1_reg, out_reg, label=None):
        self.add_alu_cmd('reg_alu', in0, alu_op, in1_reg, out_reg, label=label)

    def add_reg_write(self, name, value, dtype=None, label=None):
        """Write an immediate to a named register, declaring it on first use."""
        if name not in self._regs:
            self.declare_reg(name, dtype if dtype is not None else ('int',))
        elif dtype is not None:
            assert tuple(dtype) == self._regs[name]['dtype']
        self.add_reg_alu(value, 'id0', name, name, label)

    def add_jump_cond(self, in0, alu_op, in1_reg, jump_label, label=None):
        self.add_alu_cmd('jump_cond', in0, alu_op, in1_reg,
                         jump_label=jump_label, label=label)

    def add_jump_fproc(self, in0, alu_op, jump_label, func_id=None, label=None):
        self.add_alu_cmd('jump_fproc', in0, alu_op, jump_label=jump_label,
                         func_id=func_id, label=label)

    def add_inc_qclk(self, in0, label=None):
        self.add_alu_cmd('inc_qclk', in0, 'add', label=label)

    def add_phase_reset(self, label=None):
        self._append({'op': 'pulse_reset'}, label)

    def add_done_stb(self, label=None):
        self._append({'op': 'done_stb'}, label)

    def add_idle(self, end_time, label=None):
        self._append({'op': 'idle', 'end_time': end_time}, label)

    def add_jump_i(self, jump_label, label=None):
        self._append({'op': 'jump_i', 'jump_label': jump_label}, label)

    def _append(self, cmd, label=None):
        if label is not None:
            cmd['label'] = label
        self._program.append(cmd)

    def add_env(self, name, env, elem_ind):
        if np.any(np.abs(env) > 1):
            raise ValueError('envelope magnitude must be <= 1')
        self._env_dicts[elem_ind][name] = env

    def add_freq(self, freq, elem_ind, freq_ind=None):
        freqs = self._freq_lists[elem_ind]
        if freq_ind is None:
            freqs.append(freq)
        elif freq_ind >= len(freqs):
            freqs.extend([None] * (freq_ind - len(freqs)))
            freqs.append(freq)
        elif freqs[freq_ind] is None:
            freqs[freq_ind] = freq
        else:
            raise ValueError(f'frequency index {freq_ind} already occupied')

    def add_pulse(self, freq, phase, amp, start_time, env, elem_ind,
                  label=None, tag=None):
        """Add a pulse; freq/phase/amp may name (typed) registers.

        At most one parameter per machine instruction can be
        register-sourced; extra register parameters are loaded by
        preceding parameter-write-only instructions.
        """
        if isinstance(env, np.ndarray):
            if np.any((np.abs(np.real(env)) > 1) | (np.abs(np.imag(env)) > 1)):
                raise ValueError('envelope must lie within the unit square')
            envkey = self._hash_env(env)
            self._env_dicts[elem_ind].setdefault(envkey, env)
        elif isinstance(env, dict):
            envkey = self._hash_env(env)
            self._env_dicts[elem_ind].setdefault(envkey, env)
        elif isinstance(env, str):
            envkey = env
            if envkey not in self._env_dicts[elem_ind]:
                if envkey == 'cw':
                    self._env_dicts[elem_ind][envkey] = 'cw'
                else:
                    raise ValueError(f'envelope not found: {envkey}')
        else:
            raise TypeError('env must be an array, paradict, or name')

        if isinstance(freq, str):
            assert freq in self._regs and self._regs[freq]['dtype'] == ('int',)
        elif freq not in self._freq_lists[elem_ind]:
            self.add_freq(freq, elem_ind)
        if isinstance(amp, str):
            assert amp in self._regs and self._regs[amp]['dtype'] == ('amp', elem_ind)
        if isinstance(phase, str):
            assert phase in self._regs and self._regs[phase]['dtype'] == ('phase', elem_ind)

        # split out extra register-sourced parameters into write-only cmds
        reg_params = [k for k, v in (('freq', freq), ('amp', amp), ('phase', phase))
                      if isinstance(v, str)]
        params = {'freq': freq, 'amp': amp, 'phase': phase}
        first = True
        for extra in reg_params[:-1]:
            write = {'op': 'pulse', extra: params.pop(extra),
                     'elem': elem_ind}
            if label is not None and first:
                # the label must address the whole split group: a jump
                # landing here (e.g. a loop back-edge) must re-execute
                # the parameter writes, not just the final trigger
                write['label'] = label
                first = False
            self._program.append(write)
        cmd = {'op': 'pulse', **params, 'start_time': start_time,
               'env': envkey, 'elem': elem_ind}
        if label is not None and first:
            cmd['label'] = label
        if tag is not None:
            cmd['tag'] = tag
        self._program.append(cmd)

    # -- assembly --------------------------------------------------------

    def get_compiled_program(self):
        """Assemble: returns (cmd_buf bytes, env buffers, freq buffers)."""
        cmd_words = []
        env_raw, env_word_maps = self._get_env_buffers()
        freq_raw, freq_ind_maps = self._get_freq_buffers()
        labelmap = self._get_cmd_labelmap()

        for cmd in self._program:
            op = cmd['op']
            if op == 'pulse':
                elem = cmd['elem']
                cfg = self._elem_cfgs[elem]
                args = {}
                if 'freq' in cmd:
                    if isinstance(cmd['freq'], str):
                        args['freq_regaddr'] = self._regs[cmd['freq']]['index']
                    else:
                        args['freq_word'] = cfg.get_freq_addr(
                            freq_ind_maps[elem][cmd['freq']])
                if 'phase' in cmd:
                    if isinstance(cmd['phase'], str):
                        args['phase_regaddr'] = self._regs[cmd['phase']]['index']
                    else:
                        args['phase_word'] = cfg.get_phase_word(cmd['phase'])
                if 'amp' in cmd:
                    if isinstance(cmd['amp'], str):
                        args['amp_regaddr'] = self._regs[cmd['amp']]['index']
                    else:
                        args['amp_word'] = cfg.get_amp_word(cmd['amp'])
                if 'env' in cmd:
                    args['env_word'] = env_word_maps[elem][cmd['env']]
                if 'start_time' in cmd:
                    args['cmd_time'] = cmd['start_time']
                args['cfg_word'] = cfg.get_cfg_word(elem, None)
                cmd_words.append(isa.pulse_cmd(**args))

            elif op in ('reg_alu', 'jump_cond', 'alu_fproc', 'jump_fproc', 'inc_qclk'):
                if isinstance(cmd['in0'], str):
                    in0 = self._regs[cmd['in0']]['index']
                    im_or_reg = 'r'
                else:
                    in0 = cmd['in0']
                    im_or_reg = 'i'
                    # immediates interacting with typed registers are encoded
                    # in that register's hardware representation
                    key = cmd.get('out_reg') or cmd.get('in1_reg')
                    if key is not None:
                        dtype = self._regs[key]['dtype']
                        if dtype[0] == 'phase':
                            in0 = self._elem_cfgs[dtype[1]].get_phase_word(in0)
                        elif dtype[0] == 'amp':
                            in0 = self._elem_cfgs[dtype[1]].get_amp_word(in0)
                cmd_words.append(isa.alu_cmd(
                    op, im_or_reg, in0, cmd.get('alu_op'),
                    self._regs[cmd['in1_reg']]['index'] if 'in1_reg' in cmd else 0,
                    self._regs[cmd['out_reg']]['index'] if 'out_reg' in cmd else None,
                    labelmap[cmd['jump_label']] if 'jump_label' in cmd else None,
                    cmd.get('func_id')))

            elif op == 'jump_i':
                cmd_words.append(isa.jump_i(labelmap[cmd['jump_label']]))
            elif op == 'pulse_reset':
                cmd_words.append(isa.pulse_reset())
            elif op == 'idle':
                cmd_words.append(isa.idle(cmd['end_time']))
            elif op == 'done_stb':
                cmd_words.append(isa.done_cmd())
            elif op == 'sync':
                cmd_words.append(isa.sync(cmd['barrier_id']))
            else:
                raise ValueError(f'unsupported op {op}')

        return isa.cmds_to_bytes(cmd_words), env_raw, freq_raw

    def get_sim_program(self) -> list[dict]:
        """The program with envelope names replaced by data (for simulators)."""
        out = []
        for cmd in self._program:
            cmd = copy.deepcopy(cmd)
            if cmd['op'] == 'pulse' and 'env' in cmd:
                cmd['env'] = self._env_dicts[cmd['elem']][cmd['env']]
            out.append(cmd)
        return out

    @property
    def regs(self) -> dict:
        return {name: dict(r) for name, r in self._regs.items()}

    def _get_cmd_labelmap(self) -> dict:
        labelmap = {}
        for i, cmd in enumerate(self._program):
            if 'label' in cmd:
                labels = cmd['label'] if isinstance(cmd['label'], tuple) \
                    else (cmd['label'],)
                for label in labels:
                    if label in labelmap:
                        raise ValueError(f'label {label} used twice')
                    labelmap[label] = i
        return labelmap

    def _get_env_buffer(self, elem_ind):
        cur_ind = 0
        env_word_map = {}
        chunks = []
        for envkey, env in self._env_dicts[elem_ind].items():
            buf = self._elem_cfgs[elem_ind].get_env_buffer(env)
            if envkey == 'cw':
                env_word_map[envkey] = self._elem_cfgs[elem_ind].get_cw_env_word(cur_ind)
            else:
                env_word_map[envkey] = self._elem_cfgs[elem_ind].get_env_word(
                    cur_ind, len(buf))
            cur_ind += len(buf)
            chunks.append(np.asarray(buf))
        env_raw = np.concatenate(chunks) if chunks else np.zeros(0)
        return env_raw, env_word_map

    def _get_env_buffers(self):
        data, maps = [], []
        for i in range(self.n_element):
            d, m = self._get_env_buffer(i)
            data.append(np.asarray(d, dtype=np.uint32).tobytes())
            maps.append(m)
        return data, maps

    def _get_freq_buffers(self):
        data, maps = [], []
        for i in range(self.n_element):
            buf = self._elem_cfgs[i].get_freq_buffer(self._freq_lists[i])
            data.append(np.asarray(buf, dtype=np.uint32).tobytes())
            maps.append({f: self._freq_lists[i].index(f)
                         for f in self._freq_lists[i] if f is not None})
        return data, maps

    @staticmethod
    def _hash_env(env) -> str:
        if isinstance(env, np.ndarray):
            return str(hash(env.data.tobytes()))
        if isinstance(env, dict):
            return str(hash(json.dumps(env, sort_keys=True)))
        raise TypeError(f'cannot hash envelope of type {type(env)}')


class GlobalAssembler:
    """Assemble a CompiledProgram for every processor core.

    Resolves pulse ``dest`` channels to element indices and named fproc
    func_ids to hardware ids using the channel configs, then delegates to
    one :class:`SingleCoreAssembler` per core.
    """

    def __init__(self, compiled_program, channel_configs: dict,
                 elementconfig_class):
        self.assemblers: dict[str, SingleCoreAssembler] = {}
        self.channel_configs = channel_configs
        compiled_program = copy.deepcopy(compiled_program)

        if compiled_program.fpga_config is not None:
            hw_clk = int(np.round(channel_configs['fpga_clk_freq']))
            prog_clk = int(np.round(compiled_program.fpga_config.fpga_clk_freq))
            if hw_clk != prog_clk:
                raise ValueError(
                    f'program target clock {prog_clk} Hz != hardware clock {hw_clk} Hz')

        for proc_group in compiled_program.proc_groups:
            elem_cfgs = {}
            core_ind = str(channel_configs[proc_group[0]].core_ind)
            for chan in proc_group:
                chan_cfg = channel_configs[chan]
                if chan_cfg.core_ind != int(core_ind):
                    raise ValueError(f'{chan}: inconsistent core index in group')
                elem_cfgs[chan_cfg.elem_ind] = elementconfig_class(**chan_cfg.elem_params)
            inds = sorted(elem_cfgs)
            if inds != list(range(len(inds))):
                raise ValueError('element indices must be 0..n-1 within a core')

            program = compiled_program.program[proc_group]
            program = self._resolve_dests_and_fproc(program)
            program = self._resolve_duplicate_jump_labels(program)
            asm = SingleCoreAssembler([elem_cfgs[i] for i in inds])
            asm.from_list(program)
            self.assemblers[core_ind] = asm

    def _resolve_dests_and_fproc(self, program: list[dict]) -> list[dict]:
        out = []
        for statement in program:
            statement = dict(statement)
            if statement['op'] == 'pulse':
                statement['elem_ind'] = self.channel_configs[statement['dest']].elem_ind
                del statement['dest']
            elif statement['op'] in ('alu_fproc', 'jump_fproc'):
                func_id = statement.get('func_id')
                if isinstance(func_id, tuple):
                    statement['func_id'] = getattr(
                        self.channel_configs[func_id[0]], func_id[1])
                elif isinstance(func_id, str):
                    statement['func_id'] = self.channel_configs[func_id]
                elif func_id is not None and not isinstance(func_id, int):
                    raise TypeError(f'bad func_id {func_id}')
            out.append(statement)
        return out

    @staticmethod
    def _resolve_duplicate_jump_labels(program: list[dict]) -> list[dict]:
        """Merge runs of consecutive jump_label statements into one."""
        out = []
        combined: dict[str, str] = {}
        cur_label = None
        for statement in program:
            if statement['op'] == 'jump_label':
                if cur_label is None:
                    cur_label = statement['dest_label']
                    out.append(statement)
                else:
                    combined[statement['dest_label']] = cur_label
            else:
                cur_label = None
                out.append(statement)
        if combined:
            out = [dict(s, jump_label=combined[s['jump_label']])
                   if s.get('jump_label') in combined else s for s in out]
        return out

    @property
    def register_maps(self) -> dict:
        """Declared variables per core:
        ``{core_ind: {name: {'index', 'dtype'}}}`` — the handle a host
        needs to preload register-parameterized programs (the reference
        writes these registers over the FPGA bus at run time; here they
        seed ``init_regs``).  Kept out of ``get_assembled_program`` so
        its output stays format-identical to the reference's BRAM
        buffers (pinned by the golden-parity tests)."""
        return {core_ind: asm.register_map
                for core_ind, asm in self.assemblers.items()}

    def get_assembled_program(self) -> dict:
        """Returns {core_ind: {'cmd_buf', 'env_buffers', 'freq_buffers'}}."""
        assembled = {}
        for core_ind, asm in self.assemblers.items():
            cmd_buf, env_raw, freq_raw = asm.get_compiled_program()
            assembled[core_ind] = {'cmd_buf': cmd_buf, 'env_buffers': env_raw,
                                   'freq_buffers': freq_raw}
        return assembled
