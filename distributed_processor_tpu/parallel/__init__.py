from .mesh import make_mesh, shot_sharding
from .sweep import sharded_simulate, sweep_stats, sharded_demod
