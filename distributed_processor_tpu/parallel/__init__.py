from .mesh import make_mesh, make_cores_mesh, shot_sharding
from .driver import run_physics_sweep, run_multi_sweep, run_cores_sweep
from .sweep import (sharded_simulate, sweep_stats, sweep_stat_sums,
                    sharded_demod, sharded_physics_stats,
                    sharded_physics_stat_sums, sharded_multi_stats,
                    sharded_cores_simulate, sharded_cores_rounds,
                    sharded_cores_stat_sums,
                    sharded_cores_stats, run_spanned)
from .param_sweep import (swept_pulse_machine_program, grid_init_regs,
                          sweep_cfg, AMP_REG, FREQ_REG)
from .multihost import (initialize_multihost, make_global_mesh,
                        host_local_batch, host_local_mesh,
                        dp_row_offset, cross_host_sum,
                        global_shot_array)
