from .mesh import make_mesh, shot_sharding
from .driver import run_physics_sweep, run_multi_sweep
from .sweep import (sharded_simulate, sweep_stats, sharded_demod,
                    sharded_physics_stats, sharded_multi_stats,
                    run_spanned)
from .param_sweep import (swept_pulse_machine_program, grid_init_regs,
                          sweep_cfg, AMP_REG, FREQ_REG)
from .multihost import (initialize_multihost, make_global_mesh,
                        host_local_batch, global_shot_array)
