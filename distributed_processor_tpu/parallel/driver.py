"""Host-side sweep driver: compile once, stream batches, accumulate.

The last layer of SURVEY §7 step 7: the reference re-runs programs from
the host one shot at a time; here the host's only job is to stream
batch keys into one jitted computation and fold the returned statistics
— resumable via :class:`..utils.results.SweepAccumulator`, so a
million-shot physics-closed sweep survives interruption.

The per-batch computation reduces on-device (sums, not per-shot
arrays), so host traffic per batch is a few KB regardless of batch
size.
"""

from __future__ import annotations

import zlib

import numpy as np
import jax
import jax.numpy as jnp

from .. import isa
from ..sim.interpreter import (InterpreterConfig, FaultError, FAULT_CODES,
                               _fault_policy, fault_shot_counts)
from ..utils.results import SweepAccumulator
from .sweep import physics_batch_stats


# v3: batch stats gained `allzero_sum` (joint RB survival) — older
# checkpoints' accumulator states lack the key and must not resume
# v4: batch stats gained `clean_shots` (the survival denominator —
# dividing the clean-shot numerator by total shots biased survival low
# by the errored/unresolved fraction); v3 states lack the key
# v5: batch stats gained `fault_shots` (per-code trapped-shot counts,
# the trap-and-report runtime); v4 states lack the key
FINGERPRINT_VERSION = 5


def _jsonable(v):
    """Dataclass/complex/tuple values as stable JSON-able structures —
    field-by-field, so the fingerprint survives cosmetic repr changes
    (float formatting, dataclass field reordering) and mismatches can
    be reported per field."""
    import dataclasses
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return {f.name: _jsonable(getattr(v, f.name))
                for f in dataclasses.fields(v)}
    if isinstance(v, complex):
        return [v.real, v.imag]
    if isinstance(v, (np.ndarray, jax.Array)):
        return _jsonable(np.asarray(v).tolist())   # complex dtypes recurse
    if isinstance(v, (tuple, list)):
        return [_jsonable(x) for x in v]
    if isinstance(v, (np.integer, np.floating, np.bool_)):
        return v.item()
    return v


def _sweep_fingerprint(mp, model, batch: int, key, cfg,
                       init_regs, n_dp: int = 0) -> dict:
    """Identity of a sweep for checkpoint validation: resuming with a
    different program, model, config, registers, batch size, or key
    must fail loudly, not silently mix incompatible accumulations.
    Versioned (``fingerprint_version``), with the model/config stored
    as structured field dicts rather than repr strings."""
    import dataclasses
    crc = 0
    for f in dataclasses.fields(mp.soa):          # every operand plane
        crc = zlib.crc32(
            np.ascontiguousarray(getattr(mp.soa, f.name)).tobytes(), crc)
    for t in mp.tables:                           # env/freq content
        for env in t.envs:
            crc = zlib.crc32(np.ascontiguousarray(env).tobytes(), crc)
        for fr in t.freqs:
            crc = zlib.crc32(
                np.ascontiguousarray(fr['freq']).tobytes(), crc)
    regs_crc = 0 if init_regs is None else zlib.crc32(
        np.ascontiguousarray(np.asarray(init_regs)).tobytes())
    return {
        'fingerprint_version': FINGERPRINT_VERSION,
        'batch': int(batch),
        'key': np.asarray(jax.random.key_data(key)).tolist(),
        'program_crc': int(crc),
        'model': _jsonable(model),
        'cfg': _jsonable(cfg),
        'init_regs_crc': int(regs_crc),
        # the dp extent changes the per-shard key folding, hence the
        # noise stream — a mesh checkpoint is not a single-device one
        'n_dp': int(n_dp),
    }


def run_physics_sweep(mp, model, total_shots: int, batch: int,
                      key=0, cfg: InterpreterConfig = None,
                      init_regs=None, checkpoint: str = None,
                      checkpoint_every: int = 0, span: int = 1,
                      mesh=None, strict_resume: bool = False,
                      **cfg_kw) -> dict:
    """Physics-closed sweep: ``total_shots`` in ``batch``-sized steps.

    Each batch is one jitted epoch-loop execution (thermal sampling →
    interpretation → window synthesis → demod → branch resolution);
    per-batch sums fold into a :class:`SweepAccumulator`.  With
    ``checkpoint`` set, the sweep resumes from the saved state: already
    -accumulated batches are skipped (the per-batch key stream is
    deterministic in the batch index, so a resumed sweep produces the
    identical result), and a checkpoint written by a different sweep
    (other program/model/batch/key) is rejected.

    With ``mesh`` given, every batch shards over the mesh ``dp`` axis
    (``batch`` divisible by the axis size): each shard runs its own
    epoch loop on its local shots with a key folded by (batch, shard),
    and only the psum-reduced sums reach the host — the full-scale
    shape of the BASELINE 1M-shot multi-chip sweep.

    ``init_regs``: optional register file, shared by every batch
    (``[n_cores, 16]``) — sweep axes inside a batch come from
    register-parameterized programs (see ``decoder.make_init_regs``).

    ``span``: batches folded into ONE device dispatch (a ``lax.scan``
    over batch indices with an on-device donated stats carry — see
    ``sim.interpreter.make_span_runner``), amortizing per-call
    dispatch/tunnel latency; spans are pipelined 1 deep so host
    checkpoint writes overlap device compute.  Bit-identical to the
    per-batch loop (``span=1``, the default): the same ``fold_in(key,
    i)`` stream folds into the same int32 sums.  Span is an execution
    strategy, not sweep identity — it does not enter the checkpoint
    fingerprint, so checkpoints are interchangeable across span
    choices; ``checkpoint_every`` stays in BATCH units, with writes
    snapping to span edges (grid-aligned, so a resume landing mid-span
    first completes its span cell).

    Returns ``{'shots', 'mean_pulses' [C], 'meas1_rate' [C],
    'survival00_rate' (joint P(every first-slot bit reads 0) — the
    multi-qubit RB survival), 'err_shots', 'fault_shots' (per-code
    trapped-shot counts, see ``sim.interpreter.FAULT_CODES``),
    'incomplete_batches'}``.  ``cfg.fault_mode='strict'`` raises
    :class:`~..sim.interpreter.FaultError` after the sweep completes
    (and checkpoints) if any shot trapped.
    """
    from ..sim.physics import (run_physics_batch, prepare_physics_tables,
                               validate_physics_tables)
    from dataclasses import replace
    cfg = replace(cfg, **cfg_kw) if cfg else InterpreterConfig(**cfg_kw)
    cfg = replace(cfg, record_pulses=False)       # stats only
    # strict faults are a host-side reporting policy, not sweep identity:
    # normalize to 'count' BEFORE the fingerprint and the jitted step, so
    # checkpoints interchange between modes and the jit cache stays one
    cfg, strict_faults = _fault_policy(cfg)
    if total_shots <= 0 or batch <= 0:
        raise ValueError(f'need positive total_shots/batch, got '
                         f'{total_shots}/{batch}')
    if total_shots % batch:
        raise ValueError(f'total_shots {total_shots} not divisible by '
                         f'batch {batch}')
    if span < 1:
        raise ValueError(f'span must be >= 1, got {span}')
    n_batches = total_shots // batch
    if isinstance(key, int):
        key = jax.random.PRNGKey(key)
    # resolve tables once (separate small jit) — the per-batch step
    # takes them as device-array args instead of re-deriving them every
    # batch inside its own module (see physics.prepare_physics_tables)
    tables = prepare_physics_tables(mp, model)
    # inside the jitted step the carried build parameters are tracers,
    # so validate here, eagerly, where they are concrete
    validate_physics_tables(mp, model, tables)

    if mesh is not None:
        from jax.sharding import PartitionSpec as P
        from .sweep import shard_map      # version shim lives there
        if 'cores' in mesh.axis_names and mesh.shape['cores'] > 1:
            # loud blocker, same naming as the engine ladder: physics
            # sweeps shard SHOTS only (cores_ineligible owns the why)
            from ..sim.interpreter import cores_ineligible
            reason = cores_ineligible(mp, replace(cfg, physics=True))
            raise ValueError(
                f'run_physics_sweep shards shots over dp only; a '
                f"cores={mesh.shape['cores']} mesh axis is ineligible "
                f'here: {reason} — injected-bits programs shard cores '
                f'via run_cores_sweep / sweep.sharded_cores_stats')
        n_dp = mesh.shape['dp']
        if batch % n_dp:
            raise ValueError(f'batch {batch} not divisible by mesh '
                             f'dp={n_dp}')
        local_shots = batch // n_dp

        def local(k, tabs):
            k_local = jax.random.fold_in(k, jax.lax.axis_index('dp'))
            out = run_physics_batch(mp, model, k_local, local_shots,
                                    init_regs=init_regs, cfg=cfg,
                                    tables=tabs)
            stats = dict(physics_batch_stats(out),
                         incomplete=out['incomplete'].astype(jnp.int32))
            stats = jax.tree.map(lambda x: jax.lax.psum(x, 'dp'), stats)
            # a batch is incomplete if ANY shard was — don't count shards
            stats['incomplete'] = jnp.minimum(stats['incomplete'], 1)
            return stats

        sharded = jax.jit(shard_map(local, mesh=mesh, in_specs=(P(), P()),
                                    out_specs=P(), check_vma=False))
        step = lambda k: sharded(k, tables)
    else:
        @jax.jit
        def step(k, tabs):
            out = run_physics_batch(mp, model, k, batch,
                                    init_regs=init_regs, cfg=cfg,
                                    tables=tabs)
            return dict(physics_batch_stats(out),
                        incomplete=out['incomplete'].astype(jnp.int32))
        _step = step
        step = lambda k: _step(k, tables)

    meta = _sweep_fingerprint(mp, model, batch, key, cfg, init_regs,
                              mesh.shape['dp'] if mesh is not None else 0)
    if checkpoint and checkpoint_every <= 0:
        checkpoint_every = 1          # a requested checkpoint that never
                                      # writes mid-run resumes nothing
    # strict_resume: reject version-skewed/unfingerprinted checkpoints
    # outright instead of the warn-and-accept legacy path
    # (utils/results.py SweepAccumulator.resume)
    acc = SweepAccumulator.resume(checkpoint, checkpoint_every, meta=meta,
                                  strict=strict_resume) \
        if checkpoint else SweepAccumulator(meta=meta)
    if acc.n_batches > n_batches:
        raise ValueError(
            f'checkpoint already holds {acc.n_batches} batches '
            f'({acc.n_batches * batch} shots) > requested {total_shots}')
    if span > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .sweep import run_spanned
        run_spanned(step, acc, key, n_batches, span,
                    out_sharding=(NamedSharding(mesh, P())
                                  if mesh is not None else None))
    else:
        for i in range(acc.n_batches, n_batches):
            # key derived from the batch INDEX, not a split chain:
            # resuming from batch i reproduces the same stream
            stats = step(jax.random.fold_in(key, i))
            acc.add({k: np.asarray(v) for k, v in stats.items()})
    if checkpoint:
        acc.save()

    shots_done = acc.n_batches * batch
    incomplete = int(acc.state['incomplete'])
    if incomplete:
        # shots that hit the step budget contribute partial counts to
        # the sums, so the means below are diluted — say so loudly
        # rather than letting the counter go unnoticed
        import warnings
        warnings.warn(
            f'{incomplete}/{acc.n_batches} batches contain shots that '
            f'did not finish (step budget); mean_pulses/meas1_rate '
            f'include their partial counts — raise max_steps or treat '
            f'the means as lower bounds', stacklevel=2)
    # survival over CLEAN shots only: allzero_sum already excludes
    # errored/unresolved shots from the numerator, so dividing by
    # shots_done would bias the rate low by exactly that fraction
    clean = int(acc.state['clean_shots'])
    faults = {name: int(n) for (name, _), n
              in zip(FAULT_CODES, np.asarray(acc.state['fault_shots']))}
    if strict_faults and any(faults.values()):
        raise FaultError(acc.state['fault_shots'])
    from ..sim.interpreter import resolve_engine
    return {
        'shots': shots_done,
        # which interpreter engine the epoch loop ran (the ladder's
        # choice for this program/cfg — results metadata, satellite of
        # the engine-ladder work)
        'engine': resolve_engine(mp, cfg),
        'mean_pulses': acc.state['pulse_sum'] / shots_done,
        'meas1_rate': acc.state['meas1_sum'] / shots_done,
        'survival00_rate': float(acc.state['allzero_sum'] / clean)
        if clean else float('nan'),
        'clean_shots': clean,
        'err_shots': int(acc.state['err_shots']),
        # per-code counts of shots that trapped (sim.interpreter
        # FAULT_CODES order) — zero everywhere for a healthy sweep
        'fault_shots': faults,
        'incomplete_batches': incomplete,
    }


def run_cores_sweep(mp, total_shots: int, batch: int, p1=0.5, key=0,
                    cfg: InterpreterConfig = None, init_regs=None,
                    mesh=None, **cfg_kw) -> dict:
    """Injected-bits sweep of ONE many-core program with its core axis
    sharded over the mesh ``'cores'`` axis (docs/PERF.md "ICI
    fabric"): the cross-chip twin of :func:`run_multi_sweep`'s
    injected-bits loop, for programs whose carry no single device can
    hold.  Measurement bits are Bernoulli(``p1``) per (shot, core,
    slot) from a per-batch key folded on the batch INDEX (the same
    deterministic stream contract as the other drivers); per-batch
    integer sums come back replicated from
    :func:`.sweep.sharded_cores_stat_sums` and fold host-side.

    ``mesh`` must be a ``('dp', 'cores')`` mesh
    (:func:`.mesh.make_cores_mesh`) — required, there is no
    single-device fallback to mis-shard onto.  Returns
    ``run_multi_sweep``-style scalars: ``shots``, ``engine``
    (always ``'generic'`` — the only rung hosting the collective
    fabric), ``mean_pulses [n_cores]``, ``err_rate``, ``err_shots``,
    ``mean_qclk [n_cores]``, ``fault_shots`` (per-code name → count).
    ``cfg.fault_mode='strict'`` raises
    :class:`~..sim.interpreter.FaultError` after the sweep if any
    shot trapped.
    """
    from dataclasses import replace
    from .sweep import sharded_cores_stat_sums
    cfg = replace(cfg, **cfg_kw) if cfg else InterpreterConfig(**cfg_kw)
    cfg, strict_faults = _fault_policy(cfg)
    if mesh is None:
        raise ValueError("run_cores_sweep needs a ('dp', 'cores') mesh "
                         '(parallel.mesh.make_cores_mesh)')
    if total_shots <= 0 or batch <= 0:
        raise ValueError(f'need positive total_shots/batch, got '
                         f'{total_shots}/{batch}')
    if total_shots % batch:
        raise ValueError(f'total_shots {total_shots} not divisible by '
                         f'batch {batch}')
    if isinstance(key, int):
        key = jax.random.PRNGKey(key)
    n_cores = mp.n_cores
    p1 = jnp.broadcast_to(jnp.asarray(p1, jnp.float32), (n_cores,))
    sums = None
    for i in range(total_shots // batch):
        k = jax.random.fold_in(key, i)
        bits = (jax.random.uniform(k, (batch, n_cores, cfg.max_meas))
                < p1[None, :, None]).astype(jnp.int32)
        stats = sharded_cores_stat_sums(mp, bits, mesh,
                                        init_regs=init_regs, cfg=cfg)
        host = {name: np.asarray(v) for name, v in stats.items()}
        sums = host if sums is None else \
            {name: sums[name] + host[name] for name in sums}
    faults = {name: int(n) for (name, _), n
              in zip(FAULT_CODES, sums['fault_shots'])}
    if strict_faults and any(faults.values()):
        raise FaultError(sums['fault_shots'])
    return {
        'shots': total_shots,
        'engine': 'generic',     # the rung hosting the collective fabric
        'mean_pulses': sums['pulse_sum'] / total_shots,
        'err_rate': float(sums['err_shots'] / total_shots),
        'err_shots': int(sums['err_shots']),
        'mean_qclk': sums['qclk_sum'] / total_shots,
        'fault_shots': faults,
    }


def _ensemble_fingerprint(mmp, batch: int, key, cfg, init_regs, p1,
                          n_dp: int = 0) -> dict:
    """Sweep identity for the multi-program path: the CRC covers every
    operand plane of the STACKED ``[n_progs, n_cores, n_instr]``
    program tensor, so resuming with any member of the ensemble swapped
    (or reordered, or a different count) is rejected — a per-program
    fingerprint would accept a shuffled ensemble whose per-batch key
    stream no longer lines up with the accumulated statistics."""
    import dataclasses
    crc = 0
    for f in dataclasses.fields(mmp.soa):
        crc = zlib.crc32(
            np.ascontiguousarray(getattr(mmp.soa, f.name)).tobytes(), crc)
    regs_crc = 0 if init_regs is None else zlib.crc32(
        np.ascontiguousarray(np.asarray(init_regs)).tobytes())
    return {
        'fingerprint_version': FINGERPRINT_VERSION,
        'multi': True,
        'n_progs': int(mmp.n_progs),
        'batch': int(batch),
        'key': np.asarray(jax.random.key_data(key)).tolist(),
        'program_crc': int(crc),
        'p1': np.asarray(p1, np.float64).tolist(),
        'cfg': _jsonable(cfg),
        'init_regs_crc': int(regs_crc),
        'n_dp': int(n_dp),
    }


def run_multi_sweep(mps, total_shots: int, batch: int, p1=0.5,
                    key=0, cfg: InterpreterConfig = None,
                    init_regs=None, checkpoint: str = None,
                    checkpoint_every: int = 0, span: int = 1,
                    mesh=None, strict_resume: bool = False,
                    **cfg_kw) -> dict:
    """Injected-bits sweep over a PROGRAM ENSEMBLE: ``total_shots`` per
    program in ``batch``-sized steps, every batch one execution of the
    shape-bucketed multi-program executable (all ensemble members vmapped
    inside one jit — the compile-amortization path, see
    ``sim.interpreter.simulate_multi_batch``).

    Measurement bits are Bernoulli(``p1``) per (program, shot, core,
    slot) — ``p1`` a scalar or per-core array — exercising data-dependent
    control flow (active-reset branches) the way ``sample_meas_bits``
    feeds single programs.  The per-batch key folds the batch INDEX, so
    a resumed sweep reproduces the identical stream; with ``mesh``, the
    shot axis shards over ``dp`` and each shard folds its axis index.

    The checkpoint fingerprint covers the ENTIRE stacked ensemble (every
    operand plane of the ``[n_progs, n_cores, n_instr]`` tensor), so
    resuming with a changed, reordered, or re-padded ensemble fails
    loudly.

    ``span`` folds that many batches into one device dispatch exactly
    as in :func:`run_physics_sweep` — bit-identical stats, checkpoint
    writes snapping to span edges, span absent from the fingerprint.

    Returns per-program arrays: ``mean_pulses [n_progs, n_cores]``,
    ``err_rate [n_progs]``, ``err_shots [n_progs]`` (the summed int
    numerator behind ``err_rate`` — clean accounting matching
    ``run_physics_sweep``), ``mean_qclk [n_progs, n_cores]``, plus
    ``shots`` (per program), ``fault_shots`` (per-code name →
    ``[n_progs]`` trapped-shot counts) and ``incomplete_batches``.
    """
    from dataclasses import replace
    from ..decoder import MultiMachineProgram, stack_machine_programs
    from ..sim.interpreter import (_program_constants, _run_batch,
                                   program_traits)
    mmp = mps if isinstance(mps, MultiMachineProgram) \
        else stack_machine_programs(mps)
    if cfg is None:
        cfg_kw.setdefault('max_steps', 2 * mmp.n_instr + 64)
        cfg_kw.setdefault('max_pulses', mmp.n_instr + 2)
        cfg = InterpreterConfig(**cfg_kw)
    else:
        cfg = replace(cfg, **cfg_kw)
    # program-as-data path: the content-keyed engines (straightline,
    # block) would retrace per sequence — always the vmapped generic
    cfg = replace(cfg, record_pulses=False, straightline=False,
                  engine=None)
    cfg, strict_faults = _fault_policy(cfg)   # see run_physics_sweep
    if total_shots <= 0 or batch <= 0:
        raise ValueError(f'need positive total_shots/batch, got '
                         f'{total_shots}/{batch}')
    if total_shots % batch:
        raise ValueError(f'total_shots {total_shots} not divisible by '
                         f'batch {batch}')
    if span < 1:
        raise ValueError(f'span must be >= 1, got {span}')
    n_batches = total_shots // batch
    if isinstance(key, int):
        key = jax.random.PRNGKey(key)
    soa, spc, interp, sync_part = _program_constants(mmp, cfg)
    traits = program_traits(mmp)
    n_progs, n_cores = mmp.n_progs, mmp.n_cores
    p1 = jnp.broadcast_to(jnp.asarray(p1, jnp.float32), (n_cores,))
    if init_regs is not None:
        init_regs = np.asarray(init_regs, np.int32)
        if init_regs.ndim == 2:
            init_regs = np.broadcast_to(
                init_regs[None], (n_progs,) + init_regs.shape)
        if init_regs.shape[0] != n_progs:
            raise ValueError(
                f'init_regs leading axis {init_regs.shape[0]} != '
                f'n_progs {n_progs}')
    regs_dev = jnp.zeros((n_progs, n_cores, isa.N_REGS), jnp.int32) \
        if init_regs is None else jnp.asarray(init_regs)

    def local_stats(k, shots_here):
        bits = (jax.random.uniform(
            k, (n_progs, shots_here, n_cores, cfg.max_meas))
            < p1[None, None, :, None]).astype(jnp.int32)

        def one(s, sy, b, r):
            out = _run_batch(s, spc, interp, sy, b, cfg, n_cores,
                             jnp.broadcast_to(r[None],
                                              (shots_here,) + r.shape),
                             traits)
            return dict(pulse_sum=jnp.sum(out['n_pulses'], axis=0),
                        err_shots=jnp.sum(jnp.any(out['err'] != 0,
                                                  axis=1)),
                        qclk_sum=jnp.sum(out['qclk'], axis=0),
                        fault_shots=fault_shot_counts(out['fault']),
                        incomplete=out['incomplete'].astype(jnp.int32))
        return jax.vmap(one)(soa, sync_part, bits, regs_dev)

    if mesh is not None:
        from jax.sharding import PartitionSpec as P
        from .sweep import shard_map
        n_dp = mesh.shape['dp']
        if batch % n_dp:
            raise ValueError(f'batch {batch} not divisible by mesh '
                             f'dp={n_dp}')
        local_shots = batch // n_dp

        def local(k):
            k_local = jax.random.fold_in(k, jax.lax.axis_index('dp'))
            stats = local_stats(k_local, local_shots)
            stats = jax.tree.map(lambda x: jax.lax.psum(x, 'dp'), stats)
            # a program's batch is incomplete if ANY shard was
            stats['incomplete'] = jnp.minimum(stats['incomplete'], 1)
            return stats

        step = jax.jit(shard_map(local, mesh=mesh, in_specs=(P(),),
                                 out_specs=P(), check_vma=False))
    else:
        step = jax.jit(lambda k: local_stats(k, batch))

    meta = _ensemble_fingerprint(
        mmp, batch, key, cfg, init_regs, p1,
        mesh.shape['dp'] if mesh is not None else 0)
    if checkpoint and checkpoint_every <= 0:
        checkpoint_every = 1
    acc = SweepAccumulator.resume(checkpoint, checkpoint_every, meta=meta,
                                  strict=strict_resume) \
        if checkpoint else SweepAccumulator(meta=meta)
    if acc.n_batches > n_batches:
        raise ValueError(
            f'checkpoint already holds {acc.n_batches} batches '
            f'({acc.n_batches * batch} shots/program) > requested '
            f'{total_shots}')
    if span > 1:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .sweep import run_spanned
        run_spanned(step, acc, key, n_batches, span,
                    out_sharding=(NamedSharding(mesh, P())
                                  if mesh is not None else None))
    else:
        for i in range(acc.n_batches, n_batches):
            stats = step(jax.random.fold_in(key, i))
            acc.add({k: np.asarray(v) for k, v in stats.items()})
    if checkpoint:
        acc.save()

    shots_done = acc.n_batches * batch
    incomplete = int(np.sum(acc.state['incomplete']))
    if incomplete:
        import warnings
        warnings.warn(
            f'{incomplete} (program, batch) pairs contain shots that '
            f'did not finish (step budget); means include their partial '
            f'counts — raise max_steps or treat them as lower bounds',
            stacklevel=2)
    fault_pp = np.asarray(acc.state['fault_shots'])   # [n_progs, n_codes]
    if strict_faults and fault_pp.any():
        raise FaultError(fault_pp.sum(axis=0))
    return {
        'shots': shots_done,
        'n_progs': n_progs,
        'engine': 'generic',     # program-as-data path (see above)
        'mean_pulses': acc.state['pulse_sum'] / shots_done,
        'err_rate': acc.state['err_shots'] / shots_done,
        # the integer numerator behind err_rate, per program — exact
        # accounting a rate cannot carry (run_physics_sweep parity)
        'err_shots': np.asarray(acc.state['err_shots']).copy(),
        'mean_qclk': acc.state['qclk_sum'] / shots_done,
        # per-program per-code trapped-shot counts, keyed by code name
        # (run_physics_sweep parity; arrays because this is an ensemble)
        'fault_shots': {name: fault_pp[:, i].copy() for i, (name, _)
                        in enumerate(FAULT_CODES)},
        'incomplete_batches': incomplete,
    }
