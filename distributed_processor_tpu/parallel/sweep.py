"""Sharded sweep execution: shots / sweep points over the device mesh.

The data-parallel story of the framework (SURVEY §2.3): the reference
re-runs programs host-side for every shot and sweep point; here they are
a sharded batch axis.  ``shard_map`` partitions the shot axis over the
mesh ``'dp'`` axis, each device vmaps the interpreter over its local
shots, and summary statistics come back through ``psum`` over ICI.
"""

from __future__ import annotations

import functools
import inspect

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
try:
    from jax import shard_map as _shard_map
except ImportError:      # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

# the replication-check kwarg was renamed check_rep -> check_vma across
# jax versions; every caller here uses the new name, so translate it
# when running on a jax that only knows the old one
if 'check_vma' in inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:                    # pragma: no cover - depends on jax version
    @functools.wraps(_shard_map)
    def shard_map(f, *args, check_vma=None, **kw):
        if check_vma is not None:
            kw['check_rep'] = check_vma
        return _shard_map(f, *args, **kw)

from .. import isa
from ..sim.interpreter import (InterpreterConfig, _program_constants,
                               _run_batch, _run_batch_engine, _pad_meas,
                               _soa_static, resolve_engine, carry_packspec,
                               use_packed_carry, fault_shot_counts,
                               program_traits, _fault_policy,
                               _check_strict, _check_single_round)
from ..utils.profiling import counter_inc


def _mesh_engine(mp, cfg: InterpreterConfig, trim_regs: bool = True):
    """``(engine, prog, pack)`` for the shard-local executor.  The
    sharded paths predate the engine ladder and always ran the generic
    engine; ``cfg.engine=None`` keeps that default (no auto-upgrade),
    while an explicit engine resolves through the same ladder as
    simulate_batch and runs inside every shard's local jit — including
    the pallas rung's bit-packed carry layout (``pack``, a host-static
    :func:`~..sim.interpreter.carry_packspec` tuple)."""
    if cfg.engine is None:
        return 'generic', None, None
    eng = resolve_engine(mp, cfg)
    pack = carry_packspec(mp, cfg, trim_regs=trim_regs) \
        if eng == 'pallas' and use_packed_carry(cfg) else None
    return eng, (_soa_static(mp) if eng != 'generic' else None), pack


def _shotwise_init_regs(init_regs, n_shots, n_cores):
    """Normalize ``init_regs`` to ``[n_shots, n_cores, N_REGS]`` int32,
    broadcasting the 2-D per-core form the way ``simulate_batch`` does
    (shard_map shards axis 0, so it must be the shot axis)."""
    if init_regs is None:
        return jnp.zeros((n_shots, n_cores, isa.N_REGS), jnp.int32)
    init_regs = jnp.asarray(init_regs, jnp.int32)
    if init_regs.ndim == 2:
        init_regs = jnp.broadcast_to(init_regs[None],
                                     (n_shots,) + init_regs.shape)
    if init_regs.shape[0] != n_shots:
        raise ValueError(
            f'init_regs leading axis {init_regs.shape[0]} != n_shots '
            f'{n_shots} (pass [n_shots, n_cores, n_regs] or the 2-D '
            f'per-core form)')
    return init_regs


def sharded_simulate(mp, meas_bits, mesh, init_regs=None,
                     cfg: InterpreterConfig = None, **kw):
    """Run a shot batch sharded over the mesh dp axis.

    ``meas_bits``: ``[n_shots, n_cores, n_meas]`` with n_shots divisible
    by the dp axis size.  Returns the same pytree as ``simulate_batch``,
    with outputs sharded over shots.
    """
    from dataclasses import replace
    cfg = replace(cfg, **kw) if cfg else InterpreterConfig(**kw)
    soa, spc, interp, sync_part = _program_constants(mp, cfg)
    meas_bits = _pad_meas(meas_bits, cfg.max_meas)
    eng, prog, pack = _mesh_engine(mp, cfg, trim_regs=init_regs is None)

    def local(mb, ir):
        out = _run_batch_engine(soa, spc, interp, sync_part, mb, cfg,
                                mp.n_cores, ir, engine=eng, prog=prog,
                                pack=pack)
        # drop scalar diagnostics: every remaining leaf is shot-leading
        out.pop('steps')
        out.pop('incomplete')
        out.pop('op_hist', None)
        return out

    init_regs = _shotwise_init_regs(init_regs, meas_bits.shape[0],
                                    mp.n_cores)

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P('dp'), P('dp')), out_specs=P('dp'),
                   check_vma=False)
    return jax.jit(fn)(meas_bits, init_regs)


def sweep_stat_sums(mp, meas_bits, mesh, init_regs=None,
                    cfg: InterpreterConfig = None, **kw):
    """The un-normalized integer sums under :func:`sweep_stats`:
    ``pulse_sum [n_cores]``, ``err_shots``, ``qclk_sum [n_cores]``,
    ``fault_shots`` — psum-reduced over the mesh's dp axis only.

    This is the multi-controller building block: on a host-local mesh
    each process computes its shard's exact integer sums here and the
    final cross-host reduction rides the coordination-service KV store
    (:func:`.multihost.cross_host_sum`) instead of an XLA collective —
    integer addition in a deterministic process order, so the global
    statistics are bit-identical on every controller AND to a
    single-process run of the same global batch (the CPU backend
    cannot jit multiprocess computations at all, which is why the DCN
    hop happens on the host).
    """
    from dataclasses import replace
    cfg = replace(cfg, **kw) if cfg else InterpreterConfig(**kw)
    _check_single_round(cfg)
    # statistics only ever reduce n_pulses/err/qclk — don't carry the
    # [B, C, 9*max_pulses] record state through the while_loop
    cfg = replace(cfg, record_pulses=False)
    soa, spc, interp, sync_part = _program_constants(mp, cfg)
    meas_bits = _pad_meas(meas_bits, cfg.max_meas)
    n_shots = meas_bits.shape[0]

    trim_regs = init_regs is None
    init_regs = _shotwise_init_regs(init_regs, n_shots, mp.n_cores)
    eng, prog, pack = _mesh_engine(mp, cfg, trim_regs=trim_regs)

    def local(mb, ir):
        out = _run_batch_engine(soa, spc, interp, sync_part, mb, cfg,
                                mp.n_cores, ir, engine=eng, prog=prog,
                                pack=pack)
        pulse_sum = jnp.sum(out['n_pulses'], axis=0)      # [n_cores]
        err_shots = jnp.sum(jnp.any(out['err'] != 0, axis=1))
        qclk_sum = jnp.sum(out['qclk'], axis=0)
        stats = dict(pulse_sum=pulse_sum, err_shots=err_shots,
                     qclk_sum=qclk_sum,
                     fault_shots=fault_shot_counts(out['fault']))
        return jax.tree.map(lambda x: jax.lax.psum(x, 'dp'), stats)

    fn = shard_map(local, mesh=mesh, in_specs=(P('dp'), P('dp')),
                   out_specs=P(), check_vma=False)
    return jax.jit(fn)(meas_bits, init_regs)


def sweep_stats(mp, meas_bits, mesh, init_regs=None,
                cfg: InterpreterConfig = None, **kw):
    """Sharded run reduced to global statistics (no per-shot outputs
    leave the devices): mean pulse counts, error rate, mean final qclk.

    The reduction is a ``psum`` over the dp axis — the ICI-collective
    path that replaces the reference's host-side accumulation.
    """
    n_shots = np.asarray(meas_bits).shape[0]
    out = sweep_stat_sums(mp, meas_bits, mesh, init_regs=init_regs,
                          cfg=cfg, **kw)
    return dict(mean_pulses=out['pulse_sum'] / n_shots,
                err_rate=out['err_shots'] / n_shots,
                mean_qclk=out['qclk_sum'] / n_shots,
                fault_shots=out['fault_shots'])


# ---------------------------------------------------------------------------
# sharded-cores execution (docs/PERF.md "ICI fabric"): ONE program's
# core axis over the mesh 'cores' axis.  The per-core interpreter lanes
# run on different devices; the fproc fabric and sync barrier read
# producer-side state through lax.all_gather collectives inside the
# epoch loop (sim/interpreter.py _step under cfg.cores_axis) — the ICI
# stand-in for the gateware's sync_iface/fproc wiring, bit-identical to
# the single-device generic engine by construction.


def _cores_cfg(mp, mesh, cfg: InterpreterConfig) -> InterpreterConfig:
    """Validate + normalize a config for sharded-cores execution on
    ``mesh``: the mesh must carry ``('dp', 'cores')`` axes, the
    program's core count must split evenly over the cores axis, and
    the (mp, cfg) pair must be eligible —
    :func:`~..sim.interpreter.resolve_engine` raises with the blocker
    (:func:`~..sim.interpreter.cores_ineligible` names it) otherwise."""
    from dataclasses import replace
    for axis in ('dp', 'cores'):
        if axis not in mesh.axis_names:
            raise ValueError(
                f"sharded-cores execution needs a ('dp', 'cores') mesh "
                f'(parallel.mesh.make_cores_mesh); got axes '
                f'{tuple(mesh.axis_names)}')
    if cfg.cores_axis is None:
        cfg = replace(cfg, cores_axis='cores')
    elif cfg.cores_axis != 'cores':
        raise ValueError(
            f"cfg.cores_axis={cfg.cores_axis!r} does not name this "
            f"mesh's 'cores' axis")
    n_shards = mesh.shape['cores']
    if mp.n_cores % n_shards:
        raise ValueError(
            f'{mp.n_cores} program cores not divisible over the '
            f'cores axis ({n_shards} shards)')
    resolve_engine(mp, cfg)       # raises with the named blocker
    return cfg


# the executors are cached per (mesh, cfg, traits) — NOT per program:
# the program tensor and per-core constants are traced arguments, so
# every same-shape program shares one trace and the retrace contract is
# at most one per mesh shape (the 'cores_trace' counter +
# tests/test_ici_fabric.py pin it)
_CORES_SPECS = (P('cores'), P('cores'), P('cores'), P(),
                P('dp', 'cores'), P('dp', 'cores'))


@functools.lru_cache(maxsize=64)
def _cores_executor(mesh, cfg: InterpreterConfig, traits):
    """Full-output executor: program planes / per-core constants shard
    along 'cores' (axis 0); ``sync_part`` stays replicated full-width
    (the barrier needs every participant); shots shard along 'dp' with
    the core axis of meas_bits/init_regs along 'cores'."""

    def local(soa, spc, interp, sync_part, mb, ir):
        counter_inc('cores_trace')
        out = _run_batch(soa, spc, interp, sync_part, mb, cfg,
                         int(soa.shape[0]), ir, traits)
        # drop scalar diagnostics: every remaining leaf is [B, C, ...]
        out.pop('steps')
        out.pop('incomplete')
        out.pop('op_hist', None)
        return out

    fn = shard_map(local, mesh=mesh, in_specs=_CORES_SPECS,
                   out_specs=P('dp', 'cores'), check_vma=False)
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _cores_stats_executor(mesh, cfg: InterpreterConfig, traits):
    """Stats executor: per-core partial sums concatenate to full width
    over 'cores' (tiled all_gather — a deterministic shard-order
    concat, NOT a reduction: each core's sum lives on exactly one
    shard), cross-core folds (err/fault are any-over-cores) gather
    FIRST so every shard folds the identical full-width words, and
    only the shot axis reduces with a ``psum`` (over 'dp')."""

    def local(soa, spc, interp, sync_part, mb, ir):
        counter_inc('cores_trace')
        out = _run_batch(soa, spc, interp, sync_part, mb, cfg,
                         int(soa.shape[0]), ir, traits)
        gat = lambda x, a: jax.lax.all_gather(x, 'cores', axis=a,
                                              tiled=True)
        stats = dict(
            pulse_sum=gat(jnp.sum(out['n_pulses'], axis=0), 0),
            err_shots=jnp.sum(jnp.any(gat(out['err'], 1) != 0, axis=1)),
            qclk_sum=gat(jnp.sum(out['qclk'], axis=0), 0),
            fault_shots=fault_shot_counts(gat(out['fault'], 1)))
        return jax.tree.map(lambda x: jax.lax.psum(x, 'dp'), stats)

    fn = shard_map(local, mesh=mesh, in_specs=_CORES_SPECS,
                   out_specs=P(), check_vma=False)
    return jax.jit(fn)


def _cores_args(mp, meas_bits, mesh, init_regs, cfg):
    """Shared argument prep for the sharded-cores entry points."""
    soa, spc, interp, sync_part = _program_constants(mp, cfg)
    meas_bits = _pad_meas(meas_bits, cfg.max_meas)
    n_shots = meas_bits.shape[0]
    n_dp = mesh.shape['dp']
    if n_shots % n_dp:
        raise ValueError(f'{n_shots} shots not divisible by dp={n_dp}')
    init_regs = _shotwise_init_regs(init_regs, n_shots, mp.n_cores)
    return soa, spc, interp, sync_part, meas_bits, init_regs


@functools.lru_cache(maxsize=64)
def _cores_block_executor(mesh, cfg: InterpreterConfig, prog):
    """GSPMD block-engine executor for ``engine='block'`` under a
    cores mesh: the block-compiled engine traces as the ordinary
    single-device computation (``cores_axis`` cleared — block
    boundary steps gather full-width state, so no shard-local
    collectives are needed) and XLA partitions it over the sharded
    inputs (:func:`_run_cores_block` places them ``P('cores')`` /
    ``P('dp', 'cores')``).  Same trace as the local block engine, so
    bit-identity with it is by construction; cached per
    (mesh, cfg, prog) — the static program specializes the block
    table, exactly like ``_run_batch_blk_jit``'s content key."""
    from dataclasses import replace
    lcfg = replace(cfg, cores_axis=None)

    def local(spc, interp, sync_part, mb, ir):
        counter_inc('cores_trace')
        out = _run_batch_engine(None, spc, interp, sync_part, mb, lcfg,
                                int(mb.shape[1]), ir, engine='block',
                                prog=prog)
        # drop scalar diagnostics: every remaining leaf is [B, C, ...]
        out.pop('steps')
        out.pop('incomplete')
        out.pop('op_hist', None)
        return out

    return jax.jit(local,
                   out_shardings=NamedSharding(mesh, P('dp', 'cores')))


def _run_cores_block(mp, mesh, cfg, args):
    """Place the prepared sharded-cores arguments for GSPMD and run
    the block executor: per-core constants along 'cores', shot/core
    batch planes along ('dp', 'cores'), ``sync_part`` replicated."""
    soa, spc, interp, sync_part, mb, ir = args
    put = lambda x, spec: jax.device_put(x, NamedSharding(mesh, spec))
    return _cores_block_executor(mesh, cfg, _soa_static(mp))(
        put(spc, P('cores')), put(interp, P('cores')),
        put(sync_part, P()), put(mb, P('dp', 'cores')),
        put(ir, P('dp', 'cores')))


def sharded_cores_simulate(mp, meas_bits, mesh, init_regs=None,
                           cfg: InterpreterConfig = None, **kw):
    """Run ONE program with its core axis sharded over the mesh
    ``'cores'`` axis (shots still shard over ``'dp'``): the per-core
    interpreter lanes run on different devices and the fproc/sync
    barrier is ``lax`` collectives inside the epoch loop — the real
    distributed processor, with ICI standing in for the gateware's
    ``sync_iface``/``fproc`` fabric.  Bit-identical per stat (fault
    words included) to the single-device generic engine by
    construction; tests/test_ici_fabric.py pins it on the golden
    suite.

    ``meas_bits``: ``[n_shots, n_cores, n_meas]`` with ``n_shots``
    divisible by the dp axis size and ``n_cores`` divisible by the
    cores axis size.  Returns the ``simulate_batch`` pytree (minus the
    scalar diagnostics), sharded ``P('dp', 'cores')``.
    """
    from dataclasses import replace
    cfg = replace(cfg, **kw) if cfg else InterpreterConfig(**kw)
    _check_single_round(cfg)
    cfg, strict = _fault_policy(cfg)
    cfg = _cores_cfg(mp, mesh, cfg)
    args = _cores_args(mp, meas_bits, mesh, init_regs, cfg)
    if resolve_engine(mp, cfg) == 'block':
        out = _run_cores_block(mp, mesh, cfg, args)
    else:
        out = _cores_executor(mesh, cfg, program_traits(mp))(*args)
    return _check_strict(out, strict)


def sharded_cores_stat_sums(mp, meas_bits, mesh, init_regs=None,
                            cfg: InterpreterConfig = None, **kw):
    """The un-normalized integer sums under
    :func:`sharded_cores_stats` (``sweep_stat_sums`` parity:
    ``pulse_sum [n_cores]``, ``err_shots``, ``qclk_sum [n_cores]``,
    ``fault_shots``), computed with the core axis sharded over the
    mesh ``'cores'`` axis and shots over ``'dp'``.  Replicated
    outputs (``out_specs=P()``)."""
    from dataclasses import replace
    cfg = replace(cfg, **kw) if cfg else InterpreterConfig(**kw)
    _check_single_round(cfg)
    # statistics only ever reduce n_pulses/err/qclk — don't carry the
    # [B, C, 9*max_pulses] record state through the while_loop
    cfg = replace(cfg, record_pulses=False)
    cfg = _cores_cfg(mp, mesh, cfg)
    args = _cores_args(mp, meas_bits, mesh, init_regs, cfg)
    if resolve_engine(mp, cfg) == 'block':
        return _cores_block_stat_reduce(_run_cores_block(mp, mesh, cfg,
                                                         args))
    return _cores_stats_executor(mesh, cfg, program_traits(mp))(*args)


# sharded-cores rounds scan: the streaming-QEC round axis (leading,
# replicated — every shard scans the same round schedule over its own
# shot/core tile) composes with the ('dp', 'cores') layout
_CORES_ROUNDS_SPECS = (P('cores'), P('cores'), P('cores'), P(),
                       P(None, 'dp', 'cores'), P('dp', 'cores'))


@functools.lru_cache(maxsize=64)
def _cores_rounds_executor(mesh, cfg: InterpreterConfig, traits):
    """R-round scan around the sharded-cores local: each scan step is
    exactly the :func:`_cores_executor` local body (bit-identity per
    round by construction), with the round axis carried by the scan so
    R rounds on the mesh are still ONE dispatch."""

    def local(soa, spc, interp, sync_part, mb, ir):
        counter_inc('cores_trace')

        def body(carry, mbr):
            out = _run_batch(soa, spc, interp, sync_part, mbr, cfg,
                             int(soa.shape[0]), ir, traits)
            out.pop('steps')
            out.pop('incomplete')
            out.pop('op_hist', None)
            return carry, out

        _, st = jax.lax.scan(body, jnp.int32(0), mb)
        return st

    fn = shard_map(local, mesh=mesh, in_specs=_CORES_ROUNDS_SPECS,
                   out_specs=P(None, 'dp', 'cores'), check_vma=False)
    return jax.jit(fn)


@functools.lru_cache(maxsize=64)
def _cores_rounds_block_executor(mesh, cfg: InterpreterConfig, prog):
    """GSPMD block-engine rounds executor: the scan body is the same
    single-device block trace :func:`_cores_block_executor` runs, and
    XLA partitions each step over the sharded inputs."""
    from dataclasses import replace
    lcfg = replace(cfg, cores_axis=None, rounds=1)

    def local(spc, interp, sync_part, mb, ir):
        counter_inc('cores_trace')

        def body(carry, mbr):
            out = _run_batch_engine(None, spc, interp, sync_part, mbr,
                                    lcfg, int(mbr.shape[1]), ir,
                                    engine='block', prog=prog)
            out.pop('steps')
            out.pop('incomplete')
            out.pop('op_hist', None)
            return carry, out

        _, st = jax.lax.scan(body, jnp.int32(0), mb)
        return st

    return jax.jit(
        local, out_shardings=NamedSharding(mesh, P(None, 'dp', 'cores')))


def sharded_cores_rounds(mp, meas_bits, mesh, init_regs=None,
                         cfg: InterpreterConfig = None, **kw):
    """R rounds of :func:`sharded_cores_simulate` in ONE dispatch:
    ``meas_bits`` is ``[rounds, n_shots, n_cores, n_meas]`` and a
    ``lax.scan`` over the leading round axis runs the sharded-cores
    body once per round — the mesh composition of
    :func:`~..sim.interpreter.simulate_rounds` (docs/PERF.md
    "Streaming QEC"), for codes too wide for one device.  Each round
    starts from a fresh init state with that round's injected bits;
    ``init_regs`` is shared across rounds.  Returns the
    :func:`sharded_cores_simulate` pytree with a leading round axis on
    every leaf, sharded ``P(None, 'dp', 'cores')``."""
    from dataclasses import replace
    cfg = replace(cfg, **kw) if cfg else InterpreterConfig(**kw)
    cfg, strict = _fault_policy(cfg)
    meas_bits = jnp.asarray(meas_bits, jnp.int32)
    if meas_bits.ndim != 4 or meas_bits.shape[2] != mp.n_cores:
        raise ValueError(
            f'meas_bits must be [rounds, n_shots, n_cores='
            f'{mp.n_cores}, n_meas]; got {tuple(meas_bits.shape)}')
    R = int(meas_bits.shape[0])
    if cfg.rounds != 1 and cfg.rounds != R:
        raise ValueError(
            f'cfg.rounds={cfg.rounds} contradicts the meas_bits round '
            f'axis {R}')
    cfg = replace(cfg, rounds=R)
    cfg = _cores_cfg(mp, mesh, cfg)
    soa, spc, interp, sync_part = _program_constants(mp, cfg)
    meas_bits = _pad_meas(meas_bits, cfg.max_meas)
    n_shots = meas_bits.shape[1]
    n_dp = mesh.shape['dp']
    if n_shots % n_dp:
        raise ValueError(f'{n_shots} shots not divisible by dp={n_dp}')
    init_regs = _shotwise_init_regs(init_regs, n_shots, mp.n_cores)
    if resolve_engine(mp, cfg) == 'block':
        put = lambda x, spec: jax.device_put(x, NamedSharding(mesh,
                                                              spec))
        out = _cores_rounds_block_executor(mesh, cfg, _soa_static(mp))(
            put(spc, P('cores')), put(interp, P('cores')),
            put(sync_part, P()), put(meas_bits, P(None, 'dp', 'cores')),
            put(init_regs, P('dp', 'cores')))
    else:
        out = _cores_rounds_executor(mesh, cfg, program_traits(mp))(
            soa, spc, interp, sync_part, meas_bits, init_regs)
    return _check_strict(out, strict)


@jax.jit
def _cores_block_stat_reduce(out):
    """``sharded_cores_stat_sums`` reduction for the GSPMD block path:
    the executor's outputs are already full-width per shard-view, so
    the sums are plain reductions (XLA inserts the cross-device
    collectives from the output shardings)."""
    return dict(
        pulse_sum=jnp.sum(out['n_pulses'], axis=0),
        err_shots=jnp.sum(jnp.any(out['err'] != 0, axis=1)),
        qclk_sum=jnp.sum(out['qclk'], axis=0),
        fault_shots=fault_shot_counts(out['fault']))


def sharded_cores_stats(mp, meas_bits, mesh, init_regs=None,
                        cfg: InterpreterConfig = None, **kw):
    """Sharded-cores run reduced to global statistics
    (:func:`sweep_stats` parity: mean pulse counts, error rate, mean
    final qclk, per-code fault counts)."""
    n_shots = np.asarray(meas_bits).shape[0]
    out = sharded_cores_stat_sums(mp, meas_bits, mesh,
                                  init_regs=init_regs, cfg=cfg, **kw)
    return dict(mean_pulses=out['pulse_sum'] / n_shots,
                err_rate=out['err_shots'] / n_shots,
                mean_qclk=out['qclk_sum'] / n_shots,
                fault_shots=out['fault_shots'])


def physics_batch_stats(out: dict) -> dict:
    """The per-batch reductions every physics-stats path shares:
    per-core pulse sums, first-slot measured-1 sums, errored shots, and
    the JOINT all-zeros count (``allzero_sum`` — the survival
    numerator of multi-qubit RB, which per-core marginals cannot
    express).

    ``allzero_sum`` counts only CLEAN, fully-measured shots: a shot
    with any error bit, or with any core's first slot never resolved
    (its bit would sit at the 0 default), must not inflate an RB
    survival estimate — so the statistic implies the every-core-reads
    program shape, and a program with spectator cores reads 0 here.
    ``clean_shots`` is the matching DENOMINATOR: a survival rate of
    clean numerator over total shots would bias low by exactly the
    errored/unresolved fraction.
    """
    first = out['meas_bits'][:, :, 0]
    clean = ~jnp.any(out['err'] != 0, axis=1) \
        & jnp.all(out['meas_bits_valid'][:, :, 0], axis=1)
    return dict(
        pulse_sum=jnp.sum(out['n_pulses'], axis=0),
        meas1_sum=jnp.sum(first, axis=0),
        allzero_sum=jnp.sum((jnp.all(first == 0, axis=1)
                             & clean).astype(jnp.int32)),
        clean_shots=jnp.sum(clean.astype(jnp.int32)),
        err_shots=jnp.sum(jnp.any(out['err'] != 0, axis=1)),
        fault_shots=fault_shot_counts(out['fault']),
    )


def sharded_multi_stats(mps, meas_bits, mesh, init_regs=None,
                        cfg: InterpreterConfig = None, **kw):
    """Multi-program ensemble reduced to per-program statistics on the
    mesh: programs ride a vmapped leading axis inside ONE compiled
    executable (same shape-bucketed program-as-data tensor as
    ``simulate_multi_batch``), shots shard over the ``dp`` axis, and
    only psum-reduced sums reach the host.

    ``mps``: list of MachinePrograms or a stacked MultiMachineProgram.
    ``meas_bits``: ``[n_progs, n_shots, n_cores, n_meas]`` with
    ``n_shots`` divisible by the dp axis size.  ``init_regs``: optional
    ``[n_progs, n_cores, 16]`` per-program register file.

    Returns ``mean_pulses [n_progs, n_cores]``, ``err_rate [n_progs]``,
    ``mean_qclk [n_progs, n_cores]``.
    """
    from dataclasses import replace
    from ..decoder import MultiMachineProgram, stack_machine_programs
    from ..sim.interpreter import _program_constants, program_traits
    mmp = mps if isinstance(mps, MultiMachineProgram) \
        else stack_machine_programs(mps)
    if cfg is None:
        kw.setdefault('max_steps', 2 * mmp.n_instr + 64)
        kw.setdefault('max_pulses', mmp.n_instr + 2)
        cfg = InterpreterConfig(**kw)
    else:
        cfg = replace(cfg, **kw)
    # program-as-data path: content-keyed engines would defeat the
    # bucket amortization, so the vmapped generic engine always runs
    cfg = replace(cfg, record_pulses=False, straightline=False,
                  engine=None)
    soa, spc, interp, sync_part = _program_constants(mmp, cfg)
    traits = program_traits(mmp)
    n_progs, n_cores = mmp.n_progs, mmp.n_cores
    meas_bits = _pad_meas(meas_bits, cfg.max_meas)
    if meas_bits.ndim != 4 or meas_bits.shape[0] != n_progs:
        raise ValueError(
            f'meas_bits must be [n_progs={n_progs}, n_shots, n_cores, '
            f'n_meas]; got {tuple(meas_bits.shape)}')
    n_shots = meas_bits.shape[1]
    n_dp = mesh.shape['dp']
    if n_shots % n_dp:
        raise ValueError(f'{n_shots} shots not divisible by dp={n_dp}')
    if init_regs is None:
        init_regs = jnp.zeros((n_progs, n_cores, isa.N_REGS), jnp.int32)
    init_regs = jnp.asarray(init_regs, jnp.int32)

    def local(mb, ir):
        def one(s, sy, b, r):
            out = _run_batch(s, spc, interp, sy, b, cfg, n_cores,
                             jnp.broadcast_to(r[None],
                                              (b.shape[0],) + r.shape),
                             traits)
            return dict(pulse_sum=jnp.sum(out['n_pulses'], axis=0),
                        err_shots=jnp.sum(jnp.any(out['err'] != 0,
                                                  axis=1)),
                        qclk_sum=jnp.sum(out['qclk'], axis=0),
                        fault_shots=fault_shot_counts(out['fault']))
        stats = jax.vmap(one)(soa, sync_part, mb, ir)
        return jax.tree.map(lambda x: jax.lax.psum(x, 'dp'), stats)

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(None, 'dp'), P()), out_specs=P(),
                   check_vma=False)
    out = jax.jit(fn)(meas_bits, init_regs)
    return dict(mean_pulses=out['pulse_sum'] / n_shots,
                err_rate=out['err_shots'] / n_shots,
                mean_qclk=out['qclk_sum'] / n_shots,
                fault_shots=out['fault_shots'])


def sharded_physics_stat_sums(mp, model, key, shots: int, mesh,
                              dp_offset: int = 0, cfg=None, **kw):
    """The un-normalized sums under :func:`sharded_physics_stats`
    (psum-reduced over this mesh's dp axis only; see
    :func:`physics_batch_stats` for the fields).

    ``dp_offset`` places this mesh's dp rows on a larger GLOBAL dp
    grid for key derivation: shard *i* folds ``i + dp_offset`` into
    ``key``, so a host-local mesh computing rows ``[offset, offset +
    n_dp)`` of a multi-controller run draws exactly the noise streams
    the equivalent single-process global mesh would — per-shard
    computations are identical and the cross-host sum of these
    integers reproduces the single-process statistics bit-for-bit.
    ``shots`` is THIS mesh's shot count (``n_dp * local_shots``).
    """
    from ..sim.physics import run_physics_batch
    from dataclasses import replace
    from ..sim.interpreter import InterpreterConfig
    cfg = replace(cfg, **kw) if cfg else InterpreterConfig(**kw)
    cfg = replace(cfg, record_pulses=False)   # stats never read rec_*
    n_dp = mesh.shape['dp']
    if shots % n_dp:
        raise ValueError(f'{shots} shots not divisible by dp={n_dp}')
    local_shots = shots // n_dp
    if isinstance(key, int):
        key = jax.random.PRNGKey(key)

    def local():
        k_local = jax.random.fold_in(
            key, jax.lax.axis_index('dp') + dp_offset)
        out = run_physics_batch(mp, model, k_local, local_shots, cfg=cfg)
        return jax.tree.map(lambda x: jax.lax.psum(x, 'dp'),
                            physics_batch_stats(out))

    fn = shard_map(local, mesh=mesh, in_specs=(), out_specs=P(),
                   check_vma=False)
    return jax.jit(fn)()


def sharded_physics_stats(mp, model, key, shots: int, mesh,
                          cfg=None, **kw):
    """Physics-closed execution sharded over the mesh dp axis: every
    shard runs its own epoch loop (thermal sampling -> interpretation ->
    window synthesis -> matched-filter demod -> branch resolution, see
    sim/physics.py) on its local shots, statistics psum over ICI.

    The epoch while_loop's completion test is shard-local, so shards
    finish independently — no cross-shard synchronisation beyond the
    final reduction.  Each shard derives its noise key by folding the
    dp axis index into ``key``.

    Returns mean_pulses [n_cores], err_rate, meas1_rate [n_cores]
    (fraction of first-slot measurement bits reading 1).
    """
    out = sharded_physics_stat_sums(mp, model, key, shots, mesh,
                                    cfg=cfg, **kw)
    return dict(mean_pulses=out['pulse_sum'] / shots,
                err_rate=out['err_shots'] / shots,
                meas1_rate=out['meas1_sum'] / shots,
                fault_shots=out['fault_shots'])


def sharded_demod(adc, weights, mesh):
    """Demod with shots over 'dp' and the sample contraction over 'mp':
    each device holds a ``[S/dp, N/mp]`` ADC block and a ``[N/mp, 2M]``
    weight block; partial products psum over 'mp' (ICI reduce)."""

    def local(a, w):
        acc = a @ w
        acc = jax.lax.psum(acc, 'mp')
        return acc.reshape(acc.shape[0], -1, 2)

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P('dp', 'mp'), P('mp', None)),
                   out_specs=P('dp'), check_vma=False)
    return jax.jit(fn)(jnp.asarray(adc, jnp.float32),
                       jnp.asarray(weights, jnp.float32))


def run_spanned(step, acc, key, n_batches: int, span: int,
                out_sharding=None) -> None:
    """Drive a per-batch stats ``step`` (``key -> pytree of int32
    sums``) from ``acc.n_batches`` up to ``n_batches`` with ``span``
    batches folded into each dispatch
    (:func:`..sim.interpreter.make_span_runner`), pipelined 1 deep:
    span ``j+1`` is dispatched BEFORE span ``j``'s sums are fetched, so
    the host-side fold and checkpoint write of span ``j`` overlap span
    ``j+1``'s device execution.

    Span starts stay on the ABSOLUTE batch grid (indices that are
    multiples of ``span``): a resume landing mid-span first runs the
    partial span completing its grid cell, so checkpoint boundaries —
    and the set of compiled span sizes (at most full + leading partial
    + trailing partial) — are independent of where a previous run
    stopped.

    Two carry buffers ping-pong through the runner: each is donated to
    a dispatch, fetched to host numpy only after the NEXT dispatch is
    in flight, and re-donated only after that fetch — no buffer is read
    after donation.  ``out_sharding`` (e.g. ``NamedSharding(mesh,
    P())`` for a psum-reduced mesh step) places the initial carries
    where the step's outputs live, so donation can alias them.
    """
    from ..sim.interpreter import make_span_runner
    runner = make_span_runner(step)
    shapes = jax.eval_shape(step, key)

    def make_carry():
        zeros = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype),
                             shapes)
        return zeros if out_sharding is None \
            else jax.device_put(zeros, out_sharding)

    donors = [make_carry(), make_carry()]
    in_flight = None
    i = acc.n_batches
    while i < n_batches:
        size = min(span - i % span, n_batches - i)
        cur = runner(donors.pop(0), key, jnp.int32(i), span=size)
        if in_flight is not None:
            stats, n = in_flight
            host = {k: np.asarray(v) for k, v in stats.items()}
            donors.append(stats)          # re-donate AFTER the fetch
            acc.add_span(host, n)         # overlaps `cur` on device
        in_flight = (cur, size)
        i += size
    if in_flight is not None:
        stats, n = in_flight
        acc.add_span({k: np.asarray(v) for k, v in stats.items()}, n)
