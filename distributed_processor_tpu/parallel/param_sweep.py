"""Register-parameterized sweeps: hardware-style parameter scans as data.

The reference sweeps parameters by recompiling per point host-side (or
by register-writing between runs); here a sweep axis is *data*: the
program reads pulse parameters from processor registers, and the
initial register file varies per sweep point / shot
(``init_regs[point, core, reg]``).  One compile, one jit — the 2D
amplitude x frequency grid of BASELINE config 5 is a single sharded
batch.

Reference mechanism: register-sourced pulse parameters
(hdl/pulse_reg.sv:73-82; assembler reg params assembler.py:319-335).
"""

from __future__ import annotations

import numpy as np

from .. import isa
from ..decoder import machine_program_from_cmds
from ..sim.interpreter import InterpreterConfig
from ..sim.oracle import START_NCLKS


AMP_REG = 0    # register holding the swept amplitude word
FREQ_REG = 1   # register holding the swept frequency-buffer address
RDLO_ELEM = 2


def swept_pulse_machine_program(n_cores: int, env_word: int = (3 << 12),
                                n_pulses: int = 1, spacing: int = 40,
                                readout: bool = True, elem_cfgs=None):
    """Build a machine program whose drive amplitude and frequency come
    from registers AMP_REG / FREQ_REG (per-core), repeated ``n_pulses``
    times, optionally followed by a readout pulse.

    Pulse parameters that sweep are *not* in the program text — only the
    register indices are, so a full 2D grid runs from one compilation.
    """
    cores = []
    for _ in range(n_cores):
        cmds = []
        t = START_NCLKS
        for _ in range(n_pulses):
            # two-instruction reg-parameterized pulse (one reg per instr,
            # reference: assembler.py:319-335 multi-reg split)
            cmds.append(isa.pulse_cmd(amp_regaddr=AMP_REG))
            cmds.append(isa.pulse_cmd(freq_regaddr=FREQ_REG, phase_word=0,
                                      env_word=env_word, cfg_word=0,
                                      cmd_time=t))
            t += spacing
        if readout:
            cmds.append(isa.pulse_cmd(freq_word=0, phase_word=0,
                                      amp_word=0xffff, env_word=env_word,
                                      cfg_word=RDLO_ELEM, cmd_time=t))
        cmds.append(isa.done_cmd())
        cores.append(cmds)
    return machine_program_from_cmds(cores, elem_cfgs=elem_cfgs)


def grid_init_regs(amp_words, freq_addrs, n_cores: int) -> np.ndarray:
    """Build ``init_regs`` for the full 2D grid: returns
    ``[n_amp * n_freq, n_cores, 16]``, amp-major (frequency varies
    fastest: point k = (amp[k // n_freq], freq[k % n_freq]))."""
    amp_words = np.asarray(amp_words, dtype=np.int64)
    freq_addrs = np.asarray(freq_addrs, dtype=np.int64)
    aa, ff = np.meshgrid(amp_words, freq_addrs, indexing='ij')
    n_points = aa.size
    regs = np.zeros((n_points, n_cores, isa.N_REGS), dtype=np.int32)
    regs[:, :, AMP_REG] = aa.reshape(-1, 1)
    regs[:, :, FREQ_REG] = ff.reshape(-1, 1)
    return regs


def sweep_cfg(mp, n_pulses_per_core: int, **kw) -> InterpreterConfig:
    defaults = dict(max_steps=mp.n_instr + 8,
                    max_pulses=n_pulses_per_core + 2,
                    max_meas=2, max_resets=1)
    defaults.update(kw)
    return InterpreterConfig(**defaults)
