"""Device-mesh construction for sharded sweeps.

The reference's "distributed backend" is on-chip wiring between cores
(reference: hdl/sync_iface.sv, hdl/fproc_meas.sv); scaling to more
shots/sweep points is host-side re-running.  Here the scale axes are
first-class: a `jax.sharding.Mesh` whose ``'dp'`` axis shards shots /
sweep points (data parallel over ICI) and whose optional ``'mp'`` axis
shards long demod contractions.  All cross-core coupling (fproc, sync)
stays inside a shard — one shot never spans devices — so the only
collectives are reductions of results, which ride ICI allreduce.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_dp: int = None, n_mp: int = 1, devices=None) -> Mesh:
    """Build a ``('dp', 'mp')`` mesh over available devices."""
    devices = devices if devices is not None else jax.devices()
    if n_dp is None:
        n_dp = len(devices) // n_mp
    devs = np.asarray(devices[:n_dp * n_mp]).reshape(n_dp, n_mp)
    return Mesh(devs, ('dp', 'mp'))


def make_cores_mesh(n_cores: int = None, n_dp: int = None,
                    devices=None) -> Mesh:
    """Build a ``('dp', 'cores')`` mesh: the ``'cores'`` axis shards a
    SINGLE program's core axis over chips — the per-core interpreter
    lanes run on different devices and the fproc/sync fabric rides
    ``lax.all_gather`` collectives over ICI (docs/PERF.md "ICI
    fabric") — while ``'dp'`` still shards shots.

    ``n_cores`` is the number of SHARDS of the core axis (devices one
    program spans), not the program's core count; the program's
    ``n_cores`` must divide evenly over it
    (``parallel.sweep.sharded_cores_simulate`` validates).  Defaults:
    all devices on the cores axis (``n_dp=1``).
    """
    devices = devices if devices is not None else jax.devices()
    if n_cores is None:
        n_cores = len(devices) // (n_dp or 1)
    if n_cores < 1:
        raise ValueError(f'need a positive cores axis; got {n_cores}')
    if n_dp is None:
        n_dp = len(devices) // n_cores
    if n_dp < 1 or n_dp * n_cores > len(devices):
        raise ValueError(
            f'mesh dp={n_dp} x cores={n_cores} needs {n_dp * n_cores} '
            f'devices; host advertises {len(devices)} (force more on '
            f'CPU with XLA_FLAGS=--xla_force_host_platform_device_'
            f'count=N)')
    devs = np.asarray(devices[:n_dp * n_cores]).reshape(n_dp, n_cores)
    return Mesh(devs, ('dp', 'cores'))


def shot_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for ``[shots, ...]`` arrays: shots over the dp axis."""
    return NamedSharding(mesh, P('dp'))


def serving_devices(n: int = None, devices=None) -> list:
    """Devices the serve tier shards its per-device executors across —
    the dp axis of the serving mesh, one independent dispatcher + warm
    jit cache per device (serve/service.py).

    LOCAL devices only: an :class:`~..serve.ExecutionService` lives in
    one host process, so pod-scale multihost serving shards SERVICES
    across hosts (parallel/multihost.py), never executors across
    processes.  ``n`` takes the first n devices; asking for more than
    the host advertises is an error rather than a silent shrink (the
    bench acceptance gates on real per-device traffic).
    """
    devs = list(devices) if devices is not None else jax.local_devices()
    if n is not None:
        if not 1 <= n <= len(devs):
            raise ValueError(
                f'requested {n} serving devices; host advertises '
                f'{len(devs)} (force more on CPU with XLA_FLAGS='
                f'--xla_force_host_platform_device_count=N)')
        devs = devs[:n]
    return devs
