"""Multi-host execution: DCN-spanning meshes.

The reference's distributed story ends at on-chip wiring (SURVEY §2.3);
here scaling past one host is the standard JAX multi-controller model:
every host runs the same program, `jax.distributed.initialize` wires the
processes, and a global mesh spans all devices.  Shot batches stay
host-local (the dp axis is ordered so each host's shard lives on its own
devices — collectives for statistics ride ICI within a host and DCN
across hosts only for the final psum).

Two reduction strategies coexist:

* **Global-mesh collectives** (`make_global_mesh` + the sweep
  functions): the psum itself crosses DCN inside XLA.  This is the TPU
  pod path; the CPU backend refuses multiprocess jit computations
  outright, so it cannot back the 2-process CI test.
* **Host-local compute + coordination-service reduction**
  (`host_local_mesh` + `dp_row_offset` + `cross_host_sum`): every
  controller jits only over its OWN devices (so any backend works),
  produces exact integer partial sums, and the final reduction rides
  the `jax.distributed` KV store in deterministic process order —
  bit-identical on every controller and to a single-process run of the
  same global batch.  This is also the wire the serve-tier fleet's
  coordinator-less siblings (serve/transport.py) mirror one level up.

Single-process runs fall back transparently, so everything here is
exercised by the regular test suite; multi-host needs no code changes,
only `initialize_multihost()` before first jax use on each controller.
"""

from __future__ import annotations

import json

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def initialize_multihost(coordinator_address: str = None,
                         num_processes: int = None,
                         process_id: int = None,
                         auto: bool = False) -> dict:
    """Initialise the multi-controller runtime.  Returns topology info.

    ``auto=True`` lets JAX auto-detect the cluster (TPU pod slices);
    explicit coordinator/num_processes/process_id works everywhere else.
    With neither, this is a no-op suitable for single-process runs."""
    if auto:
        jax.distributed.initialize()
    elif num_processes is not None and num_processes > 1:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    return {'process_index': jax.process_index(),
            'process_count': jax.process_count(),
            'local_devices': len(jax.local_devices()),
            'global_devices': len(jax.devices())}


def make_global_mesh(n_mp: int = 1) -> Mesh:
    """A ('dp', 'mp') mesh over every device of every process, ordered so
    consecutive dp rows are host-local (shot shards never straddle DCN)."""
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    if n_mp < 1 or len(devs) % n_mp:
        raise ValueError(
            f'{len(devs)} devices not divisible by n_mp={n_mp}')
    n_dp = len(devs) // n_mp
    return Mesh(np.asarray(devs).reshape(n_dp, n_mp), ('dp', 'mp'))


def host_local_batch(mesh: Mesh, global_shots: int) -> tuple[int, int]:
    """Split a global shot count: returns (local_shots, local_offset) for
    this process given equal sharding over the dp axis."""
    n_dp = mesh.devices.shape[0]
    if global_shots % n_dp:
        raise ValueError(f'{global_shots} shots not divisible by dp={n_dp}')
    per_dev = global_shots // n_dp
    local_rows = [i for i in range(n_dp)
                  if mesh.devices[i, 0].process_index == jax.process_index()]
    return per_dev * len(local_rows), per_dev * (local_rows[0]
                                                 if local_rows else 0)


def host_local_mesh(n_mp: int = 1) -> Mesh:
    """A ('dp', 'mp') mesh over THIS process's devices only.

    Computations jitted over it never require cross-process XLA
    collectives, so they run on every backend (the CPU runtime rejects
    multiprocess computations); pair with :func:`dp_row_offset` and
    :func:`cross_host_sum` to reproduce a global-mesh reduction
    exactly."""
    devs = sorted(jax.local_devices(), key=lambda d: d.id)
    if n_mp < 1 or len(devs) % n_mp:
        raise ValueError(
            f'{len(devs)} local devices not divisible by n_mp={n_mp}')
    n_dp = len(devs) // n_mp
    return Mesh(np.asarray(devs).reshape(n_dp, n_mp), ('dp', 'mp'))


def dp_row_offset(global_mesh: Mesh) -> int:
    """This process's first dp row on the global mesh — the offset that
    places a host-local mesh's shards on the global dp grid (for
    key-derivation parity: `sweep.sharded_physics_stat_sums` folds
    ``axis_index('dp') + dp_offset``)."""
    n_dp = global_mesh.devices.shape[0]
    rows = [i for i in range(n_dp)
            if global_mesh.devices[i, 0].process_index
            == jax.process_index()]
    return rows[0] if rows else 0


def cross_host_sum(tag: str, tree, timeout_s: float = 120.0):
    """Sum a pytree of integer arrays across every process, through the
    ``jax.distributed`` coordination-service KV store (host-level DCN,
    no XLA collectives).

    Each process publishes its partial sums under ``tag`` and its
    process index, then folds every peer's contribution IN PROCESS
    ORDER — integer addition, deterministic order, so all controllers
    compute bit-identical totals.  ``tag`` must be unique per
    logical reduction (keys are never deleted from the store).
    Single-process: returns the tree as host numpy unchanged."""
    leaves, treedef = jax.tree.flatten(tree)
    local = [np.asarray(leaf) for leaf in leaves]
    if jax.process_count() == 1:
        return jax.tree.unflatten(treedef, local)
    from jax._src.distributed import global_state
    client = global_state.client
    if client is None:
        raise RuntimeError('cross_host_sum needs '
                           'jax.distributed.initialize '
                           '(initialize_multihost) first')
    payload = json.dumps([{'shape': list(leaf.shape),
                           'dtype': str(leaf.dtype),
                           'data': leaf.ravel().tolist()}
                          for leaf in local])
    client.key_value_set(
        f'dproc/sum/{tag}/{jax.process_index()}', payload)
    total = None
    for pid in range(jax.process_count()):
        raw = client.blocking_key_value_get(
            f'dproc/sum/{tag}/{pid}', int(timeout_s * 1e3))
        peer = [np.asarray(d['data'], dtype=d['dtype']).reshape(
                    d['shape'])
                for d in json.loads(raw)]
        total = peer if total is None \
            else [a + b for a, b in zip(total, peer)]
    return jax.tree.unflatten(treedef, total)


def global_shot_array(mesh: Mesh, local_data, global_shape) -> jax.Array:
    """Assemble a dp-sharded global array from per-host local shards
    (single-process: a plain device_put with the shot sharding)."""
    sharding = NamedSharding(mesh, P('dp'))
    if jax.process_count() == 1:
        return jax.device_put(np.asarray(local_data), sharding)
    return jax.make_array_from_process_local_data(
        sharding, np.asarray(local_data), global_shape)
