"""Multi-host execution: DCN-spanning meshes.

The reference's distributed story ends at on-chip wiring (SURVEY §2.3);
here scaling past one host is the standard JAX multi-controller model:
every host runs the same program, `jax.distributed.initialize` wires the
processes, and a global mesh spans all devices.  Shot batches stay
host-local (the dp axis is ordered so each host's shard lives on its own
devices — collectives for statistics ride ICI within a host and DCN
across hosts only for the final psum).

Single-process runs fall back transparently, so everything here is
exercised by the regular test suite; multi-host needs no code changes,
only `initialize_multihost()` before first jax use on each controller.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def initialize_multihost(coordinator_address: str = None,
                         num_processes: int = None,
                         process_id: int = None,
                         auto: bool = False) -> dict:
    """Initialise the multi-controller runtime.  Returns topology info.

    ``auto=True`` lets JAX auto-detect the cluster (TPU pod slices);
    explicit coordinator/num_processes/process_id works everywhere else.
    With neither, this is a no-op suitable for single-process runs."""
    if auto:
        jax.distributed.initialize()
    elif num_processes is not None and num_processes > 1:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
    return {'process_index': jax.process_index(),
            'process_count': jax.process_count(),
            'local_devices': len(jax.local_devices()),
            'global_devices': len(jax.devices())}


def make_global_mesh(n_mp: int = 1) -> Mesh:
    """A ('dp', 'mp') mesh over every device of every process, ordered so
    consecutive dp rows are host-local (shot shards never straddle DCN)."""
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    if n_mp < 1 or len(devs) % n_mp:
        raise ValueError(
            f'{len(devs)} devices not divisible by n_mp={n_mp}')
    n_dp = len(devs) // n_mp
    return Mesh(np.asarray(devs).reshape(n_dp, n_mp), ('dp', 'mp'))


def host_local_batch(mesh: Mesh, global_shots: int) -> tuple[int, int]:
    """Split a global shot count: returns (local_shots, local_offset) for
    this process given equal sharding over the dp axis."""
    n_dp = mesh.devices.shape[0]
    if global_shots % n_dp:
        raise ValueError(f'{global_shots} shots not divisible by dp={n_dp}')
    per_dev = global_shots // n_dp
    local_rows = [i for i in range(n_dp)
                  if mesh.devices[i, 0].process_index == jax.process_index()]
    return per_dev * len(local_rows), per_dev * (local_rows[0]
                                                 if local_rows else 0)


def global_shot_array(mesh: Mesh, local_data, global_shape) -> jax.Array:
    """Assemble a dp-sharded global array from per-host local shards
    (single-process: a plain device_put with the shot sharding)."""
    sharding = NamedSharding(mesh, P('dp'))
    if jax.process_count() == 1:
        return jax.device_put(np.asarray(local_data), sharding)
    return jax.make_array_from_process_local_data(
        sharding, np.asarray(local_data), global_shape)
