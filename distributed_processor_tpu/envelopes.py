"""Pulse-envelope function library.

The reference outsources envelope synthesis to its signal-generator element
(external LBL-QubiC/gateware repo); only the parametric *description* format
appears in its configs (python/test/qubitcfg.json: ``{'env_func': name,
'paradict': {...}}``).  This module defines the numerical envelope functions
for the TPU backend.  Envelopes are complex baseband arrays normalised to
|env| <= 1, sampled at the element's envelope sample rate.

All functions take ``(paradict, twidth, sample_rate)`` and return a complex
numpy array.  Register a new shape with :func:`register_env_func`.
"""

from __future__ import annotations

import numpy as np

_ENV_FUNCS: dict = {}


def register_env_func(name: str):
    def deco(fn):
        _ENV_FUNCS[name] = fn
        return fn
    return deco


def get_env_func(name: str):
    try:
        return _ENV_FUNCS[name]
    except KeyError:
        raise KeyError(f'unknown env_func {name!r}; registered: {sorted(_ENV_FUNCS)}')


def n_samples(twidth: float, sample_rate: float) -> int:
    return int(np.round(twidth * sample_rate))


def sample_env(env_desc: dict, sample_rate: float, twidth: float = None) -> np.ndarray:
    """Synthesise an envelope from a ``{'env_func', 'paradict'}`` description."""
    paradict = dict(env_desc['paradict'])
    if twidth is None:
        twidth = paradict['twidth']
    paradict.setdefault('twidth', twidth)
    return get_env_func(env_desc['env_func'])(paradict, twidth, sample_rate)


@register_env_func('square')
def square(paradict: dict, twidth: float, sample_rate: float) -> np.ndarray:
    """Constant envelope: amplitude * exp(i phase)."""
    amplitude = paradict.get('amplitude', 1.0)
    phase = paradict.get('phase', 0.0)
    n = n_samples(twidth, sample_rate)
    return np.full(n, amplitude * np.exp(1j * phase), dtype=np.complex128)


@register_env_func('cos_edge_square')
def cos_edge_square(paradict: dict, twidth: float, sample_rate: float) -> np.ndarray:
    """Flat-top pulse with raised-cosine rising/falling edges.

    ``ramp_fraction``: fraction of the total width taken by the two ramps
    combined (each edge is ramp_fraction/2 of the width); alternatively an
    absolute per-edge ``ramp_length`` in seconds overrides it.
    """
    n = n_samples(twidth, sample_rate)
    if 'ramp_length' in paradict:
        n_ramp = min(n_samples(paradict['ramp_length'], sample_rate), n // 2)
    else:
        n_ramp = int(np.round(paradict.get('ramp_fraction', 0.25) * n / 2))
    t = np.arange(n) / sample_rate
    env = np.ones(n, dtype=np.complex128)
    if n_ramp > 0:
        t_ramp = n_ramp / sample_rate
        env[:n_ramp] = 0.5 * (1 - np.cos(np.pi * t[:n_ramp] / t_ramp))
        env[n - n_ramp:] = 0.5 * (1 - np.cos(np.pi * (twidth - t[n - n_ramp:]) / t_ramp))
    return env * paradict.get('amplitude', 1.0)


@register_env_func('gaussian')
def gaussian(paradict: dict, twidth: float, sample_rate: float) -> np.ndarray:
    """Truncated gaussian, edges lifted to zero and peak renormalised to 1.

    ``sigmas``: total width expressed in standard deviations (sigma =
    twidth / sigmas).
    """
    n = n_samples(twidth, sample_rate)
    sigma = twidth / paradict.get('sigmas', 3)
    t = (np.arange(n) + 0.5) / sample_rate - twidth / 2
    env = np.exp(-t ** 2 / (2 * sigma ** 2))
    edge = np.exp(-(twidth / 2) ** 2 / (2 * sigma ** 2))
    env = (env - edge) / (1 - edge)
    return (env * paradict.get('amplitude', 1.0)).astype(np.complex128)


@register_env_func('DRAG')
def drag(paradict: dict, twidth: float, sample_rate: float) -> np.ndarray:
    """DRAG pulse: gaussian I with a derivative-quadrature correction.

    Q(t) = alpha * dI/dt / (2 pi delta); ``delta`` is the anharmonicity in
    Hz, ``alpha`` the DRAG coefficient, ``sigmas`` as for ``gaussian``.
    """
    n = n_samples(twidth, sample_rate)
    sigma = twidth / paradict.get('sigmas', 3)
    alpha = paradict.get('alpha', 0.0)
    delta = paradict['delta']
    t = (np.arange(n) + 0.5) / sample_rate - twidth / 2
    env_i = np.exp(-t ** 2 / (2 * sigma ** 2))
    edge = np.exp(-(twidth / 2) ** 2 / (2 * sigma ** 2))
    env_i = (env_i - edge) / (1 - edge)
    d_env = -(t / sigma ** 2) * np.exp(-t ** 2 / (2 * sigma ** 2)) / (1 - edge)
    env_q = alpha * d_env / (2 * np.pi * delta)
    env = env_i + 1j * env_q
    peak = np.max(np.abs(env))
    if peak > 1:
        env = env / peak
    return (env * paradict.get('amplitude', 1.0)).astype(np.complex128)
