"""Experiment-curve fitting for the models/experiments generators.

The reference stack ends at compile + gateware; its users fit T1/T2/RB
curves with external tooling.  This module closes that loop for the TPU
build: the fits run as jitted Gauss-Newton refinements (``jnp``), so a
sweep's statistics can stay on-device end-to-end.

Decay constants are fitted in log space (``tau = exp(theta)``,
``p = exp(theta)``): the parameterization is smooth and positive by
construction, so an overshooting Gauss-Newton step cannot land in a
clipped zero-gradient region and silently return garbage.

All fitters take plain arrays and return plain floats — they are
data-side math, usable on hardware data as much as on simulated sweeps.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def _gauss_newton(residual_fn, theta0, n_iter: int = 100):
    """Levenberg-Marquardt (adaptively damped Gauss-Newton).

    ``residual_fn(theta) -> [N]``; returns the refined parameter vector.
    The damping factor shrinks 10x on improving steps and grows 10x on
    rejected ones (rejected steps keep the previous iterate), which
    makes the solver robust to poor initializations — a fixed small
    damping lets one early overshoot diverge the whole fit.  Fixed
    iteration count keeps it jittable.
    """
    jac_fn = jax.jacfwd(residual_fn)

    def body(_, carry):
        theta, lam = carry
        r = residual_fn(theta)
        J = jac_fn(theta)
        A = J.T @ J + lam * jnp.eye(theta.shape[0])
        step = jnp.linalg.solve(A, J.T @ r)
        cand = theta - step
        better = jnp.sum(residual_fn(cand) ** 2) < jnp.sum(r ** 2)
        theta = jnp.where(better, cand, theta)
        lam = jnp.clip(jnp.where(better, lam * 0.1, lam * 10.0),
                       1e-12, 1e12)
        return theta, lam

    theta0 = jnp.asarray(theta0)
    theta, _ = jax.lax.fori_loop(0, n_iter, body,
                                 (theta0, jnp.float32(1e-3)))
    return theta


@jax.jit
def _fit_exp(x, y):
    # init: c from the tail, a from the head, tau from the log-slope of
    # the first half (guarded against non-positive values)
    c0 = y[-1]
    a0 = y[0] - c0
    half = max(x.shape[0] // 2, 2)
    z = jnp.log(jnp.clip(jnp.abs(y[:half] - c0), 1e-9, None))
    slope = (z[-1] - z[0]) / (x[half - 1] - x[0] + 1e-30)
    tau0 = jnp.where(slope < 0, -1.0 / slope, (x[-1] - x[0]) / 2)

    def resid(th):
        a, log_tau, c = th
        return a * jnp.exp(-x * jnp.exp(-log_tau)) + c - y

    a, log_tau, c = _gauss_newton(
        resid, jnp.stack([a0, jnp.log(jnp.clip(tau0, 1e-30, None)), c0]))
    return jnp.stack([a, jnp.exp(log_tau), c])


def fit_exp_decay(x, y):
    """Fit ``y = a * exp(-x / tau) + c``.  Returns ``(a, tau, c)``."""
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    a, tau, c = np.asarray(_fit_exp(x, y), float)
    return float(a), float(tau), float(c)


def fit_t1(delays_s, p_excited):
    """T1 from an excited-population decay curve (models/experiments
    ``t1_program`` sweeps).  Returns ``(t1_s, fit_params)``."""
    a, tau, c = fit_exp_decay(delays_s, p_excited)
    return tau, (a, tau, c)


@jax.jit
def _fit_rb(m, y):
    B0 = y[-1]
    A0 = y[0] - B0
    # p init from the ratio of successive decays
    ratio = jnp.clip((y[1] - B0) / jnp.where(
        jnp.abs(y[0] - B0) < 1e-9, 1e-9, y[0] - B0), 1e-6, 1.0)
    p0 = ratio ** (1.0 / jnp.clip(m[1] - m[0], 1e-30, None))

    def resid(th):
        A, log_p, B = th
        return A * jnp.exp(m * log_p) + B - y       # p**m, p = e^log_p

    A, log_p, B = _gauss_newton(
        resid, jnp.stack([A0, jnp.log(jnp.clip(p0, 1e-6, None)), B0]))
    return jnp.stack([A, jnp.exp(log_p), B])


def fit_rb(depths, survival):
    """Randomized-benchmarking decay fit: ``survival = A * p**m + B``.

    Returns ``(p, error_per_clifford, (A, p, B))`` with the standard
    single-qubit (d=2) average error per Clifford ``r = (1-p)/2``.
    """
    A, p, B = np.asarray(_fit_rb(jnp.asarray(depths, jnp.float32),
                                 jnp.asarray(survival, jnp.float32)), float)
    p = float(np.clip(p, 0.0, 1.0))
    return p, (1.0 - p) / 2.0, (float(A), p, float(B))


@jax.jit
def _fit_ramsey(t, y, theta0):
    def resid(th):
        a, log_tau, f, phi, c = th
        return (a * jnp.exp(-t * jnp.exp(-log_tau))
                * jnp.cos(2 * jnp.pi * f * t + phi) + c - y)
    a, log_tau, f, phi, c = _gauss_newton(resid, theta0, n_iter=100)
    return jnp.stack([a, jnp.exp(log_tau), f, phi, c])


def fit_ramsey(delays_s, p_excited):
    """Damped-cosine fit for Ramsey fringes:
    ``p = a * exp(-t/tau) * cos(2*pi*f*t + phi) + c``.

    Returns ``(f_hz, t2_star_s, params)``; the frequency initializer
    takes the dominant nonzero FFT bin, so the sweep should cover at
    least one oscillation period.
    """
    t = np.asarray(delays_s, np.float64)
    y = np.asarray(p_excited, np.float64)
    c0 = float(y.mean())
    # dominant frequency from the (uniformly-sampled) FFT
    dt = float(t[1] - t[0])
    spec = np.abs(np.fft.rfft(y - c0))
    freqs = np.fft.rfftfreq(len(y), dt)
    f0 = float(freqs[1 + int(np.argmax(spec[1:]))])
    a0 = float(2 * spec.max() / len(y))
    tau0 = float(t[-1] - t[0]) / 2

    theta0 = jnp.asarray([a0, np.log(tau0), f0, 0.0, c0], jnp.float32)
    a, tau, f, phi, c = np.asarray(
        _fit_ramsey(jnp.asarray(t, jnp.float32),
                    jnp.asarray(y, jnp.float32), theta0), float)
    return abs(float(f)), float(tau), (float(a), float(tau), float(f),
                                       float(phi), float(c))
