"""TPU signal-generator element: concrete word encodings + buffer layouts.

The reference keeps its signal element out-of-repo (separate gateware repo);
this module defines the numeric contract our simulator executes.  Layouts
follow the bit-field sizes fixed by the processor ISA (hdl/pulse_iface.sv:1-6)
and the freq/env buffer shapes observable in the reference's disassembler
(python/distproc/asmparse.py:46-86):

* phase word: 17-bit, phase/(2 pi) * 2^17, wrapped
* amp word: 16-bit, amp * (2^16 - 1) for amp in [0, 1]
* env word: 24-bit = {12-bit length, 12-bit start address}; addresses and
  lengths count groups of 4 envelope samples (four parallel memory banks);
  length 0xfff is the continuous-wave sentinel
* env buffer: one uint32 per sample = signed 16-bit Q (LSB) | I << 16
  (the reference disassembler's convention: real = high half,
  reference python/distproc/asmparse.py:60-63)
* freq buffer: 16 uint32 words per frequency — word 0 is the 32-bit phase
  increment freq/fsamp * 2^32, words 1..15 are the IQ unit phasors
  exp(2 pi i k freq / fsamp) for the element's parallel sample lanes,
  packed signed-15-bit I<<16 | Q
* cfg word: 4-bit = {2-bit mode, 2-bit element index}
"""

from __future__ import annotations

import numpy as np

from .hwconfig import ElementConfig
from .envelopes import sample_env

PHASE_BITS = 17
AMP_BITS = 16
FREQ_ADDR_BITS = 9
ENV_ADDR_BITS = 12
ENV_LEN_BITS = 12
ENV_CW_SENTINEL = (1 << ENV_LEN_BITS) - 1
ENV_BANKS = 4          # envelope samples per address step
FREQ_BUF_WORDS = 16    # uint32 words per frequency entry
IQ_SCALE = 2 ** 15 - 1


def pack_iq(i, q) -> np.ndarray:
    """Pack signed 16-bit I (high half) and Q (low half) into uint32
    (reference: python/distproc/asmparse.py:60-63 reads real = high)."""
    iw = np.asarray(np.round(i), dtype=np.int64) & 0xffff
    qw = np.asarray(np.round(q), dtype=np.int64) & 0xffff
    return ((iw << 16) | qw).astype(np.uint32)


def unpack_iq(words) -> np.ndarray:
    """Inverse of :func:`pack_iq`; returns complex I + 1j*Q."""
    w = np.asarray(words, dtype=np.uint32).astype(np.int64)
    q = w & 0xffff
    i = (w >> 16) & 0xffff
    i = np.where(i >= 1 << 15, i - (1 << 16), i)
    q = np.where(q >= 1 << 15, q - (1 << 16), q)
    return i + 1j * q


class TPUElementConfig(ElementConfig):
    """Concrete element for the TPU execution backend.

    ``samples_per_clk``: DAC samples per FPGA clock (16 for qdrv/rdrv at
    8 GS/s, 4 for rdlo at 2 GS/s with a 500 MHz clock).
    ``interp_ratio``: envelope interpolation — the envelope memory holds
    one sample per ``interp_ratio`` DAC samples.
    """

    def __init__(self, samples_per_clk: int = 16, interp_ratio: int = 1,
                 fpga_clk_period: float = 2.e-9):
        super().__init__(fpga_clk_period, samples_per_clk)
        self.interp_ratio = interp_ratio

    @property
    def env_sample_freq(self) -> float:
        return self.sample_freq / self.interp_ratio

    # -- scalar word encodings -------------------------------------------

    def get_phase_word(self, phase: float) -> int:
        frac = (phase / (2 * np.pi)) % 1.0
        return int(np.round(frac * (1 << PHASE_BITS))) % (1 << PHASE_BITS)

    def phase_from_word(self, word: int) -> float:
        return 2 * np.pi * (int(word) % (1 << PHASE_BITS)) / (1 << PHASE_BITS)

    def get_amp_word(self, amplitude: float) -> int:
        if not 0 <= amplitude <= 1:
            raise ValueError(f'amplitude {amplitude} must be in [0, 1]')
        return int(np.round(amplitude * ((1 << AMP_BITS) - 1)))

    def amp_from_word(self, word: int) -> float:
        return int(word) / ((1 << AMP_BITS) - 1)

    def get_cfg_word(self, elem_ind: int, mode_bits: int | None) -> int:
        if mode_bits is None:
            mode_bits = 0
        return ((mode_bits & 0b11) << 2) | (elem_ind & 0b11)

    def length_nclks(self, tlength: float) -> int:
        return int(np.ceil(tlength / self.fpga_clk_period))

    # -- envelope buffer --------------------------------------------------

    def get_env_word(self, env_start_ind: int, env_length: int) -> int:
        addr = env_start_ind // ENV_BANKS
        length = int(np.ceil(env_length / ENV_BANKS))
        if addr >= 1 << ENV_ADDR_BITS:
            raise ValueError('envelope memory overflow')
        if length >= ENV_CW_SENTINEL:
            raise ValueError('envelope too long')
        return (length << ENV_ADDR_BITS) | addr

    def get_cw_env_word(self, env_start_ind: int) -> int:
        return (ENV_CW_SENTINEL << ENV_ADDR_BITS) | (env_start_ind // ENV_BANKS)

    def env_word_fields(self, env_word: int) -> tuple[int, int, bool]:
        """Return (start_sample, n_samples, is_cw) from a 24-bit env word."""
        addr = env_word & ((1 << ENV_ADDR_BITS) - 1)
        length = (env_word >> ENV_ADDR_BITS) & ((1 << ENV_LEN_BITS) - 1)
        return addr * ENV_BANKS, length * ENV_BANKS, length == ENV_CW_SENTINEL

    def get_env_buffer(self, env) -> np.ndarray:
        """Quantise an envelope (array or paradict) to the packed IQ buffer."""
        if isinstance(env, str) and env == 'cw':
            return np.zeros(0, dtype=np.uint32)
        if isinstance(env, dict):
            env = sample_env(env, self.env_sample_freq)
        env = np.asarray(env)
        if np.any(np.abs(np.real(env)) > 1) or np.any(np.abs(np.imag(env)) > 1):
            raise ValueError('envelope samples must lie within the unit square')
        # pad to a whole number of bank groups
        pad = (-len(env)) % ENV_BANKS
        if pad:
            env = np.concatenate([env, np.zeros(pad, env.dtype)])
        return pack_iq(np.real(env) * IQ_SCALE, np.imag(env) * IQ_SCALE)

    # -- frequency buffer -------------------------------------------------

    def get_freq_buffer(self, freqs) -> np.ndarray:
        """Build the NCO frequency buffer: 16 uint32 words per frequency."""
        words = np.zeros(FREQ_BUF_WORDS * len(freqs), dtype=np.uint32)
        for n, freq in enumerate(freqs):
            if freq is None:
                continue
            base = n * FREQ_BUF_WORDS
            words[base] = np.uint32(int(np.round(
                (freq / self.sample_freq) * 2 ** 32)) % (1 << 32))
            k = np.arange(1, FREQ_BUF_WORDS)
            ph = 2 * np.pi * freq * k / self.sample_freq
            words[base + 1:base + FREQ_BUF_WORDS] = pack_iq(
                np.cos(ph) * IQ_SCALE, np.sin(ph) * IQ_SCALE)
        return words

    def get_freq_addr(self, freq_ind: int) -> int:
        if freq_ind >= 1 << FREQ_ADDR_BITS:
            raise ValueError('frequency buffer overflow')
        return freq_ind

    def freq_from_buffer(self, freq_buffer: np.ndarray, freq_addr: int) -> float:
        entry = np.asarray(freq_buffer, dtype=np.uint32)[
            freq_addr * FREQ_BUF_WORDS]
        return float(entry) / 2 ** 32 * self.sample_freq


def parse_env_buffer(buf) -> np.ndarray:
    """Decode a packed env buffer (bytes or uint32 array) to complex IQ."""
    if isinstance(buf, (bytes, bytearray)):
        buf = np.frombuffer(buf, dtype=np.dtype(np.uint32).newbyteorder('<'))
    return unpack_iq(buf)


def parse_freq_buffer(buf, fsamp: float) -> dict:
    """Decode a freq buffer: returns {'freq': array, 'iq15': array[n, 15]}."""
    if isinstance(buf, (bytes, bytearray)):
        buf = np.frombuffer(buf, dtype=np.dtype(np.uint32).newbyteorder('<'))
    entries = np.asarray(buf, dtype=np.uint32).reshape(-1, FREQ_BUF_WORDS)
    freq = entries[:, 0].astype(np.float64) / 2 ** 32 * fsamp
    return {'freq': freq, 'iq15': unpack_iq(entries[:, 1:])}
