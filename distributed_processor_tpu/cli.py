"""Command-line interface: compile, disassemble, and run programs.

Usage (also installed as the ``dproc-tpu`` console script)::

    python -m distributed_processor_tpu compile prog.json -o out.json
    python -m distributed_processor_tpu disasm out.json --core 0
    python -m distributed_processor_tpu run prog.qasm --shots 1024
    python -m distributed_processor_tpu sweep prog.json --shots 65536 \\
        --batch 4096 --span 8 --checkpoint sweep.npz
    python -m distributed_processor_tpu trace prog.json

Programs are JSON instruction lists (the compiler input format) or
OpenQASM 3 source (by ``.qasm`` extension or ``--qasm``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np


def _load_program(path: str, force_qasm: bool = False):
    with open(path) as f:
        text = f.read()
    if force_qasm or path.endswith('.qasm'):
        return text
    return json.loads(text)


def _make_sim(args):
    from .simulator import Simulator
    from .qchip import QChip
    qchip = QChip(args.qchip) if args.qchip else None
    return Simulator(qchip=qchip, n_qubits=args.qubits)


def cmd_compile(args):
    if args.cache_dir:
        cmd_compile_cached(args)
        return
    prog, _ = _compile_asm(args)
    if args.output:
        prog.save(args.output)
        print(f'wrote {args.output}')
    else:
        for grp, instrs in prog.program.items():
            print(f'# core group {grp}')
            for i in instrs:
                print(f'  {i}')


def cmd_compile_cached(args):
    """``compile --cache-dir DIR``: source -> MachineProgram through
    the persistent content-addressed compile cache; prints one JSON
    line with hit/miss status, the content key and cache counters (a
    second identical invocation reports a disk hit)."""
    if args.output:
        raise SystemExit('--cache-dir prints a cache summary; '
                         '-o/--output applies to assembly output only')
    import time
    from .compilecache import CompileCache
    sim = _make_sim(args)
    program = _load_program(args.program, args.qasm)
    cache = CompileCache(cache_dir=args.cache_dir)
    t0 = time.perf_counter()
    mp, status, key = cache.get_or_compile(
        program, sim.qchip, channel_configs=sim.channel_configs,
        fpga_config=sim.fpga_config, n_qubits=args.qubits)
    dt = time.perf_counter() - t0
    stats = cache.stats()
    print(json.dumps({
        'status': status,                 # miss | disk (warm across runs)
        'hit': status != 'miss',
        'key': key,
        'qchip_fingerprint': sim.qchip.fingerprint(),
        'n_cores': mp.n_cores,
        'n_instr': mp.n_instr,
        'elapsed_ms': round(dt * 1e3, 3),
        'cache_dir': args.cache_dir,
        'cache': {k: stats[k] for k in
                  ('hits', 'misses', 'disk_hits', 'size')},
    }, indent=2))


def _compile_asm(args):
    """Load + compile to a CompiledProgram (shared by compile/disasm/
    dump commands); returns (CompiledProgram, Simulator)."""
    sim = _make_sim(args)
    program = _load_program(args.program, args.qasm)
    if isinstance(program, str):
        from .frontend import qasm_to_program
        program = qasm_to_program(program)
    from .pipeline import compile_program
    prog = compile_program(program, sim.qchip, fpga_config=sim.fpga_config)
    return prog, sim


def _assemble(args):
    """Compile + assemble; returns (assembled bufs, channel_configs)."""
    from .assembler import GlobalAssembler
    from .elements import TPUElementConfig
    prog, sim = _compile_asm(args)
    asm = GlobalAssembler(prog, sim.channel_configs, TPUElementConfig)
    return asm.get_assembled_program(), sim.channel_configs


def _select_cores(assembled, core) -> list:
    """Numerically ordered core keys, or the validated --core choice."""
    if core is None:
        return sorted(assembled, key=int)
    key = str(core)
    if key not in assembled:
        raise SystemExit(
            f'no core {key} in this program (has: '
            f'{", ".join(sorted(assembled, key=int))})')
    return [key]


def _fmt_operands(d: dict) -> str:
    parts = []
    for k, v in d.items():
        if k == 'op':
            continue
        if isinstance(v, tuple) or (isinstance(v, list) and len(v) == 2
                                    and v[0] == 'reg'):
            v = f'r{v[1]}'
        parts.append(f'{k}={v}')
    return ' '.join(parts)


def cmd_disasm(args):
    """Full-operand disassembly of the assembled command buffers — the
    analog of the reference's ``asmparse.cmdparse`` field dump
    (reference: python/distproc/asmparse.py:12-44)."""
    assembled, _ = _assemble(args)
    from . import isa
    for core in _select_cores(assembled, args.core):
        print(f'# core {core}')
        for i, d in enumerate(isa.disassemble(assembled[core]['cmd_buf'])):
            print(f'  {i:4d}: {d["op"]:<17s} {_fmt_operands(d)}'.rstrip())


def cmd_envdump(args):
    """Decode env buffers to complex I/Q samples (reference:
    asmparse.envparse, asmparse.py:46-63)."""
    assembled, _ = _assemble(args)
    from .elements import parse_env_buffer
    for core in _select_cores(assembled, args.core):
        for e, buf in enumerate(assembled[core]['env_buffers']):
            iq = parse_env_buffer(buf)
            print(f'# core {core} elem {e}: {len(iq)} samples')
            for k in range(0, len(iq), 1 if args.full else max(len(iq)//8, 1)):
                print(f'  [{k:5d}] {iq[k].real:+.6f} {iq[k].imag:+.6f}j')


def cmd_freqdump(args):
    """Decode freq buffers: word 0 = freq/fsamp*2^32, words 1-15 = IQ
    phase offsets of the 16-sample parallel NCO (reference:
    asmparse.freqparse, asmparse.py:64-86)."""
    assembled, ccfgs = _assemble(args)
    from .elements import parse_freq_buffer
    # fsamp per element from any qubit's channel configs on that core
    for core in _select_cores(assembled, args.core):
        elems = {}
        for name, cc in ccfgs.items():
            if not hasattr(cc, 'core_ind') or str(cc.core_ind) != core:
                continue
            elems[cc.elem_ind] = \
                cc.elem_params['samples_per_clk'] * ccfgs['fpga_clk_freq']
        for e, buf in enumerate(assembled[core]['freq_buffers']):
            if not len(buf):
                continue
            fsamp = elems.get(e, 1.0)
            parsed = parse_freq_buffer(buf, fsamp)
            print(f'# core {core} elem {e} (fsamp {fsamp:.3e})')
            for k, f in enumerate(parsed['freq']):
                iq0 = parsed['iq15'][k, 0]
                print(f'  [{k:3d}] freq {f:.6e} Hz  '
                      f'iq[1] {iq0.real:+.5f}{iq0.imag:+.5f}j')


def _fault_table(fault_shots: dict) -> None:
    """Print the nonzero trapped-shot counts to stderr (the JSON result
    on stdout stays machine-parseable)."""
    nz = {k: int(v) for k, v in fault_shots.items() if v}
    if not nz:
        return
    w = max(len(k) for k in nz)
    print('fault summary (trapped shots, docs/ROBUSTNESS.md):',
          file=sys.stderr)
    for k, v in nz.items():
        print(f'  {k:<{w}}  {v}', file=sys.stderr)


def _fault_shot_dict(fault) -> dict:
    from .sim.interpreter import FAULT_CODES, fault_shot_counts
    counts = np.asarray(fault_shot_counts(fault))
    return {name: int(c) for (name, _), c in zip(FAULT_CODES, counts)}


def cmd_run(args):
    sim = _make_sim(args)
    kw = {}
    if args.physics:
        if args.p1 is not None:
            raise SystemExit(
                '--p1 injects bits; --physics resolves them in-sim — '
                'use --p1-init for the thermal initial state instead')
        from .sim.device import DeviceModel
        from .sim.physics import ReadoutPhysics
        if args.device != 'statevec' and args.depol2:
            raise SystemExit('--depol2 (two-qubit Pauli channel on '
                             'coupling pulses) needs --device statevec')
        if args.device != 'statevec' and args.leak:
            raise SystemExit('--leak (computational-subspace leakage) '
                             'needs --device statevec')
        if args.device == 'parity' and (args.detuning_hz or args.t1_us
                                        or args.t2_us or args.depol):
            raise SystemExit(
                '--detuning-hz/--t1-us/--t2-us/--depol need '
                '--device bloch or statevec (the parity counter has no '
                'such physics)')
        any_leak = args.leak or args.leak2
        if args.leak_bit != 1 and not (args.device == 'statevec'
                                       and any_leak):
            raise SystemExit('--leak-bit has no effect without '
                             '--device statevec and a leakage channel '
                             '(--leak or --leak2)')
        if args.device != 'statevec' and (args.leak2 or args.seep):
            raise SystemExit('--leak2/--seep need --device statevec')
        if args.seep and not any_leak:
            raise SystemExit('--seep needs a leakage channel '
                             '(--leak or --leak2)')
        if args.leak_iq is not None and not (args.device == 'statevec'
                                             and any_leak):
            raise SystemExit('--leak-iq needs --device statevec with '
                             '--leak or --leak2 > 0')
        if args.classify3 and args.leak_iq is None:
            raise SystemExit('--classify3 needs --leak-iq')
        dev = DeviceModel(args.device,
                          detuning_hz=args.detuning_hz,
                          t1_s=args.t1_us * 1e-6 if args.t1_us else
                          float('inf'),
                          t2_s=args.t2_us * 1e-6 if args.t2_us else
                          float('inf'),
                          depol_per_pulse=args.depol,
                          depol2_per_pulse=args.depol2,
                          leak_per_pulse=args.leak,
                          leak_readout_bit=args.leak_bit,
                          leak2_per_pulse=args.leak2,
                          seep_per_pulse=args.seep)
        kw['physics'] = ReadoutPhysics(
            sigma=args.sigma, p1_init=args.p1_init, device=dev,
            g2=(complex(args.leak_iq[0], args.leak_iq[1])
                if args.leak_iq is not None else None),
            classify3=args.classify3)
    else:
        kw['p1'] = args.p1
    if args.engine:
        kw['engine'] = args.engine
    if args.max_steps is not None:
        kw['max_steps'] = args.max_steps
    from .decoder import validate_program, ProgramValidationError
    mp = sim.compile(_load_program(args.program, args.qasm))
    try:
        # pre-flight: reject always-wrong programs with instruction
        # coordinates before any compile/dispatch cost
        validate_program(mp, sim.interpreter_config(mp, **{
            k: v for k, v in kw.items() if k == 'engine'}))
    except ProgramValidationError as e:
        raise SystemExit(str(e))
    out = sim.run(mp, shots=args.shots, **kw)
    from .sim.interpreter import resolve_engine
    n_pulses = np.asarray(out['n_pulses'])
    err = np.asarray(out['err'])
    faults = _fault_shot_dict(out['fault'])
    result = {
        'shots': args.shots,
        'engine': resolve_engine(out['_mp'], out['_cfg']),
        'mean_pulses_per_core': np.atleast_2d(n_pulses).mean(0).tolist(),
        'error_shots': int(np.any(np.atleast_2d(err) != 0, -1).sum()),
        'fault_shots': faults,
        'steps': int(out['steps']),
    }
    if args.physics:
        bits = np.asarray(out['meas_bits'])
        result['meas1_rate_per_core'] = \
            np.atleast_3d(bits)[..., 0].mean(0).tolist()
        result['epochs'] = int(out['epochs'])
        if 'leaked' in out:
            # the leak rate itself, separable from meas1 (which folds
            # leaked shots in at --leak-bit)
            result['leaked_rate_per_core'] = \
                np.atleast_2d(np.asarray(out['leaked'])).mean(0).tolist()
        if 'meas_class' in out:
            # 3-class discrimination: first-slot class-2 rate per core
            cls = np.atleast_3d(np.asarray(out['meas_class']))
            result['class2_rate_per_core'] = \
                (cls[..., 0] == 2).mean(0).tolist()
    print(json.dumps(result, indent=2))
    _fault_table(faults)
    if args.strict_faults and any(faults.values()):
        raise SystemExit(2)


def cmd_sweep(args):
    """Physics-closed statistics sweep: ``--shots`` total in
    ``--batch``-sized jitted steps through ``parallel.run_physics_sweep``
    — resumable via ``--checkpoint``, with ``--span`` batches folded
    into each device dispatch (bit-identical statistics, fewer host
    round-trips)."""
    if args.span < 1:
        raise SystemExit('--span must be >= 1')
    if args.span > 1 and args.checkpoint_every and \
            args.checkpoint_every % args.span:
        raise SystemExit(
            f'--checkpoint-every counts BATCHES but writes snap to span '
            f'edges: {args.checkpoint_every} is not a multiple of '
            f'--span {args.span}, so checkpoints would land later than '
            f'asked — pick a multiple, or drop --span')
    if args.device == 'parity' and (args.detuning_hz or args.t1_us
                                    or args.t2_us or args.depol):
        raise SystemExit(
            '--detuning-hz/--t1-us/--t2-us/--depol need '
            '--device bloch or statevec (the parity counter has no '
            'such physics)')
    sim = _make_sim(args)
    mp = sim.compile(_load_program(args.program, args.qasm))
    from .decoder import validate_program, ProgramValidationError
    from .sim.device import DeviceModel
    from .sim.physics import ReadoutPhysics
    from .sim.interpreter import FaultError
    from .parallel import run_physics_sweep
    dev = DeviceModel(args.device,
                      detuning_hz=args.detuning_hz,
                      t1_s=args.t1_us * 1e-6 if args.t1_us else
                      float('inf'),
                      t2_s=args.t2_us * 1e-6 if args.t2_us else
                      float('inf'),
                      depol_per_pulse=args.depol)
    model = ReadoutPhysics(sigma=args.sigma, p1_init=args.p1_init,
                           device=dev)
    cfg_kw = {'engine': args.engine} if args.engine else {}
    if args.max_steps is not None:
        cfg_kw['max_steps'] = args.max_steps
    if args.strict_faults:
        cfg_kw['fault_mode'] = 'strict'
    cfg = sim.interpreter_config(mp, **cfg_kw)
    try:
        validate_program(mp, cfg)
    except ProgramValidationError as e:
        raise SystemExit(str(e))
    try:
        out = run_physics_sweep(mp, model, args.shots, args.batch,
                                key=args.key, cfg=cfg,
                                checkpoint=args.checkpoint,
                                checkpoint_every=args.checkpoint_every,
                                span=args.span,
                                strict_resume=args.strict_resume)
    except FaultError as e:
        # the sweep completed (and checkpointed); the counts failed the
        # strict gate — report the per-code table and exit nonzero
        from .sim.interpreter import FAULT_CODES
        _fault_table({name: int(n) for (name, _), n
                      in zip(FAULT_CODES, e.counts)})
        raise SystemExit(2)
    print(json.dumps({k: (v.tolist() if isinstance(v, np.ndarray) else v)
                      for k, v in out.items()}, indent=2))
    _fault_table(out.get('fault_shots', {}))


def cmd_trace(args):
    sim = _make_sim(args)
    mp = sim.compile(_load_program(args.program, args.qasm))
    from .sim import simulate
    out = simulate(mp, cfg=sim.interpreter_config(mp, trace=True))
    if args.vcd:
        from .utils.vcd import write_vcd
        n = write_vcd(args.vcd, out, core_labels=mp.core_inds)
        print(f'wrote {args.vcd}: {n} value changes '
              f'({mp.n_cores} cores, {int(out["steps"])} steps)')
        return
    steps = int(out['steps'])
    for c in range(mp.n_cores):
        print(f'# core {mp.core_inds[c]}')
        for s in range(steps):
            pc = int(out['trace_pc'][c, s])
            t = int(out['trace_time'][c, s])
            print(f'  step {s:4d}  t={t:8d}  pc={pc}')


def cmd_serve_bench(args):
    from .serve.benchmark import (availability_under_chaos,
                                  compile_front_door,
                                  continuous_batching_comparison,
                                  fleet_failover,
                                  multi_device_scaling,
                                  open_loop_latency)
    if args.fleet:
        # fleet-federation mode: N replica PROCESSES behind the
        # FleetRouter; SIGKILL the loaded replica mid-stream and require
        # goodput to stay positive inside the kill window with every
        # completion bit-identical (docs/FLEET.md)
        row = fleet_failover(
            n_replicas=args.fleet, n_reqs=args.requests,
            rate_hz=args.rate_hz, n_qubits=args.qubits,
            depth=args.depth, shots=args.shots, seed=args.seed)
    elif args.source_mode:
        # the compile front door: tenants submit SOURCE programs via
        # submit_source; content-addressed dedup + singleflight +
        # bit-identity vs compile+submit asserted inside the row
        row = compile_front_door(
            n_tenants=args.tenants, n_programs=args.programs,
            n_qubits=args.qubits, depth=args.depth, shots=args.shots,
            seed=args.seed)
    elif args.chaos:
        # availability under injected faults: crash/hang/slowdown under
        # _run_batch, goodput + tails with the supervision stack
        # (retries, breaker quarantine, canary re-admission) healing
        row = availability_under_chaos(
            n_reqs=args.requests, rate_hz=args.rate_hz,
            n_qubits=args.qubits, depth=args.depth, shots=args.shots,
            seed=args.seed, devices=args.devices,
            p_crash=args.p_crash, p_hang=args.p_hang,
            p_slow=args.p_slow)
    elif args.dp:
        # multi-device closed-loop scaling: needs that many visible
        # devices in THIS process (off-TPU: XLA_FLAGS=
        # --xla_force_host_platform_device_count=N; bench.py shells
        # out to a forced child automatically, the CLI does not)
        row = multi_device_scaling(
            dp_list=[int(x) for x in args.dp.split(',') if x],
            n_reqs=args.requests, n_qubits=args.qubits,
            depth=args.depth, shots=args.shots, seed=args.seed)
    elif args.open_loop or args.slo:
        # --trace-out with the sample left at 0 means "trace them all"
        sample = args.trace_sample or (1.0 if args.trace_out else 0.0)
        row = open_loop_latency(
            n_reqs=args.requests, rate_hz=args.rate_hz,
            n_qubits=args.qubits, shots=args.shots, seed=args.seed,
            devices=args.devices, slo=args.slo,
            warmup_catalog=args.warmup_catalog,
            trace_sample=sample, trace_out=args.trace_out)
    else:
        sample = args.trace_sample or (1.0 if args.trace_out else 0.0)
        row = continuous_batching_comparison(
            n_reqs=args.requests, n_qubits=args.qubits,
            depth=args.depth, shots=args.shots, seed=args.seed,
            max_wait_ms=args.max_wait_ms,
            trace_sample=sample, trace_out=args.trace_out)
    print(json.dumps(row, indent=2))


def cmd_trace_view(args):
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        'tools'))
    from traceview import format_table, summarize
    try:
        summary = summarize(args.trace)
    except (OSError, ValueError) as e:
        # empty/invalid trace files exit nonzero with the reason, not
        # a traceback (tools/traceview.py raises ValueError for both)
        raise SystemExit(f'trace-view: {e}')
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(format_table(summary))


_TENANT_VIEW_COLS = ('tenant', 'weight', 'queued', 'submitted',
                     'completed', 'failed', 'shed', 'quota_rejected',
                     'shots', 'device_ms', 'compile_ms', 'bytes_wire')


def _print_tenant_view(tenant_rows: dict, as_json: bool) -> None:
    """``fleet-status --tenants``: fold each replica's
    ``stats()['tenants']`` block into one fleet-level row per tenant
    (meters summed — they are monotone billing counters, so summation
    is exact; ``weight`` is shared config, reported once).  Table by
    default, the full per-replica breakdown with ``--json``."""
    agg = {}
    for per_tenant in tenant_rows.values():
        for tenant, row in per_tenant.items():
            a = agg.setdefault(tenant, {c: 0 for c in
                                        _TENANT_VIEW_COLS[2:]})
            a['weight'] = row.get('weight', 1.0)
            for c in _TENANT_VIEW_COLS[2:]:
                a[c] += row.get(c, 0)
    if as_json:
        print(json.dumps({'tenants': agg, 'replicas': tenant_rows},
                         indent=2))
        return
    if not agg:
        print('no tenant traffic recorded yet')
        return
    out = []
    for tenant in sorted(agg):
        r = {'tenant': tenant}
        for c in _TENANT_VIEW_COLS[1:]:
            v = agg[tenant].get(c, 0)
            r[c] = round(v, 1) if isinstance(v, float) else v
        out.append(r)
    widths = {c: max(len(c), *(len(str(r[c])) for r in out))
              for c in _TENANT_VIEW_COLS}
    print('  '.join(c.ljust(widths[c]) for c in _TENANT_VIEW_COLS))
    for r in out:
        print('  '.join(str(r[c]).ljust(widths[c])
                        for c in _TENANT_VIEW_COLS))


def cmd_fleet_status(args):
    """Live fleet flight deck: poll each replica DIRECTLY over the
    fleet wire (the same ``gossip`` / ``fleet-metrics`` ops the router
    uses) and print one status row per replica — no router process
    required, so this works against any fleet you can reach.  With
    ``--prometheus``, re-expose every replica's metrics with a
    ``replica`` label plus fleet rollups (docs/OBSERVABILITY.md
    "Fleet observability")."""
    from .serve.transport import ReplicaClient
    rows, snaps, errors = [], {}, []
    tenant_rows = {}        # addr -> stats()['tenants'] block
    for addr in args.replica:
        host, _, port = addr.rpartition(':')
        host = host or '127.0.0.1'
        try:
            client = ReplicaClient((host, int(port)))
        except (OSError, ValueError) as e:
            errors.append((addr, f'{type(e).__name__}: {e}'))
            rows.append({'replica': addr, 'error': str(e)})
            continue
        try:
            g = client.call('gossip', {}, timeout_s=args.timeout)
            if args.prometheus:
                m = client.call('fleet-metrics', {},
                                timeout_s=args.timeout)
                snaps[addr] = m['metrics']
        except Exception as e:          # noqa: BLE001 - keep polling
            errors.append((addr, f'{type(e).__name__}: {e}'))
            rows.append({'replica': addr, 'error': str(e)})
            continue
        finally:
            client.close()
        st = g.get('stats', {})
        fl = g.get('flight', {})
        tenant_rows[addr] = st.get('tenants') or {}
        # mismatches/audits (plus any scrubber quarantines): a nonzero
        # numerator is a silent-data-corruption alarm, not noise
        ig = st.get('integrity') or {}
        rows.append({
            'replica': addr,
            'health': st.get('health'),
            'queue_depth': st.get('queue_depth'),
            'est_wait_ms': st.get('est_wait_ms'),
            'completed': st.get('completed'),
            'integrity': (f"{ig.get('mismatches', 0)}"
                          f"/{ig.get('audits', 0)}" if ig else ''),
            'flight_recorded': fl.get('recorded'),
            'flight_dropped': fl.get('dropped'),
            'flight_counts': fl.get('counts'),
        })
    if not any('error' not in r for r in rows):
        for addr, err in errors:
            print(f'fleet-status: {addr}: {err}', file=sys.stderr)
        raise SystemExit('fleet-status: no replica reachable')
    if args.tenants:
        _print_tenant_view(tenant_rows, as_json=args.json)
        return
    if args.prometheus:
        from .obs import merged_prometheus_text
        lines = merged_prometheus_text(snaps, label='replica')
        print('\n'.join(lines))
        return
    if args.json:
        print(json.dumps(rows, indent=2))
        return
    cols = ('replica', 'health', 'queue_depth', 'est_wait_ms',
            'completed', 'integrity', 'flight_recorded',
            'flight_dropped')
    widths = {c: max(len(c), *(len(str(r.get(c, ''))) for r in rows))
              for c in cols}
    print('  '.join(c.ljust(widths[c]) for c in cols))
    for r in rows:
        if 'error' in r:
            print(f"{r['replica'].ljust(widths['replica'])}  "
                  f"UNREACHABLE: {r['error']}")
            continue
        print('  '.join(str(r.get(c, '')).ljust(widths[c])
                        for c in cols))
        if r.get('flight_counts'):
            counts = ' '.join(f'{k}={v}' for k, v in
                              sorted(r['flight_counts'].items()))
            print(f'  flight: {counts}')


def cmd_warmup(args):
    """AOT-compile a learned bucket catalog offline.

    The in-process executable cache dies with this process, so the
    point of offline warmup is (a) validating that every catalog entry
    still compiles, with per-spec timings, and (b) with ``--jax-cache``
    pre-baking the persistent XLA compilation cache that serving
    processes started with the same cache dir then LOAD instead of
    recompiling — catalog replay in the server turns into disk reads.
    """
    import jax
    if args.jax_cache:
        jax.config.update('jax_compilation_cache_dir', args.jax_cache)
        jax.config.update(
            'jax_persistent_cache_min_compile_time_secs', 0.0)
    from .serve.catalog import BucketCatalog
    from .sim.interpreter import aot_compile_batch
    specs = BucketCatalog(args.catalog).load()
    devs = jax.local_devices()[:max(1, args.devices)]
    compiled, total_ms = 0, 0.0
    for spec in specs:
        for d in devs:
            dt_ms = aot_compile_batch(spec, d) * 1e3
            compiled += 1 if dt_ms > 0 else 0
            total_ms += dt_ms
            print(json.dumps({'spec': spec.label(),
                              'device': str(d),
                              'compile_ms': round(dt_ms, 1),
                              'cached': dt_ms == 0.0}))
    print(json.dumps({'catalog': args.catalog, 'specs': len(specs),
                      'devices': len(devs), 'compiled': compiled,
                      'total_compile_ms': round(total_ms, 1),
                      'jax_cache': args.jax_cache}))


def cmd_qec_stream(args):
    """Streaming-QEC driver (docs/SERVING.md "Streaming sessions"):
    run R rounds of the repetition (or surface-cycle-shaped) QEC
    workload either as round chunks through a ``StreamSession`` — each
    chunk ONE device-resident scan dispatch with the decoder in the
    loop (``--stream``, the default) — or as R sequential single-round
    dispatches with a host-side decode (``--per-round``), printing the
    decoded corrections summary and wall time as JSON so the two modes
    are directly comparable."""
    import time
    from dataclasses import replace
    from .models.qec import (qec_config, qec_multiround_machine_program,
                             repetition_decode_spec,
                             surface_cycle_config,
                             surface_cycle_machine_program,
                             surface_decode_spec)
    from .ops.decode import decode_history
    from .sim.interpreter import simulate_batch
    if args.surface:
        mp = surface_cycle_machine_program(args.distance)
        cfg = surface_cycle_config(args.distance)
        dec = surface_decode_spec(args.distance)
    else:
        mp = qec_multiround_machine_program(n_data=args.distance,
                                            rounds=1)
        cfg = qec_config(args.distance)
        dec = repetition_decode_spec(args.distance)
    cfg = replace(cfg, record_pulses=False,
                  **({'engine': args.engine} if args.engine else {}))
    rng = np.random.default_rng(args.key)
    mb = rng.integers(0, 2, (args.rounds, args.shots, mp.n_cores,
                             cfg.max_meas)).astype(np.int32)
    t0 = time.perf_counter()
    if args.per_round:
        for r in range(args.rounds):
            np.asarray(simulate_batch(mp, mb[r], cfg=cfg)['err'])
        hist = np.transpose(mb[:, :, list(dec.cores), dec.slot],
                            (1, 0, 2))
        decoded = np.asarray(decode_history(hist, dec.scheme))
        mode = (f'{args.rounds} per-round dispatches + host decode '
                f'(--per-round)')
        chunks = args.rounds
    else:
        from .serve import ExecutionService
        svc = ExecutionService()
        try:
            with svc.open_stream(mp, cfg=cfg, decode=dec) as sess:
                for i in range(0, args.rounds, args.chunk):
                    sess.submit_rounds(mb[i:i + args.chunk])
                summary = sess.close(timeout=600)
        finally:
            svc.shutdown()
        decoded = summary['decoded']
        chunks = summary['chunks']
        mode = (f'streaming session: {chunks} chunk dispatches of '
                f'<= {args.chunk} rounds, decoder in the loop '
                f'(--stream)')
    dt = time.perf_counter() - t0
    print(json.dumps({
        'mode': mode,
        'scheme': dec.scheme,
        'distance': args.distance,
        'rounds': args.rounds,
        'shots': args.shots,
        'dispatches': chunks,
        'engine': cfg.engine,
        'wall_s': round(dt, 3),
        'rounds_per_s': round(args.rounds / dt, 1),
        'corrected_shots': int((decoded.sum(axis=-1) > 0).sum()),
        'mean_correction_weight':
            round(float(decoded.sum(axis=-1).mean()), 4),
    }, indent=2))


def cmd_calibrate(args):
    """Closed-loop calibration driver (docs/CALIBRATION.md): run one
    knob's gradient-descent loop through an in-process
    ``ExecutionService`` — candidate programs through the compile
    front door, gradient steps from the differentiable physics model,
    convergence written back to the live qchip (flushing exactly the
    stale compile-cache epoch).  Prints the step count, loss
    trajectory and final parameters as JSON; exits nonzero on a
    diverged loop."""
    from .calib import calibrate
    from .models import make_default_qchip
    from .serve import ExecutionService
    from .sim.grad import LossSpec
    spec = None
    if args.knob == 'amplitude':
        # the device-truth X90 amplitude the loop estimates: defaults
        # drifted from the nominal 0.48 so the writeback is a real
        # retune, not a no-op
        spec = LossSpec(knob='amplitude', x90_amp=args.true_x90)
    qchip = make_default_qchip(args.qubits)
    svc = ExecutionService()
    try:
        result = calibrate(svc, qchip, knob=args.knob,
                           qubit=f'Q{args.qubit}', spec=spec,
                           start=args.start, lr=args.lr,
                           max_steps=args.steps, shots=args.shots,
                           tenant=args.tenant, n_qubits=args.qubits)
        snap = svc.stats()['calibration']
    finally:
        svc.shutdown()
    out = result.to_dict()
    out['losses'] = [round(v, 8) for v in out['losses']]
    out['service'] = snap
    print(json.dumps(out, indent=2))
    if result.diverged:
        raise SystemExit(
            f"calibrate: {args.knob} loop diverged after "
            f"{result.steps} steps: {result.detail.get('reason')}")


def main(argv=None):
    ap = argparse.ArgumentParser(prog='dproc-tpu',
                                 description=__doc__.split('\n')[0])
    ap.add_argument('--qchip', help='calibration JSON (default: built-in)')
    ap.add_argument('--qubits', type=int, default=8)
    ap.add_argument('--qasm', action='store_true',
                    help='treat the program file as OpenQASM 3')
    sub = ap.add_subparsers(dest='command', required=True)

    p = sub.add_parser('compile', help='compile to per-core assembly')
    p.add_argument('program')
    p.add_argument('-o', '--output')
    p.add_argument('--cache-dir', metavar='DIR',
                   help='compile source -> MachineProgram through the '
                        'persistent content-addressed compile cache '
                        'rooted here; prints hit/miss JSON (rerun the '
                        'same command to see the warm disk hit)')
    p.set_defaults(fn=cmd_compile)

    p = sub.add_parser('disasm', help='full-operand disassembly of the '
                                      'assembled command buffers')
    p.add_argument('program')
    p.add_argument('--core', type=int)
    p.set_defaults(fn=cmd_disasm)

    p = sub.add_parser('envdump', help='decode envelope BRAM buffers to I/Q')
    p.add_argument('program')
    p.add_argument('--core', type=int)
    p.add_argument('--full', action='store_true',
                   help='print every sample (default: 8 per buffer)')
    p.set_defaults(fn=cmd_envdump)

    p = sub.add_parser('freqdump', help='decode frequency BRAM buffers '
                                        '(16-word parallel-NCO entries)')
    p.add_argument('program')
    p.add_argument('--core', type=int)
    p.set_defaults(fn=cmd_freqdump)

    p = sub.add_parser('run', help='simulate shots')
    p.add_argument('program')
    p.add_argument('--shots', type=int, default=1)
    p.add_argument('--p1', type=float, default=None,
                   help='Bernoulli P(measure 1) per qubit (injected bits)')
    p.add_argument('--physics', action='store_true',
                   help='close the measurement loop with the DSP chain '
                        '(synthesis -> demod -> discriminate) instead of '
                        'injecting bits')
    p.add_argument('--sigma', type=float, default=0.05,
                   help='physics: per-sample ADC noise std dev')
    p.add_argument('--p1-init', type=float, default=0.1,
                   help='physics: thermal excited-state probability')
    p.add_argument('--device', choices=('parity', 'bloch', 'statevec'),
                   default='parity',
                   help='physics: qubit co-state model — parity counter, '
                        'SU(2) Bloch vector, or entangling statevec '
                        '(full per-shot state vector; CNOT/CZ coupling '
                        'map auto-derived from the program + gate '
                        'library) — sim/device.py')
    p.add_argument('--detuning-hz', type=float, default=0.0,
                   help='bloch/statevec: qubit-drive detuning '
                        '(Ramsey fringes)')
    p.add_argument('--t1-us', type=float, default=0.0,
                   help='bloch/statevec: T1 in microseconds (0 = off)')
    p.add_argument('--t2-us', type=float, default=0.0,
                   help='bloch/statevec: T2 in microseconds (0 = off)')
    p.add_argument('--depol', type=float, default=0.0,
                   help='bloch/statevec: 1q depolarization per drive pulse')
    p.add_argument('--depol2', type=float, default=0.0,
                   help='statevec: 2q Pauli channel per coupling pulse')
    p.add_argument('--leak', type=float, default=0.0,
                   help='statevec: leakage probability per 1q drive '
                        'pulse (x P(|1>); CPTP trajectory unraveling)')
    p.add_argument('--leak-bit', type=int, default=1, choices=(0, 1),
                   help='statevec: bit a leaked core reads out as '
                        '(the fast path; see --leak-iq for the IQ-level '
                        'alternative)')
    p.add_argument('--leak2', type=float, default=0.0,
                   help='statevec: coupling-pulse-induced control '
                        'leakage probability (x P(|1>) per coupling '
                        'pulse — the dominant 2q-gate mechanism)')
    p.add_argument('--seep', type=float, default=0.0,
                   help='statevec: |2>->|1> seepage probability per '
                        'drive pulse on a leaked core (0 = absorbing)')
    p.add_argument('--leak-iq', type=float, nargs=2, default=None,
                   metavar=('RE', 'IM'),
                   help='statevec: |2> IQ channel response g2 — leaked '
                        'cores traverse the real demod chain instead of '
                        'the forced --leak-bit (docs/PHYSICS.md '
                        '"Leakage readout")')
    p.add_argument('--classify3', action='store_true',
                   help='statevec + --leak-iq: 3-class nearest-centroid '
                        'discrimination; reports per-core class-2 rates')
    p.add_argument('--engine',
                   choices=('auto', 'generic', 'block', 'straightline',
                            'pallas'),
                   default=None,
                   help='interpreter engine ladder (docs/PERF.md "Engine '
                        'ladder"): auto picks the pallas megastep '
                        'kernel on TPU backends when eligible, else '
                        'straightline for small branch-free programs, '
                        'else block (CFG-superinstruction) when '
                        'eligible, else generic fetch-dispatch; '
                        'pallas/block/straightline raise with the '
                        'reason when ineligible (default: generic)')
    p.add_argument('--strict-faults', action='store_true',
                   help='exit nonzero (status 2) if any shot trapped a '
                        'runtime fault (budget exhaustion, record '
                        'overflow, deadlock/starvation — see '
                        'docs/ROBUSTNESS.md); default: report '
                        'fault_shots counts and a summary table on '
                        'stderr, exit 0')
    p.add_argument('--max-steps', type=int, default=None,
                   help='interpreter step budget override (default: '
                        'sized by static loop analysis); shots still '
                        'running at the budget trap budget_exhausted')
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser('sweep', help='physics-closed statistics sweep '
                                     '(resumable, span-batched)')
    p.add_argument('program')
    p.add_argument('--shots', type=int, default=4096,
                   help='total shots (a multiple of --batch)')
    p.add_argument('--batch', type=int, default=256,
                   help='shots per batch (one jitted execution)')
    p.add_argument('--span', type=int, default=1,
                   help='batches folded into ONE device dispatch via an '
                        'on-device scan (dispatch/tunnel latency paid '
                        'once per span); default 1 keeps the per-batch '
                        'host loop. Statistics are bit-identical for '
                        'any span, and checkpoints are interchangeable '
                        'across spans. --checkpoint-every stays counted '
                        'in BATCHES with writes at span edges, so it '
                        'must be a multiple of --span')
    p.add_argument('--key', type=int, default=0, help='base PRNG seed')
    p.add_argument('--checkpoint', metavar='FILE',
                   help='resumable accumulator checkpoint (atomic npz); '
                        'an interrupted sweep rerun with the same '
                        'arguments continues where it stopped')
    p.add_argument('--checkpoint-every', type=int, default=0,
                   help='batches between checkpoint writes (default '
                        'with --checkpoint: every batch)')
    p.add_argument('--strict-resume', action='store_true',
                   help='reject unfingerprinted or version-skewed '
                        'checkpoints instead of warning')
    p.add_argument('--sigma', type=float, default=0.05,
                   help='per-sample ADC noise std dev')
    p.add_argument('--p1-init', type=float, default=0.1,
                   help='thermal excited-state probability')
    p.add_argument('--device', choices=('parity', 'bloch', 'statevec'),
                   default='parity',
                   help='qubit co-state model (see `run --help`)')
    p.add_argument('--detuning-hz', type=float, default=0.0,
                   help='bloch/statevec: qubit-drive detuning')
    p.add_argument('--t1-us', type=float, default=0.0,
                   help='bloch/statevec: T1 in microseconds (0 = off)')
    p.add_argument('--t2-us', type=float, default=0.0,
                   help='bloch/statevec: T2 in microseconds (0 = off)')
    p.add_argument('--depol', type=float, default=0.0,
                   help='bloch/statevec: 1q depolarization per pulse')
    p.add_argument('--engine',
                   choices=('auto', 'generic', 'block', 'straightline',
                            'pallas'),
                   default=None,
                   help='interpreter engine ladder (see `run --help`); '
                        'the chosen engine is reported in the result '
                        'metadata')
    p.add_argument('--strict-faults', action='store_true',
                   help='run with fault_mode=strict: after the sweep '
                        'completes (and checkpoints), exit nonzero '
                        '(status 2) with a per-code table if any shot '
                        'trapped a runtime fault (docs/ROBUSTNESS.md); '
                        'default: fault_shots counts in the JSON result '
                        'plus a stderr summary when nonzero')
    p.add_argument('--max-steps', type=int, default=None,
                   help='interpreter step budget override (see '
                        '`run --help`)')
    p.set_defaults(fn=cmd_sweep)

    p = sub.add_parser('serve-bench',
                       help='continuous-batching service benchmark: N '
                            'concurrent submissions vs N sequential '
                            'dispatches, warm, bit-identity checked')
    p.add_argument('--requests', type=int, default=32,
                   help='concurrent single-program requests')
    p.add_argument('--shots', type=int, default=32,
                   help='shots per request')
    p.add_argument('--depth', type=int, default=2,
                   help='RB depth of each random program')
    p.add_argument('--seed', type=int, default=0,
                   help='ensemble seed')
    p.add_argument('--max-wait-ms', type=float, default=100.0,
                   help='coalescing deadline passed to the service')
    p.add_argument('--dp', metavar='N,N,...',
                   help="multi-device scaling mode: run the closed-"
                        "loop workload at each executor count (e.g. "
                        "'1,2'); needs that many visible devices")
    p.add_argument('--open-loop', action='store_true',
                   help='open-loop latency mode: p50/p99 under '
                        'Poisson-ish mixed-bucket arrivals')
    p.add_argument('--rate-hz', type=float, default=40.0,
                   help='open-loop offered arrival rate')
    p.add_argument('--devices', type=int, default=None,
                   help='open-loop: shard the service across this '
                        'many devices (default: classic single-device '
                        'path)')
    p.add_argument('--chaos', action='store_true',
                   help='availability under seeded fault injection: '
                        'open-loop stream with crashes/hangs/slowdowns '
                        'injected under the executors; reports goodput '
                        'fraction, retries, breaker trips, '
                        're-admissions (bit-identity asserted)')
    p.add_argument('--p-crash', type=float, default=0.08,
                   help='chaos: per-dispatch injected crash probability')
    p.add_argument('--p-hang', type=float, default=0.02,
                   help='chaos: per-dispatch injected hang probability '
                        '(past the watchdog)')
    p.add_argument('--p-slow', type=float, default=0.10,
                   help='chaos: per-dispatch injected slowdown '
                        'probability (below the watchdog)')
    p.add_argument('--source-mode', action='store_true',
                   help='compile front-door mode: tenants submit '
                        'SOURCE programs via submit_source through '
                        'the content-addressed compile cache; reports '
                        'cold compiles, warm hit rate, singleflight '
                        'dedup and speedup vs uncached '
                        'compile-per-request (bit-identity asserted)')
    p.add_argument('--tenants', type=int, default=4,
                   help='source-mode: tenants submitting the same '
                        'program set')
    p.add_argument('--programs', type=int, default=4,
                   help='source-mode: distinct programs per tenant')
    p.add_argument('--slo', action='store_true',
                   help='open-loop latency-SLO mode: the same seeded '
                        'arrival trace runs cold (empty catalog, '
                        'compiles in-window) then warm (catalog '
                        'replay); asserts warmed p99 < unwarmed p99 '
                        'with zero cold hits (implies --open-loop)')
    p.add_argument('--warmup-catalog', metavar='PATH',
                   help='open-loop: learned bucket catalog to replay '
                        'at service startup and record new buckets '
                        'into (serve/catalog.py)')
    p.add_argument('--trace-sample', type=float, default=0.0,
                   help='fraction of requests carrying a lifecycle '
                        'trace (docs/OBSERVABILITY.md); default 0=off')
    p.add_argument('--trace-out', metavar='PATH',
                   help='export the measured round as Chrome Trace '
                        'Event JSON (Perfetto / chrome://tracing '
                        'loadable; implies --trace-sample 1.0 unless '
                        'set); summarize with `trace-view`')
    p.add_argument('--fleet', type=int, default=0, metavar='N',
                   help='fleet-federation mode: route the open-loop '
                        'stream across N replica processes behind the '
                        'FleetRouter, SIGKILL the loaded replica '
                        'mid-stream, and report kill-window goodput, '
                        'failovers and respawns (bit-identity '
                        'asserted; docs/FLEET.md)')
    p.set_defaults(fn=cmd_serve_bench)

    p = sub.add_parser('trace-view',
                       help='per-stage p50/p99 waterfall of an '
                            'exported request trace (serve-bench '
                            '--trace-out, ExecutionService.dump_trace)')
    p.add_argument('trace', help='Chrome Trace Event JSON file')
    p.add_argument('--json', action='store_true',
                   help='emit the summary as JSON instead of a table')
    p.set_defaults(fn=cmd_trace_view)

    p = sub.add_parser('fleet-status',
                       help='poll live replicas over the fleet wire '
                            '(gossip + fleet-metrics ops): one status '
                            'row per replica, or --prometheus for the '
                            'replica-labeled merged exposition')
    p.add_argument('replica', nargs='+', metavar='HOST:PORT',
                   help='replica wire addresses (ReplicaServer); bare '
                        'ports default the host to 127.0.0.1')
    p.add_argument('--prometheus', action='store_true',
                   help='print the merged Prometheus exposition '
                        '(every metric with a replica label + fleet '
                        'rollups) instead of the table')
    p.add_argument('--tenants', action='store_true',
                   help='per-tenant flight deck instead of the replica '
                        'table: queued/served/shed/quota-rejected plus '
                        'the billing meters (shots, device-ms, '
                        'compile-ms, bytes-on-wire) summed across '
                        'replicas; combine with --json for the '
                        'per-replica breakdown (docs/SERVING.md '
                        '"Tenants")')
    p.add_argument('--json', action='store_true',
                   help='emit the status rows as JSON')
    p.add_argument('--timeout', type=float, default=5.0,
                   help='per-replica wire timeout in seconds')
    p.set_defaults(fn=cmd_fleet_status)

    p = sub.add_parser('warmup',
                       help='AOT-compile a learned bucket catalog '
                            'offline: validates every entry with '
                            'per-spec compile timings and, with '
                            '--jax-cache, pre-bakes the persistent '
                            'XLA cache that serving processes load '
                            'at startup')
    p.add_argument('catalog',
                   help='bucket catalog JSON written by '
                        'ExecutionService(warmup_catalog=...) or '
                        'serve-bench --warmup-catalog')
    p.add_argument('--devices', type=int, default=1,
                   help='compile on the first N local devices')
    p.add_argument('--jax-cache', metavar='DIR',
                   help='persistent XLA compilation cache dir to '
                        'populate (point the server at the same dir)')
    p.set_defaults(fn=cmd_warmup)

    p = sub.add_parser('qec-stream',
                       help='R-round QEC with the decoder in the loop: '
                            'one streaming scan dispatch per chunk vs '
                            'R per-round dispatches')
    p.add_argument('--rounds', type=int, default=32)
    p.add_argument('--distance', type=int, default=3,
                   help='code distance (data qubits for the repetition '
                        'workload)')
    p.add_argument('--engine', choices=['generic', 'block',
                                        'straightline', 'pallas'],
                   help='pin the interpreter engine (default: auto)')
    p.add_argument('--shots', type=int, default=256)
    p.add_argument('--chunk', type=int, default=8,
                   help='rounds per streaming chunk (one dispatch each)')
    p.add_argument('--per-round', action='store_true',
                   help='dispatch every round separately and decode on '
                        'the host (the baseline --stream amortizes)')
    p.add_argument('--surface', action='store_true',
                   help='surface-code-cycle-shaped workload (ancilla '
                        'syndrome cores + chain matching) instead of '
                        'the repetition rounds')
    p.add_argument('--key', type=int, default=7,
                   help='seed for the injected measurement planes')
    p.set_defaults(fn=cmd_qec_stream)

    p = sub.add_parser('calibrate',
                       help='gradient-descent knob tuning through the '
                            'serve tier: candidate programs via the '
                            'compile front door, writeback to the live '
                            'qchip on convergence')
    p.add_argument('--knob', choices=['amplitude', 'drag',
                                      'readout_window'],
                   default='amplitude')
    p.add_argument('--qubit', type=int, default=0,
                   help='qubit index to tune')
    p.add_argument('--qubits', type=int, default=argparse.SUPPRESS,
                   help='qchip size override, placeable after the '
                        'subcommand (default: the global --qubits)')
    p.add_argument('--start', type=float, default=None,
                   help='initial parameter guess (default: per-knob)')
    p.add_argument('--lr', type=float, default=None,
                   help='gradient-descent step size (default: '
                        'per-knob; a too-large value demonstrates the '
                        'diverged path and the nonzero exit)')
    p.add_argument('--steps', type=int, default=None,
                   help='step budget before the loop counts as '
                        'diverged')
    p.add_argument('--shots', type=int, default=8)
    p.add_argument('--true-x90', type=float, default=0.52,
                   help='device-truth X90 amplitude of the amplitude '
                        "knob's forward model (drifted from the "
                        'nominal 0.48 so the writeback is a retune)')
    p.add_argument('--tenant', help='tenant identity for the session')
    p.set_defaults(fn=cmd_calibrate)

    p = sub.add_parser('trace', help='instruction trace (1 shot)')
    p.add_argument('program')
    p.add_argument('--vcd', metavar='FILE',
                   help='write a VCD waveform (GTKWave-compatible) '
                        'instead of printing — the analog of the '
                        "reference's Verilator --trace output")
    p.set_defaults(fn=cmd_trace)

    args = ap.parse_args(argv)
    args.fn(args)


if __name__ == '__main__':
    main()
