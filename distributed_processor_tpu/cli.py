"""Command-line interface: compile, disassemble, and run programs.

Usage (also installed as the ``dproc-tpu`` console script)::

    python -m distributed_processor_tpu compile prog.json -o out.json
    python -m distributed_processor_tpu disasm out.json --core 0
    python -m distributed_processor_tpu run prog.qasm --shots 1024
    python -m distributed_processor_tpu trace prog.json

Programs are JSON instruction lists (the compiler input format) or
OpenQASM 3 source (by ``.qasm`` extension or ``--qasm``).
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def _load_program(path: str, force_qasm: bool = False):
    with open(path) as f:
        text = f.read()
    if force_qasm or path.endswith('.qasm'):
        return text
    return json.loads(text)


def _make_sim(args):
    from .simulator import Simulator
    from .qchip import QChip
    qchip = QChip(args.qchip) if args.qchip else None
    return Simulator(qchip=qchip, n_qubits=args.qubits)


def cmd_compile(args):
    sim = _make_sim(args)
    program = _load_program(args.program, args.qasm)
    if isinstance(program, str):
        from .frontend import qasm_to_program
        program = qasm_to_program(program)
    from .pipeline import compile_program
    prog = compile_program(program, sim.qchip, fpga_config=sim.fpga_config)
    if args.output:
        prog.save(args.output)
        print(f'wrote {args.output}')
    else:
        for grp, instrs in prog.program.items():
            print(f'# core group {grp}')
            for i in instrs:
                print(f'  {i}')


def cmd_disasm(args):
    sim = _make_sim(args)
    mp = sim.compile(_load_program(args.program, args.qasm))
    from . import isa
    for c in range(mp.n_cores) if args.core is None else [args.core]:
        print(f'# core {mp.core_inds[c]}')
        soa = mp.soa
        from .isa import _KIND_NAMES
        for i in range(mp.n_instr):
            kind = int(soa.kind[c, i])
            print(f'  {i:4d}: {_KIND_NAMES[kind]}')


def cmd_run(args):
    sim = _make_sim(args)
    out = sim.run(_load_program(args.program, args.qasm), shots=args.shots,
                  p1=args.p1)
    n_pulses = np.asarray(out['n_pulses'])
    err = np.asarray(out['err'])
    result = {
        'shots': args.shots,
        'mean_pulses_per_core': np.atleast_2d(n_pulses).mean(0).tolist(),
        'error_shots': int(np.any(np.atleast_2d(err) != 0, -1).sum()),
        'steps': int(out['steps']),
    }
    print(json.dumps(result, indent=2))


def cmd_trace(args):
    sim = _make_sim(args)
    mp = sim.compile(_load_program(args.program, args.qasm))
    from .sim import simulate
    out = simulate(mp, cfg=sim.interpreter_config(mp, trace=True))
    steps = int(out['steps'])
    for c in range(mp.n_cores):
        print(f'# core {mp.core_inds[c]}')
        for s in range(steps):
            pc = int(out['trace_pc'][c, s])
            t = int(out['trace_time'][c, s])
            print(f'  step {s:4d}  t={t:8d}  pc={pc}')


def main(argv=None):
    ap = argparse.ArgumentParser(prog='dproc-tpu',
                                 description=__doc__.split('\n')[0])
    ap.add_argument('--qchip', help='calibration JSON (default: built-in)')
    ap.add_argument('--qubits', type=int, default=8)
    ap.add_argument('--qasm', action='store_true',
                    help='treat the program file as OpenQASM 3')
    sub = ap.add_subparsers(dest='command', required=True)

    p = sub.add_parser('compile', help='compile to per-core assembly')
    p.add_argument('program')
    p.add_argument('-o', '--output')
    p.set_defaults(fn=cmd_compile)

    p = sub.add_parser('disasm', help='decode the assembled machine program')
    p.add_argument('program')
    p.add_argument('--core', type=int)
    p.set_defaults(fn=cmd_disasm)

    p = sub.add_parser('run', help='simulate shots')
    p.add_argument('program')
    p.add_argument('--shots', type=int, default=1)
    p.add_argument('--p1', type=float, default=None,
                   help='Bernoulli P(measure 1) per qubit')
    p.set_defaults(fn=cmd_run)

    p = sub.add_parser('trace', help='instruction trace (1 shot)')
    p.add_argument('program')
    p.set_defaults(fn=cmd_trace)

    args = ap.parse_args(argv)
    args.fn(args)


if __name__ == '__main__':
    main()
