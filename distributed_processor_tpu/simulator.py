"""User-facing facade: compile, execute, and render programs.

Ties the full stack together (the reference stops at FPGA BRAM bytes;
everything past `GlobalAssembler` is the TPU backend this framework
adds):

    dict program / OpenQASM 3
        -> Compiler (IR passes) -> GlobalAssembler -> decoder
        -> JAX ISA interpreter (shots batched on device)
        -> element waveform synthesis / readout demod (ops/)

Example::

    sim = Simulator(n_qubits=2)
    out = sim.run('qubit[2] q; h q[0]; cx q[0], q[1];', shots=1024)
    wf = sim.waveforms(out)          # per-core per-element I/Q traces
"""

from __future__ import annotations

import numpy as np
import jax

from .hwconfig import FPGAConfig
from .decoder import MachineProgram
from .pipeline import compile_to_machine
from .models.channels import make_channel_configs
from .models.default_qchip import make_default_qchip
from .sim.interpreter import InterpreterConfig, simulate, simulate_batch
from .elements import IQ_SCALE
from .ops.waveform import synthesize_element
from .ops.demod import demod_iq, discriminate


class Simulator:
    """Compile-and-execute facade for N-qubit programs."""

    def __init__(self, qchip=None, n_qubits: int = 8, channel_configs=None,
                 fpga_config: FPGAConfig = None):
        self.n_qubits = n_qubits
        self.qchip = qchip or make_default_qchip(n_qubits)
        self.channel_configs = channel_configs or make_channel_configs(n_qubits)
        self.fpga_config = fpga_config or FPGAConfig(n_cores=n_qubits)

    # -- compilation -----------------------------------------------------

    def compile(self, program) -> MachineProgram:
        """Compile a dict program or OpenQASM 3 source string."""
        if isinstance(program, str):
            from .frontend import qasm_to_program
            program = qasm_to_program(program)
        return compile_to_machine(program, self.qchip,
                                  channel_configs=self.channel_configs,
                                  fpga_config=self.fpga_config)

    def interpreter_config(self, mp: MachineProgram,
                           **kw) -> InterpreterConfig:
        """Sized-to-the-program interpreter config.

        Budgets come from static loop analysis
        (:meth:`~.decoder.MachineProgram.static_bounds`): counter loops
        the compiler emits are sized exactly; unanalyzable back-edges
        get a bounded fallback.  Pass ``max_steps``/``max_pulses``
        explicitly for programs whose iteration counts are data-driven.
        """
        kw.pop('has_loops', None)       # superseded by static analysis
        defaults = dict(max_meas=16, max_resets=4)
        if 'max_steps' not in kw or 'max_pulses' not in kw:
            # the pure-Python scan is skipped when both budgets are
            # caller-supplied (large programs in hot paths)
            bounds = mp.static_bounds()
            defaults.update(
                max_steps=bounds['max_steps'],
                max_pulses=min(bounds['max_pulses'], 4096))
        defaults.update(kw)
        return InterpreterConfig.from_fpga_config(self.fpga_config,
                                                  **defaults)

    # -- execution -------------------------------------------------------

    def run(self, program, shots: int = 1, meas_bits=None, p1=None,
            key=None, init_regs=None, physics=None, **cfg_kw) -> dict:
        """Compile (if needed) and execute ``shots`` shots.

        Measurement bits come from (in priority order) ``physics`` (a
        :class:`~.sim.physics.ReadoutPhysics` — bits emerge in-sim from
        synthesized + demodulated readout windows, nothing injected),
        ``meas_bits`` (``[shots, n_cores, n_meas]``), Bernoulli sampling
        with per-qubit probabilities ``p1`` (needs ``key``), or zeros.
        The result dict carries the machine program under ``'_mp'`` for
        waveform rendering.
        """
        mp = program if isinstance(program, MachineProgram) \
            else self.compile(program)
        cfg = self.interpreter_config(mp, **cfg_kw)
        if physics is not None:
            if meas_bits is not None or p1 is not None:
                raise ValueError(
                    'physics= resolves measurement bits in-sim; '
                    'meas_bits=/p1= cannot also be given')
            from .sim.physics import run_physics_batch, physics_config
            if physics.device.kind == 'statevec':
                from dataclasses import replace as _rep
                if not physics.device.couplings:
                    # derive the (core, freq-word) -> (target, kind)
                    # coupling map from this program + gate library, so
                    # CNOT/CZ calibrations entangle without manual wiring
                    from .models.coupling import couplings_from_qchip
                    physics = _rep(physics, device=_rep(
                        physics.device,
                        couplings=couplings_from_qchip(mp, self.qchip)))
                if physics.device.couplings and 'max_steps' not in cfg_kw:
                    # the discrete-event gate serializes cross-core pulse
                    # triggers (worst case one core per step): scale the
                    # statically-derived step budget by the core count
                    cfg = _rep(cfg, max_steps=cfg.max_steps * mp.n_cores)
            out = dict(run_physics_batch(
                mp, physics, key if key is not None else jax.random.PRNGKey(0),
                shots, init_regs=init_regs, cfg=cfg))
            self._warn_truncation(out, cfg)
            out['_mp'] = mp
            out['_cfg'] = physics_config(cfg, physics)  # effective config
            return out
        if meas_bits is None and p1 is not None:
            from .models.readout import sample_meas_bits
            key = key if key is not None else jax.random.PRNGKey(0)
            meas_bits = sample_meas_bits(
                key, np.broadcast_to(np.asarray(p1, np.float32),
                                     (mp.n_cores,)),
                shots, cfg.max_meas)
        if shots == 1 and (meas_bits is None or meas_bits.ndim == 2):
            out = dict(simulate(mp, meas_bits=meas_bits,
                                init_regs=init_regs, cfg=cfg))
        else:
            if meas_bits is None:
                meas_bits = np.zeros((shots, mp.n_cores, cfg.max_meas), int)
            out = dict(simulate_batch(mp, meas_bits, init_regs=init_regs,
                                      cfg=cfg))
        self._warn_truncation(out, cfg)
        out['_mp'] = mp
        out['_cfg'] = cfg
        return out

    @staticmethod
    def _warn_truncation(out: dict, cfg) -> None:
        """A run that exhausted its step or pulse budget is truncated,
        not merely erroneous — say so loudly instead of leaving a quiet
        error bit (round-1 review: deep loops silently truncated)."""
        import warnings
        from .sim.interpreter import ERR_PULSE_OVERFLOW
        if bool(np.asarray(out.get('incomplete', False))):
            warnings.warn(
                f'run truncated: not all shots finished within max_steps='
                f'{cfg.max_steps}; results are partial — raise max_steps '
                f'(data-driven loops cannot be sized statically)',
                RuntimeWarning, stacklevel=3)
        if np.any(np.asarray(out['err']) & ERR_PULSE_OVERFLOW):
            warnings.warn(
                f'pulse records truncated: a core emitted more than '
                f'max_pulses={cfg.max_pulses} pulses; raise max_pulses',
                RuntimeWarning, stacklevel=3)

    # -- rendering -------------------------------------------------------

    def waveforms(self, out: dict, shot: int = None, n_clks: int = None,
                  cores=None) -> dict:
        """Render element output traces from a run's pulse records.

        Returns ``{core_ind: [trace_elem0, trace_elem1, ...]}`` where each
        trace is ``float32 [n_samples, 2]`` I/Q.  For batched runs pass
        ``shot`` to select one shot.
        """
        mp: MachineProgram = out['_mp']
        if 'rec_gtime' not in out:
            raise ValueError(
                'run has no pulse records (record_pulses=False was set); '
                'rendering needs a run with record_pulses=True')
        if shot is None and np.asarray(out['n_pulses']).ndim == 2:
            raise ValueError(
                'batched run: pass shot= to select which shot to render '
                '(n_pulses has a leading shot axis)')
        sel = (lambda a: np.asarray(a)) if shot is None \
            else (lambda a: np.asarray(a)[shot])
        n_pulses = sel(out['n_pulses'])
        gtime, dur = sel(out['rec_gtime']), sel(out['rec_dur'])
        if n_clks is None:
            end = gtime + dur
            n_clks = int(end.max()) + 8
        result = {}
        for c in (cores if cores is not None else range(mp.n_cores)):
            tables = mp.tables[c]
            traces = []
            for e, ecfg in enumerate(tables.elem_cfgs):
                freq_table = tables.freqs[e]['freq'] if e < len(tables.freqs) \
                    else np.zeros(0)
                freq_rel_table = np.concatenate(
                    [np.asarray(freq_table) / ecfg.sample_freq, [0.0]])
                rec_freq = sel(out['rec_freq'])[c]
                rec = {
                    'gtime': sel(out['rec_gtime'])[c],
                    'env': sel(out['rec_env'])[c],
                    'phase': sel(out['rec_phase'])[c],
                    'amp': sel(out['rec_amp'])[c],
                    'elem': sel(out['rec_elem'])[c],
                    'freq_rel': freq_rel_table[
                        np.clip(rec_freq, 0, len(freq_rel_table) - 1)],
                    'n_pulses': n_pulses[c],
                }
                env_table = np.asarray(tables.envs[e]) / IQ_SCALE \
                    if e < len(tables.envs) and len(tables.envs[e]) \
                    else np.zeros(1, complex)
                traces.append(np.asarray(synthesize_element(
                    rec, env_table, spc=ecfg.samples_per_clk,
                    interp=ecfg.interp_ratio, n_clks=n_clks, elem=e)))
            result[c] = traces
        return result

    def demod_readout(self, out: dict, adc_traces, windows) -> np.ndarray:
        """Demodulate external ADC traces against per-measurement windows
        (``[n_samples, 2M]`` weight matrix) — see :mod:`.ops.demod`."""
        return demod_iq(adc_traces, windows)
