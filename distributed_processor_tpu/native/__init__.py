"""Native (C++) host-runtime components, loaded via ctypes.

The compute path is JAX/XLA; the runtime *around* it — here the
command-buffer codec at the FPGA-BRAM boundary — is native, compiled
on first use with the system toolchain and cached next to the package.
Every entry point has a pure-Python fallback (the :mod:`..isa` codec),
and bit-exactness between the two is covered by tests.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, 'soa_codec.cpp')
_LIB = os.path.join(_HERE, 'libsoacodec.so')

_lock = threading.Lock()
_lib = None
_tried = False

N_FIELDS = 19
CMD_BYTES = 16


def _build() -> bool:
    cmd = ['g++', '-O2', '-shared', '-fPIC', '-o', _LIB + '.tmp', _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(_LIB + '.tmp', _LIB)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def get_lib():
    """ctypes handle to the codec library, or None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB) or \
                os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        lib.soa_decode.restype = ctypes.c_int
        lib.soa_decode.argtypes = [
            ctypes.c_char_p, ctypes.c_int,
            np.ctypeslib.ndpointer(np.int32, flags='C_CONTIGUOUS')]
        lib.encode_pulse_batch.restype = None
        lib.encode_pulse_batch.argtypes = [
            np.ctypeslib.ndpointer(np.int32, flags='C_CONTIGUOUS')] * 6 + [
            ctypes.c_int,
            np.ctypeslib.ndpointer(np.uint8, flags='C_CONTIGUOUS')]
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def decode_soa_fields(buf: bytes):
    """Decode a command buffer to the ``[N_FIELDS, n]`` int32 array
    (SOA_FIELDS order), or None if the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    if len(buf) % CMD_BYTES:
        raise ValueError('command buffer length must be a multiple of 16')
    n = len(buf) // CMD_BYTES
    out = np.zeros((N_FIELDS, n), dtype=np.int32)
    rc = lib.soa_decode(bytes(buf), n, out)
    if rc:
        raise ValueError(f'instruction {rc - 1}: unknown opcode')
    return out


def encode_pulse_batch(cmd_time, env, phase, freq, amp, cfg):
    """Batch-encode full-parameter timed pulse commands -> bytes, or
    None if the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    arrs = [np.ascontiguousarray(a, dtype=np.int32)
            for a in (cmd_time, env, phase, freq, amp, cfg)]
    n = len(arrs[0])
    if any(len(a) != n for a in arrs):
        raise ValueError('field arrays must have equal length')
    out = np.zeros(n * CMD_BYTES, dtype=np.uint8)
    lib.encode_pulse_batch(arrs[0], arrs[1], arrs[2], arrs[3], arrs[4],
                           arrs[5], n, out)
    return out.tobytes()
