// Native command codec: 128-bit command buffers <-> SoA field arrays.
//
// This is the host-side hot loop at the FPGA-BRAM boundary (the
// reference's equivalent work is the per-instruction Python encode in
// python/distproc/assembler.py:349-429 and the cocotb-side parsing in
// python/distproc/asmparse.py:12-44).  Large sweep compilations decode
// thousands of commands per core; doing the bit-slicing in C++ keeps
// the program-upload path off the Python interpreter.
//
// Field order must match distributed_processor_tpu.isa.SOA_FIELDS.
// Built with: g++ -O2 -shared -fPIC -o libsoacodec.so soa_codec.cpp

#include <cstdint>
#include <cstring>

namespace {

constexpr int CMD_BYTES = 16;
constexpr int N_FIELDS = 19;

// SOA_FIELDS order (isa.py):
enum Field {
    F_KIND = 0, F_ALU_OP, F_IN0_IS_REG, F_IMM, F_IN0_REG, F_IN1_REG,
    F_OUT_REG, F_JUMP_ADDR, F_FUNC_ID, F_BARRIER, F_CMD_TIME,
    F_P_ENV, F_P_PHASE, F_P_FREQ, F_P_AMP, F_P_CFG,
    F_P_WEN, F_P_REGSEL, F_P_REG,
};

// instruction kinds (isa.py K_*)
enum Kind {
    K_PULSE_WRITE = 0, K_PULSE_TRIG, K_REG_ALU, K_JUMP_I, K_JUMP_COND,
    K_ALU_FPROC, K_JUMP_FPROC, K_INC_QCLK, K_SYNC, K_DONE, K_PULSE_RESET,
    K_IDLE,
};

// 5-bit opcode -> kind (-1 = invalid); mirrors isa._OP5_TO_KIND
int op5_to_kind(int op5) {
    switch (op5) {
        case 0b10000: return K_PULSE_WRITE;
        case 0b10010: return K_PULSE_TRIG;
        case 0b00010: case 0b00011: return K_REG_ALU;
        case 0b00100: return K_JUMP_I;
        case 0b00110: case 0b00111: return K_JUMP_COND;
        case 0b01000: case 0b01001: return K_ALU_FPROC;
        case 0b01010: case 0b01011: return K_JUMP_FPROC;
        case 0b01100: case 0b01101: return K_INC_QCLK;
        case 0b01110: return K_SYNC;
        case 0b10100: return K_DONE;
        case 0b10110: return K_PULSE_RESET;
        case 0b11000: return K_IDLE;
        case 0b00000: return K_DONE;   // all-zero opcode halts (ctrl.v:382)
        default: return -1;
    }
}

// extract [pos, pos+width) from a 128-bit little-endian command
inline uint64_t bits(const uint8_t* cmd, int pos, int width) {
    // assemble up to 64 bits spanning byte boundaries
    uint64_t v = 0;
    int first = pos >> 3;
    int nbytes = ((pos + width + 7) >> 3) - first;
    for (int i = nbytes - 1; i >= 0; --i)
        v = (v << 8) | cmd[first + i];
    v >>= (pos & 7);
    if (width < 64)
        v &= ((uint64_t)1 << width) - 1;
    return v;
}

const int PULSE_POS_CMD_TIME = 5;
const int PULSE_POS_CFG = 37, PULSE_W_CFG = 4;
const int PULSE_POS_AMP = 42, PULSE_W_AMP = 16;
const int PULSE_POS_FREQ = 60, PULSE_W_FREQ = 9;
const int PULSE_POS_PHASE = 71, PULSE_W_PHASE = 17;
const int PULSE_POS_ENV = 90, PULSE_W_ENV = 24;

}  // namespace

extern "C" {

// Decode n commands from buf (16 bytes each, little-endian) into
// out[N_FIELDS][n] (row-major int32).  Returns 0 on success, or
// 1-based index of the first command with an unknown opcode.
int soa_decode(const uint8_t* buf, int n, int32_t* out) {
    for (int i = 0; i < n; ++i) {
        const uint8_t* cmd = buf + (size_t)i * CMD_BYTES;
        auto put = [&](int f, int64_t v) { out[(size_t)f * n + i] = (int32_t)v; };
        int op5 = (int)bits(cmd, 123, 5);
        int kind = op5_to_kind(op5);
        if (kind < 0) return i + 1;
        put(F_KIND, kind);
        put(F_ALU_OP, bits(cmd, 120, 3));
        bool aluish = kind == K_REG_ALU || kind == K_JUMP_COND ||
                      kind == K_ALU_FPROC || kind == K_JUMP_FPROC ||
                      kind == K_INC_QCLK;
        put(F_IN0_IS_REG, aluish ? (op5 & 1) : 0);
        put(F_IMM, (int32_t)(uint32_t)bits(cmd, 88, 32));   // two's complement
        put(F_IN0_REG, bits(cmd, 116, 4));
        put(F_IN1_REG, bits(cmd, 84, 4));
        put(F_OUT_REG, bits(cmd, 80, 4));
        put(F_JUMP_ADDR, bits(cmd, 68, 8));
        put(F_FUNC_ID, bits(cmd, 52, 8));
        put(F_BARRIER, bits(cmd, 112, 8));
        put(F_CMD_TIME, (int32_t)(uint32_t)bits(cmd, PULSE_POS_CMD_TIME, 32));
        if (kind == K_PULSE_WRITE || kind == K_PULSE_TRIG) {
            struct { int pos, width; } P[5] = {
                {PULSE_POS_ENV, PULSE_W_ENV}, {PULSE_POS_PHASE, PULSE_W_PHASE},
                {PULSE_POS_FREQ, PULSE_W_FREQ}, {PULSE_POS_AMP, PULSE_W_AMP},
                {PULSE_POS_CFG, PULSE_W_CFG}};
            int fields[5] = {F_P_ENV, F_P_PHASE, F_P_FREQ, F_P_AMP, F_P_CFG};
            int wen = 0, regsel = 0;
            for (int b = 0; b < 5; ++b) {
                put(fields[b], bits(cmd, P[b].pos, P[b].width));
                int w, r;
                if (fields[b] == F_P_CFG) {
                    w = (int)bits(cmd, P[b].pos + P[b].width, 1);
                    r = 0;
                } else {
                    int ctl = (int)bits(cmd, P[b].pos + P[b].width, 2);
                    w = (ctl >> 1) & 1;
                    r = ctl & 1;
                }
                wen |= w << b;
                regsel |= r << b;
            }
            put(F_P_WEN, wen);
            put(F_P_REGSEL, regsel);
            put(F_P_REG, bits(cmd, 116, 4));
        } else {
            put(F_P_ENV, 0); put(F_P_PHASE, 0); put(F_P_FREQ, 0);
            put(F_P_AMP, 0); put(F_P_CFG, 0);
            put(F_P_WEN, 0); put(F_P_REGSEL, 0); put(F_P_REG, 0);
        }
    }
    return 0;
}

// Batch-encode timed full-parameter pulse commands (the sweep-generation
// hot path): one command per entry, all five parameters immediate.
// Fields arrays length n; writes n*16 bytes to out.
void encode_pulse_batch(const int32_t* cmd_time, const int32_t* env,
                        const int32_t* phase, const int32_t* freq,
                        const int32_t* amp, const int32_t* cfg,
                        int n, uint8_t* out) {
    for (int i = 0; i < n; ++i) {
        unsigned __int128 cmd = 0;
        auto put = [&](unsigned __int128 v, int pos) { cmd |= v << pos; };
        put((uint32_t)cmd_time[i], PULSE_POS_CMD_TIME);
        put(((uint32_t)cfg[i] & 0xf) | (1u << PULSE_W_CFG), PULSE_POS_CFG);
        put(((uint32_t)amp[i] & 0xffff) | (1u << (PULSE_W_AMP + 1)),
            PULSE_POS_AMP);
        put(((uint32_t)freq[i] & 0x1ff) | (1u << (PULSE_W_FREQ + 1)),
            PULSE_POS_FREQ);
        put(((uint32_t)phase[i] & 0x1ffff) | (1u << (PULSE_W_PHASE + 1)),
            PULSE_POS_PHASE);
        put(((uint32_t)env[i] & 0xffffff) | (1u << (PULSE_W_ENV + 1)),
            PULSE_POS_ENV);
        put((unsigned __int128)0b10010, 123);   // pulse_write_trig
        uint8_t* dst = out + (size_t)i * CMD_BYTES;
        for (int b = 0; b < CMD_BYTES; ++b)
            dst[b] = (uint8_t)(cmd >> (8 * b));
    }
}

}  // extern "C"
