"""Decode assembled binaries into the tensorised machine program consumed
by the JAX interpreter.

The assembler's output (per-core ``cmd_buf`` bytes + env/freq buffers) is
the same artifact the reference writes to FPGA BRAM.  Here it is decoded
once, on the host, into:

* a stacked :class:`~distributed_processor_tpu.isa.SoAProgram`
  (``[n_cores, n_instr]`` int32 field arrays) with two derived fields the
  simulator needs — ``p_elem`` (element index from the cfg word) and
  ``p_dur`` (pulse duration in FPGA clocks, derived from the env word and
  the element's sample geometry);
* dense element tables (envelope IQ samples, NCO frequency entries) for
  the DSP pipeline.

Nothing here is traced by JAX; the interpreter gathers from these arrays.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from . import isa
from .elements import (TPUElementConfig, parse_env_buffer, parse_freq_buffer,
                       ENV_BANKS, FREQ_BUF_WORDS)


@dataclass
class CoreTables:
    """Per-core decoded element tables (one entry per element)."""
    envs: list        # list of complex arrays (envelope samples per element)
    freqs: list       # list of {'freq': array, 'iq15': array}
    elem_cfgs: list   # list of TPUElementConfig


@dataclass
class MachineProgram:
    """A decoded multi-core machine program, ready for the interpreter."""
    soa: isa.SoAProgram          # [n_cores, n_instr]
    p_elem: np.ndarray           # [n_cores, n_instr] element index of pulses
    p_dur: np.ndarray            # [n_cores, n_instr] pulse duration (clks)
    tables: list                 # CoreTables per core
    core_inds: list              # original core indices (sorted)
    # declared program variables per core (positional order):
    # {name: {'index': reg index, 'dtype': ('int',) | ('amp', e) | ...}}
    # — the handle for preloading register-parameterized programs
    reg_maps: list = None

    @property
    def n_cores(self) -> int:
        return self.soa.kind.shape[0]

    @property
    def n_instr(self) -> int:
        return self.soa.kind.shape[1]

    @property
    def has_fproc(self) -> bool:
        return bool(np.any((self.soa.kind == isa.K_ALU_FPROC)
                           | (self.soa.kind == isa.K_JUMP_FPROC)))

    @property
    def has_sync(self) -> bool:
        return bool(np.any(self.soa.kind == isa.K_SYNC))

    @property
    def sync_participants(self) -> np.ndarray:
        """Bool[n_cores]: cores whose program contains a SYNC instruction."""
        return np.any(self.soa.kind == isa.K_SYNC, axis=1)

    def max_pulses_per_core(self, loop_bound: int = 1024) -> int:
        """Static upper bound on emitted pulses per core (loops bounded)."""
        n_pulse_instr = int(np.max(np.sum(self.soa.kind == isa.K_PULSE_TRIG, axis=1)))
        has_backjump = bool(np.any(
            (self.soa.kind == isa.K_JUMP_COND) | (self.soa.kind == isa.K_JUMP_I)
            | (self.soa.kind == isa.K_JUMP_FPROC)))
        return n_pulse_instr * (loop_bound if has_backjump else 1)

    def loop_bounds(self, core: int) -> list:
        """Statically analyzable loops on one core: ``[(start, end,
        iterations | None)]`` per backward ``jump_cond``.

        Recognizes the compiler's counter idiom (loop_shots_program /
        the reference's loop lowering, reference: compiler.py:322-324):
        counter register initialized by an immediate ``id0`` write,
        stepped by an immediate ``add`` inside the body, tested by a
        ``ge``/``le`` jump against an immediate bound.  Anything else
        (register-register compares, fproc-driven back-edges, missing
        or non-constant step) yields ``None`` — not statically bounded.
        """
        soa = self.soa
        kind = np.asarray(soa.kind[core])
        loops = []
        op_ge, op_le = isa.ALU_OPS['ge'], isa.ALU_OPS['le']
        op_add, op_id0 = isa.ALU_OPS['add'], isa.ALU_OPS['id0']
        for j in range(len(kind)):
            if kind[j] != isa.K_JUMP_COND:
                continue
            t = int(soa.jump_addr[core, j])
            if t > j:
                continue
            bound = None
            alu_op = int(soa.alu_op[core, j])
            reg_writes = (isa.K_REG_ALU, isa.K_ALU_FPROC)
            if not soa.in0_is_reg[core, j] and alu_op in (op_ge, op_le):
                lim = int(soa.imm[core, j])
                r = int(soa.in1_reg[core, j])
                step = None
                for i in range(t, j):
                    if kind[i] in reg_writes \
                            and int(soa.out_reg[core, i]) == r:
                        if kind[i] == isa.K_REG_ALU \
                                and not soa.in0_is_reg[core, i] \
                                and int(soa.alu_op[core, i]) == op_add \
                                and int(soa.in1_reg[core, i]) == r \
                                and step is None:
                            step = int(soa.imm[core, i])
                        else:
                            # fproc-driven or non-constant counter write
                            step = 0
                            break
                # init must come from a recognized immediate write: a
                # counter seeded only via init_regs (register-
                # parameterized sweeps) is data-driven, not bounded
                init = None
                for i in range(t):
                    if kind[i] in reg_writes \
                            and int(soa.out_reg[core, i]) == r:
                        init = int(soa.imm[core, i]) \
                            if (kind[i] == isa.K_REG_ALU
                                and not soa.in0_is_reg[core, i]
                                and int(soa.alu_op[core, i]) == op_id0) \
                            else None
                if init is not None and step:
                    if alu_op == op_ge and step > 0:
                        # continue while lim >= ctr (ge = signed >=);
                        # a bound already past the limit still runs the
                        # do-while body once before the back-edge test
                        bound = (lim - init) // step + 1 \
                            if lim >= init else 1
                    elif alu_op == op_le and step < 0:
                        # continue while lim < ctr (le is STRICT signed
                        # <, alu.v:25-27): ctr = init, init+step, ...
                        # stops once ctr <= lim
                        bound = (init - lim - 1) // (-step) + 1 \
                            if lim < init else 1
                    # the formulas assume the int32 counter never wraps:
                    # if the final value leaves the register range, the
                    # wrapped comparison re-enters the loop and the trip
                    # count is NOT the closed form — fall back rather
                    # than under-size the execution budget
                    if bound is not None and not (
                            -2**31 <= init + bound * step < 2**31):
                        bound = None
            loops.append((t, j, bound))
        return loops

    def static_bounds(self, loop_fallback: int = 64,
                      slack: int = 16) -> dict:
        """Execution-budget sizing from static loop analysis.

        Returns ``{'max_steps', 'max_pulses'}``: each instruction's step
        and pulse cost is multiplied by the product of iteration counts
        of the analyzable loops enclosing it (``loop_fallback`` where a
        back-edge defeats analysis) — replacing the old one-size
        ``64 * n_instr`` heuristic that silently truncated deep loops
        (round-1 review item).
        """
        kind = np.asarray(self.soa.kind)
        C, N = kind.shape
        worst_steps, worst_pulses = 0, 0
        for c in range(C):
            mult = np.ones(N, dtype=np.int64)
            for (t, j, bound) in self.loop_bounds(c):
                mult[t:j + 1] *= bound if bound else loop_fallback
            # fproc/unconditional back-edges (e.g. measurement retry,
            # poll loops exiting via a forward jump) aren't loops the
            # analysis bounds; apply the fallback over their span
            for j in range(N):
                if kind[c, j] in (isa.K_JUMP_FPROC, isa.K_JUMP_I) \
                        and int(self.soa.jump_addr[c, j]) <= j:
                    t = int(self.soa.jump_addr[c, j])
                    mult[t:j + 1] *= loop_fallback
            live = kind[c] != isa.K_DONE
            worst_steps = max(worst_steps, int(np.sum(mult[live])))
            worst_pulses = max(worst_pulses, int(np.sum(
                mult[kind[c] == isa.K_PULSE_TRIG])))
        return {'max_steps': worst_steps + slack,
                'max_pulses': max(worst_pulses, 1) + 2}


class ProgramValidationError(ValueError):
    """A machine program failed static validation.

    ``errors`` is a list of ``(code, core, instr, message)`` tuples —
    one per defect, with instruction coordinates — so callers (CLI
    pre-flight, the fault-injection harness) can match on the failure
    kind instead of parsing the message.  ``core``/``instr`` may be
    ``None`` for program-wide defects (e.g. inconsistent sync sets).
    """

    def __init__(self, errors):
        self.errors = list(errors)
        lines = [f'[{code}] core={core} instr={instr}: {msg}'
                 for code, core, instr, msg in self.errors]
        super().__init__('program validation failed:\n  '
                         + '\n  '.join(lines))

    def __reduce__(self):
        # rebuild from the structured error list, not the rendered
        # message — default exception pickling would replay __init__
        # with the message string and corrupt ``errors`` on the far
        # side of the fleet wire (serve/transport.py)
        return (ProgramValidationError, (self.errors,))

    @property
    def codes(self) -> set:
        return {e[0] for e in self.errors}


def _core_validation_errors(soa, core: int, cfg=None) -> list:
    """Static defects of one core's ``[n_instr]`` instruction stream."""
    kind = np.asarray(soa.kind[core])
    jump_addr = np.asarray(soa.jump_addr[core])
    N = len(kind)
    errs = []
    jump_kinds = (isa.K_JUMP_I, isa.K_JUMP_COND, isa.K_JUMP_FPROC)
    exit_kinds = {isa.K_JUMP_COND, isa.K_JUMP_FPROC, isa.K_DONE}

    bad_kind = (kind < 0) | (kind >= isa.N_KINDS)
    for j in np.nonzero(bad_kind)[0]:
        errs.append(('illegal_op', core, int(j),
                     f'kind {int(kind[j])} outside [0, {isa.N_KINDS})'))

    for j in np.nonzero(np.isin(kind, jump_kinds))[0]:
        t = int(jump_addr[j])
        if not 0 <= t < N:
            errs.append(('jump_oob', core, int(j),
                         f'jump target {t} outside [0, {N})'))

    if not np.any(kind == isa.K_DONE):
        errs.append(('no_done', core, None,
                     'no DONE instruction — execution runs off the end '
                     'of the command buffer'))

    # provably infinite loop: a backward jump_i whose body [t, j] has no
    # possible exit — no conditional/fproc branch, no DONE, and every
    # other unconditional jump stays inside the body.  (Backward
    # jump_fproc loops — the active-reset retry idiom — always have a
    # data-dependent exit and are NOT flagged.)
    for j in np.nonzero(kind == isa.K_JUMP_I)[0]:
        t = int(jump_addr[j])
        if not 0 <= t <= j:
            continue
        body = range(t, int(j) + 1)
        if any(int(kind[i]) in exit_kinds for i in body):
            continue
        if any(int(kind[i]) == isa.K_JUMP_I
               and not t <= int(jump_addr[i]) <= j for i in body):
            continue
        errs.append(('infinite_loop', core, int(j),
                     f'unconditional backward jump to {t} encloses no '
                     f'exit — provably infinite'))

    if cfg is not None:
        n_cores = np.asarray(soa.kind).shape[0] if soa.kind.ndim > 1 \
            else 1
        fmask = np.isin(kind, (isa.K_ALU_FPROC, isa.K_JUMP_FPROC))
        fids = np.asarray(soa.func_id[core])
        fabric = getattr(cfg, 'fabric', 'sticky')
        for j in np.nonzero(fmask)[0]:
            fid = int(fids[j])
            if fabric == 'lut':
                # lut fabric: func_id 0 = own fresh result, nonzero =
                # the LUT output — which must actually be configured
                if fid != 0 and (len(getattr(cfg, 'lut_mask', ()))
                                 != n_cores
                                 or not getattr(cfg, 'lut_table', ())):
                    errs.append(('fproc_unreachable', core, int(j),
                                 f'func_id {fid} reads the LUT but '
                                 f'lut_mask/lut_table are not '
                                 f'configured'))
            elif not 0 <= fid < n_cores:
                errs.append(('fproc_unreachable', core, int(j),
                             f'func_id {fid} outside [0, {n_cores}) — '
                             f'no core produces this result'))
    return errs


def validate_program(mp, cfg=None) -> None:
    """Pre-flight static validation — defects caught here never reach a
    jit, never burn a dispatch, and carry instruction coordinates the
    runtime fault word cannot.

    Checks, per core: instruction kinds decodable (``illegal_op``),
    jump targets inside ``[0, n_instr)`` (``jump_oob``), a DONE
    instruction present (``no_done``), no provably infinite
    unconditional loop (``infinite_loop``); with ``cfg`` given, fproc
    reads must name a producing core — or a configured LUT under
    ``fabric='lut'`` (``fproc_unreachable``).  Across cores: if every
    SYNC participant is branch-free, their barrier sequences must agree
    (``sync_mismatch``) — a shorter partner parks the others at a
    barrier that can never fill (runtime ``FAULT_SYNC_DEADLOCK``).
    Data-dependent behavior (fproc-driven back-edges, register-bounded
    loops) is deliberately NOT flagged: the validator only rejects
    programs that are wrong on EVERY input; everything else is the
    runtime fault word's job.

    Accepts a :class:`MachineProgram` or a stacked
    :class:`MultiMachineProgram` (every ensemble member is validated).
    Raises :class:`ProgramValidationError` listing ALL defects.
    """
    kind_all = np.asarray(mp.soa.kind)
    multi = kind_all.ndim == 3
    errors = []
    for p in range(kind_all.shape[0] if multi else 1):
        soa = isa.SoAProgram(**{k: v[p] for k, v in
                                mp.soa.asdict().items()}) \
            if multi else mp.soa
        kind = np.asarray(soa.kind)
        C, N = kind.shape
        errs = []
        for c in range(C):
            errs.extend(_core_validation_errors(soa, c, cfg=cfg))
        # sync consistency: statically decidable only when every
        # participant is branch-free (its barrier sequence is the
        # textual one); any branch makes the sequence data-dependent
        part = np.nonzero(np.any(kind == isa.K_SYNC, axis=1))[0]
        if len(part) > 1:
            jump_kinds = (isa.K_JUMP_I, isa.K_JUMP_COND,
                          isa.K_JUMP_FPROC)
            if not any(np.any(np.isin(kind[c], jump_kinds))
                       for c in part):
                seqs = {c: tuple(
                    int(b) for b in np.asarray(soa.barrier[c])[
                        kind[c] == isa.K_SYNC]) for c in part}
                ref_c = int(part[0])
                for c in part[1:]:
                    if seqs[int(c)] != seqs[ref_c]:
                        errs.append((
                            'sync_mismatch', int(c), None,
                            f'barrier sequence {seqs[int(c)]} != core '
                            f'{ref_c}\'s {seqs[ref_c]} — the longer '
                            f'sequence waits at a barrier that never '
                            f'fills'))
        if multi:
            errs = [(code, (p, core) if core is not None else p,
                     instr, msg) for code, core, instr, msg in errs]
        errors.extend(errs)
    if errors:
        raise ProgramValidationError(errors)


def extract_blocks(mp: 'MachineProgram') -> list:
    """Per-core CFG extraction: partition each core's instruction range
    into maximal straight-line blocks.

    A block ends at a control-transfer / cross-core instruction
    (:data:`~distributed_processor_tpu.isa.BLOCK_TERMINATORS` plus
    DONE — the per-core analog of the reference cores retiring at a
    branch, `hdl/proc.sv` instruction loop) or just before a jump
    TARGET (every branch destination starts a block).  Returns one
    int32 ``[n_blocks, 3]`` array per core, rows ``(start, length,
    kind)`` where ``kind`` is the terminating instruction's kind or
    ``-1`` for a fall-through block (split only by an incoming edge).

    Invariants (fuzz-pinned in tests/test_blocks.py): the blocks of a
    core partition ``[0, n_instr)`` exactly, in order, and every jump
    target within range is a block start.

    This is the analysis view; the interpreter's runtime layout —
    union-refined across cores and content-deduplicated — is
    :func:`~distributed_processor_tpu.isa.build_block_table`.
    """
    kind = np.asarray(mp.soa.kind)
    jump_addr = np.asarray(mp.soa.jump_addr)
    C, N = kind.shape
    enders = set(isa.BLOCK_TERMINATORS) | {isa.K_DONE}
    out = []
    for c in range(C):
        kc = kind[c]
        term = np.isin(kc, list(enders))
        jmask = (kc == isa.K_JUMP_I) | (kc == isa.K_JUMP_COND) \
            | (kc == isa.K_JUMP_FPROC)
        leaders = {0}
        leaders.update(int(t) for t in jump_addr[c][jmask]
                       if 0 <= int(t) < N)
        leaders.update(int(i) + 1 for i in np.nonzero(term)[0]
                       if int(i) + 1 < N)
        bounds = sorted(leaders) + [N]
        rows = []
        for s, e in zip(bounds, bounds[1:]):
            k = int(kc[e - 1]) if term[e - 1] else -1
            rows.append((s, e - s, k))
        out.append(np.asarray(rows, dtype=np.int32).reshape(-1, 3))
    return out


@dataclass
class MultiMachineProgram:
    """A stacked ensemble of decoded machine programs — program-as-data.

    ``soa`` carries ``[n_progs, n_cores, n_instr]`` field arrays
    (DONE-padded into a shared shape bucket, see
    :func:`~distributed_processor_tpu.isa.shape_bucket`); element tables
    are validated identical across the ensemble so the interpreter's
    per-core constants stay unbatched.  The attribute surface mirrors
    :class:`MachineProgram` (``soa``/``tables``/``n_cores``/
    ``sync_participants``) so the interpreter's constant/traits helpers
    work on either.
    """
    soa: isa.SoAProgram          # [n_progs, n_cores, n_instr]
    p_elem: np.ndarray           # [n_progs, n_cores, n_instr]
    p_dur: np.ndarray            # [n_progs, n_cores, n_instr]
    tables: list                 # CoreTables per core (ensemble-shared)
    core_inds: list

    @property
    def n_progs(self) -> int:
        return self.soa.kind.shape[0]

    @property
    def n_cores(self) -> int:
        return self.soa.kind.shape[1]

    @property
    def n_instr(self) -> int:
        return self.soa.kind.shape[2]

    @property
    def sync_participants(self) -> np.ndarray:
        """Bool[n_progs, n_cores]: cores with a SYNC instruction."""
        return np.any(self.soa.kind == isa.K_SYNC, axis=2)


def stack_machine_programs(mps: list, pad_to: int = None,
                           bucket: bool = True) -> MultiMachineProgram:
    """Stack decoded :class:`MachineProgram`\\ s into one
    :class:`MultiMachineProgram`.

    ``bucket=True`` (default) pads ``n_instr`` up to the next power of
    two — the shape-bucket policy that lets every same-band ensemble
    share one compiled executable (``pad_to`` raises the floor further).
    Programs must agree on core count and element geometry: the
    ensemble shares one set of per-core sample-rate constants, and a
    mismatch would silently mistime pulses.  A mismatch raises
    ``ValueError`` naming the offending program INDEX, so batching
    callers (the serving runtime's coalescer) can reject the one bad
    submission instead of surfacing a shape error from deep inside a
    jit.
    """
    if not mps:
        raise ValueError('need at least one MachineProgram to stack')
    first = mps[0]
    geom = [(ec.samples_per_clk, ec.interp_ratio)
            for t in first.tables for ec in t.elem_cfgs]
    for i, mp in enumerate(mps[1:], start=1):
        if mp.n_cores != first.n_cores:
            raise ValueError(
                f'core-count mismatch in ensemble: program {i} has '
                f'{mp.n_cores} cores != program 0\'s {first.n_cores}')
        g = [(ec.samples_per_clk, ec.interp_ratio)
             for t in mp.tables for ec in t.elem_cfgs]
        if g != geom:
            raise ValueError(
                f'element geometry of program {i} differs from program '
                f'0\'s — stacked programs share per-core sample-rate '
                f'constants')
    n = max(mp.n_instr for mp in mps)
    if pad_to is not None:
        n = max(n, pad_to)
    if bucket:
        n = isa.shape_bucket(n)
    soa = isa.stack_soa_multi([mp.soa for mp in mps], pad_to=n)
    P, C, N = soa.kind.shape
    p_elem = np.zeros((P, C, N), np.int32)
    p_dur = np.zeros((P, C, N), np.int32)
    for i, mp in enumerate(mps):
        p_elem[i, :, :mp.n_instr] = mp.p_elem
        p_dur[i, :, :mp.n_instr] = mp.p_dur
    return MultiMachineProgram(soa=soa, p_elem=p_elem, p_dur=p_dur,
                               tables=first.tables,
                               core_inds=list(first.core_inds))


def machine_program_from_cmds(cmds_per_core, elem_cfgs=None,
                              pad_to: int = None) -> MachineProgram:
    """Build a MachineProgram directly from per-core 128-bit command lists.

    The raw-command analog of the reference's cocotb `load_commands` path
    (reference: cocotb/proc/test_proc.py:29-38): tests hand-assemble
    commands and run them without the compiler.  ``elem_cfgs``: element
    configs shared by every core; defaults to the standard qdrv/rdrv/rdlo
    geometry (16/16/4 samples per clock).
    """
    if elem_cfgs is None:
        elem_cfgs = [TPUElementConfig(samples_per_clk=16),
                     TPUElementConfig(samples_per_clk=16),
                     TPUElementConfig(samples_per_clk=4)]
    soas = []
    for cmds in cmds_per_core:
        if isinstance(cmds, (bytes, bytearray)):
            soas.append(isa.decode_soa(cmds))
        else:
            soas.append(isa.decode_soa(isa.cmds_to_bytes(cmds)))
    soa = isa.stack_soa(soas, pad_to=pad_to)
    n_cores, n_instr = soa.kind.shape
    tables = [CoreTables(envs=[np.zeros(0, complex)] * len(elem_cfgs),
                         freqs=[{'freq': np.zeros(0), 'iq15': np.zeros((0, 15))}] * len(elem_cfgs),
                         elem_cfgs=list(elem_cfgs))
              for _ in range(n_cores)]
    return MachineProgram(soa=soa,
                          p_elem=np.zeros((n_cores, n_instr), dtype=np.int32),
                          p_dur=np.zeros((n_cores, n_instr), dtype=np.int32),
                          tables=tables, core_inds=list(range(n_cores)))


def _pulse_duration_clks(env_word: int, cfg: TPUElementConfig) -> int:
    """Pulse duration in FPGA clocks from the env word length field."""
    _, n_samples, is_cw = cfg.env_word_fields(env_word)
    if is_cw:
        return 0
    # env samples are consumed at sample_freq / interp_ratio; one clock
    # covers samples_per_clk / interp_ratio of them
    return int(np.ceil(n_samples * cfg.interp_ratio / cfg.samples_per_clk))


def decode_assembled_program(assembled: dict, channel_configs: dict = None,
                             elem_cfgs_by_core: dict = None,
                             pad_to: int = None,
                             reg_maps: dict = None) -> MachineProgram:
    """Decode a ``GlobalAssembler.get_assembled_program()`` result.

    Element configs are needed to derive pulse durations and decode the
    env/freq buffers; provide them either via ``channel_configs`` (the same
    dict handed to GlobalAssembler, TPUElementConfig is assumed) or as an
    explicit ``{core_ind: [ElementConfig, ...]}`` mapping.
    ``reg_maps``: ``GlobalAssembler.register_maps`` — attach it so
    :func:`make_init_regs` can target declared variables by name.
    """
    core_inds = sorted(assembled, key=lambda k: int(k))
    if elem_cfgs_by_core is None:
        elem_cfgs_by_core = {}
        if channel_configs is not None:
            for chan, cfg in channel_configs.items():
                if not hasattr(cfg, 'elem_ind'):
                    continue
                per_core = elem_cfgs_by_core.setdefault(str(cfg.core_ind), {})
                per_core[cfg.elem_ind] = TPUElementConfig(**cfg.elem_params)
            elem_cfgs_by_core = {
                core: [cfgs[i] for i in sorted(cfgs)]
                for core, cfgs in elem_cfgs_by_core.items()}

    soas, tables = [], []
    for core in core_inds:
        entry = assembled[core]
        soas.append(isa.decode_soa(entry['cmd_buf']))
        cfgs = elem_cfgs_by_core.get(str(core), [])
        envs, freqs = [], []
        for e, cfg in enumerate(cfgs):
            env_buf = entry['env_buffers'][e] if e < len(entry['env_buffers']) else b''
            freq_buf = entry['freq_buffers'][e] if e < len(entry['freq_buffers']) else b''
            envs.append(parse_env_buffer(env_buf))
            freqs.append(parse_freq_buffer(freq_buf, cfg.sample_freq)
                         if len(freq_buf) >= 4 * FREQ_BUF_WORDS
                         else {'freq': np.zeros(0), 'iq15': np.zeros((0, 15))})
        tables.append(CoreTables(envs=envs, freqs=freqs, elem_cfgs=cfgs))

    soa = isa.stack_soa(soas, pad_to=pad_to)
    n_cores, n_instr = soa.kind.shape
    p_elem = np.zeros((n_cores, n_instr), dtype=np.int32)
    p_dur = np.zeros((n_cores, n_instr), dtype=np.int32)
    for c, core in enumerate(core_inds):
        cfgs = tables[c].elem_cfgs
        is_pulse = (soa.kind[c] == isa.K_PULSE_TRIG) | (soa.kind[c] == isa.K_PULSE_WRITE)
        for i in np.nonzero(is_pulse)[0]:
            elem = int(soa.p_cfg[c, i]) & 0b11   # cfg word low bits = element
            p_elem[c, i] = elem
            if elem < len(cfgs) and (soa.p_wen[c, i] >> 0) & 1:  # env written
                p_dur[c, i] = _pulse_duration_clks(int(soa.p_env[c, i]), cfgs[elem])
    return MachineProgram(soa=soa, p_elem=p_elem, p_dur=p_dur,
                          tables=tables,
                          core_inds=[int(c) for c in core_inds],
                          reg_maps=[dict((reg_maps or {}).get(c, {}))
                                    for c in core_inds])


def make_init_regs(mp: MachineProgram, assignments: dict,
                   n_shots: int = None) -> np.ndarray:
    """Register file preloading named program variables.

    ``assignments``: ``{var_name: value}`` where a value is a scalar or
    a ``[n_shots]`` array (sweep axis).  Physical values are converted
    to words by the variable's declared dtype and the core's element
    config: ``('amp', e)`` floats in [0, 1] -> 16-bit amp words,
    ``('phase', e)`` radians -> 17-bit phase words, ``('int',)``
    passthrough.  Each variable is written on every core that declared
    it.  Returns ``[n_cores, N_REGS]`` int32, or
    ``[n_shots, n_cores, N_REGS]`` when ``n_shots`` is given — feed to
    ``simulate``/``simulate_batch``/``run_physics_batch`` ``init_regs``.

    This is the simulator-side analog of the reference host writing
    parameter registers over the FPGA bus before triggering a run.
    """
    from . import isa as _isa
    if not mp.reg_maps or not any(mp.reg_maps):
        raise ValueError(
            'program declares no variables (reg_maps empty) — either it '
            'declares none, or decode_assembled_program was called '
            'without reg_maps=GlobalAssembler.register_maps '
            '(pipeline.compile_to_machine threads it automatically)')
    shape = ((n_shots, mp.n_cores, _isa.N_REGS) if n_shots is not None
             else (mp.n_cores, _isa.N_REGS))
    regs = np.zeros(shape, np.int32)

    def to_word(val, dtype, cfgs):
        # array-wise mirrors of ElementConfig.get_amp_word /
        # get_phase_word (elements.py) — the scalar methods would cost a
        # Python call per shot on million-shot sweep axes
        kind = dtype[0]
        if kind == 'int':
            return np.asarray(val).astype(np.int64)
        elem = int(dtype[1])
        if elem >= len(cfgs):
            raise ValueError(f'dtype {dtype}: core has no element {elem}')
        from .elements import AMP_BITS, PHASE_BITS
        v = np.asarray(val, float)
        if kind == 'amp':
            if np.any((v < 0) | (v > 1)):
                raise ValueError(f'amplitudes must be in [0, 1]: {v}')
            return np.round(v * ((1 << AMP_BITS) - 1)).astype(np.int64)
        frac = (v / (2 * np.pi)) % 1.0
        return np.round(frac * (1 << PHASE_BITS)).astype(np.int64) \
            % (1 << PHASE_BITS)

    for name, val in assignments.items():
        val_arr = np.asarray(val)
        if val_arr.ndim > 1 or (val_arr.ndim == 1 and n_shots is None):
            raise ValueError(
                f'{name!r}: array values need n_shots= (got shape '
                f'{val_arr.shape}, n_shots={n_shots})')
        if val_arr.ndim == 1 and n_shots is not None \
                and val_arr.shape[0] != n_shots:
            raise ValueError(
                f'{name!r}: value length {val_arr.shape[0]} != '
                f'n_shots {n_shots}')
        hit = False
        for c, rm in enumerate(mp.reg_maps):
            if name not in rm:
                continue
            hit = True
            word = to_word(val, tuple(rm[name]['dtype']),
                           mp.tables[c].elem_cfgs)
            word = (word.astype(np.int64) & 0xffffffff).astype(np.int64)
            word = word.astype(np.uint32).view(np.int32)
            regs[..., c, rm[name]['index']] = word
        if not hit:
            raise KeyError(f'variable {name!r} not declared by any core; '
                           f'declared: '
                           f'{sorted(set().union(*map(set, mp.reg_maps)))}')
    return regs
