"""End-to-end integrity fabric: content digests and typed SDC errors.

The stack's value proposition is bit-exact execution, yet every trust
boundary it crosses — pickled wire frames, shared on-disk warm tiers,
device memory — can silently flip a bit and nothing downstream would
notice: a corrupted frame unpickles cleanly into wrong numbers, a
corrupted store entry loads as a valid-looking MachineProgram, a
degrading device returns plausible garbage.  This module is the shared
vocabulary every detection point uses (docs/ROBUSTNESS.md
"Integrity"):

* :func:`content_crc32` / :func:`program_digest` / :func:`stats_digest`
  — cheap content checksums over raw buffers.  The algorithm is
  ``zlib.crc32`` (CRC-32/ISO-HDLC): it is C-speed, in the stdlib, and
  identical in every process that shares this codebase.  CRC32C
  (Castagnoli) would be marginally stronger against some burst
  patterns but needs either a hardware instruction binding or a
  third-party package — for a *detection* checksum over kilobyte-scale
  frames the ISO polynomial's guarantees are equivalent in practice,
  so we stay dependency-free.
* :func:`diff_stats` — the per-stat comparison (shape, dtype-exact
  values, fault words included) the audit sampler and scrubber use to
  judge two executions of the same program.
* :class:`IntegrityError` — the typed failure every detection point
  raises.  Deliberately a plain RuntimeError subclass so
  :func:`~.sim.interpreter.is_infrastructure_error` classifies it
  retryable: detected corruption is an infrastructure fault (retry on
  a different engine/device/replica re-derives the truth), never a
  program-class error.
* :func:`flip_bit` — the seeded single-bit corrupter the chaos harness
  and tests inject with, kept here so injection and detection agree on
  what "one flipped bit" means.

Everything here is pure computation over host numpy — no jax, no I/O —
so the compile cache, the serve tier and the transport layer can all
import it without cycles.
"""

from __future__ import annotations

import zlib
from dataclasses import fields as _dc_fields

import numpy as np


class IntegrityError(RuntimeError):
    """Silent data corruption was DETECTED at a trust boundary (wire
    frame digest, store digest, differential audit, scrubber).  A
    plain RuntimeError on purpose:
    :func:`~.sim.interpreter.is_infrastructure_error` classifies it
    infrastructure-class, so the serve retry/breaker machinery and the
    fleet router both re-execute instead of surfacing tainted bits —
    and :func:`~.serve.router.is_terminal_error` leaves it retryable
    across replicas."""


def content_crc32(chunks) -> int:
    """CRC32 folded over an iterable of ``bytes`` chunks."""
    crc = 0
    for chunk in chunks:
        crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _array_chunks(name: str, value):
    """The canonical byte stream for one named array: name, dtype,
    shape, then the C-contiguous buffer — so a digest mismatch means
    the *content* differs, not the memory layout."""
    a = np.ascontiguousarray(np.asarray(value))
    yield name.encode('utf-8')
    yield str(a.dtype).encode('ascii')
    yield np.asarray(a.shape, np.int64).tobytes()
    yield a.tobytes()


def program_digest(mp) -> int:
    """Content digest of a :class:`~.decoder.MachineProgram`: every
    SoA field array plus the pulse element/duration side tables — the
    exact buffers the interpreter gathers from, so any bit that could
    change execution changes the digest.  Computed at submit, verified
    where the program crosses a trust boundary (wire receive, store
    load)."""
    chunks = []
    for f in _dc_fields(mp.soa):
        chunks.extend(_array_chunks(f.name, getattr(mp.soa, f.name)))
    chunks.extend(_array_chunks('p_elem', mp.p_elem))
    chunks.extend(_array_chunks('p_dur', mp.p_dur))
    return content_crc32(chunks)


def stats_digest(stats: dict) -> int:
    """Content digest of a per-request result stat block (the dict
    ``simulate_batch`` returns: meas, regs, fault words, ...), key
    order independent."""
    chunks = []
    for k in sorted(stats):
        chunks.extend(_array_chunks(k, stats[k]))
    return content_crc32(chunks)


def diff_stats(got: dict, want: dict) -> list:
    """Stat keys on which two executions of the same program disagree
    (missing key, shape skew, or any value difference — fault words
    included).  Empty list = bit-identical."""
    bad = []
    for k in sorted(set(got) | set(want)):
        if k not in got or k not in want:
            bad.append(k)
            continue
        a = np.asarray(got[k])
        b = np.asarray(want[k])
        if a.shape != b.shape or not np.array_equal(a, b):
            bad.append(k)
    return bad


def flip_bit(arr, *, bit: int = 0, index: int = 0):
    """A copy of ``arr`` with exactly one bit flipped in its flattened
    element ``index`` — the canonical single-event-upset model the
    chaos ``corrupt`` action and the integrity tests inject.  Only
    integer arrays qualify (every interpreter stat is int32/int64);
    raises ValueError otherwise so a silent no-op corruption can never
    make a detection test vacuously pass."""
    a = np.array(arr, copy=True)
    if a.dtype.kind not in 'iu' or a.size == 0:
        raise ValueError(
            f'flip_bit needs a non-empty integer array, got '
            f'dtype={a.dtype} size={a.size}')
    flat = a.reshape(-1)
    i = index % flat.size
    flat[i] = flat[i] ^ np.asarray(
        1 << (bit % (8 * a.dtype.itemsize)), a.dtype)
    return a


def flip_payload_bit(data: bytes, *, bit_index: int = 0) -> bytes:
    """``data`` with one bit flipped (byte-granular index wraps) — the
    wire-frame corruption model for the transport chaos hook and the
    raw-socket regression tests."""
    if not data:
        return data
    buf = bytearray(data)
    i = (bit_index // 8) % len(buf)
    buf[i] ^= 1 << (bit_index % 8)
    return bytes(buf)
