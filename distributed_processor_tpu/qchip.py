"""Gate-library subsystem: qubit frequencies and calibrated gate definitions.

The reference imports this from the external ``qubitconfig`` package (loaded
from qubitcfg.json files; see reference python/test/qubitcfg.json and the
usage in python/distproc/ir/passes.py:308-357).  This is a self-contained
reimplementation of the behaviour the compiler depends on:

* ``QChip.gates['Q0X90']`` → :class:`Gate`, a sequence of
  :class:`GatePulse` / :class:`GateVirtualZ` entries;
* named-frequency resolution (``'Q0.freq'`` → Qubits table lookup);
* per-call gate parameter modification (``modi``) and lazy dereferencing of
  frequency names / symbolic phases.

JSON format::

    {"Qubits": {"Q0": {"freq": ..., "readfreq": ...}, ...},
     "Gates": {"Q0X90": [ {pulse fields...}, {"gate": "virtualz", ...} ]}}
"""

from __future__ import annotations

import copy
import hashlib
import json
from dataclasses import dataclass, field

from .utils import eval_numeric


@dataclass
class GateVirtualZ:
    """A virtual-Z entry inside a gate definition."""
    freq: str          # resolved ('global') frequency name, e.g. 'Q0.freq'
    phase: float

    @property
    def global_freqname(self) -> str:
        return self.freq

    def to_dict(self) -> dict:
        return {'gate': 'virtualz', 'freq': self.freq, 'phase': self.phase}


@dataclass
class GatePulse:
    """One calibrated pulse inside a gate definition."""
    dest: str
    twidth: float
    env: list | dict | None = None
    t0: float = 0.0
    amp: float = 1.0
    phase: float = 0.0
    freq: float | str | None = None     # numeric after dereference()
    freqname: str | None = None         # name preserved for the compiler

    def dereference(self, qchip: 'QChip'):
        if isinstance(self.freq, str):
            self.freqname = self.freq
            self.freq = qchip.get_qubit_freq(self.freqname)
        self.phase = eval_numeric(self.phase)
        self.amp = eval_numeric(self.amp)

    def to_dict(self) -> dict:
        d = {'dest': self.dest, 'phase': self.phase, 't0': self.t0,
             'twidth': self.twidth, 'amp': self.amp}
        d['freq'] = self.freqname if self.freqname is not None else self.freq
        if self.env is not None:
            d['env'] = self.env
        return d


@dataclass
class GateRef:
    """A composite-gate entry referencing another named gate, played with an
    optional time offset (e.g. Y-90 = virtualz . X90 . virtualz)."""
    gatename: str
    t0: float = 0.0

    def to_dict(self) -> dict:
        return {'gate': self.gatename, 't0': self.t0}


def _entry_from_dict(d: dict):
    if d.get('gate') == 'virtualz':
        return GateVirtualZ(freq=d['freq'], phase=eval_numeric(d['phase']))
    if 'gate' in d:
        return GateRef(gatename=d['gate'], t0=d.get('t0', 0.0))
    fields = {k: v for k, v in d.items() if k in
              ('dest', 'twidth', 'env', 't0', 'amp', 'phase', 'freq')}
    return GatePulse(**fields)


@dataclass
class Gate:
    """A named gate: an ordered list of pulses and virtual-z rotations."""
    name: str
    contents: list = field(default_factory=list)

    def get_pulses(self):
        return self.contents

    def get_updated_copy(self, modi: dict) -> 'Gate':
        """Return a copy with per-pulse parameter modifications applied.

        ``modi`` maps ``(pulse_index, attribute)`` → new value, e.g.
        ``{(0, 'amp'): 0.5}`` (the reference circuit format's gate
        ``modi`` field, python/distproc/compiler.py:8).
        """
        new = copy.deepcopy(self)
        for key, value in modi.items():
            ind, attr = key
            setattr(new.contents[ind], attr, value)
        return new

    def dereference(self, qchip: 'QChip'):
        """Resolve frequency names / symbolic phases and expand composite
        gate references (recursively, with the reference's t0 offset added
        to each expanded pulse)."""
        expanded = []
        for entry in self.contents:
            if isinstance(entry, GateRef):
                sub = qchip.get_gate(entry.gatename)
                for sub_entry in sub.contents:
                    if isinstance(sub_entry, GatePulse):
                        sub_entry.t0 += entry.t0
                    expanded.append(sub_entry)
            else:
                if isinstance(entry, GatePulse):
                    entry.dereference(qchip)
                expanded.append(entry)
        self.contents = expanded

    @property
    def dest_channels(self) -> set:
        return {p.dest for p in self.contents if isinstance(p, GatePulse)}

    def to_dict(self) -> list:
        return [c.to_dict() for c in self.contents]


class QChip:
    """The chip calibration object: qubit frequency table + gate library."""

    def __init__(self, source: str | dict):
        if isinstance(source, str):
            with open(source) as f:
                source = json.load(f)
        self.qubits: dict = copy.deepcopy(source.get('Qubits', {}))
        self.gates: dict[str, Gate] = {}
        for name, entries in source.get('Gates', {}).items():
            self.gates[name] = Gate(
                name, [_entry_from_dict(e) for e in entries])

    def get_qubit_freq(self, freqname: str) -> float:
        """Resolve 'Q0.freq'-style names against the Qubits table."""
        if not isinstance(freqname, str):
            return freqname
        try:
            qubit, attr = freqname.split('.', 1)
            return float(self.qubits[qubit][attr])
        except (ValueError, KeyError):
            raise KeyError(f'cannot resolve frequency name {freqname!r}')

    def get_gate(self, name: str, modi: dict = None) -> Gate:
        """Fetch a dereferenced (numeric-frequency) copy of a gate."""
        gate = self.gates[name]
        if modi is not None:
            gate = gate.get_updated_copy(modi)
        else:
            gate = copy.deepcopy(gate)
        gate.dereference(self)
        return gate

    @property
    def dest_channels(self) -> set:
        out = set()
        for gate in self.gates.values():
            out |= gate.dest_channels
        return out

    def to_dict(self) -> dict:
        return {'Qubits': copy.deepcopy(self.qubits),
                'Gates': {name: g.to_dict() for name, g in self.gates.items()}}

    def fingerprint(self) -> str:
        """Stable content hash of the calibration state (frequency table
        + gate library): equal for two QChips built from the same source
        regardless of dict-key order, changed by any retune — one gate
        amplitude, one qubit frequency.  This names the *calibration
        epoch* in compile-cache keys (see compilecache/), so a qchip
        update invalidates exactly the entries compiled against it.
        """
        blob = json.dumps(self.to_dict(), sort_keys=True,
                          default=_fingerprint_default,
                          separators=(',', ':'))
        return hashlib.sha256(blob.encode()).hexdigest()


def _fingerprint_default(obj):
    """json.dumps fallback for calibration values that aren't JSON
    scalars: numpy arrays/scalars (duck-typed, no numpy import here)
    and complex amplitudes; anything else keys on its repr."""
    if isinstance(obj, complex):
        return ['__complex__', obj.real, obj.imag]
    if hasattr(obj, 'dtype') and hasattr(obj, 'tolist'):
        return obj.tolist()
    return repr(obj)
