"""Gradient-based calibration service (docs/CALIBRATION.md).

The closed-loop tuning vertical: differentiable forward models live in
:mod:`..sim.grad`, the serve-tier traffic class in :mod:`.session`
(opened via ``ExecutionService.open_calibration``), and the
gradient-descent loops — candidate submission, convergence detection,
live-qchip writeback, stale-epoch flush — in :mod:`.loops`.
"""

from .loops import CalibResult, calibrate
from .session import CalibrationSession
