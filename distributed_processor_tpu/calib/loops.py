"""Gradient-descent calibration loops, closed through the serve tier.

Each loop tunes ONE knob of a live :class:`~..qchip.QChip` — drive
amplitude, DRAG coefficient, readout-window placement — by descending
the differentiable forward model in :mod:`..sim.grad`:

1. the current parameter guess becomes a candidate program (gate
   ``modi`` overrides — the same per-call parameterization hardware
   calibration sweeps use),
2. the candidate is submitted through the serving tier's compile front
   door (``submit_source`` under a :class:`~.session.
   CalibrationSession`), so it pays the full production path — content-
   addressed compile cache, tenant quotas, coalesced dispatch,
3. the demuxed result's as-executed pulse records close the loop: the
   candidate's quantized amplitude word is read back out of
   ``rec_amp`` and the gradient is evaluated at the value the device
   actually played (docs/CALIBRATION.md "Closing the loop"),
4. :func:`~..sim.grad.grad_loss` yields the step; convergence /
   divergence is decided on the loss trajectory.

On convergence the loop **writes back** to the live qchip object and
submits one post-writeback probe through the same service: the compile
cache's lineage tracking (PR 9) sees the mutated fingerprint and
flushes exactly the stale epoch's entries —
``compilecache.writeback_flushes`` counts these loops in production.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as np

from ..models.experiments import rabi_program
from ..sim.grad import AMP_SCALE, LossSpec, PARAM_NAME, grad_loss

# ADC/DAC sample cadence of the forward model: one readout-window
# sample is 1 ns, so a window start of s samples writes back as a
# read-pulse t0 of s * 1e-9 seconds
SAMPLE_RATE = 1e9

# default step budget / step sizes per knob (the loss scales differ:
# see docs/CALIBRATION.md "Knobs")
_DEFAULTS = {
    'amplitude': dict(lr=0.3, xtol=1e-4, max_steps=40, start=0.30),
    'drag': dict(lr=1.0, xtol=1e-3, max_steps=40, start=0.1),
    'readout_window': dict(lr=3000.0, xtol=0.75, max_steps=80,
                           start=32.0),
}
# divergence guard rails: a parameter escaping its physical range is a
# diverged loop, not an exception
_BOUNDS = {
    'amplitude': (0.0, 1.5),
    'drag': (-5.0, 5.0),
    'readout_window': (0.0, None),   # upper bound bound to the horizon
}


@dataclass
class CalibResult:
    """Outcome of one calibration loop (JSON-able via ``to_dict``)."""
    knob: str
    converged: bool
    diverged: bool
    steps: int
    params: dict
    losses: list
    fp_before: str = None      # qchip fingerprint before writeback
    fp_after: str = None       # ... after (differs iff written back)
    flushed: int = None        # stale-epoch entries the probe flushed
    session: dict = None       # CalibrationSession.close() summary
    detail: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            'knob': self.knob, 'converged': self.converged,
            'diverged': self.diverged, 'steps': self.steps,
            'params': self.params, 'losses': self.losses,
            'fp_before': self.fp_before, 'fp_after': self.fp_after,
            'flushed': self.flushed, 'detail': self.detail,
        }


def _executed_amp(res, amp: float) -> float:
    """Close the loop on the demuxed pulse records: find the candidate
    amplitude's quantized word in the as-executed ``rec_amp`` record
    and return it as a fraction — the linearization point the gradient
    is evaluated at.  A missing word means the serving tier did not
    play the candidate we think it played; that is a loop bug, not a
    physics outcome, so it raises."""
    word = int(round(amp * AMP_SCALE))
    rec = np.asarray(res['rec_amp'])
    if not np.any(rec == word):
        raise RuntimeError(
            f'candidate amp word {word} absent from executed rec_amp '
            f'(words played: {sorted(set(rec.ravel().tolist()))[:8]})')
    return word / AMP_SCALE


def _make_candidate(knob: str, qubit: str, x: float,
                    nominal: dict) -> list:
    """The candidate program for one step: gate-``modi`` overrides of
    the knob's parameter (every candidate differs from its neighbors
    by one float — the compile-cache key stress shape)."""
    if knob == 'amplitude':
        return rabi_program(qubit, x)
    if knob == 'drag':
        para = dict(nominal['paradict'], alpha=float(x))
        return [
            {'name': 'X90', 'qubit': [qubit],
             'modi': {(0, 'env'): {'env_func': 'DRAG',
                                   'paradict': para}}},
            {'name': 'read', 'qubit': [qubit]},
        ]
    # readout_window: shift both read pulses (rdrv + rdlo) to the
    # candidate window start
    t0 = float(x) / SAMPLE_RATE
    return [
        {'name': 'X90', 'qubit': [qubit]},
        {'name': 'read', 'qubit': [qubit],
         'modi': {(0, 't0'): t0, (1, 't0'): t0}},
    ]


def _apply_writeback(qchip, knob: str, qubit: str, x: float) -> None:
    """Write the converged value into the LIVE qchip object — the
    real-writer side of the PR 9 calibration-epoch machinery (the next
    submission through a lineage-tracking cache flushes the old
    epoch)."""
    if knob == 'amplitude':
        qchip.gates[qubit + 'X90'].contents[0].amp = float(x)
    elif knob == 'drag':
        gate = qchip.gates[qubit + 'X90'].contents[0]
        gate.env = dict(gate.env)
        gate.env['paradict'] = dict(gate.env['paradict'],
                                    alpha=float(x))
    else:
        t0 = float(x) / SAMPLE_RATE
        for pulse in qchip.gates[qubit + 'read'].contents:
            pulse.t0 = t0


def calibrate(service, qchip, *, knob: str = 'amplitude',
              qubit: str = 'Q0', spec: LossSpec = None,
              start: float = None, lr: float = None, xtol: float = None,
              max_steps: int = None, shots: int = 16,
              tenant: str = None, priority: int = 0,
              write_back: bool = True, n_qubits: int = 8,
              result_timeout: float = 300.0) -> CalibResult:
    """Run one knob's closed-loop calibration through ``service``.

    Opens a :class:`~.session.CalibrationSession`, descends
    :func:`~..sim.grad.grad_loss` with per-step candidate submissions
    (dependent traffic: step k+1's candidate is computed from step k's
    result), and on convergence writes the tuned value back to the
    live ``qchip`` and submits a post-writeback probe so the compile
    cache flushes exactly the stale epoch.  Returns a
    :class:`CalibResult`; a diverged loop returns (``diverged=True``)
    rather than raising — divergence is a counted, observable outcome
    (``serve.calib.diverged``), not an exception.
    """
    d = _DEFAULTS[knob]   # KeyError = unknown knob, same set as grad.KNOBS
    lr = d['lr'] if lr is None else float(lr)
    xtol = d['xtol'] if xtol is None else float(xtol)
    max_steps = d['max_steps'] if max_steps is None else int(max_steps)
    x = float(d['start'] if start is None else start)
    if spec is None:
        if knob == 'drag':
            # the loss-model anharmonicity is softer than the gate's
            # nominal -270 MHz: at the nominal detuning the gaussian's
            # spectral weight underflows float32 and the gradient is
            # numerically zero (docs/CALIBRATION.md "Knobs")
            spec = LossSpec(knob='drag', drag_delta=-30e6)
        elif knob == 'readout_window':
            # a wider soft edge smooths the placement optimum's kink
            # (where the window starts falling off the record) enough
            # for plain gradient descent at the default step size
            spec = LossSpec(knob='readout_window', window_edge=8.0)
        else:
            spec = LossSpec(knob=knob)
    pname = PARAM_NAME[knob]
    lo, hi = _BOUNDS[knob]
    if knob == 'readout_window':
        hi = float(spec.window_horizon)
    nominal = {'paradict': {'alpha': 0.4, 'sigmas': 3, 'delta': -270e6}}
    session = service.open_calibration(knob=knob, tenant=tenant,
                                       priority=priority)
    converged = False
    reason = None
    prev_loss = None
    rising = 0
    with session:
        for _ in range(max_steps):
            program = _make_candidate(knob, qubit, x, nominal)
            handle = session.submit_step(program, qchip, shots=shots,
                                         n_qubits=n_qubits)
            res = handle.result(timeout=result_timeout)
            # close the loop on the as-executed records where the knob
            # is an amplitude; other knobs record the executed schedule
            x_exec = _executed_amp(res, x) if knob == 'amplitude' else x
            loss, grads = grad_loss({pname: x_exec}, spec)
            loss, g = float(loss), float(grads[pname])
            session.note_loss(loss)
            if not math.isfinite(loss) or not math.isfinite(g):
                reason = f'non-finite loss/gradient at {pname}={x:.6g}'
                break
            if prev_loss is not None and loss > prev_loss + 1e-12:
                rising += 1
                if rising >= 4:
                    reason = (f'loss rising for {rising} consecutive '
                              f'steps (lr too large?)')
                    break
            else:
                rising = 0
            prev_loss = loss
            step = lr * g
            if abs(step) < xtol:
                converged = True
                break
            x -= step
            if (lo is not None and x < lo) or \
                    (hi is not None and x > hi):
                reason = f'{pname}={x:.6g} escaped bounds ({lo}, {hi})'
                break
        if converged:
            session.mark_converged({pname: x})
        else:
            if reason is None:
                reason = f'step budget ({max_steps}) exhausted'
            session.mark_diverged(reason)
        steps = session.steps
        losses = list(session.losses)
    summary = {'sid': session.sid, 'state': session.state,
               'reason': session.reason}
    result = CalibResult(knob=knob, converged=converged,
                         diverged=not converged, steps=steps,
                         params={pname: x}, losses=losses,
                         session=summary,
                         detail={'reason': reason, 'lr': lr,
                                 'xtol': xtol, 'shots': shots})
    if converged and write_back:
        result.fp_before, result.fp_after, result.flushed = \
            _write_back_and_probe(service, qchip, knob, qubit, x,
                                  shots=shots, tenant=tenant,
                                  n_qubits=n_qubits,
                                  timeout=result_timeout)
    return result


def _write_back_and_probe(service, qchip, knob, qubit, x, *, shots,
                          tenant, n_qubits, timeout):
    """Mutate the live qchip and resubmit through the same service:
    the cache's lineage tracking flushes exactly the old epoch
    (counted by ``compilecache.writeback_flushes``)."""
    fp_before = qchip.fingerprint()
    _apply_writeback(qchip, knob, qubit, x)
    fp_after = qchip.fingerprint()
    cache = service.compile_cache
    flushed_before = cache.stats()['invalidated_entries']
    handle = service.submit_source(rabi_program(qubit, 0.48), qchip,
                                   shots=shots, tenant=tenant,
                                   n_qubits=n_qubits)
    handle.result(timeout=timeout)
    flushed = cache.stats()['invalidated_entries'] - flushed_before
    return fp_before, fp_after, flushed
