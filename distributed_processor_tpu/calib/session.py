"""Calibration traffic class: gradient-descent tuning sessions.

The serving counterpart of :mod:`.loops` (docs/SERVING.md
"Calibration sessions"): a :class:`CalibrationSession` is a long-lived
handle over one knob's tuning loop, mirroring the
:class:`~..serve.stream.StreamSession` shape — opened by
``ExecutionService.open_calibration``, target-generic (it only needs
``submit_source`` / ``calib_event`` / ``close_calibration``), a
context manager, closed with a summary.

Where a stream's unit of traffic is a round chunk, a calibration
session's unit is a *step*: one candidate program (the current
parameter guess) submitted through the ordinary ``submit_source``
front door — so the compile cache, tenant quotas/metering, priority
lanes and overload control all apply unchanged — whose demuxed result
feeds the gradient step that produces the NEXT candidate.  Steps are
dependent by construction (candidate k+1 needs candidate k's result),
which is exactly the bursty nearly-identical-program traffic the
compile-cache key/LRU stress tests pin (tests/test_calib.py).

Observability: every step/convergence/divergence is reported to the
service (``serve.calib.*`` counters, ``stats()['calibration']``,
flight-recorder events for the terminal transitions).
"""

from __future__ import annotations


class CalibrationSession:
    """One open calibration loop: submit candidate steps, record the
    loss trajectory, mark the terminal state.

    Not thread-safe for concurrent ``submit_step`` calls (one
    optimizer per session — steps are sequentially dependent anyway).
    ``tenant`` is a SESSION property: every candidate inherits it, so
    a loop's compiles and shots are metered and fair-queued under the
    tenant that opened it (docs/SERVING.md "Tenants").
    """

    def __init__(self, target, sid: int, *, knob: str,
                 tenant: str = None, priority: int = 0):
        self._target = target
        self.sid = sid
        self.knob = knob
        self.tenant = tenant
        self.priority = priority
        self.steps = 0
        self.losses = []           # loss trajectory, submit order
        self.params = None         # last/converged parameter dict
        self.state = 'open'        # open | converged | diverged
        self.reason = None         # divergence reason, when diverged
        self._closed = False

    # -- producer side ---------------------------------------------------

    def submit_step(self, program, qchip, *, shots: int = None,
                    meas_bits=None, cfg=None, deadline_ms: float = None,
                    **kw):
        """Submit one candidate program through the target's compile
        front door; returns its handle immediately.  Counts the step
        against the session (``serve.calib.steps``)."""
        if self._closed:
            raise RuntimeError(f'calibration {self.sid} is closed')
        handle = self._target.submit_source(
            program, qchip, shots=shots, meas_bits=meas_bits, cfg=cfg,
            priority=self.priority, deadline_ms=deadline_ms,
            tenant=self.tenant, **kw)
        self.steps += 1
        self._target.calib_event(self.sid, 'step')
        return handle

    def note_loss(self, loss) -> None:
        """Record one step's loss (the trajectory the summary and the
        ``cli calibrate`` printout report)."""
        self.losses.append(float(loss))

    # -- terminal transitions --------------------------------------------

    def mark_converged(self, params: dict = None) -> None:
        """The loop met its tolerance: record the converged parameters
        and count the convergence (``serve.calib.converged``)."""
        self._require_open()
        self.state = 'converged'
        self.params = dict(params) if params else None
        self._target.calib_event(self.sid, 'converged', knob=self.knob,
                                 steps=self.steps)

    def mark_diverged(self, reason: str = None) -> None:
        """The loop failed (loss rising, NaN, step budget): count the
        divergence (``serve.calib.diverged``) with its reason."""
        self._require_open()
        self.state = 'diverged'
        self.reason = reason
        self._target.calib_event(self.sid, 'diverged', knob=self.knob,
                                 steps=self.steps, reason=reason)

    def _require_open(self):
        if self._closed or self.state != 'open':
            raise RuntimeError(
                f'calibration {self.sid} already {self.state}')

    # -- lifecycle -------------------------------------------------------

    def close(self) -> dict:
        """Deregister the session with the target and return the
        session summary (knob, step count, terminal state, loss
        trajectory, converged params)."""
        if self._closed:
            raise RuntimeError(
                f'calibration {self.sid} is already closed')
        self._closed = True
        self._target.close_calibration(self.sid)
        return {
            'sid': self.sid,
            'knob': self.knob,
            'steps': self.steps,
            'state': self.state,
            'losses': list(self.losses),
            'params': self.params,
            'reason': self.reason,
        }

    def __enter__(self):
        return self

    def __exit__(self, *exc_info):
        if not self._closed:
            self.close()
