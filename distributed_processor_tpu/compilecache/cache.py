"""The content-addressed compile cache: source -> MachineProgram.

:class:`CompileCache` turns compilation into a service-grade stage in
front of :func:`~..pipeline.compile_to_machine`:

* **content addressing** — :func:`~.key.content_key` over (program
  source, qchip calibration fingerprint, FPGAConfig, CompilerFlags,
  channel geometry).  Identical tenant submissions — including
  re-ordered instruction dicts and byte-identical QASM text — hit one
  entry; a hit returns the SAME MachineProgram arrays a direct compile
  would produce (bit-identity is pinned in tests/test_compilecache.py).
* **LRU memory tier** over an optional persistent disk tier
  (:class:`~.store.PersistentStore`): eviction drops the in-memory
  entry only, so an evicted program comes back as a cheap disk hit,
  and a process restart starts warm.
* **singleflight** — N concurrent identical submissions block on ONE
  compile; the stampede wakes together on the winner's result (or its
  typed failure).  ``stats()['singleflight_waits']`` counts the
  dedup that saved a compile each.
* **admission validation** — the freshly-compiled program runs
  :func:`~..decoder.validate_program` before it is admitted, so a
  malformed tenant program is rejected with ``(code, core, instr)``
  coordinates and never cached, never dispatched.
* **calibration-epoch invalidation** — each entry is tagged with its
  qchip fingerprint.  Resubmitting through a mutated ``QChip`` object
  (same identity, new fingerprint) auto-flushes exactly the stale
  epoch's entries, memory and disk; other qchips' entries stay warm.
  :meth:`invalidate_epoch` does the same explicitly.

Thread-safe throughout; compilation itself runs outside the lock.
"""

from __future__ import annotations

import collections
import threading
import time

from .key import content_key
from .store import PersistentStore
from ..utils import profiling

# get_or_compile outcome labels (the `status` the caller sees)
HIT = 'hit'            # in-memory LRU hit
DISK = 'disk'          # persistent-store hit (promoted to memory)
MISS = 'miss'          # compiled here
WAIT = 'wait'          # singleflight: waited on another thread's compile


class _Flight:
    """One in-progress compile other threads can wait on."""

    __slots__ = ('event', 'result', 'exc')

    def __init__(self):
        self.event = threading.Event()
        self.result = None
        self.exc = None


class CompileCache:
    """See module docstring.  ``capacity`` bounds the in-memory LRU;
    ``cache_dir`` (optional) adds the persistent tier; ``validate``
    gates admission-time :func:`validate_program`; ``compile_fn``
    overrides the compile callable (tests inject slow/broken
    compilers) — it receives the dict-instruction program plus the
    same keyword surface as :func:`compile_to_machine`."""

    def __init__(self, capacity: int = 256, cache_dir: str = None,
                 validate: bool = True, compile_fn=None,
                 latency_window: int = 4096):
        if capacity < 1:
            raise ValueError('capacity must be >= 1')
        self.capacity = capacity
        self.validate = validate
        self._compile_fn = compile_fn
        self._store = PersistentStore(cache_dir) if cache_dir else None
        self._lock = threading.Lock()
        self._lru = collections.OrderedDict()   # key -> (mp, qchip_fp)
        self._flights = {}                      # key -> _Flight
        self._lineage = {}                      # id(qchip) -> fingerprint
        self._compile_s = collections.deque(maxlen=latency_window)
        self._hits = 0
        self._misses = 0
        self._disk_hits = 0
        self._evictions = 0
        self._singleflight_waits = 0
        self._invalidations = 0         # epoch flush events
        self._invalidated_entries = 0   # entries flushed by them
        self._writeback_flushes = 0     # ... triggered by a live-qchip
        #                                 mutation (calibration writer)
        self._validation_rejects = 0
        # optional FlightRecorder (set by ExecutionService) — epoch
        # invalidations land in the serving tier's incident timeline
        self.recorder = None

    # -- the front door --------------------------------------------------

    def get_or_compile(self, program, qchip, *, channel_configs=None,
                       fpga_config=None, compiler_flags=None,
                       n_qubits: int = 8, pad_to=None, element_cls=None):
        """Compile-or-hit: returns ``(MachineProgram, status, key)``
        where status is one of ``'hit' | 'disk' | 'miss' | 'wait'``.

        Raises :class:`~..decoder.ProgramValidationError` (with
        instruction coordinates) when the compiled program fails
        admission validation — every stampeded waiter of the same
        submission gets the same typed error.
        """
        qchip_fp = qchip.fingerprint()
        self._note_epoch(qchip, qchip_fp)
        key = content_key(program, qchip, channel_configs=channel_configs,
                          fpga_config=fpga_config,
                          compiler_flags=compiler_flags,
                          n_qubits=n_qubits, pad_to=pad_to,
                          element_cls=element_cls,
                          qchip_fingerprint=qchip_fp)
        while True:
            with self._lock:
                hit = self._lru.get(key)
                if hit is not None:
                    self._lru.move_to_end(key)
                    self._hits += 1
                    profiling.counter_inc('compilecache.hits')
                    return hit[0], HIT, key
                flight = self._flights.get(key)
                if flight is None:
                    flight = _Flight()
                    self._flights[key] = flight
                    owner = True
                else:
                    self._singleflight_waits += 1
                    profiling.counter_inc('compilecache.singleflight_waits')
                    owner = False
            if not owner:
                flight.event.wait()
                if flight.exc is not None:
                    raise flight.exc
                return flight.result, WAIT, key
            return self._fill_flight(flight, key, qchip_fp, program, qchip,
                                     channel_configs, fpga_config,
                                     compiler_flags, n_qubits, pad_to,
                                     element_cls)

    def _fill_flight(self, flight, key, qchip_fp, program, qchip,
                     channel_configs, fpga_config, compiler_flags,
                     n_qubits, pad_to, element_cls):
        """Flight owner: disk probe, else compile+validate; publish the
        result (or the typed failure) to every waiter."""
        try:
            mp = self._store.load(key, qchip_fp) if self._store else None
            if mp is not None:
                status = DISK
                with self._lock:
                    self._disk_hits += 1
                profiling.counter_inc('compilecache.disk_hits')
            else:
                status = MISS
                mp = self._compile(program, qchip, channel_configs,
                                   fpga_config, compiler_flags, n_qubits,
                                   pad_to, element_cls)
            self._admit(key, qchip_fp, mp, write_disk=(status == MISS))
        except BaseException as e:
            flight.exc = e
            raise
        else:
            flight.result = mp
            return mp, status, key
        finally:
            with self._lock:
                self._flights.pop(key, None)
            flight.event.set()

    def _compile(self, program, qchip, channel_configs, fpga_config,
                 compiler_flags, n_qubits, pad_to, element_cls):
        from ..decoder import validate_program
        t0 = time.perf_counter()
        if isinstance(program, str):
            from ..frontend import qasm_to_program
            program = qasm_to_program(program)
        if self._compile_fn is not None:
            mp = self._compile_fn(program, qchip,
                                  channel_configs=channel_configs,
                                  fpga_config=fpga_config,
                                  compiler_flags=compiler_flags,
                                  n_qubits=n_qubits, pad_to=pad_to)
        else:
            from ..pipeline import compile_to_machine
            kw = {} if element_cls is None else {'element_cls': element_cls}
            mp = compile_to_machine(program, qchip,
                                    channel_configs=channel_configs,
                                    fpga_config=fpga_config,
                                    compiler_flags=compiler_flags,
                                    n_qubits=n_qubits, pad_to=pad_to, **kw)
        dt = time.perf_counter() - t0
        if self.validate:
            try:
                validate_program(mp)
            except Exception:
                with self._lock:
                    self._validation_rejects += 1
                raise
        with self._lock:
            self._misses += 1
            self._compile_s.append(dt)
        profiling.counter_inc('compilecache.misses')
        profiling.registry().observe('compilecache.compile_ms', dt * 1e3)
        return mp

    def _admit(self, key, qchip_fp, mp, write_disk: bool):
        with self._lock:
            self._lru[key] = (mp, qchip_fp)
            self._lru.move_to_end(key)
            while len(self._lru) > self.capacity:
                self._lru.popitem(last=False)
                self._evictions += 1
        if write_disk and self._store is not None:
            self._store.save(key, qchip_fp, mp)

    # -- calibration epochs ----------------------------------------------

    def _note_epoch(self, qchip, qchip_fp: str) -> None:
        """Auto epoch tracking: the cache remembers the fingerprint it
        last saw for each live QChip OBJECT; a resubmission through a
        mutated qchip (one gate amplitude retuned) flushes exactly the
        stale epoch's entries.  Object identity only ties a mutation to
        its previous epoch — correctness never depends on it, since the
        fingerprint is part of every content key (a missed flush costs
        memory, never staleness)."""
        flush = None
        with self._lock:
            prev = self._lineage.get(id(qchip))
            if prev is not None and prev != qchip_fp:
                flush = prev
            self._lineage[id(qchip)] = qchip_fp
        if flush is not None:
            # a lineage-triggered flush means a LIVE qchip was written
            # between submissions — the calibration-writeback signature
            # (calib/loops.py); counted separately from explicit
            # invalidate_epoch calls so dashboards can tell retunes
            # from administrative flushes
            with self._lock:
                self._writeback_flushes += 1
            profiling.counter_inc('compilecache.writeback_flushes')
            self.invalidate_epoch(flush)

    def invalidate_epoch(self, qchip_fp: str) -> int:
        """Flush every entry (memory + disk) keyed to this calibration
        fingerprint; other epochs' entries stay warm.  Returns the
        number of entries flushed."""
        with self._lock:
            stale = [k for k, (_, fp) in self._lru.items()
                     if fp == qchip_fp]
            for k in stale:
                del self._lru[k]
            n = len(stale)
        if self._store is not None:
            n += self._store.invalidate_epoch(qchip_fp)
        with self._lock:
            self._invalidations += 1
            self._invalidated_entries += n
        profiling.counter_inc('compilecache.invalidations')
        if self.recorder is not None:
            self.recorder.record('cache_invalidate', qchip_fp=qchip_fp,
                                 entries=n)
        return n

    # -- introspection ---------------------------------------------------

    def stats(self) -> dict:
        """Counter snapshot + compile-time percentiles, shaped for
        ``ExecutionService.stats()['compile_cache']``."""
        with self._lock:
            times = sorted(self._compile_s)
            snap = {
                'size': len(self._lru),
                'capacity': self.capacity,
                'hits': self._hits,
                'misses': self._misses,
                'disk_hits': self._disk_hits,
                'evictions': self._evictions,
                'singleflight_waits': self._singleflight_waits,
                'invalidations': self._invalidations,
                'invalidated_entries': self._invalidated_entries,
                'writeback_flushes': self._writeback_flushes,
                'validation_rejects': self._validation_rejects,
                'persistent': self._store.path if self._store else None,
            }
        if times:
            def pct(p):
                return times[min(len(times) - 1,
                                 int(p / 100.0 * len(times)))]
            snap['compile_ms_p50'] = round(pct(50) * 1e3, 3)
            snap['compile_ms_p99'] = round(pct(99) * 1e3, 3)
        else:
            snap['compile_ms_p50'] = snap['compile_ms_p99'] = 0.0
        snap['compile_samples'] = len(times)
        return snap

    def clear(self) -> None:
        """Drop the memory tier (the persistent tier is untouched —
        use ``PersistentStore.clear`` via ``.store`` for that)."""
        with self._lock:
            self._lru.clear()

    @property
    def store(self) -> PersistentStore | None:
        return self._store


_DEFAULT_CACHE = None
_DEFAULT_LOCK = threading.Lock()


def default_cache() -> CompileCache:
    """Process-wide shared cache (memory tier only) — the zero-config
    front door used by :func:`~..pipeline.cached_compile_to_machine`."""
    global _DEFAULT_CACHE
    with _DEFAULT_LOCK:
        if _DEFAULT_CACHE is None:
            _DEFAULT_CACHE = CompileCache()
        return _DEFAULT_CACHE
