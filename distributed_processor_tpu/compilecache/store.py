"""On-disk persistent tier of the compile cache.

One zlib-compressed pickle per entry, version-stamped like the sweep
checkpoint format (``parallel/driver.py`` fingerprints, the
``utils/results.py`` corrupt-tolerant load): a payload dict carries a
magic string, a format version, the content key, the qchip calibration
fingerprint and the :class:`~..decoder.MachineProgram` itself.  Writes
are atomic (tmp + ``os.replace``, the ``save_results`` discipline), so
a killed process can never leave a half-written entry that a later
process trusts.  Any load failure — corrupt zlib stream, truncated
pickle, version skew, key mismatch — is a MISS, never an exception:
the cache recompiles and overwrites.

Format v2 adds an integrity digest (docs/ROBUSTNESS.md "Integrity"):
the MachineProgram is pickled separately and stored alongside a CRC32
of those exact bytes, verified before unpickling on load.  The outer
zlib stream has its own adler32, but that only covers the compressed
blob on THIS read — the digest pins the program content across the
store's whole shared-warm-tier lifetime (an entry written by one
replica and mmap'd, copied, or rsync'd to another still proves out).
A digest mismatch counts ``integrity.store_digest_fail`` and is the
usual remove+miss.  v1 entries fail the version check and recompile —
the standard skew path, no migration needed.

The filename encodes ``<content-key>-<qchip-fp[:16]>.mpc`` so epoch
invalidation can unlink exactly one calibration epoch's entries
without deserializing anything.
"""

from __future__ import annotations

import glob
import os
import pickle
import zlib

from ..integrity import content_crc32
from ..utils import profiling

STORE_MAGIC = 'dproc-compilecache'
STORE_VERSION = 2
_SUFFIX = '.mpc'


class PersistentStore:
    """Directory-backed entry store; every method is process-safe in
    the crash sense (atomic writes, tolerant reads) — cross-process
    LOCKING is not attempted: two processes racing the same key both
    write valid identical entries and one ``os.replace`` wins."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def _fname(self, key: str, qchip_fp: str) -> str:
        return os.path.join(self.path, f'{key}-{qchip_fp[:16]}{_SUFFIX}')

    def load(self, key: str, qchip_fp: str):
        """The MachineProgram for ``key``, or None (miss/corrupt/skew)."""
        fname = self._fname(key, qchip_fp)
        try:
            with open(fname, 'rb') as f:
                payload = pickle.loads(zlib.decompress(f.read()))
            if (payload.get('magic') != STORE_MAGIC
                    or payload.get('version') != STORE_VERSION
                    or payload.get('key') != key):
                raise ValueError('version/key skew')
            blob = payload['mp_pickle']
            if content_crc32((blob,)) != payload['crc']:
                profiling.counter_inc('integrity.store_digest_fail')
                raise ValueError('store entry digest mismatch')
            return pickle.loads(blob)
        except FileNotFoundError:
            return None
        except (OSError, zlib.error, pickle.UnpicklingError, EOFError,
                ValueError, KeyError, AttributeError, ImportError,
                IndexError):
            # corrupt or stale-format entry: drop it so the rewrite
            # after recompile starts clean
            try:
                os.remove(fname)
            except OSError:
                pass
            return None

    def save(self, key: str, qchip_fp: str, mp) -> None:
        mp_pickle = pickle.dumps(mp, protocol=pickle.HIGHEST_PROTOCOL)
        payload = {'magic': STORE_MAGIC, 'version': STORE_VERSION,
                   'key': key, 'qchip_fp': qchip_fp,
                   'mp_pickle': mp_pickle,
                   'crc': content_crc32((mp_pickle,))}
        blob = zlib.compress(pickle.dumps(payload))
        fname = self._fname(key, qchip_fp)
        tmp = fname + '.tmp'
        with open(tmp, 'wb') as f:
            f.write(blob)
        os.replace(tmp, fname)

    def invalidate_epoch(self, qchip_fp: str) -> int:
        """Unlink every entry written under this calibration epoch;
        returns how many files were removed."""
        n = 0
        pattern = os.path.join(self.path, f'*-{qchip_fp[:16]}{_SUFFIX}')
        for fname in glob.glob(pattern):
            try:
                os.remove(fname)
                n += 1
            except OSError:
                pass
        return n

    def clear(self) -> int:
        n = 0
        for fname in glob.glob(os.path.join(self.path, f'*{_SUFFIX}')):
            try:
                os.remove(fname)
                n += 1
            except OSError:
                pass
        return n
