"""Content addressing for the compile front door.

A cache key must name everything that can change the compiled
:class:`~..decoder.MachineProgram` and nothing else, or identical tenant
submissions stop deduplicating (over-keying) / calibration updates serve
stale pulses (under-keying).  The key covers five components:

* **program source** — a dict-instruction list (canonicalized: dict-key
  order, tuples-vs-lists and numpy scalars are normalized away, so two
  tenants building "the same" program with different dict orderings
  collide onto one entry) or raw OpenQASM 3 text (keyed byte-for-byte:
  a cache hit never even parses);
* **qchip calibration epoch** — :meth:`~..qchip.QChip.fingerprint`, a
  stable hash of the frequency table + gate library, so a recalibration
  is a new key (and the old epoch's entries are flushable as a group);
* **FPGAConfig** — every timing constant changes scheduling;
* **CompilerFlags** — resolve/schedule toggles change the IR pipeline;
* **channel geometry** — ``n_qubits``/``pad_to``/the channel-config map
  and the element class decide buffer layout and decode shapes.

The canonical form is a tagged JSON tree (``_canon``) hashed with
sha256; ``KEY_VERSION`` is baked into the digest so a canonicalization
change can never alias old persistent-store entries.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import is_dataclass

KEY_VERSION = 1


def _canon(obj):
    """Recursively convert ``obj`` to a canonical JSON-able tree.

    Dicts become sorted ``['__dict__', [[k, v], ...]]`` pairs (the
    whole point: instruction dicts hash identically regardless of key
    insertion order), tuples/lists are tagged distinctly (a ``('reg',
    0)`` operand must not collide with ``['reg', 0]`` — they are the
    same to the compiler but tagging both ways costs nothing and keeps
    the mapping injective), numpy arrays/scalars go through ``tolist``
    with dtype+shape preserved, dataclasses and plain objects flatten
    to their field dicts, and anything else falls back to ``repr``.
    """
    if obj is None or isinstance(obj, (bool, int, str)):
        return obj
    if isinstance(obj, float):
        return float(obj)
    if isinstance(obj, complex):
        return ['__complex__', float(obj.real), float(obj.imag)]
    if isinstance(obj, dict):
        try:
            # the hot path: homogeneous (string) keys sort natively
            items = sorted(obj.items())
        except TypeError:
            items = sorted(obj.items(),
                           key=lambda kv: json.dumps(_canon(kv[0]),
                                                     sort_keys=True))
        return ['__dict__', [[_canon(k), _canon(v)] for k, v in items]]
    if isinstance(obj, (list, tuple)):
        return ['__tuple__' if isinstance(obj, tuple) else '__list__',
                [_canon(v) for v in obj]]
    if is_dataclass(obj) and not isinstance(obj, type):
        return ['__dataclass__', type(obj).__name__, _canon(vars(obj))]
    if hasattr(obj, 'dtype') and hasattr(obj, 'tolist'):
        # numpy array or scalar, without importing numpy here
        shape = list(getattr(obj, 'shape', ()))
        return ['__ndarray__', str(obj.dtype), shape, _canon(obj.tolist())]
    if hasattr(obj, '__dict__'):
        return ['__object__', type(obj).__name__, _canon(vars(obj))]
    return ['__repr__', repr(obj)]


def canonical_json(obj) -> str:
    """Deterministic JSON encoding of ``_canon(obj)`` (no whitespace,
    sorted containers already canonicalized)."""
    return json.dumps(_canon(obj), separators=(',', ':'))


def canonical_program(program):
    """Canonical form of a program source: QASM3 text keys as raw bytes
    (a warm hit never parses), dict-instruction lists key on the
    order-insensitive canonical tree."""
    if isinstance(program, str):
        return ['qasm3', program]
    return ['dict', _canon(list(program))]


def content_key(program, qchip, *, channel_configs=None, fpga_config=None,
                compiler_flags=None, n_qubits: int = 8, pad_to=None,
                element_cls=None, qchip_fingerprint: str = None) -> str:
    """The content-addressed cache key: sha256 hex digest over every
    compile input (see module docstring for the anatomy).

    ``qchip_fingerprint`` short-circuits the qchip hash when the caller
    already computed it (the cache computes it once per submission to
    drive epoch invalidation too).  Defaults are resolved the same way
    :func:`~..pipeline.compile_to_machine` resolves them, so an
    explicitly-passed default object and an omitted argument produce
    the SAME key.
    """
    from ..compiler import CompilerFlags
    from ..elements import TPUElementConfig
    from ..hwconfig import FPGAConfig
    if qchip_fingerprint is None:
        qchip_fingerprint = qchip.fingerprint()
    if fpga_config is None:
        fpga_config = FPGAConfig(n_cores=n_qubits)
    if compiler_flags is None:
        compiler_flags = CompilerFlags()
    if element_cls is None:
        element_cls = TPUElementConfig
    chan = (['auto', int(n_qubits)] if channel_configs is None
            else _canon(channel_configs))
    # every component below is ALREADY canonical, so the payload is a
    # fixed-order list dumped directly — re-running _canon over it
    # (canonical_json) would double the per-hit key cost for nothing
    payload = [
        'key_version', KEY_VERSION,
        'program', canonical_program(program),
        'qchip', qchip_fingerprint,
        'fpga_config', _canon(fpga_config),
        'compiler_flags', _canon(compiler_flags),
        'channels', chan,
        'n_qubits', int(n_qubits),
        'pad_to', None if pad_to is None else int(pad_to),
        'element_cls', f'{element_cls.__module__}.{element_cls.__qualname__}',
    ]
    blob = json.dumps(payload, separators=(',', ':'))
    return hashlib.sha256(blob.encode()).hexdigest()


def machine_program_bytes(mp) -> bytes:
    """Canonical byte serialization of a :class:`MachineProgram` —
    the determinism oracle: two compiles of the same source are correct
    iff these bytes are equal (tests/test_compilecache.py pins it).

    Arrays contribute dtype+shape+raw bytes in fixed field order; the
    non-array remainder (core indices, register maps, element configs)
    contributes its canonical JSON.
    """
    from .. import isa
    parts = []

    def _arr(a):
        import numpy as np
        a = np.ascontiguousarray(a)
        parts.append(f'{a.dtype}{a.shape}'.encode())
        parts.append(a.tobytes())

    for f in isa.SOA_FIELDS:
        _arr(getattr(mp.soa, f))
    _arr(mp.p_elem)
    _arr(mp.p_dur)
    for t in mp.tables:
        for e in t.envs:
            _arr(e)
        for fr in t.freqs:
            _arr(fr['freq'])
            _arr(fr['iq15'])
        parts.append(canonical_json(t.elem_cfgs).encode())
    parts.append(canonical_json(
        {'core_inds': list(mp.core_inds),
         'reg_maps': mp.reg_maps}).encode())
    return b'\x00'.join(parts)
