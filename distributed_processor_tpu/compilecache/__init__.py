"""Multi-tenant compile front door: a content-addressed cache from
program source (dict-instruction list or OpenQASM 3 text) to compiled
:class:`~..decoder.MachineProgram`.

See docs/COMPILE_CACHE.md for the key anatomy, epoch invalidation
rules, singleflight semantics and the persistence format.
"""

from .cache import CompileCache, default_cache, DISK, HIT, MISS, WAIT
from .key import (KEY_VERSION, canonical_json, canonical_program,
                  content_key, machine_program_bytes)
from .store import PersistentStore, STORE_VERSION

__all__ = [
    'CompileCache', 'default_cache', 'HIT', 'DISK', 'MISS', 'WAIT',
    'KEY_VERSION', 'canonical_json', 'canonical_program', 'content_key',
    'machine_program_bytes', 'PersistentStore', 'STORE_VERSION',
]
