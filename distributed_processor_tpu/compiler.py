"""Compiler driver: QubiC-format circuit → per-core assembly programs.

Input program format (parity with the reference circuit format,
python/distproc/compiler.py:1-106): a list of instruction dicts —

* gates: ``{'name': gatename, 'qubit': [qubitid], 'modi': {...}}``
* pulses: ``{'name': 'pulse', 'freq', 'phase', 'amp', 'twidth', 'env',
  'dest', ['start_time']}``
* virtual-z: ``{'name': 'virtual_z', 'qubit'/'freq', 'phase'}``
* ``declare_freq``, ``bind_phase``, ``read_fproc``, ``alu_fproc``,
  ``barrier``, ``delay``, ``branch_fproc``, ``branch_var``, ``loop``,
  ``alu``, ``set_var``, ``declare`` — see the IR instruction classes.

Compilation: lower to IR → run the pass pipeline (:func:`get_passes`) →
:meth:`Compiler.compile` splits instructions across processor cores and
emits the assembly dialect consumed by
:mod:`distributed_processor_tpu.assembler`.
"""

from __future__ import annotations

import copy
import json
import logging
from dataclasses import dataclass, field

import numpy as np

from . import hwconfig as hw
from .ir import IRProgram, CoreScoper, passes
from .ir.program import DEFAULT_PROC_GROUPING

logger = logging.getLogger(__name__)


@dataclass
class CompilerFlags:
    resolve_gates: bool = True
    schedule: bool = True


def get_passes(fpga_config: hw.FPGAConfig, qchip=None,
               compiler_flags: CompilerFlags | dict = None,
               qubit_grouping=('{qubit}.qdrv', '{qubit}.rdrv', '{qubit}.rdlo'),
               proc_grouping=DEFAULT_PROC_GROUPING) -> list:
    """The canonical pass pipeline (see module docstring of ir.passes)."""
    if compiler_flags is None:
        compiler_flags = CompilerFlags()
    elif isinstance(compiler_flags, dict):
        compiler_flags = CompilerFlags(**compiler_flags)

    cur_passes = [passes.FlattenProgram(),
                  passes.MakeBasicBlocks(),
                  passes.ScopeProgram(qubit_grouping),
                  passes.RegisterVarsAndFreqs(qchip)]
    if compiler_flags.resolve_gates:
        if qchip is None:
            raise ValueError('a QChip object is required to resolve gates')
        cur_passes.append(passes.ResolveGates(qchip, qubit_grouping))
    cur_passes.extend([passes.GenerateCFG(),
                       passes.ResolveHWVirtualZ(),
                       passes.ResolveVirtualZ(),
                       passes.ResolveFreqs(),
                       passes.ResolveFPROCChannels(fpga_config),
                       passes.RescopeVars()])
    if compiler_flags.schedule:
        cur_passes.append(passes.Schedule(fpga_config, proc_grouping))
    else:
        cur_passes.append(passes.LintSchedule(fpga_config, proc_grouping))
    return cur_passes


class Compiler:
    """Compile a circuit down to per-core assembly.

    Usage::

        compiler = Compiler(program)
        compiler.run_ir_passes(get_passes(fpga_config, qchip))
        compiled = compiler.compile()
    """

    def __init__(self, program, proc_grouping=DEFAULT_PROC_GROUPING):
        self.ir_prog = IRProgram(program)
        self._proc_grouping = proc_grouping

    def run_ir_passes(self, pass_list: list):
        for ir_pass in pass_list:
            ir_pass.run_pass(self.ir_prog)

    def compile(self) -> 'CompiledProgram':
        self._core_scoper = CoreScoper(self.ir_prog.scope, self._proc_grouping)
        asm_progs = {grp: [{'op': 'phase_reset'}]
                     for grp in self._core_scoper.proc_groupings_flat}
        for blockname in self.ir_prog.blocknames_by_ind:
            self._compile_block(
                asm_progs, self.ir_prog.blocks[blockname]['instructions'])
        for grp in self._core_scoper.proc_groupings_flat:
            asm_progs[grp].append({'op': 'done_stb'})
        return CompiledProgram(asm_progs, fpga_config=self.ir_prog.fpga_config)

    def _compile_block(self, asm_progs, instructions):
        groups_bydest = self._core_scoper.proc_groupings
        for instr in instructions:
            if instr.name == 'pulse':
                env = instr.env
                if isinstance(env, (list, tuple)) and env and isinstance(env[0], dict):
                    if len(env) > 1:
                        logger.warning('only the first env paradict of %s is used', env)
                    env = env[0]
                if isinstance(env, dict):
                    if 'twidth' not in env['paradict']:
                        env = copy.deepcopy(env)
                        env['paradict']['twidth'] = instr.twidth
                    elif env['paradict']['twidth'] != instr.twidth:
                        raise ValueError('pulse twidth differs from envelope twidth')
                asm = {'op': 'pulse', 'freq': instr.freq, 'phase': instr.phase,
                       'amp': instr.amp, 'env': env,
                       'start_time': instr.start_time, 'dest': instr.dest}
                if instr.tag is not None:
                    asm['tag'] = instr.tag
                asm_progs[groups_bydest[instr.dest]].append(asm)
                continue

            if instr.name == 'jump_label':
                emit = {'op': 'jump_label', 'dest_label': instr.label}
            elif instr.name == 'declare':
                dtype = instr.dtype
                if dtype in ('phase', 'amp'):
                    dtype = (dtype, 0)
                emit = {'op': 'declare_reg', 'name': instr.var, 'dtype': dtype}
            elif instr.name == 'alu':
                emit = {'op': 'reg_alu', 'in0': instr.lhs, 'in1_reg': instr.rhs,
                        'alu_op': instr.op, 'out_reg': instr.out}
            elif instr.name == 'set_var':
                emit = {'op': 'reg_alu', 'in0': instr.value, 'in1_reg': instr.var,
                        'alu_op': 'id0', 'out_reg': instr.var}
            elif instr.name == 'read_fproc':
                emit = {'op': 'alu_fproc', 'in0': 0, 'alu_op': 'id1',
                        'func_id': instr.func_id, 'out_reg': instr.var}
            elif instr.name == 'alu_fproc':
                emit = {'op': 'alu_fproc', 'in0': instr.lhs, 'alu_op': instr.op,
                        'func_id': instr.func_id, 'out_reg': instr.out}
            elif instr.name == 'jump_fproc':
                emit = {'op': 'jump_fproc', 'in0': instr.cond_lhs,
                        'alu_op': instr.alu_cond, 'jump_label': instr.jump_label,
                        'func_id': instr.func_id}
            elif instr.name == 'jump_cond':
                emit = {'op': 'jump_cond', 'in0': instr.cond_lhs,
                        'alu_op': instr.alu_cond, 'jump_label': instr.jump_label,
                        'in1_reg': instr.cond_rhs}
            elif instr.name == 'jump_i':
                emit = {'op': 'jump_i', 'jump_label': instr.jump_label}
            elif instr.name == 'loop_end':
                emit = {'op': 'inc_qclk',
                        'in0': -self.ir_prog.loops[instr.loop_label].delta_t}
            elif instr.name == 'idle':
                emit = {'op': 'idle', 'end_time': instr.end_time}
            else:
                raise NotImplementedError(f'cannot compile {instr.name}')

            for core in self._core_scoper.get_groups_bydest(instr.scope):
                asm_progs[core].append(dict(emit))


@dataclass
class CompiledProgram:
    """Per-core assembly output of the compiler.

    ``program`` maps proc-group tuples (the channels driven by one core,
    e.g. ``('Q0.qdrv', 'Q0.rdrv', 'Q0.rdlo')``) to assembly instruction
    lists in the dialect of :mod:`distributed_processor_tpu.assembler`
    (pulse statements carry a ``dest`` channel instead of ``elem_ind``).
    """

    program: dict
    fpga_config: hw.FPGAConfig = None

    @property
    def proc_groups(self):
        return self.program.keys()

    def to_dict(self) -> dict:
        progdict = {}
        for grp, instrs in self.program.items():
            # '|'-join keeps tuple keys JSON-safe; a trailing '|' marks a
            # single-channel group so from_dict restores the right type
            key = ('|'.join(grp) if len(grp) > 1 else grp[0] + '|') \
                if isinstance(grp, tuple) else grp
            out_instrs = []
            for instr in instrs:
                instr = dict(instr)
                if isinstance(instr.get('env'), np.ndarray):
                    env = instr['env']
                    instr['env'] = {'__ndarray__': True,
                                    're': np.real(env).tolist(),
                                    'im': np.imag(env).tolist()}
                if isinstance(instr.get('func_id'), tuple):
                    instr['func_id'] = {'__tuple__': list(instr['func_id'])}
                if isinstance(instr.get('dtype'), tuple):
                    instr['dtype'] = {'__tuple__': list(instr['dtype'])}
                out_instrs.append(instr)
            progdict[key] = out_instrs
        out = {'program': progdict}
        if self.fpga_config is not None:
            out['fpga_config'] = self.fpga_config.to_dict()
        return out

    def save(self, filename: str):
        """Serialise to JSON (the reference's save/load is stubbed;
        this one round-trips, see :func:`load_compiled_program`)."""
        with open(filename, 'w') as f:
            json.dump(self.to_dict(), f, indent=2)

    @classmethod
    def from_dict(cls, d: dict) -> 'CompiledProgram':
        program = {}
        for key, instrs in d['program'].items():
            grp = tuple(s for s in key.split('|') if s) if '|' in key else key
            out_instrs = []
            for instr in instrs:
                instr = dict(instr)
                env = instr.get('env')
                if isinstance(env, dict) and env.get('__ndarray__'):
                    instr['env'] = np.array(env['re']) + 1j * np.array(env['im'])
                for k in ('func_id', 'dtype'):
                    if isinstance(instr.get(k), dict) and '__tuple__' in instr[k]:
                        instr[k] = tuple(instr[k]['__tuple__'])
                out_instrs.append(instr)
            program[grp] = out_instrs
        fpga_config = None
        if 'fpga_config' in d:
            fpga_config = hw.FPGAConfig(**d['fpga_config'])
        return cls(program, fpga_config)


def load_compiled_program(filename: str) -> CompiledProgram:
    with open(filename) as f:
        return CompiledProgram.from_dict(json.load(f))
