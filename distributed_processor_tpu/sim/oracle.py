"""Scalar Python golden model of the distributed-processor execution.

This is the TPU build's analog of the reference's cocotb golden models
(reference: cocotb/proc/test_proc.py:639-653 `evaluate_alu_exp` plus the
documented FSM latency constants, test_proc.py:8-19): a slow, obviously
correct interpreter the vectorised JAX engine is tested against on
randomized programs.

Timing model
------------
The oracle tracks, per core, the same two quantities the Schedule pass
uses (ir/passes.py `_TimedPass`):

* ``time`` — global clock; the point at which the next instruction may
  issue (``last_instr_end_t`` in the scheduler).  Seeded ``START_NCLKS``.
* ``offset`` — qclk origin: ``qclk = time - offset``.  SYNC resets the
  qclk (offset := release time + QCLK_RST_DELAY); ``inc_qclk`` shifts it.

A triggered pulse fires at global time ``offset + cmd_time`` — the cycle
at which the hardware comparator ``qclk_out == pulse_cmd_time`` matches
(reference: hdl/proc.sv:130-131).  Pulse *times* are therefore exact by
construction; the per-instruction costs only determine whether a trigger
could have been missed (an error, as in hardware, where a passed qclk
would spin for a full 2^32 wrap).

Measurement fabric
------------------
A pulse emitted on the measurement element (rdlo) schedules a
discriminated bit ``meas_latency`` clks after the pulse ends
(reference: python/distproc/hwconfig.py:9 FPROC_MEAS_CLKS).  Fproc reads
support both fabric semantics present in the reference gateware:

* ``'sticky'`` — return the most recent bit latched *at the read time*
  (reference: hdl/fproc_meas.sv:18-19 sticky meas_reg; 0 if none yet);
* ``'fresh'`` — block until the first measurement completing strictly
  after the read was issued (reference: hdl/core_state_mgr.sv:45-56
  WAIT_MEAS).

Reads past the supplied injected-bit budget return 0, matching the
vector engine's zero-padding (the cocotb injection strategy never
supplies fewer bits than the program consumes; padding keeps the two
engines bit-identical when a randomized program over-reads).

All time arithmetic wraps at 32 bits (hardware counter width, matching
the int32 JAX engine): ``qclk``/``time``/``offset`` comparisons follow
two's-complement semantics once a timeline passes 2^31.
"""

from __future__ import annotations

import numpy as np

from .. import isa

START_NCLKS = 5       # schedule origin (ir/passes.py START_NCLKS)
# First instruction issues at INIT_TIME: the scheduler's START_NCLKS
# margin covers the initial command fetch plus the phase_reset the
# compiler prepends (cost pulse_regwrite_clks=3; 2 + 3 = START_NCLKS),
# so compiled programs meet their first pulse time by construction.
INIT_TIME = 2
QCLK_RST_DELAY = 4    # sync release -> qclk zero (cocotb test_proc.py:17)
MEAS_LATENCY = 64     # rdlo pulse end -> bit available (hwconfig FPROC_MEAS_CLKS)
# Sticky-fabric race window: hardware serves the latched bit through a
# 2-cycle registered handshake (reference: hdl/fproc_meas.sv:23-34), so
# a measurement landing within this many clks of the read time may or
# may not be included in the latched value on real hardware.  Both
# engines serve the deterministic latched-at-read-time bit AND flag the
# read ('sticky_race' / ERR_STICKY_RACE) so users see the hazard the
# simulation's determinism would otherwise hide (round-1 review item).
STICKY_RACE_MARGIN = 2

MASK32 = 0xffffffff

PULSE_FIELD_MASK = {'env': 0xffffff, 'phase': 0x1ffff, 'freq': 0x1ff,
                    'amp': 0xffff, 'cfg': 0xf}


def _i32(x: int) -> int:
    """Wrap to signed 32-bit (hardware register width)."""
    x &= MASK32
    return x - (1 << 32) if x >= (1 << 31) else x


def alu(op: int, in0: int, in1: int) -> int:
    """The 8-op ALU (reference: hdl/alu.v:31-51, hdl/instr_params.vh:5-12)."""
    if op == 0b000:      # id0
        return _i32(in0)
    if op == 0b001:      # add
        return _i32(in0 + in1)
    if op == 0b010:      # sub
        return _i32(in0 - in1)
    if op == 0b011:      # eq
        return int(_i32(in0) == _i32(in1))
    if op == 0b100:      # le: STRICT signed < (alu.v:25-27 — the sign
        return int(_i32(in0) < _i32(in1))     # of in0-in1, oflow-corrected)
    if op == 0b101:      # ge (signed, in0 >= in1 — ~le, alu.v:28)
        return int(_i32(in0) >= _i32(in1))
    if op == 0b110:      # id1
        return _i32(in1)
    if op == 0b111:      # zero
        return 0
    raise ValueError(f'bad alu op {op}')


class OracleCore:
    """State of one core during oracle execution."""

    def __init__(self, n_regs: int = isa.N_REGS):
        self.pc = 0
        self.regs = [0] * n_regs
        self.time = INIT_TIME
        self.offset = 0
        self.done = False
        self.err = []
        self.pulse_params = {k: 0 for k in PULSE_FIELD_MASK}
        self.pulses = []          # emitted pulse dicts
        self.resets = []          # phase-reset times (global)
        self.meas_avail = []      # global times at which bit n becomes valid
        self.meas_trig = []       # global times at which bit n was PRODUCED

    @property
    def qclk(self) -> int:
        return _i32(self.time - self.offset)


def _pulse_dur_clks(env_word: int, spc: int, interp: int) -> int:
    length = (env_word >> 12) & 0xfff
    if length == 0xfff:           # continuous-wave sentinel
        return 0
    nsamp = length * 4
    return -((-nsamp * interp) // spc)


def run_oracle(mp, meas_bits=None, fpga_config=None, fabric: str = 'sticky',
               meas_elem: int = 2, meas_latency: int = MEAS_LATENCY,
               lut_mask=None, lut_table=None,
               max_steps: int = 100000) -> dict:
    """Execute a decoded :class:`~..decoder.MachineProgram` scalar-style.

    ``meas_bits``: int array ``[n_cores, n_meas]`` — the discriminated bit
    produced by each core's n-th readout pulse (the testbench-injection
    strategy of the reference's cocotb suite).
    """
    from ..hwconfig import FPGAConfig
    cfg = fpga_config or FPGAConfig()
    soa = mp.soa
    n_cores = mp.n_cores
    meas_bits = np.zeros((n_cores, 0), dtype=int) if meas_bits is None \
        else np.asarray(meas_bits)
    cores = [OracleCore() for _ in range(n_cores)]
    sync_part = mp.sync_participants

    # element geometry per core (for pulse durations)
    def dur_of(c, elem, env_word):
        cfgs = mp.tables[c].elem_cfgs
        if elem >= len(cfgs):
            return 0
        e = cfgs[elem]
        return _pulse_dur_clks(env_word, e.samples_per_clk, e.interp_ratio)

    def _fresh(core: OracleCore, prod: OracleCore, req: int):
        for m, t in enumerate(prod.meas_avail):
            if t > req:
                bit = 0 if m >= meas_bits.shape[1] \
                    else int(meas_bits[cores.index(prod), m])   # zero-pad
                return True, bit, max(req, t)
        if prod.done:
            core.err.append('fproc_deadlock')
            return True, 0, req
        return False, 0, 0

    def fproc_read(c: int, core: OracleCore, func_id: int):
        """Return (ready, data, t_ready) for a fproc access at core.time."""
        req = core.time
        if fabric == 'lut':
            # reference: hdl/fproc_lut.sv — id 0: own fresh measurement;
            # id >= 1: syndrome LUT over the masked cores' latest bits
            if func_id == 0:
                return _fresh(core, core, req)
            masked = [i for i in range(n_cores) if lut_mask[i]]
            for i in masked:
                p = cores[i]
                if not p.meas_avail or not (p.done or p.time >= req):
                    return False, 0, 0
            # blocks until every masked input holds a valid bit
            # (meas_lut.sv LUT_WAIT); the served slot is TIME-INDEXED:
            # per producer, the newest bit PRODUCED strictly before the
            # read's required time (a producer at time == req can still
            # fire at trig == req, so the strict compare is what makes
            # the count final once causality clears).  A reader armed
            # before any production (count 0) takes slot 0 — the first
            # recorded bit, fixed once written — matching the
            # gateware's arm-then-accumulate LUT_WAIT behavior.
            addr = 0
            slots = []
            for rank, i in enumerate(masked):
                cnt = sum(1 for t in cores[i].meas_trig if t < req)
                m = max(cnt, 1) - 1
                slots.append((i, m))
                if m >= meas_bits.shape[1]:
                    bit = 0               # zero-pad (see module doc)
                else:
                    bit = int(meas_bits[i, m])
                addr |= bit << rank
            t_lut = max(cores[i].meas_avail[m] for i, m in slots)
            return True, (int(lut_table[addr]) >> c) & 1, max(req, t_lut)
        if func_id >= n_cores:
            core.err.append('fproc_id')
            return True, 0, core.time
        prod = cores[func_id]
        if fabric == 'sticky':
            if not (prod.done or prod.time >= req):
                return False, 0, 0
            if any(req - STICKY_RACE_MARGIN < t <= req + STICKY_RACE_MARGIN
                   for t in prod.meas_avail):
                core.err.append('sticky_race')
            m = sum(1 for t in prod.meas_avail if t <= req)
            data = int(meas_bits[func_id, m - 1]) \
                if 0 < m <= meas_bits.shape[1] else 0   # zero-pad past budget
            return True, data, req
        elif fabric == 'fresh':
            return _fresh(core, prod, req)
        raise ValueError(f'unknown fabric {fabric!r}')

    for _ in range(max_steps):
        if all(c.done for c in cores):
            break
        # sync barrier resolution: all live participants waiting
        at_sync = [not c.done and soa.kind[i, c.pc] == isa.K_SYNC
                   for i, c in enumerate(cores)]
        if any(at_sync) and all(
                at_sync[i] or cores[i].done
                for i in range(n_cores) if sync_part[i]):
            release = max(c.time for i, c in enumerate(cores) if at_sync[i])
            for i, c in enumerate(cores):
                if sync_part[i] and c.done:
                    c.err.append('sync_done')
                if at_sync[i]:
                    c.offset = _i32(release + QCLK_RST_DELAY)
                    c.time = _i32(release + QCLK_RST_DELAY)
                    c.pc += 1
            continue

        progressed = False
        for ci, c in enumerate(cores):
            if c.done:
                continue
            i = c.pc
            kind = int(soa.kind[ci, i])
            if kind == isa.K_SYNC:
                continue   # handled collectively above
            progressed = True

            if kind in (isa.K_PULSE_WRITE, isa.K_PULSE_TRIG):
                wen, regsel = int(soa.p_wen[ci, i]), int(soa.p_regsel[ci, i])
                for b, name in enumerate(isa.PULSE_PARAM_ORDER):
                    if wen >> b & 1:
                        if regsel >> b & 1:
                            val = c.regs[int(soa.p_reg[ci, i])]
                        else:
                            val = int(getattr(soa, 'p_' + name)[ci, i])
                        c.pulse_params[name] = val & PULSE_FIELD_MASK[name]
                if kind == isa.K_PULSE_TRIG:
                    cmd_time = int(np.int64(soa.cmd_time[ci, i]) & MASK32)
                    trig = _i32(c.offset + cmd_time)
                    if trig < c.time:
                        c.err.append('missed_trig')
                        trig = c.time
                    elem = c.pulse_params['cfg'] & 0b11
                    dur = dur_of(ci, elem, c.pulse_params['env'])
                    c.pulses.append(dict(c.pulse_params, qtime=_i32(cmd_time),
                                         gtime=trig, elem=elem, dur=dur))
                    if elem == meas_elem:
                        c.meas_avail.append(_i32(trig + dur + meas_latency))
                        c.meas_trig.append(_i32(trig))
                    c.time = _i32(trig + cfg.pulse_load_clks)
                else:
                    c.time = _i32(c.time + cfg.pulse_regwrite_clks)
                c.pc += 1

            elif kind == isa.K_REG_ALU:
                in0 = c.regs[int(soa.in0_reg[ci, i])] if soa.in0_is_reg[ci, i] \
                    else int(soa.imm[ci, i])
                in1 = c.regs[int(soa.in1_reg[ci, i])]
                c.regs[int(soa.out_reg[ci, i])] = alu(int(soa.alu_op[ci, i]), in0, in1)
                c.time = _i32(c.time + cfg.alu_instr_clks)
                c.pc += 1

            elif kind == isa.K_JUMP_I:
                c.time = _i32(c.time + cfg.jump_cond_clks)
                c.pc = int(soa.jump_addr[ci, i])

            elif kind == isa.K_JUMP_COND:
                in0 = c.regs[int(soa.in0_reg[ci, i])] if soa.in0_is_reg[ci, i] \
                    else int(soa.imm[ci, i])
                in1 = c.regs[int(soa.in1_reg[ci, i])]
                res = alu(int(soa.alu_op[ci, i]), in0, in1)
                c.time = _i32(c.time + cfg.jump_cond_clks)
                c.pc = int(soa.jump_addr[ci, i]) if res & 1 else c.pc + 1

            elif kind in (isa.K_ALU_FPROC, isa.K_JUMP_FPROC):
                ready, data, t_ready = fproc_read(ci, c, int(soa.func_id[ci, i]))
                if not ready:
                    continue            # spin; producer advances next step
                in0 = c.regs[int(soa.in0_reg[ci, i])] if soa.in0_is_reg[ci, i] \
                    else int(soa.imm[ci, i])
                res = alu(int(soa.alu_op[ci, i]), in0, data)
                c.time = _i32(t_ready + cfg.jump_fproc_clks)
                if kind == isa.K_ALU_FPROC:
                    c.regs[int(soa.out_reg[ci, i])] = res
                    c.pc += 1
                else:
                    c.pc = int(soa.jump_addr[ci, i]) if res & 1 else c.pc + 1

            elif kind == isa.K_INC_QCLK:
                in0 = c.regs[int(soa.in0_reg[ci, i])] if soa.in0_is_reg[ci, i] \
                    else int(soa.imm[ci, i])
                # qclk loads the ALU result (in1 = current qclk) with the
                # hardware pipeline compensation (reference: hdl/qclk.v:17)
                c.offset = _i32(c.time - alu(int(soa.alu_op[ci, i]), in0, c.qclk))
                c.time = _i32(c.time + cfg.alu_instr_clks)
                c.pc += 1

            elif kind == isa.K_DONE:
                c.done = True

            elif kind == isa.K_PULSE_RESET:
                c.resets.append(c.time)
                c.time = _i32(c.time + cfg.pulse_regwrite_clks)
                c.pc += 1

            elif kind == isa.K_IDLE:
                end = _i32(c.offset + int(np.int64(soa.cmd_time[ci, i]) & MASK32))
                if c.time > end:
                    c.err.append('missed_idle')
                    end = c.time
                c.time = _i32(end + cfg.pulse_load_clks)
                c.pc += 1

            else:
                raise ValueError(f'core {ci}: bad kind {kind}')
        if not progressed and not all(c.done for c in cores):
            # every live core is blocked on fproc (or an unresolvable sync)
            for c in cores:
                if not c.done:
                    c.err.append('deadlock')
            break

    return {
        'pulses': [c.pulses for c in cores],
        'resets': [c.resets for c in cores],
        'regs': np.array([c.regs for c in cores]),
        'time': np.array([c.time for c in cores]),
        'qclk': np.array([c.qclk for c in cores]),
        'done': np.array([c.done for c in cores]),
        'err': [c.err for c in cores],
        'meas_avail': [c.meas_avail for c in cores],
        'meas_time': [c.meas_trig for c in cores],
    }
