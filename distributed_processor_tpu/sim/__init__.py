from .interpreter import (InterpreterConfig, simulate, simulate_batch,
                          ERR_MISSED_TRIG, ERR_PULSE_OVERFLOW,
                          ERR_MEAS_OVERFLOW, ERR_FPROC_DEADLOCK,
                          ERR_SYNC_DONE, ERR_FPROC_ID, ERR_STICKY_RACE,
                          ERR_CW_MEAS)
from .device import DeviceModel
from .oracle import OracleCore, run_oracle
from .physics import ReadoutPhysics, run_physics_batch
