"""Fault-injection harness: mutate valid machine programs, assert no
injected defect is ever SILENT.

The trap-and-report contract has two layers — the static validator
(:func:`~distributed_processor_tpu.decoder.validate_program`) rejects
programs that are wrong on every input before they reach a jit, and the
runtime fault word traps data-dependent failures per lane — and this
module is the adversarial check that the layers compose with no gap:
every mutant is either rejected at decode, rejected by the validator,
trapped with a nonzero ``fault_shots`` code by EVERY engine that runs
it, or provably benign (a bit flip in a pulse parameter is a different
valid program, not a fault).  A mutant that hangs, crashes an engine,
or runs cleanly where its mutator guarantees breakage is a harness
failure.

Deterministic: every mutant derives from ``np.random.default_rng`` on
the (seed, case index) pair, so a failing case name reproduces exactly.
``tools/faultfuzz.py`` is the CLI front-end (``--quick`` for the tier-1
flow); ``run_fuzz`` is the library entry.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

import numpy as np

from .. import isa
from ..decoder import (machine_program_from_cmds, stack_machine_programs,
                       validate_program, ProgramValidationError)
from .interpreter import (InterpreterConfig, FAULT_CODES,
                          fault_shot_counts, simulate_batch,
                          simulate_multi_batch)

ENGINES = ('generic', 'block', 'straightline')


def _pulse(t: int = 10) -> int:
    return isa.pulse_cmd(amp_word=1000, cfg_word=0, env_word=3, cmd_time=t)


# ---------------------------------------------------------------------------
# base programs — small, valid, covering the control-flow idioms the
# mutators target (straight-line, counted loop, sync barrier, fproc)
# ---------------------------------------------------------------------------

def base_linear(rng) -> tuple:
    n = int(rng.integers(2, 6))
    core = [_pulse(10 + 20 * i) for i in range(n)] + [isa.done_cmd()]
    return [list(core), list(core)], InterpreterConfig(max_steps=256)


def base_loop(rng) -> tuple:
    iters = int(rng.integers(2, 5))
    core = [isa.alu_cmd('reg_alu', 'i', iters, 'id0', write_reg_addr=0),
            _pulse(),
            isa.alu_cmd('reg_alu', 'i', -1, 'add', 0, write_reg_addr=0),
            isa.alu_cmd('jump_cond', 'i', 0, 'le', 0, jump_cmd_ptr=1),
            isa.done_cmd()]
    return [core], InterpreterConfig(max_steps=256)


def base_sync(rng) -> tuple:
    nb = int(rng.integers(1, 3))
    cores = []
    for c in range(2):
        core = []
        for b in range(nb):
            core.append(_pulse(10 + 30 * b + 10 * c))
            core.append(isa.sync(b))
        core.append(isa.done_cmd())
        cores.append(core)
    return cores, InterpreterConfig(max_steps=256)


def base_fproc(rng) -> tuple:
    # core 0 produces a measurement (meas_elem=0: every pulse is a
    # readout); core 1 blocks on core 0's FRESH result — the fabric
    # where a producer finishing without measuring starves the reader
    prod = [_pulse(10), isa.done_cmd()]
    cons = [isa.alu_cmd('jump_fproc', 'i', 1, 'eq', jump_cmd_ptr=3,
                        func_id=0),
            _pulse(200), isa.done_cmd(), isa.done_cmd()]
    return [prod, cons], InterpreterConfig(max_steps=256, fabric='fresh',
                                           meas_elem=0)


def base_lut(rng) -> tuple:
    # data cores measure (meas_elem=0: every pulse is a readout); the
    # last core branches on the parity LUT over them — the timestamped
    # feedback fabric the fast engines serve (docs/PERF.md "Feedback
    # on the fast engines")
    n_prod = int(rng.integers(2, 4))
    prods = [[_pulse(10 + 5 * c), isa.done_cmd()] for c in range(n_prod)]
    reader = [isa.idle(100),
              isa.alu_cmd('jump_fproc', 'i', 1, 'eq', jump_cmd_ptr=3,
                          func_id=1),
              isa.jump_i(4),
              _pulse(400),
              isa.done_cmd()]
    C = n_prod + 1
    table = tuple(((1 << C) - 1) if bin(a).count('1') & 1 else 0
                  for a in range(1 << n_prod))
    cfg = InterpreterConfig(max_steps=256, meas_elem=0, fabric='lut',
                            lut_mask=(True,) * n_prod + (False,),
                            lut_table=table)
    return prods + [reader], cfg


BASE_BUILDERS = (('linear', base_linear), ('loop', base_loop),
                 ('sync', base_sync), ('fproc', base_fproc),
                 ('lut', base_lut))


# ---------------------------------------------------------------------------
# mutants
# ---------------------------------------------------------------------------

_ALL_OUTCOMES = frozenset(
    ('rejected_decode', 'illegal_op', 'jump_oob', 'no_done',
     'infinite_loop', 'fproc_unreachable', 'sync_mismatch')
    + tuple(name for name, _ in FAULT_CODES))


@dataclass
class Mutant:
    """One mutated program plus the oracle for judging its outcome."""
    name: str                 # '<base>+<mutator>#<index>'
    cmds: list                # per-core 128-bit word lists
    cfg: InterpreterConfig
    expected: frozenset       # acceptable non-clean outcome labels
    allow_clean: bool = False  # may the mutant legitimately run clean?


def mut_bit_flip(rng, cmds, cfg):
    """Flip one bit of one encoded word — anything can happen EXCEPT a
    silent hang or an engine disagreement."""
    c = int(rng.integers(len(cmds)))
    i = int(rng.integers(len(cmds[c])))
    out = [list(x) for x in cmds]
    out[c][i] = int(out[c][i]) ^ (1 << int(rng.integers(128)))
    return Mutant('', out, cfg, _ALL_OUTCOMES, allow_clean=True)


def mut_truncate_done(rng, cmds, cfg):
    """Overwrite a core's DONE terminators in place — on a MAX-LENGTH
    core, so the stacker's DONE padding cannot quietly re-terminate it:
    execution runs off the end of the buffer."""
    n = max(len(core) for core in cmds)
    longest = [c for c, core in enumerate(cmds) if len(core) == n]
    c = longest[int(rng.integers(len(longest)))]
    done = isa.done_cmd()
    out = [list(x) for x in cmds]
    out[c] = [_pulse(500) if w == done else w for w in out[c]]
    return Mutant('', out, cfg,
                  frozenset({'no_done', 'jump_oob', 'budget_exhausted'}))


def mut_drop_sync_partner(rng, cmds, cfg):
    """Remove one SYNC from one participant.

    If the core keeps other SYNCs it stays a participant with a short
    barrier sequence — statically inconsistent (validator) or a runtime
    deadlock.  Removing a core's ONLY sync shrinks the participant set
    instead (the interpreter derives participation from program
    content), leaving a smaller barrier that is trivially satisfiable —
    a semantic change, not a fault, so ``allow_clean``.  Half the time
    a no-op forward branch is prepended to the mutated core, putting
    the barrier sequence beyond static analysis and forcing the RUNTIME
    deadlock trap to catch it.
    """
    syncs = [(c, i) for c, core in enumerate(cmds)
             for i, w in enumerate(core)
             if isa.decode_soa(isa.cmds_to_bytes([w])).kind[0]
             == isa.K_SYNC]
    if not syncs:
        return None
    c, i = syncs[int(rng.integers(len(syncs)))]
    last_sync = sum(1 for cc, _ in syncs if cc == c) == 1
    out = [list(x) for x in cmds]
    del out[c][i]
    if rng.integers(2):
        # defeat the static check: a branch-free participant set is the
        # validator's precondition (base programs have no other jumps,
        # so no targets need re-aiming after the insert)
        out[c] = [isa.alu_cmd('jump_cond', 'i', 0, 'ge', 0,
                              jump_cmd_ptr=1)] + out[c]
    return Mutant('', out, cfg,
                  frozenset({'sync_mismatch', 'sync_deadlock',
                             'budget_exhausted'}),
                  allow_clean=last_sync)


def mut_starve_fproc(rng, cmds, cfg):
    """Drop the producer's measurement: a fresh-fabric reader starves —
    and on the LUT fabric a masked producer that finishes without ever
    measuring starves every table read the same way (the per-slot
    timestamp planes stay INT32_MAX, so no slot is ever selectable)."""
    if cfg.fabric not in ('fresh', 'lut'):
        return None
    out = [list(x) for x in cmds]
    done = isa.done_cmd()
    starved = [0] if cfg.fabric == 'fresh' \
        else [c for c, m in enumerate(cfg.lut_mask) if m]
    for c in starved:
        out[c] = [w for w in out[c] if w == done] or [done]
    return Mutant('', out, cfg,
                  frozenset({'fproc_starved', 'budget_exhausted'}))


def mut_retarget_jump(rng, cmds, cfg):
    """Point a jump outside the program: static jump_oob."""
    soas = [isa.decode_soa(isa.cmds_to_bytes(core)) for core in cmds]
    jumps = [(c, i) for c, s in enumerate(soas)
             for i in np.nonzero(np.isin(
                 s.kind, (isa.K_JUMP_I, isa.K_JUMP_COND,
                          isa.K_JUMP_FPROC)))[0]]
    if not jumps:
        return None
    c, i = jumps[int(rng.integers(len(jumps)))]
    n = max(len(core) for core in cmds)
    bad = n + int(rng.integers(1, 100))
    out = [list(x) for x in cmds]
    mask = ((1 << 8) - 1) << isa.JUMP_ADDR_POS
    out[c][i] = (int(out[c][i]) & ~mask) \
        + ((bad & 0xff) << isa.JUMP_ADDR_POS)
    if not 0 <= (bad & 0xff) < n:   # 8-bit field may wrap in range
        return Mutant('', out, cfg,
                      frozenset({'jump_oob', 'budget_exhausted'}))
    return Mutant('', out, cfg, _ALL_OUTCOMES, allow_clean=True)


def mut_shrink_budget(rng, cmds, cfg):
    """Valid program, starved step budget: BUDGET_EXHAUSTED — or clean
    on an engine whose coarser step accounting (a block engine
    iteration retires a whole superinstruction) finishes in budget;
    completing a VALID program is always correct."""
    return Mutant('', [list(x) for x in cmds],
                  replace(cfg, max_steps=int(rng.integers(1, 3))),
                  frozenset({'budget_exhausted'}), allow_clean=True)


def mut_overflow_records(rng, cmds, cfg):
    """Valid program, one-slot record budgets: overflow traps iff the
    program emits more than one pulse/measurement."""
    n_pulse = max(
        int(np.sum(isa.decode_soa(isa.cmds_to_bytes(core)).kind
                   == isa.K_PULSE_TRIG))
        for core in cmds)
    if n_pulse <= 1:
        return None
    exp = {'pulse_overflow'}
    if cfg.meas_elem == 0:
        exp.add('meas_overflow')
    return Mutant('', [list(x) for x in cmds],
                  replace(cfg, max_pulses=1, max_meas=1),
                  frozenset(exp))


MUTATORS = (('bit_flip', mut_bit_flip),
            ('truncate_done', mut_truncate_done),
            ('drop_sync', mut_drop_sync_partner),
            ('starve_fproc', mut_starve_fproc),
            ('retarget_jump', mut_retarget_jump),
            ('shrink_budget', mut_shrink_budget),
            ('overflow_records', mut_overflow_records))


def gen_mutants(seed: int, n: int) -> list:
    """``n`` deterministic mutants cycling (base × mutator) pairs."""
    pairs = [(bn, bf, mn, mf) for bn, bf in BASE_BUILDERS
             for mn, mf in MUTATORS]
    out = []
    k = 0
    while len(out) < n:
        bn, bf, mn, mf = pairs[k % len(pairs)]
        rng = np.random.default_rng((seed, k))
        cmds, cfg = bf(rng)
        m = mf(rng, cmds, cfg)
        k += 1
        if m is None:
            continue
        m.name = f'{bn}+{mn}#{k - 1}'
        out.append(m)
    return out


# ---------------------------------------------------------------------------
# the checker
# ---------------------------------------------------------------------------

_TIMING_INDEPENDENT = frozenset({'pulse_overflow', 'meas_overflow',
                                 'reset_overflow', 'illegal_op',
                                 'jump_oob'})


def _fault_names(fault) -> frozenset:
    counts = np.asarray(fault_shot_counts(fault))
    return frozenset(name for (name, _), c
                     in zip(FAULT_CODES, counts) if c)


def check_mutant(m: Mutant, engines=ENGINES, shots: int = 4) -> dict:
    """Judge one mutant.  Returns ``{'verdict', 'detail'}`` where
    verdict is ``rejected_decode | rejected_validator | trapped |
    benign | SILENT | MISTRAPPED | INCONSISTENT``; the capitalized
    verdicts are harness FAILURES."""
    try:
        mp = machine_program_from_cmds(m.cmds)
    except (ValueError, OverflowError) as e:
        ok = 'rejected_decode' in m.expected
        return {'verdict': 'rejected_decode' if ok else 'MISTRAPPED',
                'detail': str(e)}
    try:
        validate_program(mp, m.cfg)
    except ProgramValidationError as e:
        if e.codes & m.expected:
            return {'verdict': 'rejected_validator',
                    'detail': sorted(e.codes)}
        return {'verdict': 'MISTRAPPED',
                'detail': f'validator codes {sorted(e.codes)} not in '
                          f'expected {sorted(m.expected)}'}
    mb = np.zeros((shots, mp.n_cores, m.cfg.max_meas), np.int32)
    per_engine = {}
    for eng in engines:
        cfg = replace(m.cfg, engine=eng)
        try:
            out = simulate_batch(mp, mb, cfg=cfg)
        except ValueError as e:
            if 'ineligible' in str(e):
                continue            # engine doesn't apply to this shape
            return {'verdict': 'MISTRAPPED',
                    'detail': f'{eng} raised {e}'}
        per_engine[eng] = _fault_names(out['fault'])
    if not per_engine:
        return {'verdict': 'MISTRAPPED', 'detail': 'no engine ran'}
    # cross-engine agreement is required on the timing-INDEPENDENT
    # codes; budget/deadlock/starvation depend on engine step
    # accounting (a block iteration retires many instructions) and are
    # judged per engine against the oracle instead
    strict = {names & _TIMING_INDEPENDENT
              for names in per_engine.values()}
    if len(strict) > 1:
        return {'verdict': 'INCONSISTENT', 'detail': {
            k: sorted(v) for k, v in per_engine.items()}}
    for eng, names in per_engine.items():
        if not names:
            if not m.allow_clean:
                return {'verdict': 'SILENT',
                        'detail': f'{eng}: expected '
                                  f'{sorted(m.expected)}, no fault '
                                  f'fired'}
        elif not names & m.expected:
            return {'verdict': 'MISTRAPPED',
                    'detail': f'{eng} trapped {sorted(names)}, '
                              f'expected {sorted(m.expected)}'}
    fired = frozenset().union(*per_engine.values())
    if fired:
        return {'verdict': 'trapped', 'detail': sorted(fired)}
    return {'verdict': 'benign', 'detail': sorted(per_engine)}


def check_vmap_consistency(seed: int = 0, n: int = 8,
                           shots: int = 4) -> int:
    """Stack valid-after-mutation single-core programs and assert the
    vmapped multi-program executable reports the SAME per-program fault
    sets as per-program ``simulate_batch`` runs."""
    mps, cfgs, singles = [], [], []
    base_cfg = InterpreterConfig(max_steps=64)
    k = 0
    while len(mps) < n:
        r = np.random.default_rng((seed, 7000 + k))
        k += 1
        cmds, _ = base_loop(r)
        m = mut_shrink_budget(r, cmds, base_cfg) if k % 2 \
            else Mutant('', cmds, base_cfg, frozenset(), allow_clean=True)
        try:
            mp = machine_program_from_cmds(m.cmds)
            validate_program(mp, m.cfg)
        except (ValueError, ProgramValidationError):
            continue
        mps.append(mp)
        cfgs.append(m.cfg)
    # one shared cfg: the TIGHTEST budget, so trapping programs trap in
    # both the single and the stacked run
    cfg = replace(base_cfg,
                  max_steps=min(c.max_steps for c in cfgs))
    for mp in mps:
        mb = np.zeros((shots, mp.n_cores, cfg.max_meas), np.int32)
        singles.append(_fault_names(
            simulate_batch(mp, mb, cfg=cfg)['fault']))
    mmp = stack_machine_programs(mps)
    mb = np.zeros((mmp.n_progs, shots, mmp.n_cores, cfg.max_meas),
                  np.int32)
    out = simulate_multi_batch(mmp, mb, cfg=cfg)
    bad = 0
    for p in range(mmp.n_progs):
        stacked = _fault_names(out['fault'][p])
        if stacked != singles[p]:
            bad += 1
    return bad


def check_mesh_consistency(seed: int = 0, n: int = 4,
                           shots_per_prog: int = 8) -> int:
    """Run a mutant ensemble through ``run_multi_sweep`` with and
    without a dp=2 mesh and count fault-stat mismatches (0 = the
    sharded reduction reports exactly the per-device faults).  Returns
    -1 if fewer than 2 devices are available (check skipped)."""
    import jax
    from jax.sharding import Mesh
    if len(jax.devices()) < 2:
        return -1
    from ..parallel.driver import run_multi_sweep
    mps = []
    k = 0
    while len(mps) < n:
        r = np.random.default_rng((seed, 9000 + k))
        k += 1
        cmds, _ = base_loop(r)
        try:
            mp = machine_program_from_cmds(cmds)
            validate_program(mp)
        except (ValueError, ProgramValidationError):
            continue
        mps.append(mp)
    kw = dict(total_shots=shots_per_prog, batch=shots_per_prog,
              key=seed, max_steps=6)   # starved: every program traps
    ref = run_multi_sweep(mps, **kw)
    mesh = Mesh(np.asarray(jax.devices()[:2]), ('dp',))
    got = run_multi_sweep(mps, mesh=mesh, **kw)
    bad = 0
    for name, _ in FAULT_CODES:
        if ref['fault_shots'][name].tolist() \
                != got['fault_shots'][name].tolist():
            bad += 1
    return bad


def check_fused_consistency(seed: int = 0, n: int = 40,
                            shots: int = 4) -> dict:
    """Cross-check ``generic`` vs the fused measure-in-megastep engine
    (``engine='fused'``, in-kernel demodulation) on the
    timing-INDEPENDENT fault codes.

    :func:`run_fuzz` cannot put the fused engine in its ladder: it
    injects measurement bits, and the fused engine's whole point is
    that there is no injection — so this cross-check closes the physics
    loop instead (sigma=0: deterministic bits, identical on both
    engines) and compares fault-name sets on the codes that do not
    depend on engine step accounting.  Mutants the fused engine is
    ineligible for (loops, overflow re-resolution, decode/validator
    rejections) are skipped, not failed.  Returns ``{'checked',
    'skipped', 'failures'}``; a nonempty ``failures`` list is a harness
    failure.
    """
    from .physics import ReadoutPhysics, run_physics_batch
    checked = skipped = 0
    failures = []
    for m in gen_mutants(seed, n):
        try:
            mp = machine_program_from_cmds(m.cmds)
            validate_program(mp, m.cfg)
        except (ValueError, OverflowError, ProgramValidationError):
            skipped += 1
            continue
        # the model's readout element must match the mutant cfg's (the
        # fproc base programs pin meas_elem=0)
        model = ReadoutPhysics(sigma=0.0, meas_elem=m.cfg.meas_elem)
        names = {}
        try:
            for eng in ('generic', 'fused'):
                out = run_physics_batch(mp, model, seed, shots,
                                        cfg=replace(m.cfg, engine=eng))
                names[eng] = _fault_names(out['fault'])
        except ValueError as e:
            if 'ineligible' in str(e):
                skipped += 1
                continue
            failures.append((m.name, f'raised: {e}'))
            continue
        checked += 1
        a = names['generic'] & _TIMING_INDEPENDENT
        b = names['fused'] & _TIMING_INDEPENDENT
        if a != b:
            failures.append((m.name, {'generic': sorted(a),
                                      'fused': sorted(b)}))
    return {'checked': checked, 'skipped': skipped, 'failures': failures}


def check_feedback_consistency(seed: int = 0, n: int = 24,
                               shots: int = 4) -> dict:
    """Cross-check ``generic`` vs ``block`` vs ``pallas`` (interpret
    mode) on lut+fproc FEEDBACK mutants, timing-independent fault
    codes only.

    The timestamped fabric makes LUT reads a pure function of the
    measurement/timestamp planes and the read service time, which is
    what admitted feedback programs to the fast engines (docs/PERF.md
    "Feedback on the fast engines") — so on every valid mutant of the
    lut base the engines must agree on the codes that do not depend on
    engine step accounting (``_TIMING_INDEPENDENT``; budget/deadlock/
    starvation are judged per engine by :func:`check_mutant` instead).
    Measurement bits are (seed, case)-deterministic random draws so
    the syndrome actually varies.  Mutants an engine is ineligible for
    and decode/validator rejections are skipped, not failed.  Returns
    ``{'checked', 'skipped', 'failures'}``; nonempty ``failures`` is a
    harness failure.
    """
    checked = skipped = 0
    failures = []
    k = made = 0
    while made < n:
        mn, mf = MUTATORS[k % len(MUTATORS)]
        rng = np.random.default_rng((seed, 5000 + k))
        cmds, cfg = base_lut(rng)
        m = mf(rng, cmds, cfg)
        k += 1
        if m is None:
            continue
        made += 1
        m.name = f'lut+{mn}#{k - 1}'
        try:
            mp = machine_program_from_cmds(m.cmds)
            validate_program(mp, m.cfg)
        except (ValueError, OverflowError, ProgramValidationError):
            skipped += 1
            continue
        mb = np.random.default_rng((seed, 6000 + k)).integers(
            0, 2, (shots, mp.n_cores, m.cfg.max_meas)).astype(np.int32)
        names = {}
        try:
            for eng in ('generic', 'block', 'pallas'):
                extra = {'pallas_interpret': True} if eng == 'pallas' \
                    else {}
                out = simulate_batch(
                    mp, mb, cfg=replace(m.cfg, engine=eng, **extra))
                names[eng] = _fault_names(out['fault'])
        except ValueError as e:
            if 'ineligible' in str(e):
                skipped += 1
                continue
            failures.append((m.name, f'raised: {e}'))
            continue
        checked += 1
        strict = {eng: nm & _TIMING_INDEPENDENT
                  for eng, nm in names.items()}
        if len(set(strict.values())) > 1:
            failures.append((m.name,
                             {e: sorted(s) for e, s in strict.items()}))
    return {'checked': checked, 'skipped': skipped, 'failures': failures}


def check_audit_consistency(seed: int = 0, n: int = 24,
                            shots: int = 4) -> dict:
    """Serve the mutant corpus with ``audit_sample=1`` and count
    false-positive integrity violations (docs/ROBUSTNESS.md
    "Integrity": the auditor must never cry wolf on legitimately
    identical engines).

    Every valid mutant — including ones that trap, where
    timing-dependent fault codes legitimately differ across engines —
    goes through an :class:`~..serve.ExecutionService` whose audit
    sampler re-executes each completed batch on a different engine and
    escalates cross-engine disagreement to a served-configuration
    confirm run.  With no corruption injected, ``false_positives``
    (the service's confirmed-mismatch count) must be 0.  Mutants the
    decoder/validator reject are skipped (they never reach dispatch).
    Returns ``{'checked', 'skipped', 'audits', 'false_positives'}``.
    """
    from ..serve import ExecutionService
    checked = skipped = 0
    with ExecutionService(None, max_batch_programs=4,
                          audit_sample=1.0, audit_mode='flag') as svc:
        handles = []
        for m in gen_mutants(seed, n):
            try:
                mp = machine_program_from_cmds(m.cmds)
                validate_program(mp, m.cfg)
            except (ValueError, OverflowError, ProgramValidationError):
                skipped += 1
                continue
            cfg = replace(m.cfg, engine=None, straightline=False,
                          fault_mode='count', opcode_histogram=False)
            mb = np.zeros((shots, mp.n_cores, cfg.max_meas), np.int32)
            try:
                handles.append(svc.submit(mp, mb, cfg=cfg))
            except ValueError:
                skipped += 1     # cfg the serve path refuses typed
                continue
            checked += 1
        for h in handles:
            h.result(timeout=300)
        st = svc.stats()['integrity']
    return {'checked': checked, 'skipped': skipped,
            'audits': st['audits'],
            'false_positives': st['mismatches']}


@dataclass
class FuzzReport:
    n: int = 0
    verdicts: dict = field(default_factory=dict)
    failures: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.failures


def run_fuzz(seed: int = 0, n: int = 200, engines=ENGINES,
             shots: int = 4, progress=None) -> FuzzReport:
    """Fuzz ``n`` mutants; any SILENT/MISTRAPPED/INCONSISTENT verdict
    is recorded as a failure (``report.ok``)."""
    rep = FuzzReport()
    for m in gen_mutants(seed, n):
        res = check_mutant(m, engines=engines, shots=shots)
        rep.n += 1
        v = res['verdict']
        rep.verdicts[v] = rep.verdicts.get(v, 0) + 1
        if v not in ('rejected_decode', 'rejected_validator',
                     'trapped', 'benign'):
            rep.failures.append((m.name, v, res['detail']))
        if progress and rep.n % 25 == 0:
            progress(rep)
    return rep
