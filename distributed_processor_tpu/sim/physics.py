"""Physics-closed measurement feedback: epoch execution.

The reference closes its measurement-feedback loop in hardware: the rdlo
pulse drives the readout chain, an (out-of-repo) demodulator produces
``meas``/``meas_valid``, and the fproc fabric unblocks the waiting core
(reference: hdl/core_state_mgr.sv:45-56, hwconfig.py:9 FPROC_MEAS_CLKS).
This module closes the same loop numerically, the TPU way:

1. **Execute** — the batched interpreter runs every (shot, core) lane
   until it is done or stalled on an fproc read whose measurement bit is
   still *invalid* (fired but not yet demodulated).  Stalled shots pause
   (``interpreter._exec_loop`` physics mode).
2. **Resolve** — every fired-but-unresolved readout window is
   synthesized from its recorded pulse parameters (envelope playback +
   phase-coherent carrier, the same numeric contract as
   :func:`..ops.waveform.synthesize_element`), passed through a
   state-dependent channel response, summed with per-sample Gaussian ADC
   noise, matched-filter demodulated, and discriminated against the
   clean |0>/|1> responses.  Readout infidelity therefore *emerges* from
   the noise model instead of being injected.
3. **Resume** — the resolved bits feed the fproc fabric; paused shots
   continue.  Repeat until all shots complete (at most
   ``max_meas + 1`` epochs).

The whole epoch loop is one jitted ``lax.while_loop`` (inner instruction
loop nested inside), so a million-shot active-reset sweep with real
readout DSP is a single XLA computation.

The qubit itself is modelled classically (the reference models no
physics at all — real hardware supplies the bits): each drive-element
pulse adds ``round(amp / x90_amp)`` quarter turns to a per-(shot, core)
counter and the state bit is the half-turn parity (floor convention for
odd quarter-turn residues).  Initial states are sampled thermally.  This
is deliberately a stand-in — the framework's contract is the *control*
loop (bit timing, fabric semantics, branch resolution), not device
simulation; swap :class:`ReadoutPhysics` response parameters for a
better device model as needed.

Noise is deterministic per (shot, core, measurement-slot) given the run
key: every slot is resolved exactly once (``valid`` masks resolved slots
out of later epochs), in the epoch its lane first presents it.  The
per-sample modes fold the epoch index into the resolve key; analytic
keys its single draw per slot position, deterministic as-is.

The per-sample resolver compacts the measurement axis: each epoch
resolves the *first* pending slot of every (shot, core) lane, so the
per-sample volume is ``[B, C, W]`` per epoch and the total synthesis
work is proportional to the number of windows actually fired — not
``max_meas`` times that, which is what an all-slots resolve pass costs
for the common measure-then-branch program shape.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace

import numpy as np
import jax
import jax.numpy as jnp

from ..elements import ENV_CW_SENTINEL, IQ_SCALE
from ..ops.waveform import (PHASE_BITS, AMP_SCALE, complex_to_iq,
                            carrier_phase)
from .device import DeviceModel, STATEVEC_MAX_CORES
from .interpreter import (InterpreterConfig, _program_constants, _init_state,
                          _exec_loop, _finalize, _check_fabric,
                          program_traits, use_straightline, _soa_static,
                          resolve_engine, _fault_policy, _check_strict,
                          carry_packspec, use_packed_carry)


def _engine_static(mp, cfg: InterpreterConfig):
    """``(sl, blk, fus)`` content-keyed static programs for the physics
    epoch loop: at most one is non-``None`` when :func:`resolve_engine`
    picks a specialized engine, all ``None`` for the generic engine.
    ``fus`` selects the measure-in-megastep span kernel
    (``engine='fused'``), which demodulates windows in-kernel and
    collapses the epoch loop to one pass."""
    eng = resolve_engine(mp, cfg)
    if eng == 'straightline':
        return _soa_static(mp), None, None
    if eng == 'block':
        return None, _soa_static(mp), None
    if eng == 'fused':
        return None, None, _soa_static(mp)
    return None, None, None

# default-qchip X90 amplitude word: round(0.48 * (2^16 - 1))
X90_AMP_DEFAULT = 31457


@dataclass(frozen=True)
class ReadoutPhysics:
    """Readout-chain + classical-device model parameters.

    ``g0``/``g1``: complex channel response for a qubit in |0> / |1> —
    the resonator's state-dependent transmission the matched filter
    discriminates (scalar or per-core array).  ``sigma``: per-sample ADC
    noise standard deviation, in units of the full-scale synthesized
    window (the emergent readout infidelity depends on
    ``|g1-g0| * sqrt(window energy) / (2*sigma)``).  ``p1_init``:
    thermal excited-state probability at t=0.  ``x90_amp``: drive amp
    word equal to one quarter turn of the classical rotation model.
    ``window_samples``: static readout-window length (None = sized from
    the program's envelope tables).  ``device``: the qubit co-state
    model the loop evolves (sim/device.py) — the default 'parity'
    counter is the deterministic bit-flip toy; ``DeviceModel('bloch')``
    gives phase-sensitive SU(2) rotations with detuning/T1/T2, making
    Ramsey/T2-echo/Rabi/RB sweeps physically meaningful end-to-end.
    """
    g0: complex = 1.0 + 0.0j
    g1: complex = -0.6 + 0.8j
    # |2> channel response (scalar or per-core), for IQ-LEVEL leakage
    # readout: when set (statevec device with leak_per_pulse > 0), a
    # leaked core's readout window is synthesized and demodulated
    # through the REAL chain with this response — the leaked bit then
    # EMERGES from where g2 projects on the g0/g1 discrimination axis
    # (put it near g1 to model the usual |2>-reads-as-|1> geometry)
    # instead of being forced (the ``leak_readout_bit`` shortcut, which
    # remains the documented fast path when g2 is None).  This is the
    # IQ-level element contract the rest of the loop implements
    # (reference: python/distproc/asmparse.py:46-63).
    g2: complex = None
    # 3-class discrimination (needs g2): nearest-centroid in the IQ
    # plane against {g0*E, g1*E, g2*E}.  The run output gains
    # ``meas_class`` ([B, C, M] in {0, 1, 2}) — the observable a
    # leakage-detection experiment reads; the fabric bit a branching
    # program sees maps class 2 to ``leak_readout_bit``.
    classify3: bool = False
    sigma: float = 0.05
    p1_init: float = 0.1
    x90_amp: int = X90_AMP_DEFAULT
    drive_elem: int = 0
    meas_elem: int = 2
    window_samples: int = None
    device: DeviceModel = DeviceModel(kind='parity')
    # resonator ring-up time constant in DAC samples of the measurement
    # element (0 = instantaneous response).  With ring_tau > 0 the
    # state-dependent transmission builds up over the window as
    # ``g_s * (1 - exp(-(s+1)/ring_tau))`` — the transient of a driven
    # resonator with linewidth kappa = 2/(ring_tau * t_sample) — so
    # early samples carry less discrimination information than their
    # energy suggests.  This is the channel structure a flat-response
    # matched-filter shortcut cannot collapse: 'persample' and 'fused'
    # simulate it sample-by-sample; 'analytic' remains the EXACT
    # distribution only for ring_tau == 0 and becomes a flat-response
    # approximation otherwise (docs/PHYSICS.md "Readout channel").
    ring_tau: float = 0.0
    # samples per resolve step: the matched filter streams over the
    # window in chunks of this size (lax.scan), so peak memory is
    # O(B*C*M*chunk) instead of O(B*C*M*W) — million-shot batches with
    # 2k-sample readout windows fit HBM
    resolve_chunk: int = 512
    # CW (hold-until-next) readout envelopes: integration horizon in
    # DAC samples.  0 = refuse (ERR_CW_MEAS, the safe default — a CW
    # window has no intrinsic length); > 0 = demodulate CW measurement
    # windows over exactly this many samples (must be <= the table
    # window W), with the envelope playing through its table and
    # holding the final sample — the element contract's CW word
    # (reference: python/distproc/hwconfig.py:12-67 get_cw_env_word)
    # becomes usable for readout instead of an error.
    cw_horizon: int = 0
    # ADC noise color: AR(1) pole per sample (0 = white).  With
    # 0 < noise_ar1 < 1 the per-sample resolver draws stationary
    # unit-variance AR(1) noise (exact, IIR state carried across
    # chunks; the in-chunk recursion is one lower-triangular matmul on
    # the MXU).  Positively-correlated noise is NOT collapsed by the
    # matched filter the way white noise is — the accumulated noise
    # variance gains the double sum over rho^|t-t'| — so assignment
    # fidelity degrades for smooth envelopes; tests/test_ringdown.py
    # measures the penalty.  'analytic' (white-noise closed form) and
    # 'fused' (in-kernel white generator) refuse rather than silently
    # whiten.
    noise_ar1: float = 0.0
    # fused-mode ADC noise generator: None = auto (in-kernel
    # counter-based RNG on real TPU, streamed threefry under
    # interpret); True/False forces it.  Static — part of the compiled
    # program (tests/test_tpu_kernels.py pins the two generators'
    # statistical parity by compiling both).
    fused_native_rng: bool = None
    # 'persample': synthesize + demodulate every window sample (the
    # general path — required once the channel model grows structure a
    # matched filter can't collapse).  'fused': the same per-sample
    # chain as one Pallas kernel (ops/resolve_pallas.py) — synthesis,
    # in-kernel ADC noise, matched filter all in VMEM; same math,
    # different noise generator (bit-identical to 'persample' at
    # sigma=0, statistically equivalent at finite sigma), much faster
    # on TPU.  'analytic': the EXACT distributional shortcut for this
    # white-noise matched-filter model — the filter is linear, so
    # acc = g_s*E + sigma*sqrt(E)*xi with window energy E from an
    # envelope prefix sum; same bit distribution at O(B*C*M) instead
    # of O(B*C*M*W)
    resolve_mode: str = 'persample'


def _physics_tables(mp, meas_elem: int):
    """Stack per-core measurement-element tables into dense constants."""
    C = mp.n_cores
    envs, frels, spcs, interps = [], [], [], []
    for c in range(C):
        t = mp.tables[c]
        if meas_elem < len(t.elem_cfgs):
            ec = t.elem_cfgs[meas_elem]
            spcs.append(int(ec.samples_per_clk))
            interps.append(int(ec.interp_ratio))
            env = np.asarray(t.envs[meas_elem]) if meas_elem < len(t.envs) \
                else np.zeros(0, complex)
            if meas_elem < len(t.freqs) and len(t.freqs[meas_elem]['freq']):
                fr = np.asarray(t.freqs[meas_elem]['freq'],
                                np.float64) / ec.sample_freq
            else:
                fr = np.zeros(0)
        else:
            spcs.append(4)
            interps.append(1)
            env, fr = np.zeros(0, complex), np.zeros(0)
        envs.append(complex_to_iq(env / IQ_SCALE) if len(env)
                    else np.zeros((0, 2), np.float32))
        frels.append(fr.astype(np.float32))
    L = max((len(e) for e in envs), default=0) or 1
    F = max((len(f) for f in frels), default=0) or 1
    env_stack = np.zeros((C, L, 2), np.float32)
    freq_stack = np.zeros((C, F), np.float32)
    for c in range(C):
        env_stack[c, :len(envs[c])] = envs[c]
        freq_stack[c, :len(frels[c])] = frels[c]
    w_auto = max((len(envs[c]) * interps[c] for c in range(C)), default=0) or 1
    # spc/interp stay numpy: they parameterize static (compile-time)
    # structure, and callers may run under an outer trace where jnp
    # constants would become tracers
    return (jnp.asarray(env_stack), jnp.asarray(freq_stack),
            np.asarray(spcs, np.int32), np.asarray(interps, np.int32),
            int(w_auto))


def _window_scalars(st: dict, tables, cw_samp: int = 0):
    """Per-measurement synthesis scalars, ``[B,C,M]`` each.
    ``cw_samp``: static CW-readout horizon in DAC samples (0 = CW
    windows stay zero-length; the interpreter flags them as errors)."""
    env_stack, freq_stack, spc_m, interp_m = tables
    B, C, M = st['meas_env'].shape
    amp = st['meas_amp'].astype(jnp.float32) / AMP_SCALE          # [B,C,M]
    ph = 2 * jnp.pi * st['meas_phase'].astype(jnp.float32) \
        / (1 << PHASE_BITS)
    F = freq_stack.shape[1]
    c_idx = jnp.broadcast_to(jnp.arange(C)[None, :, None], (B, C, M))
    f_rel = freq_stack[c_idx, jnp.clip(st['meas_freq'], 0, F - 1)]
    envw = st['meas_env']
    addr = (envw & 0xfff) * 4
    nw = (envw >> 12) & 0xfff
    interp_c = interp_m[None, :, None]
    spc_c = spc_m[None, :, None]
    n_samp = jnp.where(nw == ENV_CW_SENTINEL, cw_samp, nw * 4 * interp_c)
    n0_car = st['meas_gtime'] * spc_c
    # factored carrier: theta(s) = A + 2*pi*f*s with the per-window
    # scalar A = 2*pi*f*n0 + ph — the only transcendentals taken at
    # [B,C,M] scale; the s-dependence comes from the basis table.
    # carrier_phase keeps A exact at large n0 (split-precision NCO)
    A = carrier_phase(f_rel, n0_car, ph)
    return dict(amp=amp, ph=ph, f_rel=f_rel, addr=addr, n_samp=n_samp,
                interp_c=interp_c, n0_car=n0_car, c_idx=c_idx,
                cosA=jnp.cos(A), sinA=jnp.sin(A),
                f_idx=jnp.clip(st['meas_freq'], 0, F - 1))


def _aligned_chunk(chunk: int, W: int, interps) -> int:
    """Resolve-chunk width actually used: capped at W and rounded up so
    every core's chunk covers whole envelope samples (multiple of each
    interp ratio) — the same value must size the env-plane padding."""
    chunk = min(chunk or W, W)
    align = int(np.lcm.reduce(np.asarray(interps))) if len(interps) else 1
    return -(-chunk // align) * align


def _pad_env_planes(env_stack, pad: int):
    """Split ``[C,L,2]`` env tables into I/Q planes padded with ``pad``
    copies of the final sample, so a window chunk reads a contiguous
    ``dynamic_slice`` with the reference's hold-last-sample overrun
    semantics (the clamp in :func:`..ops.waveform.synthesize_element`)."""
    C = env_stack.shape[0]
    last = env_stack[:, -1:, :]
    env_pad = jnp.concatenate(
        [env_stack, jnp.broadcast_to(last, (C, pad, 2))], axis=1)
    return env_pad[..., 0], env_pad[..., 1]


def _toeplitz_tables(env_pads, width: int, interps):
    """Per-core sliding-window (Toeplitz) env tables for a fixed chunk
    width: ``T[c][p, i, j] = env_plane_p[c][i + j]``, ``[2, R, seg]``
    per core.  Chunk-invariant — build once per resolve, outside the
    scan (XLA does not reliably hoist the gather out of while bodies)."""
    env_i_pad, env_q_pad = env_pads                   # [C, Lp] each
    Lp = env_i_pad.shape[1]
    tables = []
    for c in range(len(interps)):
        seg = -(-width // int(interps[c]))
        R = Lp - seg + 1                              # valid slice starts
        win = jnp.arange(R)[:, None] + jnp.arange(seg)[None, :]
        tables.append(jnp.stack([env_i_pad[c][win], env_q_pad[c][win]], 0))
    return tables


def _carrier_basis(freq_stack, W: int):
    """Carrier basis ``cos/sin(2*pi*f*s)`` for every table frequency:
    two ``[C, F, W]`` arrays, a few KB — computed once per resolve so
    the per-sample carrier needs no transcendentals (the old direct
    ``cos(2*pi*f*(n0+s))`` ran at ~2 GS/s on the VPU and dominated the
    resolve; the factored form is two small MXU matmuls + multiplies)."""
    s = jnp.arange(W, dtype=jnp.int32)
    theta = carrier_phase(freq_stack[..., None], s)               # [C,F,W]
    return jnp.cos(theta), jnp.sin(theta)


def _synth_window_chunk(sc: dict, toeplitz, basis, s0, width: int, interps):
    """Synthesize samples ``[s0, s0+width)`` of every recorded readout
    window: ``[B,C,M,width]`` I/Q (``M`` is whatever window axis ``sc``
    carries — all slots, or the single compacted pending slot).

    Same numeric contract as :func:`..ops.waveform.synthesize_element`
    (env addressing ``(env&0xfff)*4 + s//interp``, phase-coherent
    carrier from the global phase origin, ``amp/AMP_SCALE`` scaling) in
    windowed per-measurement form — pinned against it by
    tests/test_physics.py::test_window_matches_synthesize_element.

    The envelope read rides the MXU: each window's contiguous env
    segment is fetched as ``one_hot(start) @ T`` where ``T`` is the
    sliding-window (Toeplitz) view of the padded per-core table — TPU
    per-element gathers serialize, and even batched ``dynamic_slice``
    lowers to a slow gather; a [B*M, R] x [R, seg] matmul against a
    few-hundred-row table is data-independent and fast.  Requires
    ``s0`` divisible by each core's interp ratio (chunk sizes are
    multiples of every interp ratio by construction).
    """
    B, C, M = sc['amp'].shape
    # phase-coherent carrier from the global phase origin — identical in
    # the synthesized signal and the matched-filter reference, so float32
    # carrier-phase rounding cancels in the demod product.  Factored as
    # e^{i theta} = e^{iA} * basis(f, s): per-window scalar rotation of
    # the precomputed per-frequency basis rows
    basis_cos, basis_sin = basis                      # [C, F, W] each
    F = basis_cos.shape[1]
    bslice = jax.lax.dynamic_slice(
        jnp.stack([basis_cos, basis_sin], 0), (0, 0, 0, s0),
        (2, C, F, width))
    s_lane = s0 + jnp.arange(width, dtype=jnp.int32)[None, None, :]
    zero = jnp.float32(0)

    if len(set(interps)) == 1:
        # homogeneous element geometry (the common case): one batched
        # formulation over the core axis instead of a per-core Python
        # unroll — C-fold fewer graph segments (compile time) and one
        # C-batched MXU matmul for the envelope fetch.  One-hot rows
        # make the matmul an exact row select, so this is bit-identical
        # to the per-core path.
        interp = int(interps[0])
        T = jnp.stack(toeplitz, 0)                    # [C, 2, R, seg]
        R = T.shape[2]
        base = jnp.clip(sc['addr'] + s0 // interp, 0, R - 1)   # [B, C, M]
        oh = jax.nn.one_hot(base, R, dtype=jnp.float32)        # [B,C,M,R]
        segs = jnp.einsum('bcmr,cprs->pbcms', oh, T,
                          preferred_element_type=jnp.float32,
                          precision=jax.lax.Precision.HIGHEST)
        rep = lambda a: jnp.repeat(a, interp, axis=-1)[..., :width]
        e_i, e_q = rep(segs[0]), rep(segs[1])         # [B, C, M, width]
        bc = jnp.broadcast_to(bslice[0, :, 0][None, :, None, :],
                              (B, C, M, width))
        bs = jnp.broadcast_to(bslice[1, :, 0][None, :, None, :],
                              (B, C, M, width))
        for f in range(1, F):
            m = (sc['f_idx'] == f)[..., None]
            bc = jnp.where(m, bslice[0, :, f][None, :, None, :], bc)
            bs = jnp.where(m, bslice[1, :, f][None, :, None, :], bs)
        cosA, sinA = sc['cosA'][..., None], sc['sinA'][..., None]
        cth = cosA * bc - sinA * bs
        sth = sinA * bc + cosA * bs
        amp = sc['amp'][..., None]
        in_win = s_lane[:, :, None, :] < sc['n_samp'][..., None]
        y_i = jnp.where(in_win, amp * (e_i * cth - e_q * sth), zero)
        y_q = jnp.where(in_win, amp * (e_i * sth + e_q * cth), zero)
        return y_i, y_q

    y_is, y_qs = [], []
    # everything per core stays [B, M, width] and fuses into the two
    # final stacks — materializing separate env and carrier stacks
    # doubles peak HBM at bench batch sizes
    for c in range(C):
        interp = int(interps[c])
        seg = -(-width // interp)
        T = toeplitz[c]                               # [2, R, seg]
        R = T.shape[1]
        base = jnp.clip(sc['addr'][:, c, :] + s0 // interp, 0, R - 1)
        oh = jax.nn.one_hot(base.reshape(-1), R, dtype=jnp.float32)
        # HIGHEST precision: the default MXU bf16 operand rounding would
        # quantize env samples past the synthesize_element parity
        # tolerance (the one_hot side is exact either way)
        segs = jnp.einsum('br,prs->pbs', oh, T,
                          preferred_element_type=jnp.float32,
                          precision=jax.lax.Precision.HIGHEST)
        rep = lambda a: jnp.repeat(
            a.reshape(B, M, seg), interp, axis=-1)[..., :width]
        e_i, e_q = rep(segs[0]), rep(segs[1])         # [B, M, width]

        # carrier row select: F is small (table frequencies per core), so
        # a select chain stays elementwise and fuses into the final y
        # kernel — the one-hot einsum here materialized a [B*M, width]
        # f32 row matrix per core per chunk (GBs of pure HBM traffic at
        # bench batch).  Numerically identical: a 0/1-weighted f32 sum
        # of rows equals the selected row exactly.
        f_idx = sc['f_idx'][:, c, :]                  # [B, M]
        bc = jnp.broadcast_to(bslice[0, c, 0][None, None, :], (B, M, width))
        bs = jnp.broadcast_to(bslice[1, c, 0][None, None, :], (B, M, width))
        for f in range(1, F):
            m = (f_idx == f)[..., None]
            bc = jnp.where(m, bslice[0, c, f][None, None, :], bc)
            bs = jnp.where(m, bslice[1, c, f][None, None, :], bs)
        cosA = sc['cosA'][:, c, :, None]
        sinA = sc['sinA'][:, c, :, None]
        cth = cosA * bc - sinA * bs
        sth = sinA * bc + cosA * bs
        amp = sc['amp'][:, c, :, None]
        in_win = s_lane < sc['n_samp'][:, c, :, None]
        y_is.append(jnp.where(in_win, amp * (e_i * cth - e_q * sth), zero))
        y_qs.append(jnp.where(in_win, amp * (e_i * sth + e_q * cth), zero))
    return jnp.stack(y_is, axis=1), jnp.stack(y_qs, axis=1)


def _synth_windows(st: dict, tables, W: int):
    """Full-window synthesis (``[B,C,M,W]`` I/Q) — one chunk of width W."""
    sc = _window_scalars(st, tables)
    interps = tuple(int(x) for x in np.asarray(tables[3]))
    toeplitz = _toeplitz_tables(_pad_env_planes(tables[0], W), W, interps)
    basis = _carrier_basis(tables[1], W)
    return _synth_window_chunk(sc, toeplitz, basis, jnp.int32(0), W, interps)


def _compact_pending_slot(st: dict, valid, tables, cw_samp: int = 0):
    """First fired-but-unresolved measurement slot per (shot, core).

    Returns ``(sc, state_sel, oh_slot, has_pending)``: the compacted
    window-synthesis scalars (each ``[B, C, 1]`` — the singleton window
    axis lets :func:`_synth_window_chunk` run unchanged), the chosen
    slot's device-state bit, the slot one-hot over the measurement axis,
    and the lanes that actually have a pending slot.  Slots resolve
    exactly once: ``valid`` masks resolved slots out of the selection.
    """
    B, C, M = valid.shape
    fired = jnp.arange(M)[None, None, :] < st['n_meas'][..., None]
    pending = fired & ~valid                                     # [B,C,M]
    has_pending = jnp.any(pending, axis=-1)                      # [B,C]
    slot = jnp.argmax(pending, axis=-1).astype(jnp.int32)        # [B,C]
    oh_slot = (slot[..., None]
               == jnp.arange(M, dtype=jnp.int32)[None, None, :])  # [B,C,M]
    take = lambda a: jnp.sum(jnp.where(oh_slot, a, 0), axis=-1)[..., None]
    st_sel = {k: take(st[k]) for k in
              ('meas_amp', 'meas_phase', 'meas_freq', 'meas_env',
               'meas_gtime')}
    sc = _window_scalars(st_sel, tables, cw_samp)
    return sc, take(st['meas_state']), oh_slot, has_pending


def _scatter_slot_bit(bits, valid, new_bit, oh_slot, has_pending):
    """Write the resolved bit (``[B, C]``) back into its slot and mark
    it valid — only on lanes that had a pending slot."""
    resolved = oh_slot & has_pending[..., None]                  # [B,C,M]
    bits = jnp.where(resolved, new_bit[..., None], bits)
    return bits, valid | resolved


def _ar1_tables(rho, chunk: int):
    """AR(1) in-chunk recursion as one lower-triangular matmul:
    ``n[i] = sum_j T[i, j] w[j] + rpow[i] * n_carry`` with
    ``T[i, j] = c * rho^(i-j)`` (i >= j, c = sqrt(1 - rho^2)) and
    ``rpow[i] = rho^(i+1)`` — exact unit-variance stationary AR(1),
    sequential only across chunks (one carried sample), MXU work
    within them."""
    i = jnp.arange(chunk, dtype=jnp.float32)
    d = i[:, None] - i[None, :]
    c = jnp.sqrt(jnp.maximum(1.0 - rho * rho, 0.0))
    T = jnp.where(d >= 0, c * rho ** d, 0.0)                # [ck, ck]
    return T, rho ** (i + 1.0)


def _resolve(st: dict, bits, valid, key, tables, env_pads, response,
             W: int, chunk: int = None, interps=None, prebuilt=None,
             ring: bool = False, cw: int = 0, colored=None,
             iq3=None, cls=None):
    """Demodulate pending readout windows into bits — one slot per
    (shot, core) per call.  ``prebuilt``: optional ``(toeplitz, basis)``
    built once by the caller — pass it when calling from inside a loop
    (XLA does not hoist the table gathers out of while bodies).

    The measurement contract being implemented numerically is the
    reference's readout word formats and hold timing
    (reference: python/distproc/asmparse.py:46-86, hwconfig.py:112-115);
    the bit produced here is what hardware presents on the fabric's
    ``meas`` inputs.

    Each call resolves the FIRST fired-but-unresolved slot of every
    (shot, core) lane: slots resolve exactly once (``valid`` masks them
    out afterwards), so compacting the measurement axis away makes the
    per-sample work O(B*C*W) per epoch and the TOTAL per-sample work
    proportional to the number of windows actually fired — the
    all-slots form re-synthesized every window every epoch, ``M`` times
    more work for the common block-after-measure program shape.  ``key``
    must differ per call (the caller folds in the epoch index): a slot
    is resolved in exactly one epoch, so per-epoch keying keeps bits
    deterministic per (shot, core, slot) for a given run key.

    The window streams through a ``lax.scan`` in chunks of ``chunk``
    samples (synthesis + channel response + ADC noise + matched-filter
    accumulation per chunk), so peak memory is independent of W.
    """
    g0, g1, sigma, inv_ring = response        # [C,2], [C,2], scalars
    B, C, M = bits.shape
    if interps is None:
        interps = tuple(int(x) for x in np.asarray(tables[3]))
    chunk = _aligned_chunk(chunk, W, interps)
    n_chunks = -(-W // chunk)
    sc, state_sel, oh_slot, has_pending = \
        _compact_pending_slot(st, valid, tables, cw)
    # honor the W truncation exactly (the last chunk may run past W, and
    # a model.window_samples shorter than the natural envelope window
    # must clip the integration the way the unchunked path's shape did)
    sc = dict(sc, n_samp=jnp.minimum(sc['n_samp'], W))

    # state-dependent channel response for the chosen slot (3-way when
    # IQ-level leakage readout records state 2 for leaked cores)
    gs = jnp.where(state_sel[..., None] == 1,
                   g1[None, :, None, :], g0[None, :, None, :])   # [B,C,1,2]
    gs = _gs3(gs, state_sel, iq3[0] if iq3 is not None else None)
    gs_i, gs_q = gs[..., 0], gs[..., 1]

    if prebuilt is not None:
        toeplitz, basis = prebuilt
    else:
        toeplitz = _toeplitz_tables(env_pads, chunk, interps)
        # basis covers the padded span so the last chunk's slice stays
        # in range (samples past W are masked by in_win anyway)
        basis = _carrier_basis(tables[1], n_chunks * chunk)

    def chunk_body(carry, k):
        if colored is None:
            acc_i, acc_q, energy = carry
        else:
            acc_i, acc_q, energy, n_prev = carry
        y_i, y_q = _synth_window_chunk(sc, toeplitz, basis, k * chunk,
                                       chunk, interps)           # [B,C,1,w]
        # one fused I+Q noise draw (leading axis of 2 — a TRAILING axis
        # of 2 would tile-pad 64x on TPU (8,128) lanes and blow HBM)
        white = jax.random.normal(
            jax.random.fold_in(key, k), (2, B, C, 1, chunk), jnp.float32)
        if colored is None:
            nz = sigma * white
        else:
            # AR(1) coloring: whites through the triangular kernel plus
            # the cross-chunk IIR carry (exact stationary process)
            T_rho, rpow = colored
            n_cur = jnp.einsum('zbcms,ts->zbcmt', white, T_rho) \
                + n_prev[..., None] * rpow
            nz = sigma * n_cur
        # resonator ring-up: the state-dependent transmission builds as
        # w(s) = 1 - exp(-(s+1)/ring_tau) over the window (the template
        # y and the ADC noise are NOT scaled — only the signal path).
        # `ring` is static: the flat model compiles the factor out
        # entirely, and when active, w is a [chunk] row broadcast
        if ring:
            s_rel = (k * chunk + jnp.arange(chunk, dtype=jnp.int32)
                     + 1).astype(jnp.float32)
            w = 1.0 - jnp.exp(-s_rel * inv_ring)
        else:
            w = jnp.float32(1.0)
        r_i = w * (gs_i[..., None] * y_i - gs_q[..., None] * y_q) + nz[0]
        r_q = w * (gs_i[..., None] * y_q + gs_q[..., None] * y_i) + nz[1]
        # matched filter: acc = sum conj(y) * r
        acc_i = acc_i + jnp.sum(r_i * y_i + r_q * y_q, axis=-1)  # [B,C,1]
        acc_q = acc_q + jnp.sum(r_q * y_i - r_i * y_q, axis=-1)
        energy = energy + jnp.sum(y_i * y_i + y_q * y_q, axis=-1)
        if colored is None:
            return (acc_i, acc_q, energy), None
        return (acc_i, acc_q, energy, n_cur[..., -1]), None

    zeros = jnp.zeros((B, C, 1), jnp.float32)
    carry0 = (zeros, zeros, zeros)
    if colored is not None:
        # stationary initial IIR state (unit variance, like the process)
        carry0 = carry0 + (jax.random.normal(
            jax.random.fold_in(key, 0x41523149), (2, B, C, 1), jnp.float32),)
    (acc_i, acc_q, energy, *_), _ = jax.lax.scan(
        chunk_body, carry0, jnp.arange(n_chunks, dtype=jnp.int32))
    new_bit, new_cls = _acc_to_bit(acc_i, acc_q, energy, g0, g1, iq3)
    if new_cls is not None:
        cls, _ = _scatter_slot_bit(cls, valid, new_cls[..., 0], oh_slot,
                                   has_pending)
    bits, valid = _scatter_slot_bit(bits, valid, new_bit[..., 0], oh_slot,
                                    has_pending)
    return bits, valid, cls


def _resolve_fused(st: dict, bits, valid, key, tables, fused_tables,
                   response, W: int, Lp: int, ck: int, ring: bool = False,
                   native_rng: bool = None, rows: tuple = None,
                   cw: int = 0, iq3=None, cls=None):
    """Slot-compacted resolve through the fused Pallas kernel
    (:func:`..ops.resolve_pallas.resolve_windows_fused`): same
    per-sample chain as :func:`_resolve` with every intermediate in
    VMEM and in-kernel ADC noise.  Bit-identical to the XLA path at
    sigma=0; same noise distribution (different generator) otherwise.
    ``fused_tables`` come from ``build_fused_tables`` — built once per
    run, NOT per epoch.
    """
    from ..ops.resolve_pallas import resolve_windows_fused
    g0, g1, sigma, inv_ring = response
    sc, state_sel, oh_slot, has_pending = \
        _compact_pending_slot(st, valid, tables, cw)
    state_sel = state_sel[..., 0]                             # [B, C]
    gs = jnp.where(state_sel[..., None] == 1,
                   g1[None, :, :], g0[None, :, :])            # [B, C, 2]
    gs = _gs3(gs, state_sel, iq3[0] if iq3 is not None else None)
    acc_i, acc_q, energy = resolve_windows_fused(
        sc, fused_tables, gs[..., 0], gs[..., 1], sigma, inv_ring, key,
        W, Lp, ck=ck, ring=ring, native_rng=native_rng, rows=rows,
        interpret=jax.default_backend() != 'tpu')
    new_bit, new_cls = _acc_to_bit(acc_i, acc_q, energy, g0, g1, iq3)
    if new_cls is not None:
        cls, _ = _scatter_slot_bit(cls, valid, new_cls[..., 0], oh_slot,
                                   has_pending)
    bits, valid = _scatter_slot_bit(bits, valid, new_bit[..., 0], oh_slot,
                                    has_pending)
    return bits, valid, cls


def _discriminate_acc(acc_i, acc_q, energy, g0, g1):
    """Project the matched-filter accumulation onto the |0>-|1> axis
    (clean responses a_s = g_s * E) and threshold."""
    a0_i = g0[None, :, None, 0] * energy
    a0_q = g0[None, :, None, 1] * energy
    a1_i = g1[None, :, None, 0] * energy
    a1_q = g1[None, :, None, 1] * energy
    proj = (acc_i - (a0_i + a1_i) / 2) * (a1_i - a0_i) \
        + (acc_q - (a0_q + a1_q) / 2) * (a1_q - a0_q)
    return (proj > 0).astype(jnp.int32)


def _classify3_acc(acc_i, acc_q, energy, g0, g1, g2):
    """Nearest-centroid 3-class discrimination in the IQ plane: the
    accumulation's distance to each clean response ``g_s * E``
    (maximum-likelihood under the isotropic matched-filter noise).
    Returns classes in {0, 1, 2}."""
    def dist2(g):
        return (acc_i - g[None, :, None, 0] * energy) ** 2 \
            + (acc_q - g[None, :, None, 1] * energy) ** 2
    d0, d1, d2 = dist2(g0), dist2(g1), dist2(g2)
    cls = jnp.where(d1 < d0, 1, 0)
    cls = jnp.where(d2 < jnp.minimum(d0, d1), 2, cls)
    return cls.astype(jnp.int32)


def _acc_to_bit(acc_i, acc_q, energy, g0, g1, iq3):
    """Shared tail of every resolve mode: discriminate the accumulation
    into ``(bit, cls)`` — 2-class threshold by default, 3-class
    nearest-centroid with the class-2 -> ``leak_readout_bit`` fabric
    mapping when ``classify3`` is on.  ``cls`` is None when 2-class."""
    g2, classify3, leak_bit = iq3 if iq3 is not None else (None, False, 1)
    if not classify3:
        return _discriminate_acc(acc_i, acc_q, energy, g0, g1), None
    cls = _classify3_acc(acc_i, acc_q, energy, g0, g1, g2)
    return jnp.where(cls == 2, leak_bit, cls), cls


def _gs3(gs, state_sel, g2):
    """Overlay the |2> response where the recorded device state is 2
    (leaked core under IQ-level leakage readout).  ``gs`` is
    ``[B, C, ..., 2]`` and ``state_sel`` matches it minus the I/Q
    axis; ``g2`` is ``[C, 2]``."""
    if g2 is None:
        return gs
    g2b = g2.reshape((1, -1) + (1,) * (gs.ndim - 3) + (2,))
    return jnp.where(state_sel[..., None] == 2, g2b, gs)


def _resolve_analytic(st: dict, bits, valid, key, tables, env_pads,
                      response, W: int, cw: int = 0, iq3=None, cls=None):
    """Exact distributional shortcut of :func:`_resolve` for the
    white-noise matched-filter model.

    The matched filter is linear, so demodulating (g_s*y + noise)
    against y gives exactly ``acc = g_s*E + sigma*sqrt(E)*xi`` with
    ``E = sum |y|^2`` and ``xi ~ N(0, I2)`` — same bit distribution as
    the per-sample path, no per-sample computation.  The carrier drops
    out of E (|e^{i theta}| = 1), so the window energy is
    ``amp^2 * interp * (pref[b] - pref[a])`` from a prefix sum of
    |env|^2 over the padded plane — the pad reproduces the
    hold-last-sample overrun semantics.  Noise stays deterministic per
    (shot, core, slot) given the run key.

    Use when the channel model is exactly state-scaled response plus
    white noise; per-sample mode is the general path for structured
    models.  With ``ring_tau > 0`` this shortcut is a *flat-response
    approximation*: it ignores the resonator ring-up transient (the
    ``inv_ring`` element of ``response``), so its assignment fidelity is
    optimistic at short windows — tests/test_ringdown.py measures the
    divergence, and :func:`run_physics_batch` warns on this combination.
    """
    g0, g1, sigma, _inv_ring_unmodeled = response
    B, C, M = bits.shape
    fired = jnp.arange(M)[None, None, :] < st['n_meas'][..., None]
    pending = fired & ~valid
    sc = _window_scalars(st, tables, cw)

    env_i_pad, env_q_pad = env_pads                   # [C, Lp]
    Lp = env_i_pad.shape[1]
    env2 = env_i_pad ** 2 + env_q_pad ** 2
    pref = jnp.concatenate(
        [jnp.zeros((C, 1), jnp.float32), jnp.cumsum(env2, axis=-1)], -1)
    last2 = env2[:, -1]                               # held overrun value
    interp_c = sc['interp_c']                         # [1, C, 1]
    count = jnp.minimum(sc['n_samp'], W)              # DAC samples
    n_full = count // interp_c                        # whole env samples
    n_part = count % interp_c                         # trailing partial
    a = jnp.clip(sc['addr'], 0, Lp)
    b = jnp.clip(sc['addr'] + n_full, 0, Lp)
    c_idx = sc['c_idx']
    in_table = pref[c_idx, b] - pref[c_idx, a]        # [B, C, M]
    # samples past the padded table hold the final value indefinitely
    # (the per-sample path's clamped Toeplitz base reads pure pad rows)
    held = (n_full - (b - a)).astype(jnp.float32) * last2[c_idx]
    part_val = env2[c_idx, jnp.clip(sc['addr'] + n_full, 0, Lp - 1)]
    energy = sc['amp'] ** 2 * (
        interp_c.astype(jnp.float32) * (in_table + held)
        + n_part.astype(jnp.float32) * part_val)

    gs = jnp.where(st['meas_state'][..., None] == 1,
                   g1[None, :, None, :], g0[None, :, None, :])
    gs = _gs3(gs, st['meas_state'], iq3[0] if iq3 is not None else None)
    root_e = jnp.sqrt(energy)
    k_i, k_q = jax.random.split(key)
    shape = (B, C, M)
    acc_i = gs[..., 0] * energy + sigma * root_e * \
        jax.random.normal(k_i, shape, jnp.float32)
    acc_q = gs[..., 1] * energy + sigma * root_e * \
        jax.random.normal(k_q, shape, jnp.float32)
    new_bit, new_cls = _acc_to_bit(acc_i, acc_q, energy, g0, g1, iq3)
    if new_cls is not None:
        cls = jnp.where(pending, new_cls, cls)
    bits = jnp.where(pending, new_bit, bits)
    return bits, valid | fired, cls


def _static_meas_env_addrs(mp, max_rows: int = 8):
    """The set of envelope-table addresses the resolver can ever see,
    derived statically from the program — or ``None`` when not
    derivable.

    Sound over-approximation: the pulse env latch only ever holds its
    initial 0 or an immediate the program writes (``p_env`` values at
    instructions whose write-enable includes the env field,
    PULSE_PARAM_ORDER bit 0) — unless some env write sources the word
    from a register, in which case the value set is data-dependent and
    this returns ``None`` (the resolver falls back to the full
    Toeplitz row range).  Most programs use a handful of envelopes, so
    the fused kernel's envelope fetch collapses from a [lanes, R=384]
    one-hot matmul to a ``len(addrs)``-way row select — for the bench
    program (every envelope at table offset 0) a single broadcast row.
    """
    soa = mp.soa
    wen_env = (np.asarray(soa.p_wen) & 1) == 1
    if np.any(((np.asarray(soa.p_regsel) & 1) == 1) & wen_env):
        return None
    words = np.asarray(soa.p_env)[wen_env]
    addrs = sorted({0} | {int((w & 0xfff) * 4) for w in words.ravel()})
    return tuple(addrs) if len(addrs) <= max_rows else None


_MODE_CODES = {'persample': 0, 'fused': 1, 'analytic': 2}


def _tables_meta(model: 'ReadoutPhysics', W: int, interps: tuple,
                 mp=None) -> tuple:
    """The build parameters a prebuilt tables dict must match: window,
    aligned chunk, resolve mode, measurement element, and a digest of
    the program's measurement-element envelope/frequency CONTENT —
    a W/chunk mismatch makes dynamic_slice clamping silently read wrong
    table chunks, and same-shape tables from a different program would
    otherwise demodulate with the wrong envelopes (advisor round-3 +
    round-4 review)."""
    import zlib
    digest = 0
    if mp is not None:
        h = 0
        for c in range(mp.n_cores):
            t = mp.tables[c]
            if model.meas_elem < len(t.envs):
                h = zlib.crc32(np.ascontiguousarray(
                    np.asarray(t.envs[model.meas_elem])).tobytes(), h)
            if model.meas_elem < len(t.freqs):
                h = zlib.crc32(np.ascontiguousarray(np.asarray(
                    t.freqs[model.meas_elem]['freq'], np.float64))
                    .tobytes(), h)
        digest = int(h) & 0x7fffffff
    return (W, _aligned_chunk(model.resolve_chunk, W, interps),
            _MODE_CODES[model.resolve_mode], int(model.meas_elem), digest)


def _build_mode_tables(env_stack, freq_stack, mode: str, W: int,
                       chunk: int, interps: tuple,
                       rows: tuple = None, meta: tuple = None) -> dict:
    """Per-mode resolve tables: padded env planes plus the mode's
    precomputed lookup structures (Toeplitz windows + carrier basis for
    'persample'; the DAC-resolution kernel tables for 'fused').

    Split out of the main program so callers can build them in a
    SEPARATE small jit and pass them to :func:`run_physics_batch` as
    ``tables=``: the gather-heavy table construction inside the big
    epoch-loop module measured ~30 s of extra XLA compile time at bench
    shapes, and rebuilding [C, 2, R, W] tables every batch is wasted
    runtime — built once, they are plain device arrays reused across
    batches (:func:`prepare_physics_tables`).
    """
    env_pads = _pad_env_planes(env_stack, _aligned_chunk(chunk, W, interps))
    tabs = {'env_pads': env_pads}
    if meta is not None:
        # build parameters carried WITH the tables (as a device array so
        # the dict stays a uniform pytree): run_physics_batch
        # cross-checks them when prebuilt tables are passed in
        tabs['meta'] = jnp.asarray(list(meta), jnp.int32)
    if mode == 'persample':
        chunk_a = _aligned_chunk(chunk, W, interps)
        tabs['toeplitz'] = tuple(_toeplitz_tables(env_pads, chunk_a,
                                                  interps))
        tabs['basis'] = _carrier_basis(freq_stack,
                                       -(-W // chunk_a) * chunk_a)
    elif mode == 'fused':
        from ..ops.resolve_pallas import build_fused_tables, fused_chunk
        ck = fused_chunk(chunk, W)
        t_dac, bas, _ = build_fused_tables(
            env_pads, _carrier_basis(freq_stack, W), W, interps, ck,
            rows=rows)
        tabs['t_dac'], tabs['bas'] = t_dac, bas
        # the row ADDRESSES the table was built for, carried with it:
        # the kernel's equality select is only correct against these
        # exact values, so run_physics_batch cross-checks them when
        # prebuilt tables are passed in
        tabs['rows'] = jnp.asarray([-1] if rows is None else list(rows),
                                   jnp.int32)
    return tabs


_build_tables_jit = functools.partial(
    jax.jit, static_argnames=('mode', 'W', 'chunk', 'interps', 'rows',
                              'meta'))(_build_mode_tables)


@functools.partial(jax.jit, static_argnames=('cfg', 'n_cores', 'W',
                                             'max_epochs', 'chunk',
                                             'spcs', 'interps', 'mode',
                                             'ring', 'traits',
                                             'native_rng', 'rows',
                                             'dev_static', 'cw',
                                             'colored', 'classify3',
                                             'sl', 'blk', 'fus',
                                             'fpack'))
def _run_physics_jit(soa, spc, interp, sync_part, init_states, init_regs,
                     tabs, freq_stack, g0, g1, sigma, inv_ring,
                     key, dev_params, meas_u,
                     cfg: InterpreterConfig, n_cores: int, W: int,
                     max_epochs: int, chunk: int = None,
                     spcs: tuple = (), interps: tuple = (),
                     mode: str = 'persample', ring: bool = False,
                     traits: tuple = None,
                     native_rng: bool = None, rows: tuple = None,
                     traj_key=None, dev_static: tuple = None,
                     cw: int = 0, colored: bool = False,
                     rho=None, g2=None, classify3: bool = False,
                     sl: tuple = None, blk: tuple = None,
                     fus: tuple = None, fpack: tuple = None) -> dict:
    B = init_states.shape[0]
    C, M = n_cores, cfg.max_meas
    st0 = _init_state(B, C, cfg, init_regs)
    if cfg.device == 'parity':
        st0['qturns'] = 2 * init_states
        dev = None
    elif cfg.device == 'statevec':
        # basis one-hot from the initial bits (core 0 = MSB,
        # interpreter._sv_zsign convention)
        weights = jnp.asarray([1 << (C - 1 - c) for c in range(C)],
                              jnp.int32)
        idx = jnp.sum(init_states * weights[None, :], axis=-1)
        st0['psi'] = (idx[:, None]
                      == jnp.arange(1 << C)[None, :]).astype(jnp.complex64)
        # trailing static: IQ-level leakage readout (g2 set) — leaked
        # cores record state 2 for the resolver instead of forcing the
        # discrimination bit (interpreter measurement block)
        dev = {'params': dev_params + (meas_u, traj_key),
               'static': dev_static + (g2 is not None,)}
    else:
        zf = jnp.zeros((B, C), jnp.float32)
        st0['bloch'] = jnp.stack(
            [zf, zf, 1.0 - 2.0 * init_states.astype(jnp.float32)], axis=-1)
        dev = dev_params + (meas_u,)
    st0['_steps'] = jnp.int32(0)
    st0['paused'] = jnp.zeros((B,), bool)
    bits0 = jnp.zeros((B, C, M), jnp.int32)
    valid0 = jnp.zeros((B, C, M), bool)
    # 3-class discrimination record (a scalar placeholder keeps the
    # carry pytree fixed when the classifier is off)
    cls0 = jnp.zeros((B, C, M) if classify3 else (1, 1, 1), jnp.int32)
    leak_bit = int(dev_static[6]) if dev_static is not None else 1
    iq3 = (g2, classify3, leak_bit) if g2 is not None else None
    # tables arrive prebuilt (tabs) — _window_scalars only needs the
    # frequency table and element geometry from this tuple
    tables = (None, freq_stack,
              jnp.asarray(spcs, jnp.int32), jnp.asarray(interps, jnp.int32))
    env_pads = tabs['env_pads']
    response = (g0, g1, sigma, inv_ring)
    if mode == 'fused':
        from ..ops.resolve_pallas import fused_chunk
        ck = fused_chunk(chunk, W)
        fused_tables = (tabs['t_dac'], tabs['bas'], tabs['t_dac'].shape[3])
        lp = env_pads[0].shape[1]
    elif mode == 'persample':
        prebuilt = (tabs['toeplitz'], tabs['basis'])
    colored_tabs = _ar1_tables(
        rho, _aligned_chunk(chunk, W, interps)) if colored else None
    fused_args = None
    if fus is not None:
        # measure-in-megastep: per-address DAC-resolution energy rows,
        # built ONCE outside the (single-iteration) epoch loop — the
        # kernel's whole demodulation is a masked sum against them
        from ..ops.resolve_pallas import build_energy_tables
        fused_args = {
            'e2': build_energy_tables(env_pads, rows, W, interps),
            'g0': g0, 'g1': g1, 'addrs': rows, 'w': W,
            'amp_scale': float(AMP_SCALE)}

    def cond(carry):
        st, bits, valid, _cls, ep = carry
        # run while execution can still progress (not done, step budget
        # left — a shot that ran out of steps can never finish, so don't
        # burn further full-batch passes on it) OR fired windows remain
        # unresolved (the slot-compacted resolver handles one slot per
        # lane per epoch; trailing unread measurements still must end up
        # in meas_bits), within the epoch bound either way.  The
        # straight-line executor terminates structurally (forward-only,
        # one visit per instruction) so only the epoch bound applies.
        budget_ok = True if sl is not None or fus is not None \
            else (st['_steps'] < cfg.max_steps)
        can_exec = (~jnp.all(st['done'])) & budget_ok
        fired = jnp.arange(cfg.max_meas)[None, None, :] \
            < st['n_meas'][..., None]
        unresolved = jnp.any(fired & ~valid)
        return (can_exec | unresolved) & (ep < max_epochs)

    def body(carry):
        st, bits, valid, cls, ep = carry
        if fus is not None:
            # measure-in-megastep: exec + resolve in ONE kernel pass —
            # the bit lands in its slot at the trigger, every fproc
            # read is served in-kernel, and the loop exits after this
            # iteration (epochs == 1, docs/PERF.md "fused epoch")
            from .interpreter import (_exec_span_pallas_fused,
                                      _soa_from_static,
                                      _default_pallas_interpret)
            itp = cfg.pallas_interpret
            if itp is None:
                itp = _default_pallas_interpret()
            st, bits, valid = _exec_span_pallas_fused(
                st, _soa_from_static(fus), spc, interp, bits, valid,
                cfg, itp, fused_args, pack=fpack)
            st['paused'] = jnp.any(st['phys_wait'] & ~st['done'], -1)
        elif sl is not None:
            from .interpreter import _exec_straightline, _soa_from_static
            st = _exec_straightline(st, _soa_from_static(sl), spc, interp,
                                    bits, valid, cfg, dev)
            st['paused'] = jnp.any(st['phys_wait'] & ~st['done'], -1)
        elif blk is not None:
            # the block engine runs its own while_loop and manages the
            # paused flag exactly like _exec_loop (pause at unresolved
            # fproc reads only ever happens in the boundary step)
            from .interpreter import _exec_blocks
            st = _exec_blocks(st, blk, spc, interp, sync_part, bits,
                              valid, cfg, dev)
        else:
            st = _exec_loop(st, soa, spc, interp, sync_part, bits, valid,
                            cfg, dev, traits)
        if fus is not None:
            pass    # bits landed in-kernel; nothing left to resolve
        elif mode == 'analytic':
            bits, valid, cls = _resolve_analytic(
                st, bits, valid, key, tables, env_pads, response, W, cw,
                iq3, cls)
        elif mode == 'fused':
            bits, valid, cls = _resolve_fused(
                st, bits, valid, jax.random.fold_in(key, ep), tables,
                fused_tables, response, W, lp, ck, ring, native_rng, rows,
                cw, iq3, cls)
        else:
            bits, valid, cls = _resolve(st, bits, valid, jax.random.fold_in(
                key, ep), tables, env_pads, response, W, chunk, interps,
                prebuilt, ring, cw, colored_tabs, iq3, cls)
        st = dict(st, paused=jnp.zeros_like(st['paused']))
        return st, bits, valid, cls, ep + 1

    st, bits, valid, cls, ep = jax.lax.while_loop(
        cond, body, (st0, bits0, valid0, cls0, jnp.int32(0)))
    st.pop('paused')
    out = _finalize(st, cfg)
    out['meas_bits'] = bits
    out['meas_bits_valid'] = valid
    out['epochs'] = ep
    if classify3:
        out['meas_class'] = cls
    return out


def _validate_tables(mp, model: ReadoutPhysics, tables: dict, W: int,
                     interps: tuple, rows: tuple,
                     skip_traced: bool = False) -> None:
    """Check prebuilt resolve tables were built for THIS program/model:
    a window/chunk/mode/meas_elem mismatch makes the chunk scan's
    dynamic_slice clamp silently read wrong table chunks, and a stale
    fused row set makes the kernel's equality select read the wrong
    envelope.  The build parameters ride with the dict ('meta'/'rows');
    with ``skip_traced`` they are left unchecked when they are tracers
    (an outer jit) — eager callers who cache tables and then jit their
    step should call :func:`validate_physics_tables` once, eagerly,
    where the values are concrete."""
    def traced(x):
        return isinstance(x, jax.core.Tracer)
    if 'meta' in tables:
        if traced(tables['meta']):
            if not skip_traced:
                raise ValueError(
                    'validate_physics_tables must run eagerly (the '
                    'tables are tracers here) — call it before your jit')
        else:
            want = list(_tables_meta(model, W, interps, mp))
            have = np.asarray(tables['meta']).tolist()
            if have != want:
                names = ('window_samples W', 'aligned resolve_chunk',
                         'resolve_mode code', 'meas_elem',
                         'envelope/frequency content digest')
                bad = {n: (h, w) for n, h, w in zip(names, have, want)
                       if h != w}
                raise ValueError(
                    f'prebuilt tables were built for different resolve '
                    f'parameters — (built, needed): {bad} — rebuild '
                    f'with prepare_physics_tables(mp, model)')
    if model.resolve_mode == 'fused' and not traced(tables.get('rows')):
        want = [-1] if rows is None else list(rows)
        have = np.asarray(tables['rows']).tolist() \
            if 'rows' in tables else None
        if have != want:
            raise ValueError(
                f'prebuilt tables were built for envelope addresses '
                f'{have}, but this program/model needs {want} — '
                f'rebuild with prepare_physics_tables(mp, model)')


def validate_physics_tables(mp, model: ReadoutPhysics,
                            tables: dict) -> None:
    """Eagerly validate prebuilt tables against ``(mp, model)``.

    :func:`run_physics_batch` performs this check automatically when it
    runs eagerly, but inside an outer ``jax.jit`` the carried build
    parameters are tracers and cannot be compared — so a caller that
    caches ``prepare_physics_tables`` output and passes it into a
    jitted step should call this once, eagerly, at table-cache time
    (the sweep driver does; parallel/driver.py)."""
    env_stack, freq_stack, spc_m, interp_m, w_auto = \
        _physics_tables(mp, model.meas_elem)
    W = int(model.window_samples or w_auto)
    interps = tuple(int(x) for x in np.asarray(interp_m))
    rows = _static_meas_env_addrs(mp) if model.resolve_mode == 'fused' \
        else None
    _validate_tables(mp, model, tables, W, interps, rows, skip_traced=False)


def _has_cross_core_freqs(mp, drive_elem: int = 0) -> bool:
    """Does any core's drive-element frequency table contain a value
    that appears in another core's?  The cross-resonance signature —
    used to warn when a statevec run has no coupling map.

    Covers 'zx' (CR) couplings only: a CZ-style ef drive lives solely
    in the control core's own table and is indistinguishable from a 1q
    frequency without the gate library, so CZ-only programs with
    ``couplings=()`` are NOT caught here — use
    :func:`~..models.coupling.couplings_from_qchip` (or
    ``Simulator.run``, which auto-derives) whenever the program
    contains calibrated two-qubit gates."""
    per_core = []
    for t in mp.tables:
        if drive_elem < len(t.freqs):
            per_core.append(np.asarray(t.freqs[drive_elem]['freq'],
                                       np.float64))
        else:
            per_core.append(np.zeros(0))
    for c, fc in enumerate(per_core):
        for o, fo in enumerate(per_core):
            if o == c or not len(fc) or not len(fo):
                continue
            if np.any(np.isclose(fc[:, None], fo[None, :], rtol=1e-12,
                                 atol=1.0)):
                return True
    return False


def physics_config(base: InterpreterConfig, model: ReadoutPhysics,
                   **kw) -> InterpreterConfig:
    """The effective interpreter config of a physics run.

    The :class:`ReadoutPhysics` model is authoritative for the
    device-model fields (``x90_amp``/``drive_elem``/``meas_elem``);
    conflicting values on the base config or in ``kw`` raise rather
    than being silently overridden.
    """
    base = base if base is not None else InterpreterConfig()
    defaults = InterpreterConfig()
    overrides = {}
    for name in ('x90_amp', 'drive_elem', 'meas_elem', 'cw_horizon'):
        if name in kw:
            raise ValueError(
                f'{name} is set on the ReadoutPhysics model for physics '
                f'runs, not in the interpreter config')
        mv, bv = int(getattr(model, name)), int(getattr(base, name))
        if bv != int(getattr(defaults, name)) and bv != mv:
            raise ValueError(
                f'conflicting {name}: interpreter config has {bv}, '
                f'ReadoutPhysics has {mv}; set it on the model')
        overrides[name] = mv
    if 'device' in kw:
        raise ValueError('the device model is set via '
                         'ReadoutPhysics.device, not the interpreter config')
    if base.device != defaults.device and base.device != model.device.kind:
        raise ValueError(
            f'conflicting device: interpreter config has {base.device!r}, '
            f'ReadoutPhysics.device has {model.device.kind!r}')
    return replace(base, physics=True, device=model.device.kind,
                   **overrides, **kw)


def prepare_physics_tables(mp, model: ReadoutPhysics) -> dict:
    """Build the resolve tables for ``(mp, model)`` once, eagerly, in
    their own small jit — pass the result to :func:`run_physics_batch`
    as ``tables=`` when the batch call itself is wrapped in an outer
    ``jax.jit`` (a bench/sweep step): the big program then takes the
    tables as plain device-array arguments instead of re-deriving them,
    which both removes the gather-heavy construction from its XLA
    module (~30 s less compile at bench shapes) and stops rebuilding
    them every batch.  Tables depend only on the program's envelope /
    frequency content and the model's meas_elem / window / mode — not
    on the interpreter config."""
    env_stack, freq_stack, spc_m, interp_m, w_auto = \
        _physics_tables(mp, model.meas_elem)
    W = int(model.window_samples or w_auto)
    interps = tuple(int(x) for x in np.asarray(interp_m))
    return _build_tables_jit(
        env_stack, freq_stack, model.resolve_mode, W, model.resolve_chunk,
        interps,
        _static_meas_env_addrs(mp) if model.resolve_mode == 'fused'
        else None,
        _tables_meta(model, W, interps, mp))


def run_physics_batch(mp, model: ReadoutPhysics, key, shots: int,
                      init_states=None, init_regs=None,
                      cfg: InterpreterConfig = None, tables: dict = None,
                      **kw) -> dict:
    """Execute ``shots`` shots with the measurement loop closed by DSP.

    No measurement bits are injected: readout windows are synthesized,
    demodulated, and discriminated in-sim, and branches resolve on the
    emergent bits.  ``init_states``: optional ``[shots, n_cores]`` 0/1
    initial qubit states (default: thermal sampling at ``model.p1_init``).
    ``init_regs``: optional initial register file (``[n_cores, 16]`` or
    with a leading shot axis) — the register-parameterized sweep hook.
    ``tables``: optional prebuilt resolve tables
    (:func:`prepare_physics_tables`) — pass them when wrapping this
    call in an outer jit so the table construction stays out of the
    stepped program; left ``None``, they are built here (as a separate
    small jit when called eagerly, inline under an outer trace).

    Returns the interpreter's final state plus ``meas_bits`` /
    ``meas_bits_valid`` (the resolved bits per measurement slot),
    ``meas_state`` (the device bit each readout sampled), and ``epochs``
    (resolve rounds taken).  The device trajectory depends on
    ``model.device.kind``: parity mode returns ``qturns`` (the final
    quarter-turn counter); bloch mode returns ``bloch`` (final ``[B, C,
    3]`` Bloch vectors), ``meas_p1`` (pre-projection P(1) per slot — the
    noise-free expectation value), and ``phys_t`` (last evolution time).
    """
    # did the caller size the step budget themselves?  Any caller-built
    # cfg counts as sized (its max_steps was chosen or accepted — no
    # value-coincidence heuristics); only the bare-default path (no cfg,
    # no max_steps kwarg) gets the n_cores scaling below, the same
    # scaling Simulator.run applies to its statically-derived budget
    # (statevec's discrete-event gate can serialize cross-core pulse
    # triggers — worst case one core per step)
    explicit_steps = 'max_steps' in kw or cfg is not None
    cfg = physics_config(cfg, model, **kw)
    cfg, strict_faults = _fault_policy(cfg)
    _check_fabric(cfg, mp.n_cores)
    soa, spc, interp, sync_part = _program_constants(mp, cfg)
    env_stack, freq_stack, spc_m, interp_m, w_auto = \
        _physics_tables(mp, model.meas_elem)
    W = int(model.window_samples or w_auto)
    if isinstance(key, int):
        key = jax.random.PRNGKey(key)
    key_init, key_noise = jax.random.split(key)
    C = mp.n_cores
    if init_states is None:
        p1 = jnp.broadcast_to(jnp.asarray(model.p1_init, jnp.float32), (C,))
        init_states = jax.random.bernoulli(
            key_init, p1[None, :], (shots, C)).astype(jnp.int32)
    init_states = jnp.asarray(init_states, jnp.int32)
    if init_regs is not None:
        init_regs = jnp.asarray(init_regs, jnp.int32)
    traj_key, dev_static = None, None
    if model.device.kind in ('bloch', 'statevec'):
        # projective-measurement uniforms, one per (shot, core, slot) —
        # drawn from a stream independent of the resolve noise (fold_in
        # of the parent key) so existing parity-mode draws are unchanged
        det, it1, it2 = model.device.per_clock_rates(C)
        dev_params = (jnp.asarray(det), jnp.asarray(it1), jnp.asarray(it2),
                      jnp.float32(model.device.depol_per_pulse))
        meas_u = jax.random.uniform(
            jax.random.fold_in(key, 0x424c4f43),
            (shots, C, cfg.max_meas), jnp.float32)
        if model.device.kind == 'statevec':
            if C > STATEVEC_MAX_CORES:
                raise ValueError(
                    f"device='statevec' holds a [shots, 2^n_cores] state "
                    f"vector; n_cores={C} exceeds the cap of "
                    f"{STATEVEC_MAX_CORES}")
            if not model.device.couplings and _has_cross_core_freqs(mp):
                # a drive-element frequency shared across cores is the
                # cross-resonance signature: with no coupling map those
                # pulses silently execute as 1q rotations — divergent
                # physics between this entry point and Simulator.run
                # (which auto-derives the map from the gate library)
                import warnings
                warnings.warn(
                    "device='statevec' with couplings=() but the program "
                    'drives cross-core frequencies (the cross-resonance '
                    'signature): entangling pulses will execute as 1q '
                    'rotations.  Derive the map with '
                    'models.coupling.couplings_from_qchip(mp, qchip) or '
                    'run via Simulator.run (auto-derives).  (CZ-style '
                    'ef drives cannot be detected without the gate '
                    'library — derive the map explicitly for those.)',
                    stacklevel=2)
            dev_params = dev_params + (
                jnp.float32(model.device.depol2_per_pulse),
                jnp.float32(model.device.zx90_amp),
                jnp.float32(model.device.zz90_amp),
                jnp.float32(model.device.leak_per_pulse),
                jnp.float32(model.device.leak2_per_pulse),
                jnp.float32(model.device.seep_per_pulse))
            if model.device.couplings and not explicit_steps:
                # the event-ordering gate's serialization can exhaust a
                # generic budget and flag shots incomplete (advisor
                # round 4) — scale the default the way Simulator.run
                # scales its statically-derived one
                cfg = replace(cfg, max_steps=cfg.max_steps * C)
            traj_key = jax.random.fold_in(key, 0x53563251)
            dev_static = model.device.statevec_static()
    else:
        dev_params, meas_u = None, None

    def as_iq(g):
        g = np.broadcast_to(np.asarray(g, complex), (C,))
        return jnp.asarray(
            np.stack([g.real, g.imag], axis=-1).astype(np.float32))

    # epoch bound: each epoch resolves at least one measurement, and a
    # cross-core dependency chain can serialize them — C*M+1 covers the
    # worst case (the loop exits early once every shot is done)
    if model.resolve_mode not in ('persample', 'fused', 'analytic'):
        raise ValueError(f'unknown resolve_mode {model.resolve_mode!r}')
    if model.cw_horizon < 0 or model.cw_horizon > W:
        raise ValueError(
            f'cw_horizon={model.cw_horizon} must lie in [0, W={W}] — '
            f'the resolve tables cover W samples; raise '
            f'window_samples to integrate longer CW windows')
    if not 0.0 <= model.noise_ar1 < 1.0:
        raise ValueError(f'noise_ar1={model.noise_ar1} must be in [0, 1)')
    if model.g2 is not None and (
            model.device.kind != 'statevec'
            or not (np.any(np.asarray(model.device.leak_per_pulse,
                                      np.float64))
                    or np.any(np.asarray(model.device.leak2_per_pulse,
                                         np.float64)))):
        raise ValueError(
            'g2 (the |2> IQ response) needs device=statevec with '
            'leak_per_pulse > 0 or leak2_per_pulse > 0 — no leakage '
            'channel, no |2> population')
    if model.classify3 and model.g2 is None:
        raise ValueError(
            'classify3 (3-class discrimination) needs g2 (the |2> '
            'response) set')
    if model.noise_ar1 > 0 and model.resolve_mode != 'persample':
        raise ValueError(
            f"resolve_mode={model.resolve_mode!r} generates white ADC "
            f"noise (analytic: closed form; fused: in-kernel "
            f"generator); colored noise (noise_ar1 > 0) needs "
            f"resolve_mode='persample'")
    if model.ring_tau > 0 and model.resolve_mode == 'analytic':
        import warnings
        warnings.warn(
            "resolve_mode='analytic' ignores the resonator ring-up "
            '(ring_tau > 0): bits follow the flat-response model, which '
            'is optimistic at short windows — use persample/fused for '
            'the structured channel', stacklevel=2)
    inv_ring = jnp.float32(0.0 if model.ring_tau <= 0
                           else 1.0 / model.ring_tau)
    interps = tuple(int(x) for x in np.asarray(interp_m))
    eng_sl, eng_blk, eng_fus = _engine_static(mp, cfg)
    rows = _static_meas_env_addrs(mp) \
        if (model.resolve_mode == 'fused' or eng_fus is not None) \
        else None
    fpack = None
    if eng_fus is not None:
        # program/config eligibility was settled by resolve_engine
        # (span shape, parity device, no CW, static meas bound); what
        # remains is the readout MODEL the kernel specializes: the
        # sigma=0 matched filter over statically-enumerable envelopes
        blockers = []
        if float(model.sigma) != 0.0:
            blockers.append(
                f'sigma={model.sigma} (the in-kernel demodulator is '
                f'the sigma=0 matched filter; noise draws stay with '
                f'the epoch resolver)')
        if model.ring_tau > 0:
            blockers.append('ring_tau > 0 (the resonator ring-up '
                            'transient needs the per-sample resolver)')
        if model.noise_ar1 > 0:
            blockers.append('noise_ar1 > 0 (colored ADC noise needs '
                            "resolve_mode='persample')")
        if rows is None:
            blockers.append('envelope addresses not statically '
                            'enumerable (a register-sourced envelope '
                            'write, or more than 8 distinct addresses)')
        if blockers:
            raise ValueError(
                "engine='fused' (measure-in-megastep) is ineligible "
                'for this readout model: ' + '; '.join(blockers)
                + " — use resolve_mode='fused' (the in-kernel epoch "
                'resolver) for the general model')
        if use_packed_carry(cfg):
            fpack = carry_packspec(mp, cfg,
                                   trim_regs=init_regs is None,
                                   fused=True)
    if tables is not None:
        _validate_tables(mp, model, tables, W, interps, rows,
                         skip_traced=True)
    if tables is None:
        # eager call: separate small compile; under an outer trace this
        # inlines (the status quo for jit-wrapped callers)
        tables = _build_tables_jit(env_stack, freq_stack,
                                   model.resolve_mode, W,
                                   model.resolve_chunk, interps, rows,
                                   _tables_meta(model, W, interps, mp))
    return _check_strict(_run_physics_jit(
        soa, spc, interp, sync_part, init_states, init_regs, tables,
        freq_stack, as_iq(model.g0), as_iq(model.g1),
        jnp.float32(model.sigma), inv_ring, key_noise, dev_params, meas_u,
        cfg, C, W,
        C * cfg.max_meas + 1, model.resolve_chunk,
        tuple(int(x) for x in np.asarray(spc_m)), interps,
        model.resolve_mode, model.ring_tau > 0, program_traits(mp),
        model.fused_native_rng, rows, traj_key, dev_static,
        int(model.cw_horizon), model.noise_ar1 > 0,
        jnp.float32(model.noise_ar1),
        g2=as_iq(model.g2) if model.g2 is not None else None,
        classify3=bool(model.classify3),
        sl=eng_sl, blk=eng_blk, fus=eng_fus, fpack=fpack),
        strict_faults)
