"""Qubit device co-state models for physics-closed execution.

The reference models no device physics at all — real qubits supply the
measurement bits its gateware branches on (reference:
cocotb/proc/test_proc.py:441-446 injects them; in deployment the readout
chain produces them).  This module supplies the numeric stand-in the TPU
build's closed loop evolves *in-sim*, per (shot, core) lane, inside the
interpreter's ``lax.while_loop``:

``'parity'``
    The round-1/2 classical stand-in: each drive-element pulse adds
    ``round(amp / x90_amp)`` quarter turns to an int32 counter; the
    state bit is the half-turn parity.  Deterministic, cheap, exactly
    reproducible by hand — the mode the randomized engine-vs-oracle
    fuzz and the headline bench use.

``'bloch'``
    An SU(2) co-state: a Bloch vector ``r = (x, y, z)`` (float32,
    ``|0> = +z``, ``P(1) = (1 - z)/2``) per (shot, core).  Physics:

    * **Drive pulses rotate.**  A pulse on ``drive_elem`` applies the
      right-handed rotation by ``theta = (pi/2) * amp / x90_amp`` about
      the equatorial axis ``(cos phi, sin phi, 0)`` where ``phi`` is the
      pulse's 17-bit *phase word* — so virtual-z (the compiler folds
      z-rotations into downstream pulse phase words,
      ir/passes.py ResolveVirtualZ) and amplitude sweeps (register- or
      modi-parameterized amp words) are physically meaningful.  The
      convention matches ``U = exp(-i theta/2 (cos phi X + sin phi Y))``,
      the X90 of models/rb.py at ``phi = 0``; measurement statistics
      from |0> are invariant under the global phase-sign choice, which
      is what pins it against the Clifford table
      (tests/test_device_bloch.py).
    * **Time evolves between pulses.**  At each drive/readout pulse the
      lane first applies free evolution over the elapsed global-clock
      interval since its previous one: detuning precession about z by
      ``2*pi * detuning_hz * clk_period_s`` per clock, transverse decay
      ``exp(-dt/T2)`` on (x, y), longitudinal relaxation
      ``z -> 1 + (z - 1) * exp(-dt/T1)`` toward |0>.  Scheduled delays
      therefore dephase/decay the qubit with no extra bookkeeping — the
      gap simply shows up in the next pulse's trigger time.
    * **Depolarization per drive pulse.**  ``r -> (1 - depol) * r``
      after each rotation — the ensemble-averaged depolarizing channel,
      the injectable error rate randomized benchmarking recovers.
    * **Measurement projects.**  A readout pulse samples
      ``bit ~ Bernoulli((1 - z)/2)`` (one pre-drawn uniform per
      (shot, core, slot), deterministic per run key) and collapses
      ``r -> (0, 0, 1 - 2*bit)``.  The sampled bit is what the readout
      channel (sim/physics.py) then discriminates through noise — so
      projection statistics and assignment errors layer the way they do
      on hardware.  The pre-projection ``P(1)`` is recorded per slot
      (``meas_p1``) for noise-free expectation readout in tests and
      fitting.

    All parameters may be scalars or per-core sequences; they enter the
    jitted step as traced arrays, so sweeping T1/T2/detuning never
    recompiles.

The model evolves *inside* the execution loop (sim/interpreter.py
``_step`` physics block) because feedback makes it stateful: an active
reset's conditional X180 must see the post-measurement collapsed state,
and mid-circuit measurement outcomes condition later rotations.  A
post-hoc pass over recorded pulses could not close that loop.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

DEVICE_KINDS = ('parity', 'bloch')


@dataclass(frozen=True)
class DeviceModel:
    """Device-physics parameters for :class:`~.physics.ReadoutPhysics`.

    ``detuning_hz``: qubit-minus-drive-frame frequency offset (Hz) —
    the Ramsey fringe frequency.  ``t1_s`` / ``t2_s``: relaxation and
    total transverse-coherence times (seconds; ``inf`` disables).
    ``depol_per_pulse``: depolarizing contraction applied per drive
    pulse.  ``clk_period_s``: FPGA clock period used to convert to
    per-clock rates (reference: python/distproc/hwconfig.py:102, 2 ns).
    Scalars broadcast over cores; sequences are per-core.
    """
    kind: str = 'bloch'
    detuning_hz: float | tuple = 0.0
    t1_s: float | tuple = math.inf
    t2_s: float | tuple = math.inf
    depol_per_pulse: float = 0.0
    clk_period_s: float = 2e-9

    def __post_init__(self):
        if self.kind not in DEVICE_KINDS:
            raise ValueError(f'unknown device kind {self.kind!r}; '
                             f'one of {DEVICE_KINDS}')

    def per_clock_rates(self, n_cores: int):
        """Per-core per-clock rate arrays ``(det_cyc, inv_t1, inv_t2)``:
        detuning in cycles/clock, decay in 1/clocks (0 = disabled)."""
        def bc(v):
            return np.broadcast_to(np.asarray(v, np.float64),
                                   (n_cores,)).astype(np.float64)
        det = bc(self.detuning_hz) * self.clk_period_s
        with np.errstate(divide='ignore'):
            inv_t1 = np.where(np.isinf(bc(self.t1_s)), 0.0,
                              self.clk_period_s / bc(self.t1_s))
            inv_t2 = np.where(np.isinf(bc(self.t2_s)), 0.0,
                              self.clk_period_s / bc(self.t2_s))
        return (det.astype(np.float32), inv_t1.astype(np.float32),
                inv_t2.astype(np.float32))
