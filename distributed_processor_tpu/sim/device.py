"""Qubit device co-state models for physics-closed execution.

The reference models no device physics at all — real qubits supply the
measurement bits its gateware branches on (reference:
cocotb/proc/test_proc.py:441-446 injects them; in deployment the readout
chain produces them).  This module supplies the numeric stand-in the TPU
build's closed loop evolves *in-sim*, per (shot, core) lane, inside the
interpreter's ``lax.while_loop``:

``'parity'``
    The round-1/2 classical stand-in: each drive-element pulse adds
    ``round(amp / x90_amp)`` quarter turns to an int32 counter; the
    state bit is the half-turn parity.  Deterministic, cheap, exactly
    reproducible by hand — the mode the randomized engine-vs-oracle
    fuzz and the headline bench use.

``'bloch'``
    An SU(2) co-state: a Bloch vector ``r = (x, y, z)`` (float32,
    ``|0> = +z``, ``P(1) = (1 - z)/2``) per (shot, core).  Physics:

    * **Drive pulses rotate.**  A pulse on ``drive_elem`` applies the
      right-handed rotation by ``theta = (pi/2) * amp / x90_amp`` about
      the equatorial axis ``(cos phi, sin phi, 0)`` where ``phi`` is the
      pulse's 17-bit *phase word* — so virtual-z (the compiler folds
      z-rotations into downstream pulse phase words,
      ir/passes.py ResolveVirtualZ) and amplitude sweeps (register- or
      modi-parameterized amp words) are physically meaningful.  The
      convention matches ``U = exp(-i theta/2 (cos phi X + sin phi Y))``,
      the X90 of models/rb.py at ``phi = 0``; measurement statistics
      from |0> are invariant under the global phase-sign choice, which
      is what pins it against the Clifford table
      (tests/test_device_bloch.py).
    * **Time evolves between pulses.**  At each drive/readout pulse the
      lane first applies free evolution over the elapsed global-clock
      interval since its previous one: detuning precession about z by
      ``2*pi * detuning_hz * clk_period_s`` per clock, transverse decay
      ``exp(-dt/T2)`` on (x, y), longitudinal relaxation
      ``z -> 1 + (z - 1) * exp(-dt/T1)`` toward |0>.  Scheduled delays
      therefore dephase/decay the qubit with no extra bookkeeping — the
      gap simply shows up in the next pulse's trigger time.
    * **Depolarization per drive pulse.**  ``r -> (1 - depol) * r``
      after each rotation — the ensemble-averaged depolarizing channel,
      the injectable error rate randomized benchmarking recovers.
    * **Measurement projects.**  A readout pulse samples
      ``bit ~ Bernoulli((1 - z)/2)`` (one pre-drawn uniform per
      (shot, core, slot), deterministic per run key) and collapses
      ``r -> (0, 0, 1 - 2*bit)``.  The sampled bit is what the readout
      channel (sim/physics.py) then discriminates through noise — so
      projection statistics and assignment errors layer the way they do
      on hardware.  The pre-projection ``P(1)`` is recorded per slot
      (``meas_p1``) for noise-free expectation readout in tests and
      fitting.

    All parameters may be scalars or per-core sequences; they enter the
    jitted step as traced arrays, so sweeping T1/T2/detuning never
    recompiles.

``'statevec'``
    The entangling model: one full ``2^n_cores``-dimensional state
    vector per shot (complex64 ``[B, 2^C]``), evolved as a quantum
    trajectory.  Everything 'bloch' does per-core holds (phase-word
    rotation axes, detuning precession, projective measurement), plus:

    * **Two-qubit interactions are real.**  A drive pulse on a core
      whose frequency word matches a configured coupling (see
      ``couplings``) applies an entangling rotation — ZX for
      cross-resonance pulses (control driven at the target's
      frequency), ZZ for ef-frequency drives — with angle
      ``(pi/2) * amp / zx90_amp`` (resp. ``zz90_amp``).  The default
      qchip's CNOT (echoed-CR + target X90 + virtual-z) and CZ
      calibrations compose *exactly* to CNOT / CZ under this model
      (pinned by tests/test_device_statevec.py), so GHZ preparation
      produces genuinely correlated bits and two-qubit RB sees real
      entangling errors.
    * **Noise is trajectory-unraveled.**  T1 is a quantum-jump
      amplitude-damping channel (jump probability per gap weighted by
      the qubit's excited population), pure dephasing a stochastic Z,
      1q depolarization a stochastic X/Y/Z after each drive pulse, and
      2q depolarization (``depol2_per_pulse``) a stochastic two-qubit
      Pauli after each coupling pulse.  Shot-averaged statistics
      reproduce the ensemble channels; draws are deterministic per
      (shot, step) given the run key.
    * **Measurement projects jointly.**  Readouts collapse the full
      vector (sequential conditioning across cores within a step gives
      the exact joint distribution), so GHZ parity correlations survive
      into the sampled bits and through the readout DSP chain.

    **Ordering**: cores advance per *instruction step*, not per clock,
    so cross-core application order would not match trigger-time order
    on its own.  With couplings configured, the interpreter adds a
    conservative discrete-event gate (sim/interpreter.py ``_step``
    stall mask): a pulse trigger fires only once no other live core
    could still produce an earlier-time op, making application order =
    schedule order by construction.  Pulses with *equal* trigger times
    co-fire and apply in a fixed stage order (1q rotations, couplings,
    measurements) — a genuine physical overlap either way.  See
    docs/PHYSICS.md "Entangling model".

The model evolves *inside* the execution loop (sim/interpreter.py
``_step`` physics block) because feedback makes it stateful: an active
reset's conditional X180 must see the post-measurement collapsed state,
and mid-circuit measurement outcomes condition later rotations.  A
post-hoc pass over recorded pulses could not close that loop.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

DEVICE_KINDS = ('parity', 'bloch', 'statevec')

# default two-qubit interaction reference amplitudes: the amp word that
# produces a pi/2 ZX (cross-resonance) / ZZ (ef-drive) rotation, matched
# to the default qchip's CNOT/CZ calibrations (models/default_qchip.py:
# CR_AMP = 0.35, CZ_AMP = 0.42 on the 16-bit amp scale)
ZX90_AMP_DEFAULT = 22937     # round(0.35 * (2^16 - 1))
ZZ90_AMP_DEFAULT = 27525     # round(0.42 * (2^16 - 1))

# statevec state is [shots, 2^n_cores]: cap the exponential axis
STATEVEC_MAX_CORES = 12


@dataclass(frozen=True)
class DeviceModel:
    """Device-physics parameters for :class:`~.physics.ReadoutPhysics`.

    ``detuning_hz``: qubit-minus-drive-frame frequency offset (Hz) —
    the Ramsey fringe frequency.  ``t1_s`` / ``t2_s``: relaxation and
    total transverse-coherence times (seconds; ``inf`` disables).
    ``depol_per_pulse``: depolarizing contraction applied per drive
    pulse.  ``clk_period_s``: FPGA clock period used to convert to
    per-clock rates (reference: python/distproc/hwconfig.py:102, 2 ns).
    Scalars broadcast over cores; sequences are per-core.
    """
    kind: str = 'bloch'
    detuning_hz: float | tuple = 0.0
    t1_s: float | tuple = math.inf
    t2_s: float | tuple = math.inf
    depol_per_pulse: float = 0.0
    clk_period_s: float = 2e-9
    # -- statevec-only fields (ignored by 'parity'/'bloch') -------------
    # two-qubit couplings: ((ctrl_core, freq_idx, target_core, kind),
    # ...) with kind 'zx' (cross-resonance: a drive pulse on ctrl at the
    # target's frequency applies exp(-i theta/2 Z_c (cos phi X_t +
    # sin phi Y_t))) or 'zz' (ef-frequency drive: exp(-i theta/2
    # Z_c Z_t), phase-word-independent since ZZ is diagonal).  Derive
    # from a compiled program + qchip with
    # models.coupling.couplings_from_qchip.
    couplings: tuple = ()
    zx90_amp: int = ZX90_AMP_DEFAULT   # amp word of a pi/2 ZX rotation
    zz90_amp: int = ZZ90_AMP_DEFAULT   # amp word of a pi/2 ZZ rotation
    # two-qubit depolarization per coupling pulse: with this
    # probability, one of the 15 non-identity two-qubit Paulis (uniform)
    # is applied to the coupled pair after the interaction — the
    # injectable error rate two-qubit RB recovers, distinct from the
    # single-qubit ``depol_per_pulse`` channel (which statevec applies
    # as a trajectory-sampled X/Y/Z after each 1q drive pulse).
    depol2_per_pulse: float = 0.0
    # Leakage out of the computational subspace, trajectory-unraveled
    # with an absorbing classical flag (the standard approximation for
    # a |2> level without a 3^C state space): after each 1q drive pulse
    # on core c, with probability ``leak_per_pulse * P(|1>_c)`` the
    # trajectory jumps — the state projects onto the core's |1>
    # component (collapsing entangled partners consistently, the
    # unraveling of L = |2><1|) and the core is marked leaked.  Leaked
    # cores are frozen: later drives, couplings involving them, and
    # T1/T2 no-op; their readouts return ``leak_readout_bit``
    # (|2> discriminates near |1> on most devices).  Absorbing — no
    # seepage back — and 1q-drive-induced only (CR-pulse leakage is a
    # known omission).  The run output gains a ``leaked`` [B, C] flag.
    leak_per_pulse: float = 0.0
    leak_readout_bit: int = 1
    # Coupling-pulse-induced leakage (round 5): after each coupling
    # pulse, the CONTROL core (the strongly-driven one — the dominant
    # hardware mechanism for 2q gates) leaks with probability
    # ``leak2_per_pulse * P(|1>_ctrl)``, with the same CPTP unraveling
    # (jump -> project + mark leaked; no-jump -> damp |1| amplitude) as
    # the 1q channel.  Interleaved 2q RB sees it as CZ error
    # (tests/test_leakage.py).
    leak2_per_pulse: float = 0.0
    # Seepage |2> -> |1| (round 5): a drive pulse (1q or coupling) on a
    # LEAKED core returns it to the computational subspace with this
    # probability — the core re-enters in |1> (its psi slot is exactly
    # the frozen |1> bookkeeping state) starting from the NEXT
    # instruction step; the seeping pulse itself still no-ops
    # (documented simplification).  0 keeps leakage absorbing.
    seep_per_pulse: float = 0.0

    def __post_init__(self):
        if self.kind not in DEVICE_KINDS:
            raise ValueError(f'unknown device kind {self.kind!r}; '
                             f'one of {DEVICE_KINDS}')
        for cp in self.couplings:
            if len(cp) != 4 or cp[3] not in ('zx', 'zz'):
                raise ValueError(
                    f'coupling entries are (ctrl_core, freq_idx, '
                    f'target_core, "zx"|"zz"); got {cp!r}')
            if cp[0] == cp[2]:
                raise ValueError(f'coupling {cp!r} pairs a core with itself')
        if self.leak_readout_bit not in (0, 1):
            raise ValueError('leak_readout_bit must be 0 or 1')
        for name in ('leak_per_pulse', 'leak2_per_pulse',
                     'seep_per_pulse'):
            v = np.asarray(getattr(self, name), np.float64)
            if v.ndim != 0:
                raise ValueError(
                    f'{name} must be a scalar (per-core rates are not '
                    f'supported yet)')
            if not 0.0 <= float(v) <= 1.0:
                raise ValueError(f'{name} must be in [0, 1]')
        if np.asarray(self.seep_per_pulse, np.float64) > 0 and not (
                np.asarray(self.leak_per_pulse, np.float64) > 0
                or np.asarray(self.leak2_per_pulse, np.float64) > 0):
            raise ValueError(
                'seep_per_pulse needs a leakage channel (leak_per_pulse '
                'or leak2_per_pulse > 0) — nothing can seep back')

    def statevec_static(self) -> tuple:
        """Hashable compile-time facts for the statevec step body:
        ``(couplings, has_detuning, has_decay, has_depol1, has_depol2,
        has_leak, leak_readout_bit, has_leak1, has_leak2, has_seep)`` —
        zero-rate channels are dropped from the traced step entirely
        (changing a rate between zero and nonzero recompiles; sweeping
        nonzero values does not, since the rates themselves are traced
        arrays).  ``has_leak`` is the any-leakage flag (freeze/readout
        logic); ``has_leak1``/``has_leak2`` gate the 1q- and
        coupling-induced exposure blocks separately."""
        def nz(v):
            return bool(np.any(np.asarray(v, np.float64) != 0.0))
        def finite(v):
            return bool(np.any(np.isfinite(np.asarray(v, np.float64))))
        has_leak1 = nz(self.leak_per_pulse)
        has_leak2 = nz(self.leak2_per_pulse)
        has_leak = has_leak1 or has_leak2
        return (tuple(tuple(cp) for cp in self.couplings),
                nz(self.detuning_hz),
                finite(self.t1_s) or finite(self.t2_s),
                nz(self.depol_per_pulse), nz(self.depol2_per_pulse),
                # leak_readout_bit is dead without leakage: pin it so a
                # bit-only model change can't force a spurious recompile
                has_leak,
                int(self.leak_readout_bit) if has_leak else 1,
                has_leak1, has_leak2, nz(self.seep_per_pulse))

    def per_clock_rates(self, n_cores: int):
        """Per-core per-clock rate arrays ``(det_cyc, inv_t1, inv_t2)``:
        detuning in cycles/clock, decay in 1/clocks (0 = disabled)."""
        def bc(v):
            return np.broadcast_to(np.asarray(v, np.float64),
                                   (n_cores,)).astype(np.float64)
        det = bc(self.detuning_hz) * self.clk_period_s
        with np.errstate(divide='ignore'):
            inv_t1 = np.where(np.isinf(bc(self.t1_s)), 0.0,
                              self.clk_period_s / bc(self.t1_s))
            inv_t2 = np.where(np.isinf(bc(self.t2_s)), 0.0,
                              self.clk_period_s / bc(self.t2_s))
        return (det.astype(np.float32), inv_t1.astype(np.float32),
                inv_t2.astype(np.float32))
