"""Vectorised JAX interpreter for the distributed-processor ISA.

This is the TPU-native replacement for the reference's per-qubit RTL
cores (reference: hdl/proc.sv + hdl/ctrl.v): instead of N soft CPUs
stepping an FSM, every core of every shot advances one *instruction* per
``lax.while_loop`` iteration, with all machine state held in int32
arrays shaped ``[n_shots, n_cores, ...]``.  Cross-core coupling — the
sync barrier and the measurement (fproc) fabric — is computed with
masked reductions over the core axis each step, the lockstep-convergence
equivalent of the reference's `sync_iface` / `fproc_iface` wiring
(reference: hdl/sync_iface.sv, hdl/fproc_meas.sv, hdl/core_state_mgr.sv).

TPU-shaped implementation choices (these are what make it fast):

* **No per-lane gathers.**  Dynamic indexing (program fetch by pc,
  register-file reads/writes, fproc producer selection) is done with
  one-hot multiply-reduce over the small static axis instead of
  ``take_along_axis`` — per-lane dynamic gathers serialise on the VPU,
  one-hot select vectorises (measured ~3x on v5e for the fetch alone).
* **The loop is outermost, not vmapped.**  State is batch-first, so the
  step counter stays a scalar; pulse records are written slot-indexed
  (one-hot select over ``max_pulses``), so the loop-carried record state
  is bounded by the pulse budget and independent of ``max_steps`` — a
  deep on-device loop costs steps, not memory.

Timing semantics match :mod:`.oracle` (the scalar golden model) exactly;
see that module's docstring for the contract.  The instruction-cost
model is the Schedule pass's (`ir/passes.py _TimedPass`), so any program
the compiler schedules executes without trigger misses by construction;
a program that *would* stall the hardware issue pipeline sets an error
bit instead of silently sliding the pulse (the runtime analog of
LintSchedule — reference: python/distproc/ir/passes.py:785-791).

Measurement bits are injected per (shot, core, measurement-index) —
exactly the strategy the reference's cocotb testbench uses to stand in
for the readout chain (reference: cocotb/proc/test_proc.py:441-446,
sim_modules/toplevel_sim.sv:16-18).  The DSP path (ops/) produces these
bits from demodulated waveforms when physics-in-the-loop is wanted.
"""

from __future__ import annotations

import collections
import contextlib
import functools
import os
import threading
import time
from dataclasses import dataclass, replace

import numpy as np
import jax
import jax.numpy as jnp

from .. import isa
from ..elements import PHASE_BITS
from ..hwconfig import FPGAConfig
from ..ops.decode import decode_history
from ..utils.profiling import counter_get, counter_inc
from .device import DEVICE_KINDS, STATEVEC_MAX_CORES
from .oracle import (INIT_TIME, QCLK_RST_DELAY, MEAS_LATENCY,
                     STICKY_RACE_MARGIN)

INT32_MAX = np.int32(2**31 - 1)

# error bits (per core)
ERR_MISSED_TRIG = 1      # pulse/idle trigger time already passed at issue
ERR_PULSE_OVERFLOW = 2   # more pulses than the static record buffer
ERR_MEAS_OVERFLOW = 4    # more measurements than meas_bits provides
ERR_FPROC_DEADLOCK = 8   # fproc read with producer halted and no data
ERR_SYNC_DONE = 16       # barrier released with a participant already done
ERR_FPROC_ID = 32        # fproc func_id out of range
ERR_STICKY_RACE = 64     # sticky read raced a measurement's arrival (a
                         # bit landed within STICKY_RACE_MARGIN clks of
                         # the read — hardware's 2-cycle handshake makes
                         # the latched value timing-dependent there)
ERR_CW_MEAS = 128        # physics mode: measurement pulse with a CW
                         # (hold-until-next) envelope — no defined window
                         # length, so the resolver cannot demodulate it
                         # (docs/PHYSICS.md "Known model limits")
ERR_COFIRE_ORDER = 256   # statevec: an equal-trigger-time cross-core
                         # co-fire where a coupling pulse's operator
                         # does not commute with its partner's — the
                         # engine's fixed stage order (1q, couplings,
                         # measurements) would silently pick one of two
                         # physically distinct outcomes, so it is
                         # flagged instead (the hardware has no analog:
                         # per-core sequential issue, and genuine RF
                         # overlap is not a sequenced product either).
                         # Separate the pulses with a barrier/delay.

# fault trap codes (per lane, per core) — the execution runtime's
# hardware-honest failure channel (docs/ROBUSTNESS.md).  Distinct from
# the ERR_* model-diagnostic bits above: a fault means the ENGINE could
# not faithfully execute the program (budget ran out, a barrier can
# never release, a word is malformed), so the shot's statistics are
# untrustworthy.  Carried as one extra int32 in the while-loop state;
# OR-ed on masks the step already computes, so fault-free programs are
# bit-identical with and without the carry.
FAULT_BUDGET_EXHAUSTED = 1   # steps hit max_steps with the lane live
FAULT_SYNC_DEADLOCK = 2      # barrier wait that can never release
                             # (partner done / not participating)
FAULT_FPROC_STARVED = 4      # fproc wait with no producer able to
                             # deliver (hard quiescence, not at a sync)
FAULT_PULSE_OVERFLOW = 8     # emitted pulses exceed max_pulses
FAULT_MEAS_OVERFLOW = 16     # measurements exceed max_meas
FAULT_RESET_OVERFLOW = 32    # reset records exceed max_resets
FAULT_ILLEGAL_OP = 64        # decoded kind outside the ISA, or fproc
                             # func_id out of range for the fabric
FAULT_JUMP_OOB = 128         # pc or taken branch target >= n_instr

# name <-> bit registry, in bit order (docs + aggregation schema)
FAULT_CODES = (
    ('budget_exhausted', FAULT_BUDGET_EXHAUSTED),
    ('sync_deadlock', FAULT_SYNC_DEADLOCK),
    ('fproc_starved', FAULT_FPROC_STARVED),
    ('pulse_overflow', FAULT_PULSE_OVERFLOW),
    ('meas_overflow', FAULT_MEAS_OVERFLOW),
    ('reset_overflow', FAULT_RESET_OVERFLOW),
    ('illegal_op', FAULT_ILLEGAL_OP),
    ('jump_oob', FAULT_JUMP_OOB),
)
N_FAULT_CODES = len(FAULT_CODES)


class FaultError(RuntimeError):
    """Raised host-side under ``fault_mode='strict'`` when any lane
    trapped.  ``counts`` is the ``[N_FAULT_CODES]`` per-code shot
    count (see :func:`fault_shot_counts`)."""

    def __init__(self, counts):
        self.counts = np.asarray(counts)
        parts = [f'{name}={int(n)}'
                 for (name, _), n in zip(FAULT_CODES, self.counts) if n]
        super().__init__('faulted shots: ' + (', '.join(parts) or 'none'))

    def __reduce__(self):
        # default exception pickling replays __init__ with the MESSAGE
        # as counts; rebuild from the counts array instead so the error
        # crosses the fleet wire (serve/transport.py) intact
        return (FaultError, (self.counts,))


def is_infrastructure_error(exc: BaseException) -> bool:
    """Classify an execution failure: ``True`` means the execution
    SUBSTRATE failed (XLA runtime fault, device loss, resource
    exhaustion, a chaos-injected crash) and the same program would
    plausibly succeed on a healthy executor — the serving tier's
    :class:`~..serve.supervise.RetryPolicy` may retry it.  ``False``
    means the failure is a property of the PROGRAM or the request
    itself (:class:`FaultError`, static-validation errors, bad
    arguments) and would reproduce identically anywhere: retrying is
    pure waste and can mask real bugs, so these always propagate to
    the caller on the first attempt (docs/ROBUSTNESS.md
    "serving-layer failures").

    :class:`~..integrity.IntegrityError` (detected silent data
    corruption — docs/ROBUSTNESS.md "Integrity") is a plain
    RuntimeError and therefore infrastructure-class BY DESIGN: a
    re-execution on a different engine/device/replica re-derives the
    correct bits, which is exactly what the retry machinery does.
    """
    if isinstance(exc, (FaultError, ValueError, TypeError, KeyError,
                        IndexError, AssertionError,
                        NotImplementedError)):
        return False
    # decoder.ProgramValidationError without importing decoder here
    # (decoder imports isa which this module shares; keep the layers
    # acyclic) — any *ValidationError by name is program-class
    if type(exc).__name__.endswith('ValidationError'):
        return False
    return True


def fault_shot_counts(fault) -> jnp.ndarray:
    """``fault [..., n_cores] -> [N_FAULT_CODES]`` int32: shots where
    any core trapped with each code (any-over-cores, sum-over-shots).
    Traceable — the sweep stats layers reduce it under jit."""
    f = jnp.asarray(fault)
    bits = jnp.asarray([bit for _, bit in FAULT_CODES], dtype=jnp.int32)
    per_shot = jnp.any((f[..., None] & bits) != 0, axis=-2)  # cores folded
    return jnp.sum(per_shot.astype(jnp.int32),
                   axis=tuple(range(per_shot.ndim - 1)))


# program-fetch strategy crossover: one-hot multiply-reduce up to this
# many instructions, per-lane gather beyond (see _step fetch comment)
_FETCH_ONEHOT_MAX = 128

_PMASKS = np.array([0xffffff, 0x1ffff, 0x1ff, 0xffff, 0xf], dtype=np.int32)
# field order matches isa.PULSE_PARAM_ORDER = (env, phase, freq, amp, cfg)

# gather order for the packed [n_cores, n_instr, F] program table
_FIELDS = ('kind', 'alu_op', 'in0_is_reg', 'imm', 'in0_reg', 'in1_reg',
           'out_reg', 'jump_addr', 'func_id', 'cmd_time',
           'p_env', 'p_phase', 'p_freq', 'p_amp', 'p_cfg',
           'p_wen', 'p_regsel', 'p_reg')
_F = {name: i for i, name in enumerate(_FIELDS)}

# pulse-record layout: slot-indexed, field-major flat [B, C, F*P]
# (views reshape to [B, C, F, P]) — memory is bounded by the pulse
# budget, not the step budget, and the flat trailing axis avoids TPU
# lane padding (a trailing F=9 would tile-pad to 128, 14x HBM)
_REC_FIELDS = ('qtime', 'gtime', 'env', 'phase', 'freq', 'amp', 'cfg',
               'elem', 'dur')


@dataclass(frozen=True)
class InterpreterConfig:
    """Static execution parameters (all shape-determining or trace-constant)."""
    max_steps: int = 4096
    max_pulses: int = 256
    max_meas: int = 64
    max_resets: int = 8
    fabric: str = 'sticky'        # 'sticky' | 'fresh' | 'lut'
    meas_elem: int = 2            # element index whose pulses are readouts
    meas_latency: int = MEAS_LATENCY
    # 'lut' fabric (reference: hdl/fproc_lut.sv): func_id 0 = own fresh
    # measurement; func_id >= 1 = syndrome-LUT distribution over the
    # masked input cores.  Tuples so the config stays hashable/static;
    # the gateware hard-codes these (meas_lut.sv:16-20) — here they are
    # writable configuration.
    lut_mask: tuple = ()          # bool per core: LUT address inputs
    lut_table: tuple = ()         # [2^k] entries, bit c = output for core c
    trace: bool = False           # record per-step (pc, time) per core
    # pulse-parameter records (the rec_* outputs waveform rendering
    # consumes) are loop-carried state the while_loop forces XLA to
    # keep alive — [B, C, 9*max_pulses] read+written EVERY step.  Turn
    # off for statistics-only runs (sweeps, benchmarks): n_pulses,
    # error bits, and measurement bookkeeping are all still tracked.
    record_pulses: bool = True
    # physics-in-the-loop execution (sim/physics.py): measurement bits
    # start *invalid* and are resolved by the DSP chain between epochs;
    # fproc reads whose bit is pending stall the lane until resolve.
    physics: bool = False
    # which device co-state the physics loop evolves (sim/device.py):
    # 'parity' — int32 quarter-turn counter, deterministic bit-flip toy;
    # 'bloch' — SU(2) Bloch vector with phase-sensitive rotations,
    # detuning/T1/T2 free evolution, and projective measurement.  Static
    # because it determines carry shapes and the step body.
    device: str = 'parity'
    drive_elem: int = 0           # element whose pulses rotate the qubit
    x90_amp: int = 0              # amp word of one quarter turn (0 = off)
    # physics mode: CW readout integration horizon in DAC samples.
    # 0 = a CW-envelope measurement pulse is an error (ERR_CW_MEAS —
    # no intrinsic window length); > 0 = the resolver demodulates CW
    # windows over this many samples and the bit becomes available
    # after the corresponding clocks (set via ReadoutPhysics.cw_horizon)
    cw_horizon: int = 0
    # instruction steps per while_loop iteration (static unroll of the
    # loop body): >1 amortizes per-iteration overhead XLA cannot fuse
    # across the while boundary over k steps.  Semantics are identical
    # — each sub-step runs the full step body including quiescence
    # detection, and sub-steps past the max_steps budget are masked to
    # exact no-ops (same results AND step counts as k=1, including
    # budget-exhausted shots).  Measured a WASH on v5e (the per-step
    # fixed cost is intra-step kernel latency, not loop-boundary
    # overhead — docs/PERF.md "the measured overhead budget"); kept as
    # an exact, tested knob for different devices/programs.
    steps_per_iter: int = 1
    # pack every [B, C] int32/bool control-state carry (pc, time,
    # offset, done, err, counters, ...) into ONE [K, B, C] array across
    # the while_loop boundary (K-major — a trailing K would lane-pad
    # ~14x, the measured fetch-merge failure mode).  Hypothesis under
    # test (docs/PERF.md "the measured overhead budget"): fewer carried
    # buffers -> fewer per-iteration store kernels -> lower per-step
    # fixed cost.  Semantically exact (unpack/repack at the loop edge).
    packed_ctrl: bool = False
    # emitted straight-line execution (:func:`_exec_straightline`):
    # False (default) = the generic fetch-dispatch engine; True =
    # require straight-line (raises with the ineligibility reason
    # otherwise); None = AUTO — use it whenever the program is
    # eligible (:func:`straightline_ineligible`) and small enough to
    # unroll (n_instr <= SL_AUTO_MAX_INSTR).  Not auto by default
    # because the specialization trades COMPILE time for RUN time and
    # keys the jit cache on program CONTENT — the generic engine shares
    # one compiled executable across same-shape programs, which is the
    # right default for compile-bound workloads (test suites, per-point
    # program sweeps); run-heavy single-program workloads (the bench)
    # opt in.
    straightline: bool = False
    # engine ladder selector (resolve_engine): None (default) keeps the
    # legacy ``straightline`` tri-state semantics above; 'generic' /
    # 'straightline' / 'block' / 'pallas' force an engine (the
    # specialized engines raise with the reason when the program is
    # ineligible); 'auto' walks the ladder — pallas first on TPU
    # backends where eligible (the megastep kernel keeps the lane carry
    # in VMEM across a whole span — ops/exec_pallas.py), else
    # straightline if eligible and small enough to unroll, else block
    # if eligible and the deduped body total is under
    # BLOCK_AUTO_MAX_UNROLL, else generic.  The specialized engines key
    # the jit cache on program CONTENT, so compile-bound workloads
    # should stay on 'generic'.
    engine: str = None
    # engine='pallas' interpret override: None (default) compiles the
    # megastep kernel on TPU backends and runs it under the Pallas TPU
    # interpreter elsewhere (ops/_pallas_common.default_interpret);
    # True/False force the choice (ops/selftest.py pins compiled-kernel
    # parity on the bench host with interpret=False; tier-1 CPU tests
    # ride the default).
    pallas_interpret: bool = None
    # bit-packed megastep carry (generalizes packed_ctrl's stacked
    # carry to a true bitfield layout): the HBM-crossing kernel streams
    # of the pallas/fused engines are packed into 32-bit words sized by
    # static program analysis (_carry_packspec — ISA field masks, the
    # statically-written register set, jump-target-bounded pc, clock
    # bounds, flow-bounded measurement/reset slots), with pack/unpack
    # shims traced INTO the kernel so the full-width state exists only
    # in VMEM.  Tri-state: None (default) = AUTO — pack exactly when
    # the kernel actually compiles (resolved pallas_interpret False,
    # i.e. a real TPU backend — the HBM 2*carry*steps model the pack
    # attacks only exists there; under the interpreter the shims are
    # pure overhead); True forces packing (tests pin bit-identity under
    # the interpreter); False disables.  Exact by construction: widths
    # cover every reachable value, so decode(encode(x)) == x.
    packed_carry: bool = None
    # per-opcode executed-instruction histogram: adds an
    # ``op_hist[N_KINDS]`` output counting retired instructions per
    # kind (summed over shots and cores).  Engine-invariant — the same
    # program retires the same instructions on every engine — which is
    # what makes block mode's "only pay for opcodes present" win
    # observable without trusting the engine under test.  Off by
    # default: it adds a [B, C, N_KINDS] loop carry.
    opcode_histogram: bool = False
    # trap handling (docs/ROBUSTNESS.md): 'count' (default) degrades
    # gracefully — faulted lanes report their FAULT_* word and sweeps
    # aggregate per-code ``fault_shots``; 'strict' raises
    # :class:`FaultError` host-side after dispatch when any lane
    # trapped.  Strict is purely a host-side policy: the wrappers
    # normalize the cfg to 'count' before jit so both modes share one
    # compiled executable.
    fault_mode: str = 'count'
    # cross-chip core sharding (docs/PERF.md "ICI fabric"): the name of
    # the shard_map mesh axis the per-core interpreter lanes are sharded
    # over, or None (default) for single-device execution.  When set,
    # the step body reads every producer-side word the fproc fabric and
    # sync barrier consume through ``lax.all_gather`` over this axis —
    # the gathered arrays equal the full-width arrays of a single-device
    # run bit-for-bit (tiled all_gather concatenates shards in axis
    # order, and every downstream consumer is elementwise or a
    # same-order reduction), so sharded execution is bit-identical by
    # construction.  Only the generic engine hosts the collectives
    # (:func:`cores_ineligible` names everything else loudly); entry is
    # via ``parallel.sweep.sharded_cores_simulate`` — the single-device
    # entry points reject a set ``cores_axis`` (no mesh axis to bind).
    cores_axis: str = None
    # streaming-QEC round count (docs/PERF.md "Streaming QEC"): how
    # many syndrome rounds one dispatch executes via the rounds scan.
    # Only :func:`simulate_rounds` binds rounds > 1 (it runs the
    # program once per round inside a ``lax.scan``, each round from a
    # fresh init state with that round's injected bits); the
    # single-round entry points reject rounds != 1 loudly so a
    # streaming config can never silently serve one round.  Static —
    # part of the jit cache key and the serve tier's bucket identity.
    rounds: int = 1
    alu_instr_clks: int = 5
    jump_cond_clks: int = 5
    jump_fproc_clks: int = 8
    pulse_regwrite_clks: int = 3
    pulse_load_clks: int = 3

    @classmethod
    def from_fpga_config(cls, fpga_config: FPGAConfig, **kw) -> 'InterpreterConfig':
        # the hwconfig-resident LUT contents flow through unless the
        # caller overrides them (explicit kw wins, like every field)
        if getattr(fpga_config, 'meas_lut_mask', ()):
            kw.setdefault('lut_mask', tuple(fpga_config.meas_lut_mask))
            kw.setdefault('lut_table', tuple(fpga_config.meas_lut_table))
        return cls(alu_instr_clks=fpga_config.alu_instr_clks,
                   jump_cond_clks=fpga_config.jump_cond_clks,
                   jump_fproc_clks=fpga_config.jump_fproc_clks,
                   pulse_regwrite_clks=fpga_config.pulse_regwrite_clks,
                   pulse_load_clks=fpga_config.pulse_load_clks, **kw)


def _onehot(idx, n: int) -> jnp.ndarray:
    """``[...] -> [..., n]`` int32 one-hot (TPU-friendly select mask).

    Built with ``broadcasted_iota`` rather than ``jnp.arange`` so the
    same code traces inside a Pallas kernel body (mosaic has no
    lowering for 1-D iota) — values are identical either way."""
    iota = jax.lax.broadcasted_iota(jnp.int32, idx.shape + (n,), idx.ndim)
    return (idx[..., None] == iota).astype(jnp.int32)


def _ohsel(table, oh):
    """Select ``table[..., k]`` by a one-hot mask: multiply + reduce."""
    return jnp.sum(table * oh, axis=-1)


# ---- statevec device helpers ------------------------------------------
# Basis convention: core c is bit (C-1-c) of the state index, so
# ``psi.reshape(B, 2, 2, ...)`` puts core 0 on the first qubit axis and
# a bitstring reads left-to-right as (q0, q1, ...).

_PAULI_1 = np.stack([
    np.eye(2), [[0, 1], [1, 0]], [[0, -1j], [1j, 0]], [[1, 0], [0, -1]],
]).astype(np.complex64)                                 # I, X, Y, Z
_PAULI_2 = np.stack([np.kron(_PAULI_1[a], _PAULI_1[b])
                     for a in range(4) for b in range(4)])  # [16, 4, 4]


@functools.lru_cache()
def _sv_zsign(C: int) -> np.ndarray:
    """``[C, 2^C]`` float32: Z eigenvalue (+1/-1) of core c in basis d."""
    d = np.arange(1 << C)
    return np.stack([1.0 - 2.0 * ((d >> (C - 1 - c)) & 1)
                     for c in range(C)]).astype(np.float32)


def _sv_apply_1q(psi, U, c: int, C: int):
    """Apply per-shot 2x2 ``U`` [B,2,2] to qubit ``c`` of ``psi`` [B,D]."""
    B = psi.shape[0]
    pn = jnp.moveaxis(psi.reshape((B,) + (2,) * C), 1 + c, 1)
    sh = pn.shape
    pn = jnp.einsum('bxu,bud->bxd', U, pn.reshape(B, 2, -1))
    return jnp.moveaxis(pn.reshape(sh), 1, 1 + c).reshape(B, -1)


def _sv_apply_pair(psi, U4, cc: int, tt: int, C: int):
    """Apply per-shot 4x4 ``U4`` [B,4,4] to qubits ``(cc, tt)`` (index
    within the 4-block is ``bit_cc * 2 + bit_tt``)."""
    B = psi.shape[0]
    pn = jnp.moveaxis(psi.reshape((B,) + (2,) * C), (1 + cc, 1 + tt), (1, 2))
    sh = pn.shape
    pn = jnp.einsum('bxu,bud->bxd', U4, pn.reshape(B, 4, -1))
    return jnp.moveaxis(pn.reshape(sh), (1, 2), (1 + cc, 1 + tt)) \
        .reshape(B, -1)


def _sv_rot_1q(theta, phi):
    """``exp(-i theta/2 (cos phi X + sin phi Y))`` as [B, 2, 2] c64."""
    ch, sh = jnp.cos(0.5 * theta), jnp.sin(0.5 * theta)
    cp, sp = jnp.cos(phi), jnp.sin(phi)
    d = jax.lax.complex(ch, jnp.zeros_like(ch))
    o01 = jax.lax.complex(-sh * sp, -sh * cp)     # -i e^{-i phi} sin
    o10 = jax.lax.complex(sh * sp, -sh * cp)      # -i e^{+i phi} sin
    return jnp.stack([jnp.stack([d, o01], -1),
                      jnp.stack([o10, d], -1)], -2)


def _sv_rot_zx(theta, phi):
    """``exp(-i theta/2 Z (x) (cos phi X + sin phi Y))`` as [B, 4, 4]:
    block-diagonal (control-conditioned +/- rotation of the target)."""
    up, dn = _sv_rot_1q(theta, phi), _sv_rot_1q(-theta, phi)
    z = jnp.zeros_like(up)
    return jnp.concatenate(
        [jnp.concatenate([up, z], -1), jnp.concatenate([z, dn], -1)], -2)


def _device_1q_pulse(st, cfg: InterpreterConfig, dev, fire, elem, pp,
                     trig, oh_mslot, is_meas_pulse):
    """Per-pulse parity/bloch device co-state evolution, SHARED by the
    generic (:func:`_step`) and straight-line
    (:func:`_exec_straightline`) engines so the physics cannot drift
    between them.  Returns ``(updates, state_bit)``: the device-array
    updates (parity: ``qturns``; bloch: ``bloch``/``phys_t``/
    ``meas_p1``) and the sampled state bit per (shot, core)."""
    mwr = (oh_mslot == 1) & is_meas_pulse[..., None]
    if cfg.device == 'parity':
        qturns = st['qturns']
        if cfg.x90_amp > 0:
            x90 = jnp.int32(cfg.x90_amp)
            dq = (2 * pp[..., 3] + x90) // (2 * x90)
            is_drive = fire & (elem == cfg.drive_elem)
            qturns = qturns + jnp.where(is_drive, dq, 0)
        state_bit = (qturns >> 1) & 1
        return dict(qturns=qturns), state_bit
    if dev is None:
        raise ValueError(
            "device='bloch' needs device-model parameter arrays; "
            "run it via sim.physics.run_physics_batch (the "
            "injected-bits simulate/simulate_batch path has no "
            "device co-state to evolve)")
    det_cyc, inv_t1, inv_t2, depol, meas_u = dev
    r = st['bloch']
    x, y, z = r[..., 0], r[..., 1], r[..., 2]
    is_drive = fire & (elem == cfg.drive_elem)
    touch = is_drive | is_meas_pulse
    # free evolution over the gap since this lane's previous
    # drive/readout pulse: detuning precession about z, T2 on
    # the transverse components, T1 relaxation toward |0> (+z)
    dt = (trig - st['phys_t']).astype(jnp.float32)
    alpha = (2 * np.pi) * det_cyc[None, :] * dt
    ca, sa = jnp.cos(alpha), jnp.sin(alpha)
    e2 = jnp.exp(-dt * inv_t2[None, :])
    e1 = jnp.exp(-dt * inv_t1[None, :])
    xf = e2 * (x * ca - y * sa)
    yf = e2 * (x * sa + y * ca)
    zf = 1.0 + (z - 1.0) * e1
    # drive rotation: Rodrigues about the equatorial axis
    # n = (cos phi, sin phi, 0) by theta = (pi/2) * amp / x90
    # (U = exp(-i theta/2 n.sigma), right-handed on the Bloch
    # sphere — the models/rb.py X90 at phi = 0); then the
    # per-pulse depolarizing contraction
    phi = (2 * np.pi / (1 << PHASE_BITS)) \
        * pp[..., 1].astype(jnp.float32)
    theta = ((np.pi / 2) / cfg.x90_amp if cfg.x90_amp > 0 else 0.0) \
        * pp[..., 3].astype(jnp.float32)
    nx, ny = jnp.cos(phi), jnp.sin(phi)
    cth, sth = jnp.cos(theta), jnp.sin(theta)
    ndot = nx * xf + ny * yf
    k1 = 1.0 - cth
    keep = jnp.float32(1.0) - depol
    rx = keep * (xf * cth + ny * zf * sth + nx * ndot * k1)
    ry = keep * (yf * cth - nx * zf * sth + ny * ndot * k1)
    rz = keep * (zf * cth + (nx * yf - ny * xf) * sth)
    # projective measurement: sample the evolved (pre-readout)
    # state with this slot's pre-drawn uniform, collapse to the
    # outcome pole; record P(1) for expectation-value readout
    p1 = jnp.clip((1.0 - zf) * 0.5, 0.0, 1.0)
    u_sel = jnp.sum(meas_u * oh_mslot.astype(jnp.float32), axis=-1)
    state_bit = (u_sel < p1).astype(jnp.int32) \
        * is_meas_pulse.astype(jnp.int32)
    zc = 1.0 - 2.0 * state_bit.astype(jnp.float32)
    x1 = jnp.where(is_meas_pulse, 0.0, jnp.where(is_drive, rx, x))
    y1 = jnp.where(is_meas_pulse, 0.0, jnp.where(is_drive, ry, y))
    z1 = jnp.where(is_meas_pulse, zc, jnp.where(is_drive, rz, z))
    return dict(
        bloch=jnp.stack([x1, y1, z1], axis=-1),
        phys_t=jnp.where(touch, trig, st['phys_t']),
        meas_p1=jnp.where(mwr, p1[..., None], st['meas_p1']),
    ), state_bit


def _alu_vec(op, in0, in1):
    """Vectorised 8-op ALU on int32 lanes (reference: hdl/alu.v:20-51).

    ``le`` is STRICT signed less-than: the RTL computes it as the sign
    of ``in0 - in1`` with overflow correction (alu.v:25-27
    ``le = sub[31] ^ sub_oflow``), so equal operands give 0; ``ge`` is
    its complement, in0 >= in1.  Pinned as data by the RTL-derived
    vectors (tests/goldens/rtl_timing_vectors.json).
    """
    return jnp.select(
        [op == 0, op == 1, op == 2, op == 3, op == 4, op == 5, op == 6],
        [in0, in0 + in1, in0 - in1,
         (in0 == in1).astype(jnp.int32), (in0 < in1).astype(jnp.int32),
         (in0 >= in1).astype(jnp.int32), in1],
        jnp.zeros_like(in0))


def program_traits(mp) -> tuple:
    """Static program facts that let the jitted step body drop whole
    blocks the program cannot exercise (the sync barrier, the fproc
    fabric, register-file reads/writes, register-sourced pulse params).

    Hashable — ``(frozenset of instruction kinds, any in0-from-reg,
    any pulse-param-from-reg)`` — so it rides the jit cache as a static
    argument.  The bench program (active-reset + RB), for example, has
    no REG_ALU/JUMP_COND/SYNC/INC_QCLK instructions and sources nothing
    from registers: its step body skips the sync reductions, all three
    16-wide register one-hot reads, and the register write-back mask —
    measured ~15% off the per-step cost and a smaller compile.  ``None``
    (the default everywhere) means "assume everything present".
    """
    soa = mp.soa
    return (frozenset(int(k) for k in np.unique(np.asarray(soa.kind))),
            bool(np.any(np.asarray(soa.in0_is_reg))),
            bool(np.any(np.asarray(soa.p_regsel))))


def _program_constants(mp, cfg: InterpreterConfig):
    """Host-side: freeze the decoded program into device constants."""
    soa = jnp.asarray(np.stack(
        [np.asarray(getattr(mp.soa, f)) for f in _FIELDS], axis=-1))
    n_cores = mp.n_cores
    max_elems = max((len(t.elem_cfgs) for t in mp.tables), default=0) or 1
    spc = np.ones((n_cores, max_elems), dtype=np.int32)
    interp = np.zeros((n_cores, max_elems), dtype=np.int32)
    for c, t in enumerate(mp.tables):
        for e, ec in enumerate(t.elem_cfgs):
            spc[c, e] = ec.samples_per_clk
            interp[c, e] = ec.interp_ratio
    return soa, jnp.asarray(spc), jnp.asarray(interp), \
        jnp.asarray(mp.sync_participants)


def _init_state(batch: int, n_cores: int, cfg: InterpreterConfig,
                init_regs=None) -> dict:
    if cfg.physics and cfg.device not in DEVICE_KINDS:
        raise ValueError(f'unknown device kind {cfg.device!r}; '
                         f'one of {DEVICE_KINDS}')
    B, C = batch, n_cores
    T, M, R = cfg.max_steps, cfg.max_meas, cfg.max_resets
    P = cfg.max_pulses
    z = lambda *s: jnp.zeros(s, dtype=jnp.int32)
    if init_regs is None:
        regs = z(B, C, isa.N_REGS)
    else:
        regs = jnp.broadcast_to(
            jnp.asarray(init_regs, jnp.int32), (B, C, isa.N_REGS))
    return dict(
        pc=z(B, C), regs=regs,
        time=jnp.full((B, C), INIT_TIME, jnp.int32), offset=z(B, C),
        done=jnp.zeros((B, C), bool), err=z(B, C), fault=z(B, C),
        pp=z(B, C, 5),
        n_pulses=z(B, C),
        # field-major flat [B, C, F*P]: a trailing axis of F=9 would
        # lane-pad to 128 on TPU (14x HBM + write traffic per step);
        # F*P lands near a tile multiple.  Views reshape to [B,C,F,P].
        **({'rec': z(B, C, len(_REC_FIELDS) * P)}
           if cfg.record_pulses else {}),
        n_resets=z(B, C), rst_time=z(B, C, R),
        n_meas=z(B, C),
        **({'op_hist': z(B, C, isa.N_KINDS)}
           if cfg.opcode_histogram else {}),
        meas_avail=jnp.full((B, C, M), INT32_MAX, jnp.int32),
        # lut fabric: per-slot PRODUCTION clock (the trigger time), the
        # plane that makes LUT reads time-indexed and therefore
        # dispatch-granularity-invariant (docs/PERF.md "Feedback on the
        # fast engines"); meas_avail above is the *distribution* clock
        **({'meas_time': jnp.full((B, C, M), INT32_MAX, jnp.int32)}
           if cfg.fabric == 'lut' else {}),
        **({'trace_pc': z(B, C, T), 'trace_time': z(B, C, T),
            'trace_off': z(B, C, T)}
           if cfg.trace else {}),
        # physics mode: device co-state (sim/device.py — quarter-turn
        # counter or Bloch vector) plus per-measurement pulse-parameter
        # records for the epoch resolver (sim/physics.py) — the numeric
        # stand-in for the out-of-repo readout hardware that produces
        # the meas bits (reference: hdl/fproc_meas.sv meas inputs)
        **({'meas_state': z(B, C, M),
            'meas_amp': z(B, C, M), 'meas_phase': z(B, C, M),
            'meas_freq': z(B, C, M), 'meas_env': z(B, C, M),
            'meas_gtime': z(B, C, M),
            'phys_wait': jnp.zeros((B, C), bool),
            **_device_state(cfg, B, C, M)}
           if cfg.physics else {}),
    )


def _device_state(cfg: InterpreterConfig, B: int, C: int, M: int) -> dict:
    """Device-co-state carry arrays per device kind (sim/device.py)."""
    z = lambda *s: jnp.zeros(s, dtype=jnp.int32)
    if cfg.device == 'parity':
        return {'qturns': z(B, C)}
    cont = {'phys_t': jnp.full((B, C), INIT_TIME, jnp.int32),
            'meas_p1': jnp.zeros((B, C, M), jnp.float32)}
    if cfg.device == 'bloch':
        return {'bloch': jnp.zeros((B, C, 3), jnp.float32), **cont}
    # 'statevec': one 2^C-dim state vector per shot
    if C > STATEVEC_MAX_CORES:
        raise ValueError(
            f"device='statevec' holds a [shots, 2^n_cores] state vector; "
            f"n_cores={C} exceeds the cap of {STATEVEC_MAX_CORES}")
    return {'psi': jnp.zeros((B, 1 << C), jnp.complex64),
            'leaked': jnp.zeros((B, C), bool), **cont}


def _step(st: dict, step_i, soa, spc, interp, sync_part, meas_bits,
          meas_valid, cfg: InterpreterConfig, dev=None,
          traits=None) -> dict:
    B, C = st['pc'].shape
    N = soa.shape[1]
    time, offset, regs = st['time'], st['offset'], st['regs']
    # static program traits (program_traits): blocks a program cannot
    # exercise are dropped from the traced body entirely — Python-level
    # False predicates below, not runtime masks
    has = (lambda k: True) if traits is None else (lambda k: k in traits[0])
    any_in0_reg = traits is None or traits[1]
    any_regsel = traits is None or traits[2]
    any_fproc = has(isa.K_ALU_FPROC) or has(isa.K_JUMP_FPROC)
    any_in1_reg = has(isa.K_REG_ALU) or has(isa.K_JUMP_COND)
    any_regwrite = has(isa.K_REG_ALU) or has(isa.K_ALU_FPROC)
    has_sync = has(isa.K_SYNC)

    # ---- program fetch ------------------------------------------------
    # Small programs: one-hot multiply-reduce over the instruction axis
    # (vectorises on the VPU, measured ~3x over gather on v5e at N~40).
    # Large programs: the one-hot is O(N) per step -> O(N^2) per program,
    # so switch to a per-lane gather, whose cost is flat in N.
    pc_idx = jnp.clip(st['pc'], 0, N - 1)
    if N <= _FETCH_ONEHOT_MAX:
        oh_pc = _onehot(pc_idx, N)                             # [B, C, N]
        fetched = {f: jnp.sum(soa[None, :, :, _F[f]] * oh_pc, axis=-1)
                   for f in _FIELDS}                           # each [B, C]
    else:
        rows = jnp.take_along_axis(
            soa[None], pc_idx[..., None, None], axis=2)        # [B, C, 1, F]
        fetched = {f: rows[:, :, 0, _F[f]] for f in _FIELDS}
    g = lambda f: fetched[f]
    kind = g('kind')
    live = ~st['done']

    def reg_read(idx):
        return _ohsel(regs, _onehot(idx, isa.N_REGS))

    # ---- operand fetch -------------------------------------------------
    in0 = jnp.where(g('in0_is_reg') == 1, reg_read(g('in0_reg')),
                    g('imm')) if any_in0_reg else g('imm')
    qclk = time - offset
    is_fproc = (kind == isa.K_ALU_FPROC) | (kind == isa.K_JUMP_FPROC)

    # ---- discrete-event gate, stage A (statevec + couplings only) ------
    # Base frontiers for the pulse-trigger ordering gate applied in the
    # stall-mask section below: each core's frontier lower-bounds the
    # trigger time of anything it can still emit (pending trigger if it
    # sits at one, else its local clock — trig = max(trig, time) and
    # time is monotone; sync-stalled cores are raised to the release
    # lower bound).  Computed before the fabric so the sticky branch
    # can use producer frontiers to prove a latched snapshot final.
    pt_gate = cfg.physics and cfg.device == 'statevec' \
        and dev is not None and len(dev['static'][0]) > 0
    if pt_gate:
        is_ptk = kind == isa.K_PULSE_TRIG
        trig_e = jnp.maximum(offset + g('cmd_time'), time)
        f0_gate = jnp.where(live & is_ptk, trig_e,
                            jnp.where(live, time, INT32_MAX))
        fr_gate = f0_gate
        at_sync_g = live & (kind == isa.K_SYNC)
        if has_sync:
            neg_g = jnp.int32(-INT32_MAX)
            f_part = jnp.max(jnp.where(sync_part[None, :], f0_gate, neg_g),
                             axis=-1, keepdims=True)
            fr_gate = jnp.where(at_sync_g, jnp.maximum(fr_gate, f_part),
                                fr_gate)

    # ---- fproc fabric (reference: hdl/fproc_meas.sv / core_state_mgr.sv /
    # hdl/fproc_lut.sv, selected statically by cfg.fabric; dropped
    # entirely when the program has no fproc instructions) ---------------
    fid = g('func_id')
    req = time

    # ---- cross-chip producer views (cfg.cores_axis — docs/PERF.md
    # "ICI fabric"): everything the fabric and the sync barrier read
    # from OTHER cores goes through one gather layer.  Sharded, each
    # device holds C local lanes and ``all_gather(..., tiled=True)``
    # concatenates the shards in mesh-axis order, so the gathered
    # arrays equal the full-width arrays of a single-device run
    # bit-for-bit; ``core0`` offsets local lane indices into the full
    # core axis.  Unsharded the gather is the identity (CF == C,
    # core0 == 0) and the traced computation is unchanged.
    ax = cfg.cores_axis
    if ax is None:
        _gat = lambda x: x
        core0 = jnp.int32(0)
    else:
        _gat = lambda x: jax.lax.all_gather(x, ax, axis=1, tiled=True)
        core0 = jax.lax.axis_index(ax).astype(jnp.int32) * C
    if any_fproc or has_sync:
        P_time, P_done = _gat(time), _gat(st['done'])
        CF = P_done.shape[1]                       # full core count
    if any_fproc:
        P_n_meas, P_mavail = _gat(st['n_meas']), _gat(st['meas_avail'])
        P_bits, P_valid = _gat(meas_bits), _gat(meas_valid)
        if cfg.fabric == 'lut':
            P_mtime = _gat(st['meas_time'])

    if not any_fproc:
        fid_bad = f_race = f_deadlock = f_phys = jnp.zeros((), bool)
        f_ready = jnp.ones((), bool)
        f_data = jnp.int32(0)
        f_tready = req

    def _by_producer(prod_oh):
        """Select producer-core rows for each reader: [B,CF] -> [B,C]."""
        sel = lambda arr: _ohsel(arr[:, None, :], prod_oh)
        sel_m = lambda arr: jnp.sum(
            arr[:, None, :, :] * prod_oh[..., None], axis=2)
        return sel, sel_m

    def _fresh_read(prod_oh):
        """First measurement completing strictly after the request
        (reference: hdl/core_state_mgr.sv:45-56 WAIT_MEAS).  A fired
        measurement whose bit is still *invalid* (physics pending, not
        yet demodulated) stalls the read instead of serving it."""
        sel, sel_m = _by_producer(prod_oh)
        mavail_p, bits_p = sel_m(P_mavail), sel_m(P_bits)
        valid_p = sel_m(P_valid.astype(jnp.int32))
        fresh = (mavail_p > req[..., None]) & \
            (jnp.arange(cfg.max_meas)[None, None, :]
             < sel(P_n_meas)[..., None])
        exists = jnp.any(fresh, axis=-1)
        oh_j = _onehot(jnp.argmax(fresh, axis=-1).astype(jnp.int32),
                       cfg.max_meas)
        sel_valid = _ohsel(valid_p, oh_j) == 1
        ready = exists & sel_valid
        phys = exists & ~sel_valid
        data = jnp.where(ready, _ohsel(bits_p, oh_j), 0)
        tready = jnp.where(ready,
                           jnp.maximum(req, _ohsel(mavail_p, oh_j)), req)
        dead = ~exists & (sel(P_done.astype(jnp.int32)) == 1)
        return ready | dead, data, tready, dead, phys

    fid_bad = jnp.zeros((B, C), bool)
    f_race = jnp.zeros((B, C), bool)
    if not any_fproc:
        pass          # trivial constants above; is_fproc never true
    elif cfg.fabric == 'sticky':
        # bit latched at read time; producer must have simulated past `req`
        fid_bad = fid >= CF
        oh_prod = _onehot(jnp.clip(fid, 0, CF - 1), CF)
        sel, sel_m = _by_producer(oh_prod)
        mavail_p, bits_p = sel_m(P_mavail), sel_m(P_bits)
        valid_p = sel_m(P_valid.astype(jnp.int32))
        f_time_ok = (sel(P_done.astype(jnp.int32)) == 1) \
            | (sel(P_time) >= req)
        if pt_gate:
            # under the event gate, a producer stalled at a far-future
            # trigger would freeze its clock and deadlock the sticky
            # read (and inheriting its frontier would let time-later
            # pulses overtake the reader — unsound).  Instead the
            # latched snapshot is provably FINAL once the producer's
            # frontier passes the request: any measurement it can still
            # fire lands at frontier + MEAS_LATENCY > req, comfortably
            # outside the race margin — so serve the read.
            f_time_ok = f_time_ok | (sel(fr_gate) >= req)
        m_cnt = jnp.sum((mavail_p <= req[..., None]).astype(jnp.int32), -1)
        oh_latest = _onehot(jnp.maximum(m_cnt - 1, 0), cfg.max_meas)
        latest_valid = (m_cnt == 0) | (_ohsel(valid_p, oh_latest) == 1)
        f_ready = f_time_ok & latest_valid
        f_phys = f_time_ok & ~latest_valid
        f_data = jnp.where(m_cnt > 0, _ohsel(bits_p, oh_latest), 0)
        f_tready = req
        f_deadlock = jnp.zeros((B, C), bool)
        # a measurement landing within the handshake window of the read
        # makes the hardware-latched value timing-dependent: flag it
        # (see oracle.STICKY_RACE_MARGIN)
        f_race = jnp.any(
            (mavail_p > (req - STICKY_RACE_MARGIN)[..., None])
            & (mavail_p <= (req + STICKY_RACE_MARGIN)[..., None]), -1)
    elif cfg.fabric == 'fresh':
        fid_bad = fid >= CF
        oh_prod = _onehot(jnp.clip(fid, 0, CF - 1), CF)
        f_ready, f_data, f_tready, f_deadlock, f_phys = _fresh_read(oh_prod)
    else:  # 'lut' — reference: hdl/fproc_lut.sv + meas_lut.sv
        # func_id 0: own fresh measurement (local lane core0+i in the
        # full core axis — an identity one-hot when unsharded)
        own_oh = jnp.broadcast_to(
            _onehot(core0 + jnp.arange(C, dtype=jnp.int32), CF)[None],
            (B, C, CF))
        o_ready, o_data, o_tready, o_dead, o_phys = _fresh_read(own_oh)
        # func_id >= 1: the masked cores' bits form the address; the
        # read blocks until every masked input's bit is *valid*
        # (reference: meas_lut.sv LUT_WAIT until (mask & valid) == mask)
        lmask = np.asarray(cfg.lut_mask, dtype=bool)        # [CF] full
        shifts = np.zeros(len(lmask), dtype=np.int32)
        shifts[lmask] = np.arange(int(lmask.sum()))
        lmask_j = jnp.asarray(lmask)
        # causality: every masked producer has recorded >= 1 measurement
        # and its timeline passed the reader's request
        ok = (P_n_meas >= 1)[:, None, :] \
            & (P_done[:, None, :]
               | (P_time[:, None, :] >= req[:, :, None]))    # [B, C, CF]
        l_causal = jnp.all(jnp.where(lmask_j[None, None, :], ok, True), -1)
        # TIME-INDEXED slot select (the property that makes every
        # dispatch granularity serve the same bit — docs/PERF.md
        # "Feedback on the fast engines"): per masked producer, the
        # newest bit PRODUCED strictly before the reader's request.
        # Strict (<) because a producer whose clock sits exactly at
        # ``req`` can still fire a trigger at ``req``; once its clock
        # passes ``req`` the set {m : meas_time[m] < req} is final, so
        # the count is identical whether the read is served per-step
        # or replayed later from final planes.  Count 0 (armed before
        # any production) falls back to slot 0 — the first recorded
        # bit, fixed once written, guaranteed to exist by causality —
        # matching the gateware's arm-then-accumulate LUT_WAIT.
        rec = jnp.arange(cfg.max_meas)[None, None, :] \
            < P_n_meas[:, :, None]                           # [B, CF, M]
        early = rec[:, None, :, :] \
            & (P_mtime[:, None, :, :] < req[:, :, None, None])
        cnt = jnp.sum(early.astype(jnp.int32), -1)           # [B, C, CF]
        oh_sel = _onehot(jnp.maximum(cnt - 1, 0), cfg.max_meas)
        bit = jnp.sum(P_bits[:, None, :, :] * oh_sel, -1)    # [B, C, CF]
        avail_sel = jnp.sum(jnp.where(P_mavail == INT32_MAX, 0,
                                      P_mavail)[:, None, :, :] * oh_sel, -1)
        valid_sel = jnp.sum(
            P_valid.astype(jnp.int32)[:, None, :, :] * oh_sel, -1)
        l_valid = jnp.all(jnp.where(lmask_j[None, None, :],
                                    valid_sel == 1, True), -1)
        l_ready = l_causal & l_valid
        # distribution time: the last SELECTED slot's avail over the
        # mask — per reader now that slots are request-indexed
        t_lut = jnp.max(jnp.where(lmask_j[None, None, :], avail_sel, 0),
                        axis=-1)                             # [B, C]
        addr = jnp.sum(bit * lmask_j[None, None, :]
                       * (1 << jnp.asarray(shifts))[None, None, :],
                       -1)                                   # [B, C]
        table = jnp.asarray(cfg.lut_table, jnp.int32)
        entry = _ohsel(table[None, None, :], _onehot(addr, len(table)))
        l_data = (entry >> (core0
                            + jnp.arange(C, dtype=jnp.int32))[None, :]) & 1
        is_own = fid == 0
        f_ready = jnp.where(is_own, o_ready, l_ready)
        f_data = jnp.where(is_own, o_data, l_data)
        f_tready = jnp.where(is_own, o_tready, jnp.maximum(req, t_lut))
        f_deadlock = is_own & o_dead
        f_phys = jnp.where(is_own, o_phys, l_causal & ~l_valid)
    f_ready = f_ready | fid_bad
    f_data = jnp.where(fid_bad, 0, f_data)
    f_phys = f_phys & ~fid_bad

    # ---- ALU (in1 mux per reference: hdl/proc.sv:111) ------------------
    in1 = reg_read(g('in1_reg')) if any_in1_reg else jnp.int32(0)
    if has(isa.K_INC_QCLK):
        in1 = jnp.where(kind == isa.K_INC_QCLK, qclk, in1)
    if any_fproc:
        in1 = jnp.where(is_fproc, f_data, in1)
    alu_res = _alu_vec(g('alu_op'), in0, in1)

    # ---- sync barrier (reference: ctrl.v:510-552 + qclk reset) ---------
    if has_sync:
        at_sync = live & (kind == isa.K_SYNC)
        # barrier state over the FULL core axis (sync_part is already
        # full-width; P_at/P_time/P_done are the gathered views — the
        # sharded barrier is exactly the reference barrier evaluated on
        # the cross-chip words)
        P_at = _gat(at_sync)
        live_part = sync_part[None, :] & ~P_done
        sync_ready = jnp.any(P_at, -1) \
            & jnp.all(~live_part | P_at, -1)
        release = jnp.max(jnp.where(P_at, P_time, -INT32_MAX),
                          axis=-1, keepdims=True) + QCLK_RST_DELAY  # [B, 1]
        sync_adv = at_sync & sync_ready[:, None]
        sync_err = sync_ready & jnp.any(sync_part[None, :] & P_done, -1)

    # ---- stall mask ----------------------------------------------------
    stalled = is_fproc & ~f_ready
    if has_sync:
        stalled = stalled | (at_sync & ~sync_ready[:, None])
    if pt_gate:
        # Conservative discrete-event gate: cores advance per
        # *instruction step*, so without this a core with few
        # instructions can apply a time-later pulse in an earlier step
        # than a busy neighbour's time-earlier one — fatal once
        # couplings make cross-core pulses non-commuting.  A pulse
        # trigger may fire only when no other live core could still
        # produce an earlier-time op.  Frontier bounds (stage A above)
        # are strengthened by a monotone fixpoint over stall chains —
        # a sync-stalled core's ops land at the release, which is >=
        # every participant's frontier; a fresh/LUT fproc reader
        # resumes only after its producer's next measurement, so it
        # inherits the producer's frontier (LUT: max over the masked
        # producers).  Iterating n_cores times propagates bounds
        # through chains of any length (reader -> sync -> pulse, ...);
        # each raise is justified by the previous iterate, so the
        # fixpoint is sound by induction.  Sticky readers need (and
        # may take) no inheritance: the snapshot-finality relaxation in
        # the fabric section serves them as soon as the producer's
        # frontier passes the request.  The minimum pending trigger is
        # always allowed, so the gate cannot deadlock; equal-time
        # pulses co-fire and apply in the stage order below (a genuine
        # physical overlap either way).
        fr = fr_gate
        neg = jnp.int32(-INT32_MAX)
        inherit_fproc = any_fproc and cfg.fabric in ('fresh', 'lut')
        if inherit_fproc:
            fstall = is_fproc & live & ~f_ready & ~f_phys
        for _ in range(C if (has_sync or inherit_fproc) else 0):
            if has_sync:
                f_part = jnp.max(jnp.where(sync_part[None, :], fr, neg),
                                 axis=-1, keepdims=True)
                fr = jnp.where(at_sync_g, jnp.maximum(fr, f_part), fr)
            if inherit_fproc:
                if cfg.fabric == 'fresh':
                    prod_f = _ohsel(fr[:, None, :], oh_prod)
                else:  # 'lut'
                    lut_f = jnp.max(jnp.where(lmask_j[None, :], fr, neg),
                                    axis=-1, keepdims=True)
                    prod_f = jnp.where(fid == 0, fr,
                                       jnp.broadcast_to(lut_f, fr.shape))
                fr = jnp.where(fstall, jnp.maximum(fr, prod_f), fr)
        pt_ok = jnp.all(
            (trig_e[:, :, None] <= fr[:, None, :])
            | ~live[:, None, :] | jnp.eye(C, dtype=bool)[None], axis=-1)
        stalled = stalled | (is_ptk & live & ~pt_ok)
    adv = live & ~stalled                     # cores executing this step

    # ---- pulse-register latch + trigger --------------------------------
    is_pw = kind == isa.K_PULSE_WRITE
    is_pt = kind == isa.K_PULSE_TRIG
    is_pulse = (is_pw | is_pt) & adv
    imm_vals = jnp.stack([g('p_env'), g('p_phase'), g('p_freq'),
                          g('p_amp'), g('p_cfg')], axis=-1)      # [B, C, 5]
    wen = (g('p_wen')[..., None] >> jnp.arange(5)) & 1
    if any_regsel:
        rsel = (g('p_regsel')[..., None] >> jnp.arange(5)) & 1
        regval = reg_read(g('p_reg'))
        cand = jnp.where(rsel == 1, regval[..., None], imm_vals) \
            & jnp.asarray(_PMASKS)
    else:
        cand = imm_vals & jnp.asarray(_PMASKS)
    pp = jnp.where(is_pulse[..., None] & (wen == 1), cand, st['pp'])

    cmd_time = g('cmd_time')                  # uint32 bit pattern
    trig = offset + cmd_time
    missed_trig = is_pt & adv & (trig < time)
    trig = jnp.maximum(trig, time)
    elem = pp[..., 4] & 0b11
    oh_elem = _onehot(jnp.minimum(elem, spc.shape[1] - 1), spc.shape[1])
    spc_e = _ohsel(spc[None], oh_elem)
    interp_e = _ohsel(interp[None], oh_elem)
    envw = pp[..., 0]
    env_len = (envw >> 12) & 0xfff
    nsamp = env_len * 4 * interp_e
    dur = jnp.where(env_len == 0xfff, 0, (nsamp + spc_e - 1) // spc_e)

    # ---- pulse record: slot-indexed one-hot write --------------------
    fire = is_pt & adv
    rec_of = jnp.where(fire & (st['n_pulses'] >= cfg.max_pulses),
                       ERR_PULSE_OVERFLOW, 0)
    rec_update = {}
    if cfg.record_pulses:
        rec_vals = jnp.stack(
            [cmd_time, trig, pp[..., 0], pp[..., 1], pp[..., 2], pp[..., 3],
             pp[..., 4], elem, dur], axis=-1)                    # [B, C, 9]
        oh_pslot = _onehot(jnp.minimum(st['n_pulses'], cfg.max_pulses - 1),
                           cfg.max_pulses)                       # [B, C, P]
        pwrite = (oh_pslot == 1) & (fire & (st['n_pulses'] < cfg.max_pulses)
                                    )[..., None]
        F, P = len(_REC_FIELDS), cfg.max_pulses
        rec_update['rec'] = jnp.where(
            pwrite[:, :, None, :], rec_vals[:, :, :, None],
            st['rec'].reshape(B, C, F, P)).reshape(B, C, F * P)
    n_pulses = st['n_pulses'] + fire.astype(jnp.int32)

    is_meas_pulse = fire & (elem == cfg.meas_elem)
    meas_of = jnp.where(is_meas_pulse & (st['n_meas'] >= cfg.max_meas),
                        ERR_MEAS_OVERFLOW, 0)
    oh_mslot = _onehot(jnp.minimum(st['n_meas'], cfg.max_meas - 1),
                       cfg.max_meas)
    meas_avail = jnp.where(
        (oh_mslot == 1) & is_meas_pulse[..., None],
        (trig + dur + cfg.meas_latency)[..., None], st['meas_avail'])
    if 'meas_time' in st:
        # production clock = trigger time, written exactly once per
        # slot (CW-horizon below rewrites meas_avail only — the bit's
        # production instant does not move with its distribution)
        meas_time = jnp.where(
            (oh_mslot == 1) & is_meas_pulse[..., None],
            trig[..., None], st['meas_time'])
    n_meas = st['n_meas'] + is_meas_pulse.astype(jnp.int32)

    # ---- physics co-state: device model + meas records -----------------
    # The device co-state stands in for the real qubits the reference's
    # gateware drives (the reference models no physics — hardware
    # supplies the bits).  Two models (sim/device.py): 'parity', a
    # deterministic quarter-turn counter whose state bit is the
    # half-turn parity (floor convention for odd residues); 'bloch', an
    # SU(2) Bloch vector with phase-word rotation axes, detuning/T1/T2
    # free evolution, per-pulse depolarization, and projective
    # measurement sampling.  Measurement pulses record their synthesis
    # parameters for the epoch resolver (sim/physics.py).
    phys_updates = {}
    cw_meas_err = 0
    cofire_err = 0
    if cfg.physics:
        if cfg.cw_horizon > 0:
            # CW readout with a configured horizon: the bit exists once
            # the horizon's worth of samples has been integrated — the
            # availability uses the horizon duration in clocks instead
            # of the (zero) envelope duration
            cw_clks = (cfg.cw_horizon + spc_e - 1) // spc_e
            meas_avail = jnp.where(
                (oh_mslot == 1) & (is_meas_pulse
                                   & (env_len == 0xfff))[..., None],
                (trig + cw_clks + cfg.meas_latency)[..., None], meas_avail)
        else:
            # a CW readout window has no length for the resolver to
            # demodulate — flag it loudly instead of yielding silent
            # 0 bits
            cw_meas_err = jnp.where(is_meas_pulse & (env_len == 0xfff),
                                    ERR_CW_MEAS, 0)
        mwr = (oh_mslot == 1) & is_meas_pulse[..., None]
        if cfg.device in ('parity', 'bloch'):
            phys_updates, state_bit = _device_1q_pulse(
                st, cfg, dev, fire, elem, pp, trig, oh_mslot,
                is_meas_pulse)
        else:  # 'statevec' — entangling full-state trajectory model
            if dev is None:
                raise ValueError(
                    "device='statevec' needs device-model parameters; "
                    "run it via sim.physics.run_physics_batch")
            (det_cyc, inv_t1, inv_t2, depol1, depol2, zx90, zz90, leak,
             leak2, seep, meas_u, traj_key) = dev['params']
            (couplings, has_det, has_decay, has_dp1, has_dp2,
             has_leak, leak_bit, has_leak1, has_leak2, has_seep,
             leak_iq) = dev['static']
            leaked = st['leaked']                             # [B, C]
            psi = st['psi']                                   # [B, 2^C] c64
            zsign = jnp.asarray(_sv_zsign(C))                 # [C, D]
            bit1 = (1.0 - zsign) * 0.5                        # 1 where |1>
            is_drive = fire & (elem == cfg.drive_elem)
            freqw = pp[..., 2]
            # coupling-pulse masks: a drive pulse whose frequency word
            # matches a configured (ctrl, freq_idx) entry is a 2q
            # interaction, not a 1q rotation (static unroll — the
            # coupling map is compile-time configuration)
            cp_masks = [is_drive[:, cc] & (freqw[:, cc] == fi)
                        for (cc, fi, tt, kd) in couplings]
            is_cr = jnp.zeros((B, C), bool)
            for mk, (cc, fi, tt, kd) in zip(cp_masks, couplings):
                is_cr = is_cr | (mk[:, None]
                                 & (jnp.arange(C) == cc)[None, :])
            is_1q = is_drive & ~is_cr
            touch = is_drive | is_meas_pulse
            # ---- equal-time co-fire ordering lint (review round 4
            # weak #3): when cross-core pulses land on the same trigger
            # time, the engine applies a fixed stage order (1q ->
            # couplings -> measurements) — for non-commuting operator
            # pairs that is a simulator-chosen ordering with no
            # hardware analog, so it is FLAGGED (ERR_COFIRE_ORDER on
            # the coupling's control core) instead of silently decided.
            # Commuting overlaps stay clean: 1q||1q on distinct cores,
            # Z legs vs Z measurement, zz||zz (both diagonal), and
            # couplings sharing only control (Z) legs.  Under the event
            # gate, cross-core pulses co-firing in one step always have
            # EQUAL triggers (unequal ones are serialized), so the
            # equal-trig test below is exactly the co-fire set.
            if couplings:
                eff = []
                for mk, (c1, _fi, t1, _kd) in zip(cp_masks, couplings):
                    if has_leak:
                        # leaked legs no-op the interaction (stage 4):
                        # no physics to mis-order
                        mk = mk & ~leaked[:, c1] & ~leaked[:, t1]
                    eff.append(mk)
                cof_cols = [jnp.zeros((B,), bool)] * C
                # equatorial axes agree mod pi <=> phase words agree
                # mod a half turn (X^(phi+pi) = -X^phi: same rotation
                # generator up to sign)
                half = 1 << (PHASE_BITS - 1)
                pw = pp[..., 1]
                ax_ne = lambda a, b: ((pw[:, a] - pw[:, b]) % half) != 0
                for i, (mi, (c1, _f1, t1, k1)) in enumerate(
                        zip(eff, couplings)):
                    tcc = trig[:, c1]
                    same = lambda c: fire[:, c] & (trig[:, c] == tcc)
                    # the coupling's target-leg clashes.  zx: the X leg
                    # clashes with a DIFFERENT-axis 1q drive (same-axis
                    # rotations commute) and with Z measurement; zz:
                    # the Z leg clashes with any equatorial 1q drive
                    # and commutes with measurement.
                    bad = same(t1) & is_1q[:, t1]
                    if k1 == 'zx':
                        bad = bad & ax_ne(c1, t1)
                        bad = bad | (same(t1) & is_meas_pulse[:, t1])
                    for j in range(i + 1, len(couplings)):
                        mj, (c2, _f2, t2, k2) = eff[j], couplings[j]
                        if k1 == 'zz' and k2 == 'zz':
                            continue          # both diagonal: commute
                        if k1 == 'zx' and k2 == 'zx':
                            hard = (t1 == c2) or (t2 == c1)  # X vs Z
                            soft = t1 == t2                  # X vs X
                        elif k1 == 'zx':
                            hard, soft = t1 in (c2, t2), False
                        else:
                            hard, soft = t2 in (c1, t1), False
                        if hard:
                            bad = bad | (mj & same(c2))
                        elif soft:
                            # shared X target: commute iff same axis
                            bad = bad | (mj & same(c2) & ax_ne(c1, c2))
                    hit = mi & bad
                    cof_cols[c1] = cof_cols[c1] | hit
                cofire_err = jnp.where(jnp.stack(cof_cols, axis=-1),
                                       ERR_COFIRE_ORDER, 0)
            dt = jnp.where(touch,
                           (trig - st['phys_t']).astype(jnp.float32), 0.0)
            if has_decay or has_dp1 or has_dp2 or has_leak:
                # per-step trajectory uniforms, deterministic per
                # (shot, core, step) given the run key.  Column 6 (the
                # leak-jump draw — shared by the 1q and coupling
                # exposures, which are mutually exclusive per core per
                # step) and column 7 (seepage) only exist when their
                # channels are on, so existing models keep their exact
                # draw streams (and results)
                traj_u = jax.random.uniform(
                    jax.random.fold_in(traj_key, step_i),
                    (B, C, 6 + (1 if has_leak else 0)
                     + (1 if has_seep else 0)), jnp.float32)
            # (1) free evolution: detuning precession, one exact
            # diagonal Rz over all touched cores (a [B,C]x[C,D] matmul)
            if has_det:
                alpha = (2 * np.pi) * det_cyc[None, :] * dt
                arg = jnp.einsum('bc,cd->bd', -0.5 * alpha, zsign)
                psi = psi * jax.lax.complex(jnp.cos(arg), jnp.sin(arg))
            # (2) T1 / pure-dephasing quantum jumps per touched core:
            # amplitude damping as a jump unraveling (jump prob
            # p_decay * P(|1>)), dephasing as a stochastic Z — the
            # shot-ensemble reproduces the Lindblad channels the bloch
            # model applies deterministically
            if has_decay:
                inv_phi = jnp.maximum(inv_t2 - 0.5 * inv_t1, 0.0)
                for c in range(C):
                    p_dec = 1.0 - jnp.exp(-dt[:, c] * inv_t1[c])
                    if has_leak:
                        # a leaked core is physically in |2>: its psi
                        # slot is a frozen |1> bookkeeping state that
                        # must not relax or dephase
                        p_dec = jnp.where(leaked[:, c], 0.0, p_dec)
                    p1c = jnp.sum(bit1[c][None]
                                  * (psi.real**2 + psi.imag**2), -1)
                    jump = traj_u[:, c, 0] < p_dec * p1c
                    damp = 1.0 - (1.0 - jnp.sqrt(1.0 - p_dec))[:, None] \
                        * bit1[c][None, :]
                    nrm = jnp.sqrt(jnp.maximum(1.0 - p_dec * p1c, 1e-12))
                    psi_nj = psi * (damp / nrm[:, None])
                    pn = jnp.moveaxis(psi.reshape((B,) + (2,) * C),
                                      1 + c, 1).reshape(B, 2, -1)
                    pj = jnp.stack(
                        [pn[:, 1, :], jnp.zeros_like(pn[:, 0, :])], 1)
                    pj = jnp.moveaxis(pj.reshape((B, 2) + (2,) * (C - 1)),
                                      1, 1 + c).reshape(B, -1)
                    pj = pj / jnp.sqrt(jnp.maximum(p1c, 1e-12))[:, None]
                    psi = jnp.where(jump[:, None], pj, psi_nj)
                    p_phi = 1.0 - jnp.exp(-dt[:, c] * inv_phi[c])
                    if has_leak:
                        p_phi = jnp.where(leaked[:, c], 0.0, p_phi)
                    flip = traj_u[:, c, 1] < 0.5 * p_phi
                    psi = jnp.where(flip[:, None],
                                    psi * zsign[c][None, :], psi)
            # (3) 1q drive rotations (same angle/axis convention as
            # 'bloch'), with stochastic 1q depol folded into the op
            theta1 = ((np.pi / 2) / cfg.x90_amp if cfg.x90_amp > 0
                      else 0.0) * pp[..., 3].astype(jnp.float32)
            theta1 = jnp.where(is_1q, theta1, 0.0)
            if has_leak:
                # drives on a leaked core act on |2>, far off-resonant
                # from the 0-1 transition: no-op in the model
                theta1 = jnp.where(leaked, 0.0, theta1)
            phi1 = (2 * np.pi / (1 << PHASE_BITS)) \
                * pp[..., 1].astype(jnp.float32)
            pauli1 = jnp.asarray(_PAULI_1)
            for c in range(C):
                U = _sv_rot_1q(theta1[:, c], phi1[:, c])
                if has_dp1:
                    occ = (traj_u[:, c, 2] < depol1) & is_1q[:, c]
                    if has_leak:
                        occ = occ & ~leaked[:, c]
                    pick = jnp.minimum(
                        (traj_u[:, c, 3] * 3).astype(jnp.int32), 2) + 1
                    sel = jnp.where(occ, pick, 0)
                    pmat = jnp.einsum(
                        'bk,kxy->bxy',
                        jax.nn.one_hot(sel, 4, dtype=jnp.complex64),
                        pauli1)
                    U = jnp.einsum('bxy,byu->bxu', pmat, U)
                psi = _sv_apply_1q(psi, U, c, C)
                if has_leak1:
                    # leakage channel after the rotation, the full CPTP
                    # unraveling of L = sqrt(p)|2><1| (excited
                    # population drives the 1->2 transition): with
                    # probability p * P(|1>) the trajectory JUMPS —
                    # project onto the |1> component (collapsing
                    # entangled partners consistently) and mark
                    # absorbed; otherwise the NO-JUMP back-action damps
                    # the |1> amplitude by sqrt(1-p) and renormalizes
                    # (omitting it would over-weight |1> in surviving
                    # trajectories and break the ensemble channel)
                    exposed = is_1q[:, c] & ~leaked[:, c]
                    p_eff = jnp.where(exposed, leak, 0.0)
                    p1c = jnp.sum(bit1[c][None]
                                  * (psi.real**2 + psi.imag**2), -1)
                    occ = traj_u[:, c, 6] < p_eff * p1c
                    proj = psi * (bit1[c][None, :]
                                  / jnp.sqrt(jnp.maximum(p1c,
                                                         1e-12))[:, None])
                    damp = 1.0 - (1.0 - jnp.sqrt(1.0 - p_eff))[:, None] \
                        * bit1[c][None, :]
                    nrm = jnp.sqrt(jnp.maximum(1.0 - p_eff * p1c, 1e-12))
                    psi_nj = psi * (damp / nrm[:, None])
                    psi = jnp.where(occ[:, None], proj, psi_nj)
                    leaked = leaked.at[:, c].set(leaked[:, c] | occ)
            # (4) coupling pulses: ZX (cross-resonance) / ZZ (ef drive)
            # interactions with stochastic 2q depol.  Ordering contract:
            # same-step stages apply 1q-then-coupling-then-measure;
            # non-commuting cross-core sequences need barriers
            # (sim/device.py docstring, docs/PHYSICS.md).
            amp_f = pp[..., 3].astype(jnp.float32)
            pauli2 = jnp.asarray(_PAULI_2)
            for mk, (cc, fi, tt, kd) in zip(cp_masks, couplings):
                if has_leak:
                    # interactions involving a leaked core no-op (the
                    # |2> level is out of both transition manifolds)
                    mk = mk & ~leaked[:, cc] & ~leaked[:, tt]
                ref = zz90 if kd == 'zz' else zx90
                th = jnp.where(mk, (np.pi / 2) * amp_f[:, cc] / ref, 0.0)
                if kd == 'zz':
                    zz_row = (zsign[cc] * zsign[tt])[None, :]
                    arg = -0.5 * th[:, None] * zz_row
                    psi = psi * jax.lax.complex(jnp.cos(arg),
                                                jnp.sin(arg))
                else:
                    U4 = _sv_rot_zx(th, phi1[:, cc])
                    psi = _sv_apply_pair(psi, U4, cc, tt, C)
                if has_dp2:
                    occ = (traj_u[:, cc, 4] < depol2) & mk
                    pick = jnp.minimum(
                        (traj_u[:, cc, 5] * 15).astype(jnp.int32), 14)
                    sel = jnp.where(occ, pick + 1, 0)   # 0 = identity
                    P4 = jnp.einsum(
                        'bk,kxy->bxy',
                        jax.nn.one_hot(sel, 16, dtype=jnp.complex64),
                        pauli2)
                    psi = _sv_apply_pair(psi, P4, cc, tt, C)
                if has_leak2:
                    # coupling-induced leakage of the CONTROL (the
                    # strongly-driven core — the dominant 2q-gate
                    # mechanism on hardware): same CPTP unraveling as
                    # the 1q channel, drawing the shared leak column
                    # (1q and coupling exposures are exclusive per core
                    # per step — one instruction each)
                    p_eff = jnp.where(mk, leak2, 0.0)
                    p1c = jnp.sum(bit1[cc][None]
                                  * (psi.real**2 + psi.imag**2), -1)
                    occ = traj_u[:, cc, 6] < p_eff * p1c
                    proj = psi * (bit1[cc][None, :]
                                  / jnp.sqrt(jnp.maximum(p1c,
                                                         1e-12))[:, None])
                    damp = 1.0 - (1.0 - jnp.sqrt(1.0 - p_eff))[:, None] \
                        * bit1[cc][None, :]
                    nrm = jnp.sqrt(jnp.maximum(1.0 - p_eff * p1c, 1e-12))
                    psi_nj = psi * (damp / nrm[:, None])
                    psi = jnp.where(occ[:, None], proj, psi_nj)
                    leaked = leaked.at[:, cc].set(leaked[:, cc] | occ)
            # (5) measurement: joint projective collapse, sequential
            # conditioning across cores (exact joint distribution for
            # the commuting Z measurements of a step)
            u_sel = jnp.sum(meas_u * oh_mslot.astype(jnp.float32), -1)
            p1_cols, bit_cols = [], []
            for c in range(C):
                mc = is_meas_pulse[:, c]
                p1c = jnp.clip(jnp.sum(
                    bit1[c][None] * (psi.real**2 + psi.imag**2), -1),
                    0.0, 1.0)
                if has_leak and not leak_iq:
                    # fast path: a leaked core discriminates as
                    # leak_readout_bit (|2> sits near |1> in IQ space
                    # on most devices); no collapse — its slot was
                    # projected at leak time.  Forcing p1c to exactly
                    # 0/1 forces the uniform comparison below to the
                    # leak bit.
                    p1c = jnp.where(leaked[:, c], float(leak_bit), p1c)
                bitc = (u_sel[:, c] < p1c).astype(jnp.int32) \
                    * mc.astype(jnp.int32)
                if has_leak and leak_iq:
                    # IQ-level leakage readout: record device state 2 —
                    # the resolver synthesizes the window with the g2
                    # response and the read bit emerges from the demod
                    # chain (sim/physics.py _gs3 / _classify3_acc)
                    bitc = jnp.where(leaked[:, c] & mc, 2, bitc)
                keep = jnp.where(bitc[:, None] == 1, bit1[c][None, :],
                                 1.0 - bit1[c][None, :])
                p_sel = jnp.where(bitc == 1, p1c, 1.0 - p1c)
                proj = psi * (keep
                              / jnp.sqrt(jnp.maximum(p_sel, 1e-12))[:, None])
                do_proj = mc if not has_leak else mc & ~leaked[:, c]
                psi = jnp.where(do_proj[:, None], proj, psi)
                p1_cols.append(jnp.where(mc, p1c, 0.0))
                bit_cols.append(bitc)
            p1 = jnp.stack(p1_cols, axis=-1)                  # [B, C]
            state_bit = jnp.stack(bit_cols, axis=-1)
            if has_seep:
                # seepage |2> -> |1>: a drive pulse on a PRE-STEP-leaked
                # core un-leaks it with probability `seep` — it re-enters
                # in |1> (its psi slot is exactly the frozen |1>
                # bookkeeping state) from the next step; the seeping
                # pulse itself still no-ops (sim/device.py docstring)
                seep_occ = is_drive & st['leaked'] \
                    & (traj_u[..., 7] < seep)
                leaked = leaked & ~seep_occ
            phys_updates = dict(
                psi=psi, leaked=leaked,
                phys_t=jnp.where(touch, trig, st['phys_t']),
                meas_p1=jnp.where(mwr, p1[..., None], st['meas_p1']),
            )
        phys_updates.update(
            meas_state=jnp.where(mwr, state_bit[..., None],
                                 st['meas_state']),
            meas_amp=jnp.where(mwr, pp[..., 3:4], st['meas_amp']),
            meas_phase=jnp.where(mwr, pp[..., 1:2], st['meas_phase']),
            meas_freq=jnp.where(mwr, pp[..., 2:3], st['meas_freq']),
            meas_env=jnp.where(mwr, pp[..., 0:1], st['meas_env']),
            meas_gtime=jnp.where(mwr, trig[..., None], st['meas_gtime']),
            phys_wait=is_fproc & live & f_phys & ~f_ready,
        )

    # ---- phase reset record --------------------------------------------
    is_rst = (kind == isa.K_PULSE_RESET) & adv
    oh_rslot = _onehot(jnp.minimum(st['n_resets'], cfg.max_resets - 1),
                       cfg.max_resets)
    rst_time = jnp.where((oh_rslot == 1) & is_rst[..., None],
                         time[..., None], st['rst_time'])
    n_resets = st['n_resets'] + is_rst.astype(jnp.int32)

    # ---- idle ----------------------------------------------------------
    is_idle = (kind == isa.K_IDLE) & adv
    idle_end = offset + cmd_time
    missed_idle = is_idle & (time > idle_end)
    idle_end = jnp.maximum(idle_end, time)

    # ---- register writeback --------------------------------------------
    if any_regwrite:
        wr_reg = ((kind == isa.K_REG_ALU)
                  | (kind == isa.K_ALU_FPROC)) & adv
        wr_mask = (_onehot(g('out_reg'), isa.N_REGS) == 1) \
            & wr_reg[..., None]
        regs = jnp.where(wr_mask, alu_res[..., None], regs)

    # ---- next pc -------------------------------------------------------
    branch_taken = (alu_res & 1) == 1
    pc_next = jnp.select(
        [kind == isa.K_JUMP_I,
         (kind == isa.K_JUMP_COND) | (kind == isa.K_JUMP_FPROC)],
        [g('jump_addr'),
         jnp.where(branch_taken, g('jump_addr'), st['pc'] + 1)],
        st['pc'] + 1)
    if has_sync:
        pc_next = jnp.where(sync_adv, st['pc'] + 1, pc_next)
    is_done = (kind == isa.K_DONE) & adv
    pc_next = jnp.where(adv & ~is_done, pc_next, st['pc'])

    # ---- next time / qclk offset ---------------------------------------
    time_next = jnp.select(
        [is_pt, is_pw | is_rst, is_idle,
         (kind == isa.K_REG_ALU) | (kind == isa.K_INC_QCLK),
         (kind == isa.K_JUMP_I) | (kind == isa.K_JUMP_COND),
         is_fproc],
        [trig + cfg.pulse_load_clks,
         time + cfg.pulse_regwrite_clks,
         idle_end + cfg.pulse_load_clks,
         time + cfg.alu_instr_clks,
         time + cfg.jump_cond_clks,
         f_tready + cfg.jump_fproc_clks],
        time)
    if has_sync:
        time_next = jnp.where(sync_adv, release, time_next)
    time_next = jnp.where(adv, time_next, time)

    # inc_qclk loads qclk = alu_res (with hardware pipeline compensation,
    # reference: hdl/qclk.v:17); sync resets qclk to 0 at release
    offset_next = offset
    if has(isa.K_INC_QCLK):
        offset_next = jnp.where((kind == isa.K_INC_QCLK) & adv,
                                time - alu_res, offset_next)
    if has_sync:
        offset_next = jnp.where(sync_adv, release, offset_next)

    err = st['err'] | rec_of | meas_of | cw_meas_err | cofire_err \
        | jnp.where(missed_trig | missed_idle, ERR_MISSED_TRIG, 0)
    if any_fproc:
        err = err \
            | jnp.where(is_fproc & adv & fid_bad, ERR_FPROC_ID, 0) \
            | jnp.where(is_fproc & adv & f_deadlock,
                        ERR_FPROC_DEADLOCK, 0) \
            | jnp.where(is_fproc & adv & f_race, ERR_STICKY_RACE, 0)
    if has_sync:
        err = err | jnp.where(sync_adv & sync_err[:, None],
                              ERR_SYNC_DONE, 0)

    # ---- fault word (docs/ROBUSTNESS.md) -------------------------------
    # Engine-integrity traps, OR-ed on masks computed above — fault-free
    # lanes see pure zero ORs (bit-identity with the pre-fault engine).
    # An out-of-ISA kind falls through every dispatch select as a silent
    # no-op (the masked-to-no-op failure mode); an OOB pc/branch target
    # would be clipped at fetch and re-execute the last instruction.
    # Both are flagged instead of silently "working".
    fault = st['fault'] \
        | jnp.where(rec_of != 0, FAULT_PULSE_OVERFLOW, 0) \
        | jnp.where(meas_of != 0, FAULT_MEAS_OVERFLOW, 0) \
        | jnp.where(is_rst & (st['n_resets'] >= cfg.max_resets),
                    FAULT_RESET_OVERFLOW, 0) \
        | jnp.where(adv & ((kind < 0) | (kind >= isa.N_KINDS)),
                    FAULT_ILLEGAL_OP, 0) \
        | jnp.where(adv & ~is_done & ((pc_next < 0) | (pc_next >= N)),
                    FAULT_JUMP_OOB, 0)
    if any_fproc:
        fault = fault \
            | jnp.where(is_fproc & adv & fid_bad, FAULT_ILLEGAL_OP, 0) \
            | jnp.where(is_fproc & adv & f_deadlock,
                        FAULT_FPROC_STARVED, 0)
    if has_sync:
        fault = fault | jnp.where(sync_adv & sync_err[:, None],
                                  FAULT_SYNC_DEADLOCK, 0)
    # transient (popped by the engines before the carry repacks): lanes
    # stalled AT a sync barrier this step — classifies a later hard
    # quiescence as SYNC_DEADLOCK vs FPROC_STARVED
    stall_sync = (at_sync & ~sync_ready[:, None] & live) if has_sync \
        else jnp.zeros((B, C), bool)

    hist = {}
    if 'op_hist' in st:
        # retired-instruction histogram: one count per (shot, core) per
        # executed step, bucketed by kind — engine-invariant by
        # construction (stalled cores retire nothing)
        hist['op_hist'] = st['op_hist'] \
            + _onehot(kind, isa.N_KINDS) * adv[..., None]

    tr = {}
    if cfg.trace:
        # instruction-trace export: the simulator's VCD analog
        # (reference traces RTL waveforms via Verilator --trace)
        tr['trace_pc'] = jax.lax.dynamic_update_slice(
            st['trace_pc'], st['pc'][:, :, None], (0, 0, step_i))
        tr['trace_time'] = jax.lax.dynamic_update_slice(
            st['trace_time'], time[:, :, None], (0, 0, step_i))
        # per-step qclk origin: lets the VCD export render qclk exactly
        # at every timestamp (sync/inc_qclk changes take effect at their
        # step instead of ramping retroactively)
        tr['trace_off'] = jax.lax.dynamic_update_slice(
            st['trace_off'], offset[:, :, None], (0, 0, step_i))

    return dict(st, pc=pc_next, regs=regs, time=time_next, offset=offset_next,
                done=st['done'] | is_done, err=err, fault=fault,
                _stall_sync=stall_sync, pp=pp, n_pulses=n_pulses,
                n_resets=n_resets, rst_time=rst_time,
                n_meas=n_meas, meas_avail=meas_avail,
                **({'meas_time': meas_time} if 'meas_time' in st else {}),
                **rec_update, **phys_updates, **hist, **tr)


def _split_records(rec) -> dict:
    """Split the flat field-major ``[B, C, F*P]`` record tensor into
    named ``rec_*`` field arrays (each ``[B, C, P]``)."""
    F = len(_REC_FIELDS)
    rec4 = rec.reshape(rec.shape[:-1] + (F, rec.shape[-1] // F))
    return {'rec_' + n: rec4[..., i, :] for i, n in enumerate(_REC_FIELDS)}


def _exec_loop(st0: dict, soa, spc, interp, sync_part, meas_bits, meas_valid,
               cfg: InterpreterConfig, dev=None, traits=None) -> dict:
    """Run the instruction while_loop until every shot is done (or, in
    physics mode, paused waiting for a measurement bit to be resolved).

    ``st0`` must carry ``_steps`` (total step budget, shared across
    physics epochs) and, in physics mode, ``paused`` [B] bool.  ``dev``:
    device-model parameter arrays for ``device='bloch'``
    (``(det_cyc[C], inv_t1[C], inv_t2[C], depol, meas_u[B,C,M])``) —
    step-body closure constants, not loop-carried.
    """
    # packed-control carry (cfg.packed_ctrl): every [B, C] int32/bool
    # leaf rides the loop as one [K, B, C] stack — K-major so no lane
    # padding — unpacked at the body edge (slices fuse into consumers)
    B_, C_ = st0['pc'].shape
    pack_keys = tuple(sorted(
        k for k, v in st0.items()
        if getattr(v, 'ndim', None) == 2 and v.shape == (B_, C_)
        and v.dtype in (jnp.dtype('int32'), jnp.dtype('bool')))) \
        if cfg.packed_ctrl else ()
    bool_keys = frozenset(k for k in pack_keys
                          if st0[k].dtype == jnp.dtype('bool'))
    ax = cfg.cores_axis

    def _all_cores(x):
        """``all()`` over the FULL core axis of a ``[B, C]`` mask —
        an ``all_gather`` over ``cfg.cores_axis`` when sharded (every
        shard computes the identical [B] result), the plain local
        reduction otherwise."""
        if ax is not None:
            x = jax.lax.all_gather(x, ax, axis=1, tiled=True)
        return jnp.all(x, axis=-1)

    def _more_of(st):
        """The while condition as a carried scalar: shard_map forbids
        collectives in a ``while_loop`` cond, so the sharded path
        computes the (replicated) predicate in the body and the cond
        just reads it."""
        settled = _all_cores(st['done'])
        if cfg.physics:
            settled = settled | st['paused']
        return (~jnp.all(settled)) & (st['_steps'] < cfg.max_steps)

    def pack(st):
        if not pack_keys:
            return st
        ctrl = jnp.stack([st[k].astype(jnp.int32) for k in pack_keys], 0)
        rest = {k: v for k, v in st.items() if k not in pack_keys}
        return dict(rest, _ctrl=ctrl)

    def unpack(st):
        if not pack_keys:
            return st
        st = dict(st)
        ctrl = st.pop('_ctrl')
        for idx, k in enumerate(pack_keys):
            st[k] = ctrl[idx].astype(bool) if k in bool_keys else ctrl[idx]
        return st

    def cond(carry):
        st = unpack(carry)
        if ax is not None:
            return st['_more']
        settled = jnp.all(st['done'], axis=-1)
        if cfg.physics:
            settled = settled | st['paused']
        return (~jnp.all(settled)) & (st['_steps'] < cfg.max_steps)

    def one(st):
        steps = st.pop('_steps')
        paused = st.pop('paused') if cfg.physics else None
        st_in = st
        st2 = _step(st, steps, soa, spc, interp, sync_part, meas_bits,
                    meas_valid, cfg, dev, traits)
        stall_sync = st2.pop('_stall_sync')
        # quiescence detection per shot: no live core changed state
        # (over the FULL core axis — a shard whose local lanes froze
        # must not settle while a remote producer still runs)
        same = _all_cores((st2['pc'] == st['pc'])
                          & (st2['time'] == st['time'])
                          & (st2['done'] == st['done']))         # [B]
        if cfg.physics:
            # quiescent + a core awaiting an unresolved measurement bit
            # = pause for the epoch resolver; quiescent without one is a
            # genuine deadlock as in the non-physics engine
            pending = jnp.any(st2['phys_wait'] & ~st2['done'], axis=-1)
            st2['paused'] = paused | (same & pending)
            hard = same & ~pending
        else:
            hard = same
        undone = hard[:, None] & ~st2['done']
        st2['err'] = jnp.where(undone, st2['err'] | ERR_FPROC_DEADLOCK,
                               st2['err'])
        # trap classification at hard quiescence: a lane parked at a
        # sync barrier that can never release vs. any other stall
        # (fproc wait with no producer able to deliver)
        st2['fault'] = st2['fault'] \
            | jnp.where(undone & stall_sync, FAULT_SYNC_DEADLOCK, 0) \
            | jnp.where(undone & ~stall_sync, FAULT_FPROC_STARVED, 0)
        st2['done'] = st2['done'] | hard[:, None]
        # exactness select: steps applied past the max_steps budget or
        # after the batch settles must be true no-ops — a scalar-
        # predicate select per carry leaf.  With steps_per_iter=1 the
        # while condition would have exited exactly there, so the select
        # is an identity; it is load-bearing for (a) sub-steps inside a
        # k>1 unrolled body (the condition is only evaluated between
        # k-step bodies) and (b) the multi-program path, where vmap
        # lifts the while condition to an OR over program lanes and
        # settled programs keep receiving the body until the slowest
        # lane finishes.
        settled_in = _all_cores(st_in['done'])
        if cfg.physics:
            st_in = dict(st_in, paused=paused)
            settled_in = settled_in | paused
        ok = (steps < cfg.max_steps) & ~jnp.all(settled_in)
        st2 = {k: jnp.where(ok, v, st_in[k]) for k, v in st2.items()}
        st2['_steps'] = jnp.where(ok, steps + 1, steps)
        return st2

    def body(carry):
        # static unroll: k sub-steps per while iteration (see
        # InterpreterConfig.steps_per_iter)
        st = unpack(carry)
        for _ in range(max(1, cfg.steps_per_iter)):
            st = one(st)
        if ax is not None:
            st['_more'] = _more_of(st)
        return pack(st)

    if ax is not None:
        st0 = dict(st0, _more=_more_of(st0))
    out = unpack(jax.lax.while_loop(cond, body, pack(st0)))
    out.pop('_more', None)
    return out


# AUTO straight-line cap: unrolling emits O(n_instr) specialized step
# bodies into one XLA module — past this, compile time outgrows the
# run-time win (depth-100 RB stays on the generic engine)
SL_AUTO_MAX_INSTR = 256


def _soa_static(mp) -> tuple:
    """The decoded program as a hashable jit-static value: the
    straight-line executor specializes per instruction at trace time,
    so the program must be a compile-time constant (bytes hash the
    content, so identical programs share the jit cache entry)."""
    arr = np.stack([np.asarray(getattr(mp.soa, f)) for f in _FIELDS],
                   axis=-1).astype(np.int32)
    return (arr.tobytes(), arr.shape)


def _soa_from_static(sl: tuple) -> np.ndarray:
    buf, shape = sl
    return np.frombuffer(buf, np.int32).reshape(shape)


def use_straightline(mp, cfg: InterpreterConfig) -> bool:
    """Resolve the tri-state ``cfg.straightline`` against ``mp``."""
    if cfg.straightline is False:
        return False
    reason = straightline_ineligible(mp, cfg)
    if cfg.straightline is True:
        if reason:
            raise ValueError(f'straightline=True but the program is '
                             f'ineligible: {reason}')
        return True
    return reason is None and mp.n_instr <= SL_AUTO_MAX_INSTR


def straightline_ineligible(mp, cfg: InterpreterConfig) -> str:
    """Why ``(mp, cfg)`` cannot run on the emitted straight-line
    executor (:func:`_exec_straightline`) — ``None`` when it can.

    Eligible programs are forward-jump-only (no loops), SYNC-free,
    DONE-terminated, with fproc reads only of the core's own sticky
    channel — the compiled active-reset + RB shape.  Everything else
    (loops, LUT/fresh fabrics, cross-core feedback, the statevec event
    gate, trace mode) runs on the generic fetch-dispatch engine.
    """
    if cfg.trace:
        return 'trace mode records per-step state'
    if cfg.physics and cfg.device == 'statevec':
        return 'statevec device (event-ordering gate needs the ' \
               'generic engine)'
    soa_np = _soa_from_static(_soa_static(mp)) \
        if cfg.fabric == 'lut' else None
    return _sl_ineligible_fields(np.asarray(mp.soa.kind),
                                 np.asarray(mp.soa.jump_addr),
                                 np.asarray(mp.soa.func_id), cfg,
                                 soa_np)


def _sl_ineligible_fields(kind, jump_addr, func_id,
                          cfg: InterpreterConfig, soa_np=None) -> str:
    """The straight-line SHAPE checks of :func:`straightline_ineligible`
    on packed field arrays — shared with the pallas dispatch, which
    re-derives span-vs-block mode from the jit-static program
    (:func:`_pallas_mode`) so the two decisions cannot drift.

    ``soa_np``: the full packed ``[C, N, F]`` field array, needed only
    for the lut-fabric fproc admission (:func:`_lut_span_reject`'s
    trigger-ordering dataflow); ``None`` conservatively rejects that
    combination."""
    C, N = kind.shape
    if np.any(kind == isa.K_SYNC):
        return 'SYNC barrier'
    idx = np.arange(N)[None, :]
    jmask = (kind == isa.K_JUMP_I) | (kind == isa.K_JUMP_COND) \
        | (kind == isa.K_JUMP_FPROC)
    if np.any(jmask & (jump_addr <= idx)):
        return 'backward jump (loop)'
    fmask = (kind == isa.K_ALU_FPROC) | (kind == isa.K_JUMP_FPROC)
    if np.any(fmask):
        if cfg.fabric == 'sticky':
            if np.any(fmask & (func_id != np.arange(C)[:, None])):
                return 'cross-core fproc read'
        elif cfg.fabric == 'lut':
            reason = _lut_span_reject(soa_np, fmask, func_id, cfg)
            if reason:
                return reason
        else:
            return f'fabric {cfg.fabric!r} with fproc reads'
    if np.any(kind[:, -1] != isa.K_DONE):
        return 'program not DONE-terminated'
    return None


def _lut_span_reject(soa_np, fmask, func_id,
                     cfg: InterpreterConfig) -> str:
    """Why LUT-fabric fproc reads cannot be served IN-SPAN
    (straightline / pallas-span / fused) — ``None`` when they can.

    The span serves a LUT read from the carry planes at the read's
    instruction index with no producer synchronization.  That is
    bit-identical to the generic per-step serve (a time-indexed count
    select over the planes, :func:`_step`) exactly when the planes are
    already FINAL at the read's index: the span's ascending index loop
    applies every earlier index to every core first, so the condition
    is that every masked core's **possibly-measurement** trigger sits
    at a strictly earlier instruction index than every fproc read.
    Drive triggers never touch the measurement planes, so only
    possibly-measurement triggers (cfg-nibble possible-values
    analysis, :func:`_possibly_meas_mask`) constrain the ordering —
    a syndrome round's feedback *corrections* after the read are fine.
    Own-fresh reads (``func_id == 0``) keep per-step stall semantics
    and stay span-ineligible; the block engine hosts them.
    """
    if soa_np is None:
        return "fabric 'lut' with fproc reads"
    if np.any(fmask & (func_id == 0)):
        return ("own-fresh fproc read (func_id=0) under fabric 'lut' "
                "(per-step stall semantics — block engine hosts it)")
    if cfg.lut_mask is None or cfg.lut_table is None:
        return "fabric 'lut' with fproc reads but no lut_mask/lut_table"
    C = fmask.shape[0]
    lmask = np.asarray(cfg.lut_mask, dtype=bool)
    if lmask.shape[0] != C:
        return (f'lut_mask length {lmask.shape[0]} != n_cores {C}')
    pm = _possibly_meas_mask(soa_np, cfg)
    if pm is None:
        return "fabric 'lut' with fproc reads in a looping program"
    min_read = int(np.min(np.nonzero(fmask)[1]))
    if np.any(pm[lmask, min_read:]):
        return ("fabric 'lut': a masked core's possibly-measurement "
                "trigger at or after an fproc read index (measurement "
                "planes not final at the span serve; the block engine "
                "hosts this shape)")
    return None


# AUTO block-mode cap on the total DEDUPED unrolled body length: every
# outer iteration traces one generic boundary step plus every deduped
# body, so both compile time and per-iteration run time scale with this
# sum — past it, the generic engine's shared step body wins back
BLOCK_AUTO_MAX_UNROLL = 512

ENGINES = ('auto', 'generic', 'block', 'straightline', 'pallas', 'fused')

# backends where 'auto' considers the pallas megastep engine: mosaic
# kernels only COMPILE on real TPUs — elsewhere they would run under
# the pallas interpreter, which is strictly slower than the XLA
# engines (tests monkeypatch this to exercise the auto rung on CPU)
_PALLAS_AUTO_BACKENDS = ('tpu',)


def block_ineligible(mp, cfg: InterpreterConfig) -> str:
    """Why ``(mp, cfg)`` cannot run on the block-compiled engine
    (:func:`_exec_blocks`) — ``None`` when it can.

    Block mode keeps loops, forward/backward jumps, SYNC, cross-core
    fproc reads, and non-DONE termination (the generic boundary step
    handles all of them), so almost everything straightline rejects is
    fine here.  Every fabric is eligible: sticky and fresh reads are
    interleaving-final (once a producer's clock passes the request,
    nothing it still executes can change the served value —
    ``MEAS_LATENCY`` > ``STICKY_RACE_MARGIN``), and LUT reads are
    TIME-INDEXED (per masked producer, the newest bit whose production
    clock precedes the request — ``meas_time`` plane, docs/PERF.md
    "Feedback on the fast engines"), a pure function of the planes and
    the request time, so block-granular producer progress serves
    bit-identical data by construction.  fproc kinds are block
    TERMINATORS (:data:`isa.BLOCK_TERMINATORS`), so every read is
    served by the generic boundary :func:`_step` with gathered fabric
    state.  What block mode cannot keep:

    * trace mode — per-instruction-step trace writes are indexed by the
      step counter, which block mode collapses to iterations;
    * the statevec event-ordering gate — pulse triggers must be globally
      serialized per instruction step.
    """
    if cfg.trace:
        return 'trace mode records per-instruction-step state'
    if cfg.physics and cfg.device == 'statevec':
        return 'statevec device (event-ordering gate needs the ' \
               'generic engine)'
    return None


def pallas_ineligible(mp, cfg: InterpreterConfig) -> str:
    """Why ``(mp, cfg)`` cannot run on the Pallas megastep engine
    (``engine='pallas'``) — ``None`` when it can.

    The megastep kernel executes straight-line instruction runs with
    the carry resident in VMEM, in one of two modes picked per program
    (:func:`_pallas_mode`): a forward-jump-only program runs WHOLE as
    one span kernel; anything else runs on the block engine's outer
    loop with each superinstruction body lowered to a kernel.  So
    eligibility is the straight-line rules OR the block rules, minus
    what the kernel itself cannot host:

    * no pallas support in this jax build;
    * trace mode (per-step trace writes, as for the other rungs);
    * physics mode — the device co-state and the epoch resolver's
      pause/validate loop are float/host-side machinery; the XLA
      engines keep that path.
    """
    from ..ops._pallas_common import HAS_PALLAS
    if not HAS_PALLAS:
        return 'jax.experimental.pallas unavailable in this jax build'
    if cfg.trace:
        return 'trace mode records per-step state'
    if cfg.physics:
        return 'physics mode (device co-state + epoch resolver run ' \
               'on the XLA engines)'
    if straightline_ineligible(mp, cfg) is None:
        return None
    return block_ineligible(mp, cfg)


def fused_ineligible(mp, cfg: InterpreterConfig) -> str:
    """Why ``(mp, cfg)`` cannot run on the fused measure-in-megastep
    engine (``engine='fused'``) — ``None`` when it can.

    The fused engine is the span megastep kernel with the measurement
    chain grafted INTO the kernel body: when the span hits a
    measurement trigger it demodulates the readout window in VMEM and
    lands the bit in the carry's measurement slot, so a
    branch-on-measurement program retires in ONE kernel pass — no
    epoch ``while_loop`` round-trips out to the resolver (docs/PERF.md
    "fused epoch").  That only types out for:

    * physics-closed runs — the injected-bits entry points have no
      readout window to demodulate (``sim.physics.run_physics_batch``
      is the entry point);
    * the parity device — the in-kernel discriminator consumes the
      deterministic quarter-turn co-state (bloch/statevec projections
      draw host-side uniforms the kernel cannot host);
    * span-shaped programs (the straight-line field rules) whose
      measurement count has a static bound within ``max_meas`` — an
      overflowing program re-resolves slot ``max_meas - 1`` with
      epoch-boundary ordering the single pass cannot reproduce;
    * no CW measurement windows (``cw_horizon == 0``) — a CW window
      has no static length for the in-kernel energy mask.

    Model-level gates (sigma == 0, white noise, no ring-up, 2-class
    discrimination, statically-enumerable envelope addresses) live in
    :func:`..sim.physics.run_physics_batch`, which owns the readout
    model this engine specializes.
    """
    from ..ops._pallas_common import HAS_PALLAS
    if not HAS_PALLAS:
        return 'jax.experimental.pallas unavailable in this jax build'
    if not cfg.physics:
        return ('injected-bits run (no readout window to demodulate) ' \
                '— the fused engine closes the physics loop; run via ' \
                'sim.physics.run_physics_batch')
    if cfg.device != 'parity':
        return (f'device {cfg.device!r} (the in-kernel discriminator '
                f'consumes the parity quarter-turn co-state)')
    if cfg.cw_horizon > 0:
        return 'CW measurement windows (cw_horizon > 0) have no ' \
               'static length'
    if cfg.trace:
        return 'trace mode records per-step state'
    soa_np = _soa_from_static(_soa_static(mp))
    reason = _sl_ineligible_fields(np.asarray(mp.soa.kind),
                                   np.asarray(mp.soa.jump_addr),
                                   np.asarray(mp.soa.func_id), cfg,
                                   soa_np)
    if reason:
        return reason
    mb, _ = _static_meas_bounds(soa_np, cfg)
    if mb is None:
        return 'measurement count not statically boundable'
    if mb > cfg.max_meas:
        return (f'static measurement bound {mb} exceeds max_meas='
                f'{cfg.max_meas} (overflow re-resolves the last slot '
                f'with epoch-boundary ordering)')
    return None


def cores_ineligible(mp, cfg: InterpreterConfig) -> str:
    """Why ``(mp, cfg)`` cannot run sharded over a ``'cores'`` mesh
    axis (``cfg.cores_axis`` — docs/PERF.md "ICI fabric") — ``None``
    when it can.

    Sharded execution runs the generic engine inside ``shard_map``
    with the fproc fabric and the sync barrier reading producer-side
    state through ``lax.all_gather`` over the cores axis —
    or, for ``engine='block'``, the block engine under GSPMD: the
    same single-device trace jitted against cores-sharded inputs, XLA
    inserting the fabric collectives at the boundary-step gathers
    (``parallel.sweep`` hosts the executor; bit-identical because the
    trace IS the single-device block engine).  Both are bit-identical
    to the single-device run by construction.  What the collective
    step cannot host:

    * physics mode — the epoch resolver pauses host-side between
      epochs and draws global-shape noise streams; the bloch/statevec
      device co-state is not core-separable;
    * an explicitly forced PER-SHOT-SPECIALIZED engine — straightline
      / pallas / fused trace per-program span bodies with no
      collective fabric (the block engine's boundary ``_step`` is the
      generic fabric step, so it shards; the span kernels do not);
    * trace mode — the per-step trace export assembles the full core
      axis on one host (a single-device debugging surface).
    """
    if cfg.physics:
        return ('physics mode (the epoch resolver pauses host-side '
                'between epochs and draws global-shape noise streams)')
    if cfg.engine == 'block':
        reason = block_ineligible(mp, cfg)
        if reason:
            return (f"engine='block' under cores_axis but the program "
                    f'is block-ineligible: {reason}')
    elif cfg.engine not in (None, 'auto', 'generic'):
        return (f'engine={cfg.engine!r} (the span-specialized engines '
                f'trace per-program bodies with no collective fabric — '
                f'the generic step and the block engine read through '
                f'the cores-axis gathers)')
    if cfg.straightline:
        return ('straightline=True (emitted straight-line execution '
                'has no collective fabric)')
    if cfg.trace:
        return ('trace mode assembles the full-core-axis per-step '
                'trace on one device')
    return None


@functools.lru_cache(maxsize=128)
def _block_plan(blk: tuple):
    """Cached block table for a static program: ``(bid_at, bodies)``
    from :func:`isa.build_block_table` keyed on program content."""
    soa_np = _soa_from_static(blk)
    bid_at, bodies = isa.build_block_table(
        {name: soa_np[:, :, _F[name]] for name in _FIELDS})
    return bid_at, tuple(bodies)


def _soa_traits(soa_np) -> tuple:
    """:func:`program_traits` over a packed ``[C, N, F]`` field array."""
    return (frozenset(int(k)
                      for k in np.unique(soa_np[..., _F['kind']])),
            bool(np.any(soa_np[..., _F['in0_is_reg']])),
            bool(np.any(soa_np[..., _F['p_regsel']])))


def resolve_engine(mp, cfg: InterpreterConfig) -> str:
    """Resolve ``cfg.engine`` against the program: the engine ladder.

    ``None`` preserves the legacy ``cfg.straightline`` tri-state
    (straightline vs generic only); ``'generic'`` / ``'straightline'``
    / ``'block'`` / ``'pallas'`` / ``'fused'`` force an engine (the
    specialized engines raise with the ineligibility reason —
    ``'fused'`` is the physics-only measure-in-megastep rung, never
    picked by ``'auto'`` because its remaining gates live in the
    readout MODEL, which the program/config pair cannot see);
    ``'auto'`` walks the ladder — pallas first on TPU backends
    (:data:`_PALLAS_AUTO_BACKENDS`) where eligible under the same size
    caps as the XLA rung it subsumes, then straight-line when eligible
    and small enough to unroll, then block when eligible and the
    deduped body total is under :data:`BLOCK_AUTO_MAX_UNROLL` (and at
    least one body exists), else generic.  Returns one of
    ``'generic' | 'block' | 'straightline' | 'pallas' | 'fused'``.
    """
    eng = cfg.engine
    if cfg.cores_axis is not None:
        # sharded-cores execution is its own eligibility dimension:
        # the collective fabric lives in the generic step body, which
        # also serves the block engine's boundary steps — so a set
        # cores_axis resolves to 'generic', or to 'block' when forced
        # (GSPMD executor, parallel.sweep), or raises with the
        # blocker, same ladder-naming style as the rungs.  'auto'
        # stays on 'generic': the sharded block path pays a gather
        # per boundary step either way, and the generic step is the
        # measured baseline (docs/PERF.md "ICI fabric").
        reason = cores_ineligible(mp, cfg)
        if reason:
            raise ValueError(f'cores_axis={cfg.cores_axis!r} but the '
                             f'program/config is ineligible for '
                             f'sharded-cores execution: {reason}')
        return 'block' if eng == 'block' else 'generic'
    if eng is None:
        return 'straightline' if use_straightline(mp, cfg) else 'generic'
    if eng == 'generic':
        return 'generic'
    if eng == 'straightline':
        reason = straightline_ineligible(mp, cfg)
        if reason:
            raise ValueError(f"engine='straightline' but the program "
                             f"is ineligible: {reason}")
        return 'straightline'
    if eng == 'block':
        reason = block_ineligible(mp, cfg)
        if reason:
            raise ValueError(f"engine='block' but the program is "
                             f"ineligible: {reason}")
        return 'block'
    if eng == 'pallas':
        reason = pallas_ineligible(mp, cfg)
        if reason:
            raise ValueError(f"engine='pallas' but the program is "
                             f"ineligible: {reason}")
        return 'pallas'
    if eng == 'fused':
        reason = fused_ineligible(mp, cfg)
        if reason:
            raise ValueError(f"engine='fused' (measure-in-megastep) "
                             f"but the program/config is ineligible: "
                             f"{reason}")
        return 'fused'
    if eng == 'auto':
        sl_ok = straightline_ineligible(mp, cfg) is None
        if jax.default_backend() in _PALLAS_AUTO_BACKENDS \
                and pallas_ineligible(mp, cfg) is None:
            # same size caps as the XLA rung the kernel would subsume:
            # past them, trace/compile cost dominates either way
            if sl_ok and mp.n_instr <= SL_AUTO_MAX_INSTR:
                return 'pallas'
            if not sl_ok:
                _, bodies = _block_plan(_soa_static(mp))
                if bodies and sum(L for _, L in bodies) \
                        <= BLOCK_AUTO_MAX_UNROLL:
                    return 'pallas'
        if sl_ok and mp.n_instr <= SL_AUTO_MAX_INSTR:
            return 'straightline'
        if block_ineligible(mp, cfg) is None:
            _, bodies = _block_plan(_soa_static(mp))
            if bodies and sum(L for _, L in bodies) \
                    <= BLOCK_AUTO_MAX_UNROLL:
                return 'block'
        return 'generic'
    raise ValueError(f'unknown engine {eng!r}; one of {ENGINES} or None')


def _exec_straightline(st0: dict, soa_np, spc, interp, meas_bits,
                       meas_valid, cfg: InterpreterConfig,
                       dev=None) -> dict:
    """One emitted pass over a forward-jump-only program (round-5 exec
    lever (b), docs/PERF.md "the measured overhead budget").

    The program is unrolled at TRACE time: per instruction index the
    step body is specialized against the instruction's static fields
    (numpy constants), so the generic engine's per-step program fetch
    (one-hot/gather over N), opcode dispatch (select chains over every
    kind), and while-loop carry round-trips through HBM all vanish
    from the compiled module.  Kinds absent at an index emit NOTHING —
    an RB-body pulse instruction compiles to just the pulse block.

    Control flow: each lane carries ``pc`` = next instruction index;
    a lane executes index ``i`` iff ``pc == i`` (forward jumps skip by
    setting ``pc`` past the skipped range — every index is visited at
    most once, so one pass retires every lane).  A physics-mode fproc
    read whose own bit is still invalid stalls the lane for this pass
    (``phys_wait``): the epoch resolver validates the bit and the next
    pass resumes from the same index.  Timing, error-bit, record, and
    device-co-state semantics match :func:`_step` exactly — pinned by
    tests/test_straightline.py engine-equality on shared programs.
    """
    N = soa_np.shape[1]
    st = dict(st0)
    stalled = jnp.zeros(st0['pc'].shape, bool)

    for i in range(N):
        f = {name: np.asarray(soa_np[:, i, _F[name]])
             for name in _FIELDS}
        st, stalled = _sl_apply_instr(st, stalled, i, N, f, spc, interp,
                                      meas_bits, meas_valid, cfg, dev)

    # every non-stalled lane retired at its DONE (forward-only, DONE-
    # terminated by eligibility)
    if cfg.physics:
        st['phys_wait'] = stalled
    st['_steps'] = st['_steps'] + N
    return st


def _sl_apply_instr(st: dict, stalled, i: int, N: int, f: dict, spc,
                    interp, meas_bits, meas_valid,
                    cfg: InterpreterConfig, dev=None, fused=None):
    """Apply instruction index ``i`` (static fields ``f``, one value
    per core) to every lane with ``pc == i`` — the straight-line
    engine's per-instruction step body, shared verbatim with the
    pallas megastep kernel (:func:`_exec_span_pallas`) so the two
    engines are bit-identical by construction.  Returns the updated
    ``(st, stalled)`` pair; ``st`` leaves are ``[B, C, ...]`` (``B``
    is a shot TILE inside the kernel).

    ``fused``: the measure-in-megastep directive
    (:func:`_exec_span_pallas_fused`) — energy tables, responses, and
    static window metadata.  When set, a measurement trigger also
    demodulates its readout window HERE and writes the discriminated
    bit into ``st['meas_bits']`` / ``st['meas_valid']`` (carried as
    STATE), so a later fproc read of the same slot never stalls."""
    st = dict(st)
    B, C = st['pc'].shape
    pmask_np = _PMASKS
    kind = f['kind']
    m_pw, m_pt = kind == isa.K_PULSE_WRITE, kind == isa.K_PULSE_TRIG
    m_rst, m_idle = kind == isa.K_PULSE_RESET, kind == isa.K_IDLE
    m_regalu, m_incq = kind == isa.K_REG_ALU, kind == isa.K_INC_QCLK
    m_jmpi, m_jcond = kind == isa.K_JUMP_I, kind == isa.K_JUMP_COND
    m_jfp, m_afp = kind == isa.K_JUMP_FPROC, kind == isa.K_ALU_FPROC
    m_done = kind == isa.K_DONE
    m_fproc = m_jfp | m_afp
    m_alu = m_regalu | m_incq | m_jcond | m_jfp | m_afp
    has = lambda m: bool(np.any(m))
    j = lambda a: jnp.asarray(np.asarray(a))[None]       # [1, C]

    active = (st['pc'] == i) & ~st['done'] & ~stalled
    time, offset, regs = st['time'], st['offset'], st['regs']
    err_i = jnp.zeros((B, C), jnp.int32)
    fault_i = jnp.zeros((B, C), jnp.int32)
    # out-of-ISA kind at this index retires as a silent no-op in
    # every emitted block below — trap it (static mask, free when
    # the program is well-formed)
    m_badkind = (kind < 0) | (kind >= isa.N_KINDS)
    if has(m_badkind):
        fault_i = fault_i | jnp.where(j(m_badkind), FAULT_ILLEGAL_OP,
                                      0)

    def reg_read_static(addr_c):
        oh = (np.asarray(addr_c)[:, None]
              == np.arange(isa.N_REGS)[None, :]).astype(np.int32)
        return jnp.sum(regs * jnp.asarray(oh)[None], axis=-1)

    # ---- fproc: own-core sticky read, or time-indexed LUT read ---
    if has(m_fproc) and cfg.fabric == 'lut':
        # span-lut serve (eligibility: _sl_ineligible_fields requires
        # every masked core's possibly-measurement triggers at indices
        # strictly BEFORE every fproc read, so at this index the
        # bit/timestamp planes are FINAL): the time-indexed count
        # select over final planes — newest bit per masked producer
        # with production clock strictly below the request — returns
        # exactly what the generic per-step serve returns after its
        # causality stall, with no stall needed (the stall delays the
        # serve, never the served value).  Fused mode passes its
        # carry-resident bit/valid planes as the meas args, so the
        # in-kernel chain joins here unchanged.
        req = time
        lmask = np.asarray(cfg.lut_mask, dtype=bool)        # [C]
        shifts = np.zeros(len(lmask), dtype=np.int32)
        shifts[lmask] = np.arange(int(lmask.sum()))
        lmask_j = jnp.asarray(lmask)
        rec = jnp.arange(cfg.max_meas)[None, None, :] \
            < st['n_meas'][:, :, None]                       # [B, C, M]
        early = rec[:, None, :, :] \
            & (st['meas_time'][:, None, :, :] < req[:, :, None, None])
        cnt = jnp.sum(early.astype(jnp.int32), -1)           # [B, C, C]
        oh_sel = _onehot(jnp.maximum(cnt - 1, 0), cfg.max_meas)
        bit = jnp.sum(meas_bits[:, None, :, :] * oh_sel, -1)
        avail_sel = jnp.sum(
            jnp.where(st['meas_avail'] == INT32_MAX, 0,
                      st['meas_avail'])[:, None, :, :] * oh_sel, -1)
        valid_sel = jnp.sum(
            meas_valid.astype(jnp.int32)[:, None, :, :] * oh_sel, -1)
        l_valid = jnp.all(jnp.where(lmask_j[None, None, :],
                                    valid_sel == 1, True), -1)
        t_lut = jnp.max(jnp.where(lmask_j[None, None, :], avail_sel, 0),
                        axis=-1)                             # [B, C]
        addr = jnp.sum(bit * lmask_j[None, None, :]
                       * (1 << jnp.asarray(shifts))[None, None, :], -1)
        table = jnp.asarray(cfg.lut_table, jnp.int32)
        entry = _ohsel(table[None, None, :], _onehot(addr, len(table)))
        f_data = (entry >> jnp.arange(C, dtype=jnp.int32)[None, :]) & 1
        f_race = jnp.zeros((B, C), bool)
        f_tready = jnp.maximum(req, t_lut)
        # a masked producer that retired with NO recorded measurement
        # starves every reader: the generic engine quiesces and marks
        # exactly this err/fault pair (_exec_loop / _exec_blocks), with
        # the reader's pc/time frozen at the read — replicate that
        # terminal here (the lane leaves `active`, so nothing below
        # advances it)
        starved = jnp.any(lmask_j[None, None, :]
                          & (st['n_meas'][:, None, :] == 0), -1)
        starve_i = active & j(m_fproc) & starved
        st['err'] = st['err'] | jnp.where(starve_i,
                                          ERR_FPROC_DEADLOCK, 0)
        st['fault'] = st['fault'] | jnp.where(starve_i,
                                              FAULT_FPROC_STARVED, 0)
        st['done'] = st['done'] | starve_i
        active = active & ~starve_i
        # an invalid SELECTED slot stalls the lane (physics: the epoch
        # resolver validates it and the next pass resumes) — mirrors
        # the generic serve's f_phys = l_causal & ~l_valid
        stall_i = active & j(m_fproc) & ~l_valid
        stalled = stalled | stall_i
        active = active & ~stall_i
    elif has(m_fproc):
        # own-core sticky read (eligibility guarantees)
        req = time
        mavail, bitsq = st['meas_avail'], meas_bits
        m_cnt = jnp.sum((mavail <= req[..., None]).astype(jnp.int32),
                        -1)
        oh_latest = _onehot(jnp.maximum(m_cnt - 1, 0), cfg.max_meas)
        latest_valid = (m_cnt == 0) | (_ohsel(
            meas_valid.astype(jnp.int32), oh_latest) == 1)
        f_data = jnp.where(m_cnt > 0, _ohsel(bitsq, oh_latest), 0)
        f_race = jnp.any(
            (mavail > (req - STICKY_RACE_MARGIN)[..., None])
            & (mavail <= (req + STICKY_RACE_MARGIN)[..., None]), -1)
        f_tready = time
        f_ready = latest_valid
        stall_i = active & j(m_fproc) & ~f_ready
        stalled = stalled | stall_i
        active = active & ~stall_i
    else:
        f_data = jnp.int32(0)

    # ---- ALU -----------------------------------------------------
    if has(m_alu):
        in0 = jnp.where(j(f['in0_is_reg'] == 1),
                        reg_read_static(f['in0_reg']), j(f['imm'])) \
            if np.any(f['in0_is_reg'][m_alu]) else j(f['imm'])
        in1 = jnp.int32(0)
        if np.any(m_regalu | m_jcond):
            in1 = reg_read_static(f['in1_reg'])
        if has(m_incq):
            in1 = jnp.where(j(m_incq), time - offset, in1)
        if has(m_fproc):
            in1 = jnp.where(j(m_fproc), f_data, in1)
        alu_res = _alu_vec(j(f['alu_op']), in0, in1)
        if np.any(m_regalu | m_afp):
            wr = active & j(m_regalu | m_afp)
            wr_oh = (np.asarray(f['out_reg'])[:, None]
                     == np.arange(isa.N_REGS)[None, :])
            regs = jnp.where(wr[..., None] & jnp.asarray(wr_oh)[None],
                             alu_res[..., None], regs)
            st['regs'] = regs
    else:
        alu_res = jnp.int32(0)

    # ---- pulse latch + trigger ----------------------------------
    pp = st['pp']
    if has(m_pw | m_pt):
        is_pulse = active & j(m_pw | m_pt)
        imm_vals = np.stack([f['p_env'], f['p_phase'], f['p_freq'],
                             f['p_amp'], f['p_cfg']], -1)   # [C, 5]
        wen = ((f['p_wen'][:, None] >> np.arange(5)) & 1) == 1
        if np.any(f['p_regsel']):
            rsel = ((f['p_regsel'][:, None] >> np.arange(5)) & 1)
            regval = reg_read_static(f['p_reg'])
            cand = jnp.where(jnp.asarray(rsel == 1)[None],
                             regval[..., None],
                             jnp.asarray(imm_vals)[None]) \
                & jnp.asarray(pmask_np)
        else:
            cand = jnp.asarray((imm_vals & pmask_np))[None]
        pp = jnp.where(is_pulse[..., None] & jnp.asarray(wen)[None],
                       cand, pp)
        st['pp'] = pp

    trig = offset + j(f['cmd_time'])
    if has(m_pt):
        fire = active & j(m_pt)
        err_i = err_i | jnp.where(fire & (trig < time),
                                  ERR_MISSED_TRIG, 0)
        trig = jnp.maximum(trig, time)
        elem = pp[..., 4] & 0b11
        oh_elem = _onehot(jnp.minimum(elem, spc.shape[1] - 1),
                          spc.shape[1])
        spc_e = _ohsel(spc[None], oh_elem)
        interp_e = _ohsel(interp[None], oh_elem)
        env_len = (pp[..., 0] >> 12) & 0xfff
        nsamp = env_len * 4 * interp_e
        dur = jnp.where(env_len == 0xfff, 0,
                        (nsamp + spc_e - 1) // spc_e)
        err_i = err_i | jnp.where(
            fire & (st['n_pulses'] >= cfg.max_pulses),
            ERR_PULSE_OVERFLOW, 0)
        fault_i = fault_i | jnp.where(
            fire & (st['n_pulses'] >= cfg.max_pulses),
            FAULT_PULSE_OVERFLOW, 0)
        if cfg.record_pulses:
            rec_vals = jnp.stack(
                [j(f['cmd_time']) * jnp.ones_like(trig), trig,
                 pp[..., 0], pp[..., 1], pp[..., 2], pp[..., 3],
                 pp[..., 4], elem, dur], axis=-1)
            oh_pslot = _onehot(
                jnp.minimum(st['n_pulses'], cfg.max_pulses - 1),
                cfg.max_pulses)
            pwrite = (oh_pslot == 1) \
                & (fire & (st['n_pulses'] < cfg.max_pulses))[..., None]
            FR, P = len(_REC_FIELDS), cfg.max_pulses
            st['rec'] = jnp.where(
                pwrite[:, :, None, :], rec_vals[:, :, :, None],
                st['rec'].reshape(B, C, FR, P)).reshape(B, C, FR * P)
        st['n_pulses'] = st['n_pulses'] + fire.astype(jnp.int32)

        is_meas_pulse = fire & (elem == cfg.meas_elem)
        err_i = err_i | jnp.where(
            is_meas_pulse & (st['n_meas'] >= cfg.max_meas),
            ERR_MEAS_OVERFLOW, 0)
        fault_i = fault_i | jnp.where(
            is_meas_pulse & (st['n_meas'] >= cfg.max_meas),
            FAULT_MEAS_OVERFLOW, 0)
        oh_mslot = _onehot(jnp.minimum(st['n_meas'],
                                       cfg.max_meas - 1), cfg.max_meas)
        meas_avail = jnp.where(
            (oh_mslot == 1) & is_meas_pulse[..., None],
            (trig + dur + cfg.meas_latency)[..., None],
            st['meas_avail'])
        cw_clks = 0
        if cfg.physics and cfg.cw_horizon > 0:
            cw_clks = (cfg.cw_horizon + spc_e - 1) // spc_e
            meas_avail = jnp.where(
                (oh_mslot == 1) & (is_meas_pulse
                                   & (env_len == 0xfff))[..., None],
                (trig + cw_clks + cfg.meas_latency)[..., None],
                meas_avail)
        elif cfg.physics:
            err_i = err_i | jnp.where(
                is_meas_pulse & (env_len == 0xfff), ERR_CW_MEAS, 0)
        st['meas_avail'] = meas_avail
        if 'meas_time' in st:
            # production clock (lut fabric): the trigger time, written
            # once per slot — the CW rewrite above moves only the
            # distribution clock (meas_avail)
            st['meas_time'] = jnp.where(
                (oh_mslot == 1) & is_meas_pulse[..., None],
                trig[..., None], st['meas_time'])
        st['n_meas'] = st['n_meas'] + is_meas_pulse.astype(jnp.int32)

        # ---- physics co-state (parity / bloch; statevec is
        # ineligible for this executor) — the SAME helper the
        # generic engine uses, so the physics cannot drift --------
        if cfg.physics:
            mwr = (oh_mslot == 1) & is_meas_pulse[..., None]
            dev_updates, state_bit = _device_1q_pulse(
                st, cfg, dev, fire, elem, pp, trig, oh_mslot,
                is_meas_pulse)
            st.update(dev_updates)
            st['meas_state'] = jnp.where(mwr, state_bit[..., None],
                                         st['meas_state'])
            st['meas_amp'] = jnp.where(mwr, pp[..., 3:4],
                                       st['meas_amp'])
            st['meas_phase'] = jnp.where(mwr, pp[..., 1:2],
                                         st['meas_phase'])
            st['meas_freq'] = jnp.where(mwr, pp[..., 2:3],
                                        st['meas_freq'])
            st['meas_env'] = jnp.where(mwr, pp[..., 0:1],
                                       st['meas_env'])
            st['meas_gtime'] = jnp.where(mwr, trig[..., None],
                                         st['meas_gtime'])
            if fused is not None:
                # measure-in-megastep (docs/PERF.md "fused epoch"):
                # demodulate THIS window now.  At sigma=0 the matched-
                # filter accumulation is exactly gs*E, so the bit needs
                # only the window energy — a masked sum over the static
                # per-address energy tables (no gathers; the same code
                # lowers inside the kernel body)
                energy = _fused_window_energy(fused, pp, nsamp, env_len)
                bit = _fused_discriminate(fused, energy, state_bit)
                st['meas_bits'] = jnp.where(mwr, bit[..., None],
                                            st['meas_bits'])
                st['meas_valid'] = jnp.where(
                    mwr, jnp.ones_like(st['meas_valid']),
                    st['meas_valid'])

    # ---- phase reset / idle -------------------------------------
    if has(m_rst):
        is_rst = active & j(m_rst)
        oh_rslot = _onehot(jnp.minimum(st['n_resets'],
                                       cfg.max_resets - 1),
                           cfg.max_resets)
        st['rst_time'] = jnp.where((oh_rslot == 1) & is_rst[..., None],
                                   time[..., None], st['rst_time'])
        fault_i = fault_i | jnp.where(
            is_rst & (st['n_resets'] >= cfg.max_resets),
            FAULT_RESET_OVERFLOW, 0)
        st['n_resets'] = st['n_resets'] + is_rst.astype(jnp.int32)
    if has(m_idle):
        is_idle = active & j(m_idle)
        idle_end = offset + j(f['cmd_time'])
        err_i = err_i | jnp.where(is_idle & (time > idle_end),
                                  ERR_MISSED_TRIG, 0)
        idle_end = jnp.maximum(idle_end, time)

    # ---- race flag on the proceeding read -----------------------
    if has(m_fproc):
        err_i = err_i | jnp.where(active & j(m_fproc) & f_race,
                                  ERR_STICKY_RACE, 0)

    if 'op_hist' in st:
        oh_kind = (kind[:, None]
                   == np.arange(isa.N_KINDS)[None, :]).astype(np.int32)
        st['op_hist'] = st['op_hist'] \
            + active[..., None] * jnp.asarray(oh_kind)[None]

    # ---- next pc / time / offset / done -------------------------
    pc_next = jnp.int32(i + 1)
    if has(m_jmpi | m_jcond | m_jfp):
        branch = (alu_res & 1) == 1
        pc_next = jnp.where(j(m_jmpi), j(f['jump_addr']), pc_next)
        pc_next = jnp.where(j(m_jcond | m_jfp)
                            & branch, j(f['jump_addr']), pc_next)
        # taken forward jump past the program end: the lane matches
        # no later index, retires nothing, and is left undone —
        # trap it here rather than as a bare budget fault
        m_oob = (f['jump_addr'] < 0) | (f['jump_addr'] >= N)
        if has(m_oob & (m_jmpi | m_jcond | m_jfp)):
            taken_oob = (j(m_jmpi & m_oob)
                         | (j((m_jcond | m_jfp) & m_oob) & branch))
            st['fault'] = st['fault'] | jnp.where(
                active & taken_oob, FAULT_JUMP_OOB, 0)
    st['pc'] = jnp.where(active & ~j(m_done), pc_next, st['pc'])
    time_next = time
    if has(m_pt):
        time_next = jnp.where(j(m_pt), trig + cfg.pulse_load_clks,
                              time_next)
    if has(m_pw | m_rst):
        time_next = jnp.where(j(m_pw | m_rst),
                              time + cfg.pulse_regwrite_clks,
                              time_next)
    if has(m_idle):
        time_next = jnp.where(j(m_idle),
                              idle_end + cfg.pulse_load_clks,
                              time_next)
    if has(m_regalu | m_incq):
        time_next = jnp.where(j(m_regalu | m_incq),
                              time + cfg.alu_instr_clks, time_next)
    if has(m_jmpi | m_jcond):
        time_next = jnp.where(j(m_jmpi | m_jcond),
                              time + cfg.jump_cond_clks, time_next)
    if has(m_fproc):
        # f_tready: the serve time — `time` for the sticky own-core
        # read, max(request, LUT distribution time) for the lut fabric
        time_next = jnp.where(j(m_fproc),
                              f_tready + cfg.jump_fproc_clks, time_next)
    st['time'] = jnp.where(active, time_next, time)
    if has(m_incq):
        st['offset'] = jnp.where(active & j(m_incq), time - alu_res,
                                 offset)
    st['err'] = st['err'] | jnp.where(active, err_i, 0)
    st['fault'] = st['fault'] | jnp.where(active, fault_i, 0)
    st['done'] = st['done'] | (active & j(m_done))

    return st, stalled


def _exec_block_body(st: dict, act, rows_np, spc, interp,
                     cfg: InterpreterConfig, dev=None) -> dict:
    """One deduplicated superinstruction: execute the ``[C, L, F]``
    instruction run ``rows_np`` for the lanes/cores selected by ``act``
    [B, C] (already ``bid == k``-masked and live).

    Same per-row static specialization as :func:`_exec_straightline`
    restricted to the body-safe kinds (:data:`isa.BLOCK_BODY_KINDS`):
    no fproc, jump, or sync handling — those are terminators, refined
    out of every body by :func:`isa.build_block_table`.  DONE rows are
    padding from :func:`isa.stack_soa` on heterogeneous-length
    programs: they halt the lane inline without advancing ``pc``, so
    the retired state matches the generic engine bit-for-bit.  ``pc``
    advances RELATIVELY (``pc + 1`` per retired row) because a deduped
    body runs for segments at different start addresses.
    """
    for off in range(rows_np.shape[1]):
        f = {name: np.asarray(rows_np[:, off, _F[name]])
             for name in _FIELDS}
        st = _blk_apply_row(st, act, f, spc, interp, cfg, dev)
    return st


def _blk_apply_row(st: dict, act, f: dict, spc, interp,
                   cfg: InterpreterConfig, dev=None) -> dict:
    """Apply ONE superinstruction row (static fields ``f``, one value
    per core) to the lanes selected by ``act`` — the block engine's
    per-row step body, shared verbatim with the pallas block-body
    kernel (:func:`_exec_block_body_pallas`) so the two paths are
    bit-identical by construction.  ``pc`` advances RELATIVELY."""
    st = dict(st)
    B, C = act.shape
    pmask_np = _PMASKS
    kind = f['kind']
    m_pw, m_pt = kind == isa.K_PULSE_WRITE, kind == isa.K_PULSE_TRIG
    m_rst, m_idle = kind == isa.K_PULSE_RESET, kind == isa.K_IDLE
    m_regalu, m_incq = kind == isa.K_REG_ALU, kind == isa.K_INC_QCLK
    m_done = kind == isa.K_DONE
    m_alu = m_regalu | m_incq
    has = lambda m: bool(np.any(m))
    j = lambda a: jnp.asarray(np.asarray(a))[None]       # [1, C]

    active = act & ~st['done']
    time, offset, regs = st['time'], st['offset'], st['regs']
    err_i = jnp.zeros((B, C), jnp.int32)
    fault_i = jnp.zeros((B, C), jnp.int32)
    m_badkind = (kind < 0) | (kind >= isa.N_KINDS)
    if has(m_badkind):
        fault_i = fault_i | jnp.where(j(m_badkind), FAULT_ILLEGAL_OP,
                                      0)

    def reg_read_static(addr_c):
        oh = (np.asarray(addr_c)[:, None]
              == np.arange(isa.N_REGS)[None, :]).astype(np.int32)
        return jnp.sum(regs * jnp.asarray(oh)[None], axis=-1)

    # ---- ALU (REG_ALU / INC_QCLK only) --------------------------
    if has(m_alu):
        in0 = jnp.where(j(f['in0_is_reg'] == 1),
                        reg_read_static(f['in0_reg']), j(f['imm'])) \
            if np.any(f['in0_is_reg'][m_alu]) else j(f['imm'])
        in1 = jnp.int32(0)
        if has(m_regalu):
            in1 = reg_read_static(f['in1_reg'])
        if has(m_incq):
            in1 = jnp.where(j(m_incq), time - offset, in1)
        alu_res = _alu_vec(j(f['alu_op']), in0, in1)
        if has(m_regalu):
            wr = active & j(m_regalu)
            wr_oh = (np.asarray(f['out_reg'])[:, None]
                     == np.arange(isa.N_REGS)[None, :])
            regs = jnp.where(wr[..., None] & jnp.asarray(wr_oh)[None],
                             alu_res[..., None], regs)
            st['regs'] = regs
    else:
        alu_res = jnp.int32(0)

    # ---- pulse latch + trigger ----------------------------------
    pp = st['pp']
    if has(m_pw | m_pt):
        is_pulse = active & j(m_pw | m_pt)
        imm_vals = np.stack([f['p_env'], f['p_phase'], f['p_freq'],
                             f['p_amp'], f['p_cfg']], -1)   # [C, 5]
        wen = ((f['p_wen'][:, None] >> np.arange(5)) & 1) == 1
        if np.any(f['p_regsel']):
            rsel = ((f['p_regsel'][:, None] >> np.arange(5)) & 1)
            regval = reg_read_static(f['p_reg'])
            cand = jnp.where(jnp.asarray(rsel == 1)[None],
                             regval[..., None],
                             jnp.asarray(imm_vals)[None]) \
                & jnp.asarray(pmask_np)
        else:
            cand = jnp.asarray((imm_vals & pmask_np))[None]
        pp = jnp.where(is_pulse[..., None] & jnp.asarray(wen)[None],
                       cand, pp)
        st['pp'] = pp

    trig = offset + j(f['cmd_time'])
    if has(m_pt):
        fire = active & j(m_pt)
        err_i = err_i | jnp.where(fire & (trig < time),
                                  ERR_MISSED_TRIG, 0)
        trig = jnp.maximum(trig, time)
        elem = pp[..., 4] & 0b11
        oh_elem = _onehot(jnp.minimum(elem, spc.shape[1] - 1),
                          spc.shape[1])
        spc_e = _ohsel(spc[None], oh_elem)
        interp_e = _ohsel(interp[None], oh_elem)
        env_len = (pp[..., 0] >> 12) & 0xfff
        nsamp = env_len * 4 * interp_e
        dur = jnp.where(env_len == 0xfff, 0,
                        (nsamp + spc_e - 1) // spc_e)
        err_i = err_i | jnp.where(
            fire & (st['n_pulses'] >= cfg.max_pulses),
            ERR_PULSE_OVERFLOW, 0)
        fault_i = fault_i | jnp.where(
            fire & (st['n_pulses'] >= cfg.max_pulses),
            FAULT_PULSE_OVERFLOW, 0)
        if cfg.record_pulses:
            rec_vals = jnp.stack(
                [j(f['cmd_time']) * jnp.ones_like(trig), trig,
                 pp[..., 0], pp[..., 1], pp[..., 2], pp[..., 3],
                 pp[..., 4], elem, dur], axis=-1)
            oh_pslot = _onehot(
                jnp.minimum(st['n_pulses'], cfg.max_pulses - 1),
                cfg.max_pulses)
            pwrite = (oh_pslot == 1) \
                & (fire & (st['n_pulses'] < cfg.max_pulses))[..., None]
            FR, P = len(_REC_FIELDS), cfg.max_pulses
            st['rec'] = jnp.where(
                pwrite[:, :, None, :], rec_vals[:, :, :, None],
                st['rec'].reshape(B, C, FR, P)).reshape(B, C, FR * P)
        st['n_pulses'] = st['n_pulses'] + fire.astype(jnp.int32)

        is_meas_pulse = fire & (elem == cfg.meas_elem)
        err_i = err_i | jnp.where(
            is_meas_pulse & (st['n_meas'] >= cfg.max_meas),
            ERR_MEAS_OVERFLOW, 0)
        fault_i = fault_i | jnp.where(
            is_meas_pulse & (st['n_meas'] >= cfg.max_meas),
            FAULT_MEAS_OVERFLOW, 0)
        oh_mslot = _onehot(jnp.minimum(st['n_meas'],
                                       cfg.max_meas - 1), cfg.max_meas)
        meas_avail = jnp.where(
            (oh_mslot == 1) & is_meas_pulse[..., None],
            (trig + dur + cfg.meas_latency)[..., None],
            st['meas_avail'])
        cw_clks = 0
        if cfg.physics and cfg.cw_horizon > 0:
            cw_clks = (cfg.cw_horizon + spc_e - 1) // spc_e
            meas_avail = jnp.where(
                (oh_mslot == 1) & (is_meas_pulse
                                   & (env_len == 0xfff))[..., None],
                (trig + cw_clks + cfg.meas_latency)[..., None],
                meas_avail)
        elif cfg.physics:
            err_i = err_i | jnp.where(
                is_meas_pulse & (env_len == 0xfff), ERR_CW_MEAS, 0)
        st['meas_avail'] = meas_avail
        if 'meas_time' in st:
            # production clock (lut fabric): the trigger time, written
            # once per slot — the CW rewrite above moves only the
            # distribution clock (meas_avail)
            st['meas_time'] = jnp.where(
                (oh_mslot == 1) & is_meas_pulse[..., None],
                trig[..., None], st['meas_time'])
        st['n_meas'] = st['n_meas'] + is_meas_pulse.astype(jnp.int32)

        # physics co-state: the SAME helper as _step and the
        # straightline engine, so the physics cannot drift
        if cfg.physics:
            mwr = (oh_mslot == 1) & is_meas_pulse[..., None]
            dev_updates, state_bit = _device_1q_pulse(
                st, cfg, dev, fire, elem, pp, trig, oh_mslot,
                is_meas_pulse)
            st.update(dev_updates)
            st['meas_state'] = jnp.where(mwr, state_bit[..., None],
                                         st['meas_state'])
            st['meas_amp'] = jnp.where(mwr, pp[..., 3:4],
                                       st['meas_amp'])
            st['meas_phase'] = jnp.where(mwr, pp[..., 1:2],
                                         st['meas_phase'])
            st['meas_freq'] = jnp.where(mwr, pp[..., 2:3],
                                        st['meas_freq'])
            st['meas_gtime'] = jnp.where(mwr, trig[..., None],
                                         st['meas_gtime'])
            st['meas_env'] = jnp.where(mwr, pp[..., 0:1],
                                       st['meas_env'])

    # ---- phase reset / idle -------------------------------------
    if has(m_rst):
        is_rst = active & j(m_rst)
        oh_rslot = _onehot(jnp.minimum(st['n_resets'],
                                       cfg.max_resets - 1),
                           cfg.max_resets)
        st['rst_time'] = jnp.where((oh_rslot == 1) & is_rst[..., None],
                                   time[..., None], st['rst_time'])
        fault_i = fault_i | jnp.where(
            is_rst & (st['n_resets'] >= cfg.max_resets),
            FAULT_RESET_OVERFLOW, 0)
        st['n_resets'] = st['n_resets'] + is_rst.astype(jnp.int32)
    if has(m_idle):
        is_idle = active & j(m_idle)
        idle_end = offset + j(f['cmd_time'])
        err_i = err_i | jnp.where(is_idle & (time > idle_end),
                                  ERR_MISSED_TRIG, 0)
        idle_end = jnp.maximum(idle_end, time)

    if 'op_hist' in st:
        oh_kind = (kind[:, None]
                   == np.arange(isa.N_KINDS)[None, :]).astype(np.int32)
        st['op_hist'] = st['op_hist'] \
            + active[..., None] * jnp.asarray(oh_kind)[None]

    # ---- next pc / time / offset / done (pc is RELATIVE) --------
    st['pc'] = jnp.where(active & ~j(m_done), st['pc'] + 1, st['pc'])
    time_next = time
    if has(m_pt):
        time_next = jnp.where(j(m_pt), trig + cfg.pulse_load_clks,
                              time_next)
    if has(m_pw | m_rst):
        time_next = jnp.where(j(m_pw | m_rst),
                              time + cfg.pulse_regwrite_clks,
                              time_next)
    if has(m_idle):
        time_next = jnp.where(j(m_idle),
                              idle_end + cfg.pulse_load_clks,
                              time_next)
    if has(m_regalu | m_incq):
        time_next = jnp.where(j(m_regalu | m_incq),
                              time + cfg.alu_instr_clks, time_next)
    st['time'] = jnp.where(active, time_next, time)
    if has(m_incq):
        st['offset'] = jnp.where(active & j(m_incq), time - alu_res,
                                 offset)
    st['err'] = st['err'] | jnp.where(active, err_i, 0)
    st['fault'] = st['fault'] | jnp.where(active, fault_i, 0)
    st['done'] = st['done'] | (active & j(m_done))

    return st


def _default_pallas_interpret() -> bool:
    """Resolve ``cfg.pallas_interpret=None``: compile the megastep
    kernel on TPU backends, run it under the pallas interpreter
    everywhere else (the tier-1 CPU path)."""
    from ..ops._pallas_common import default_interpret
    return default_interpret()


def _pallas_mode(prog: tuple, cfg: InterpreterConfig) -> str:
    """Which shape the pallas engine runs ``prog`` in: ``'span'`` (the
    whole forward-jump-only program as ONE kernel call) or ``'block'``
    (the block engine's outer loop with pallas superinstruction
    bodies).  Derived from the jit-static program via the same field
    checks as :func:`straightline_ineligible`, so dispatch and
    eligibility cannot drift."""
    soa_np = _soa_from_static(prog)
    span = _sl_ineligible_fields(soa_np[..., _F['kind']],
                                 soa_np[..., _F['jump_addr']],
                                 soa_np[..., _F['func_id']], cfg,
                                 soa_np) is None
    return 'span' if span else 'block'


# ---------------------------------------------------------------------------
# Bit-packed megastep carry (cfg.packed_carry, docs/PERF.md "fused
# epoch").  The pallas engines round-trip the whole machine state
# through HBM once per kernel call; most of that state is booleans,
# small enums, and clock values a STATIC program analysis can bound.
# carry_packspec() derives, host-side, a per-leaf packing directive
# (ops/exec_pallas.PackLeaf) from the decoded program + ISA field
# masks, and ships it through the jit wrappers as a hashable static
# value; ops/exec_pallas.span_call applies it at the kernel boundary.
# Soundness: every width below bounds EVERY value the field can hold
# over one span/body execution from the engine's entry state, so
# decode(encode(x)) == x for every reachable carry.

_ERR_ALL = (ERR_MISSED_TRIG | ERR_PULSE_OVERFLOW | ERR_MEAS_OVERFLOW
            | ERR_FPROC_DEADLOCK | ERR_SYNC_DONE | ERR_FPROC_ID
            | ERR_STICKY_RACE | ERR_CW_MEAS | ERR_COFIRE_ORDER)
_FAULT_ALL = functools.reduce(lambda a, b: a | b,
                              (bit for _, bit in FAULT_CODES))
_JUMP_KINDS = (isa.K_JUMP_I, isa.K_JUMP_COND, isa.K_JUMP_FPROC)
# pulse-latch regsel bits whose register sourcing makes pulse DURATION
# dynamic: env (bit 0, length nibble) and cfg (bit 4, element select)
_RSEL_TIMING = 0b10001


def _bl(x: int) -> int:
    return max(int(x).bit_length(), 1)


def _static_pc_width(soa_np):
    """Bits covering every value ``pc`` can hold: the fall-through
    range ``[0, N]`` plus every static jump target (a taken OOB jump
    parks the lane AT the raw target).  None when a negative target
    exists (sign bit needed — not worth a lane)."""
    kind = soa_np[..., _F['kind']]
    ja = soa_np[..., _F['jump_addr']]
    jm = np.isin(kind, _JUMP_KINDS)
    hi = int(soa_np.shape[1])
    if np.any(jm):
        t = ja[jm]
        if int(t.min()) < 0:
            return None
        hi = max(hi, int(t.max()))
    return _bl(hi)


def _possibly_meas_mask(soa_np, cfg: InterpreterConfig):
    """``[C, N]`` bool: True where the index is a ``K_PULSE_TRIG``
    whose LATCHED cfg nibble can select ``cfg.meas_elem`` — a forward
    possible-values analysis of the cfg nibble (init 0; a reg-sourced
    cfg write is TOP) over the forward-only span CFG.  A False trigger
    is PROVABLY a drive pulse: it never touches the measurement
    planes.  Returns ``None`` when a backward edge makes the single
    ascending pass invalid."""
    kind = soa_np[..., _F['kind']]
    C, N = kind.shape
    out = np.zeros((C, N), dtype=bool)
    for c in range(C):
        k = kind[c]
        wen = soa_np[c, :, _F['p_wen']]
        rsel = soa_np[c, :, _F['p_regsel']]
        pcfg = soa_np[c, :, _F['p_cfg']]
        ja = soa_np[c, :, _F['jump_addr']]
        is_p = np.isin(k, (isa.K_PULSE_WRITE, isa.K_PULSE_TRIG))
        jump_preds = [[] for _ in range(N)]
        for i in np.nonzero(np.isin(k, _JUMP_KINDS))[0]:
            t = int(ja[i])
            if 0 <= t < N:
                jump_preds[t].append(int(i))
        outs = [frozenset()] * N   # None = TOP (any nibble)
        for i in range(N):
            s, top = (frozenset((0,)), False) if i == 0 \
                else (frozenset(), False)
            srcs = []
            if i > 0 and int(k[i - 1]) not in (isa.K_JUMP_I, isa.K_DONE):
                srcs.append(outs[i - 1])
            for jp in jump_preds[i]:
                if jp >= i:
                    return None                  # backward edge
                srcs.append(outs[jp])
            for o in srcs:
                if o is None:
                    top = True
                else:
                    s = s | o
            own = None if top else s
            if is_p[i] and (int(wen[i]) >> 4) & 1:
                own = None if (int(rsel[i]) >> 4) & 1 \
                    else frozenset((int(pcfg[i]) & 0xf,))
            outs[i] = own
            if int(k[i]) == isa.K_PULSE_TRIG and (
                    own is None
                    or any((v & 3) == cfg.meas_elem for v in own)):
                out[c, i] = True
    return out


def _static_meas_bounds(soa_np, cfg: InterpreterConfig):
    """``(meas_bound, reset_bound)``: per-core worst-case counts of
    measurement pulses and phase resets one SPAN execution can retire.

    ``reset_bound`` is the static reset-instruction count (each span
    index retires at most once).  ``meas_bound`` is the per-core count
    of possibly-measurement triggers (:func:`_possibly_meas_mask`),
    ``None`` when a backward edge makes the analysis invalid."""
    kind = soa_np[..., _F['kind']]
    C = kind.shape[0]
    n_rst = int(max((int(np.sum(kind[c] == isa.K_PULSE_RESET))
                     for c in range(C)), default=0))
    pm = _possibly_meas_mask(soa_np, cfg)
    if pm is None:
        return None, n_rst
    bound = int(max((int(pm[c].sum()) for c in range(C)), default=0))
    return bound, n_rst


def _static_clock_bound(soa_np, cfg: InterpreterConfig, spc_np, interp_np):
    """Upper bound on every clock value (``time`` / ``meas_avail`` /
    ``rst_time`` / ``meas_gtime``) one SPAN execution can produce, or
    None when the program makes clocks data-dependent (INC_QCLK
    rewrites the offset; a reg-sourced envelope/cfg latch makes pulse
    duration dynamic).  Walks each core's instruction list once —
    sound because a span index retires at most once — accumulating the
    per-kind time advances of ``_sl_apply_instr`` with every pulse
    charged the worst static duration."""
    kind = soa_np[..., _F['kind']]
    C, N = kind.shape
    if np.any(kind == isa.K_INC_QCLK):
        return None
    bound = 0
    for c in range(C):
        k = kind[c]
        wen = soa_np[c, :, _F['p_wen']].astype(np.int64)
        rsel = soa_np[c, :, _F['p_regsel']].astype(np.int64)
        penv = soa_np[c, :, _F['p_env']].astype(np.int64)
        cmd = soa_np[c, :, _F['cmd_time']].astype(np.int64)
        is_p = np.isin(k, (isa.K_PULSE_WRITE, isa.K_PULSE_TRIG))
        if np.any((wen[is_p] & rsel[is_p] & _RSEL_TIMING) != 0):
            return None
        # worst static duration: longest latched envelope at the
        # slowest element clock (CW 0xfff counts as 0 — physics-mode
        # CW measurement windows are gated out of the packed engines)
        lens = (penv[is_p & ((wen & 1) == 1)] >> 12) & 0xfff
        lens = lens[lens != 0xfff]
        interp_max = int(np.max(interp_np[c])) if interp_np[c].size else 1
        spc_min = max(int(np.min(spc_np[c])), 1) if spc_np[c].size else 1
        dur_max = 0
        for L in np.unique(lens).tolist():
            ns = int(L) * 4 * interp_max
            dur_max = max(dur_max, -(-ns // spc_min))
        t = int(INIT_TIME)
        for i in range(N):
            ki = int(k[i])
            if ki in (isa.K_PULSE_TRIG, isa.K_IDLE):
                t = max(t, max(int(cmd[i]), 0)) + cfg.pulse_load_clks
            elif ki in (isa.K_PULSE_WRITE, isa.K_PULSE_RESET):
                t += cfg.pulse_regwrite_clks
            elif ki == isa.K_REG_ALU:
                t += cfg.alu_instr_clks
            elif ki in (isa.K_JUMP_I, isa.K_JUMP_COND):
                t += cfg.jump_cond_clks
            elif ki in (isa.K_JUMP_FPROC, isa.K_ALU_FPROC):
                t += cfg.jump_fproc_clks
        bound = max(bound, t + dur_max + cfg.meas_latency)
    return bound if 0 <= bound < 2**31 else None


def _spc_interp_np(mp):
    """Host numpy form of the element-clock tables (the values
    :func:`_program_constants` devices — needed statically here)."""
    max_elems = max((len(t.elem_cfgs) for t in mp.tables), default=0) or 1
    spc = np.ones((mp.n_cores, max_elems), np.int64)
    interp = np.zeros((mp.n_cores, max_elems), np.int64)
    for c, t in enumerate(mp.tables):
        for e, ec in enumerate(t.elem_cfgs):
            spc[c, e] = ec.samples_per_clk
            interp[c, e] = ec.interp_ratio
    return spc, interp


def use_packed_carry(cfg: InterpreterConfig) -> bool:
    """Resolve the ``cfg.packed_carry`` tri-state: AUTO packs exactly
    when the megastep kernel COMPILES (resolved ``pallas_interpret``
    False — a real TPU backend), where the HBM-crossing stream is the
    measured cost; the interpreter path stays unpacked so tier-1 CPU
    parity covers both layouts via the explicit True pin."""
    if cfg.packed_carry is not None:
        return bool(cfg.packed_carry)
    itp = cfg.pallas_interpret
    if itp is None:
        itp = _default_pallas_interpret()
    return itp is False


def carry_packspec(mp, cfg: InterpreterConfig, trim_regs: bool = True,
                   fused: bool = False):
    """Derive the bit-packed carry layout for ``(mp, cfg)`` under the
    pallas engine, as a HASHABLE nested tuple (it rides the jit
    wrappers as a static argument; :func:`_packspec_decode` rebuilds
    the ``{'state'|'consts': {key: PackLeaf}}`` dict at the kernel
    call).  ``trim_regs`` must be False when the caller injects a
    nonzero initial register file (the trim drops statically-unwritten
    registers, refilled with the zero init).  ``fused=True`` adds the
    measure-in-megastep co-state (physics measurement slots, device
    counter, in-kernel bits).  Returns None when nothing packs.
    """
    prog = _soa_static(mp)
    soa_np = _soa_from_static(prog)
    spc_np, interp_np = _spc_interp_np(mp)
    span = _pallas_mode(prog, cfg) == 'span'
    if fused and not span:
        raise ValueError('fused packspec needs a span-shaped program')
    kind = soa_np[..., _F['kind']]
    C, N = kind.shape
    PL = lambda trim=None, fill=0, widths=None, sentinel=None: \
        (trim, fill, widths, sentinel)
    st, co = {}, {}

    # flag/enum fields: width = the ISA's own value mask, any mode
    st['done'] = PL(widths=1)
    st['err'] = PL(widths=_bl(_ERR_ALL))
    st['fault'] = PL(widths=_bl(_FAULT_ALL))
    st['pp'] = PL(widths=tuple(
        int(m).bit_length() for m in _PMASKS.tolist()) * C)
    w_pc = _static_pc_width(soa_np)
    if w_pc is not None:
        st['pc'] = PL(widths=w_pc)
    if trim_regs:
        wm = np.isin(kind, (isa.K_REG_ALU, isa.K_ALU_FPROC))
        written = sorted(set(
            int(r) for r in soa_np[..., _F['out_reg']][wm].tolist())
            & set(range(isa.N_REGS)))
        if len(written) < isa.N_REGS:
            st['regs'] = PL(trim=tuple(written) or (0,))

    if span:
        # span-only: every instruction index retires at most once from
        # the zeroed entry state, so counters, slot occupancy, and (in
        # the absence of INC_QCLK / reg-sourced durations) every clock
        # value have static program bounds
        tb = _static_clock_bound(soa_np, cfg, spc_np, interp_np)
        w_t = None
        if tb is not None:
            w_t = _bl(tb)
            if tb >= (1 << w_t) - 1:
                w_t += 1    # keep the all-ones code free as a sentinel
            st['time'] = PL(widths=w_t)
        if not np.any(kind == isa.K_INC_QCLK):
            st['offset'] = PL(widths=1)
        n_pt = int(max((int(np.sum(kind[c] == isa.K_PULSE_TRIG))
                        for c in range(C)), default=0))
        m_bound, n_rst = _static_meas_bounds(soa_np, cfg)
        mb = n_pt if m_bound is None else m_bound
        st['n_pulses'] = PL(widths=_bl(n_pt))
        st['n_resets'] = PL(widths=_bl(n_rst))
        st['n_meas'] = PL(widths=_bl(mb))
        M, R = cfg.max_meas, cfg.max_resets
        mk = max(min(mb, M), 1)
        rk = max(min(n_rst, R), 1)
        mtrim = tuple(range(mk)) if mk < M else None
        st['meas_avail'] = PL(
            trim=mtrim, fill=int(INT32_MAX), widths=w_t,
            sentinel=int(INT32_MAX) if w_t is not None else None)
        if cfg.fabric == 'lut':
            # production-clock plane (time-indexed LUT reads): the
            # same trim/width/sentinel envelope as meas_avail, since
            # avail = trig + dur + latency >= trig bounds the trigger
            st['meas_time'] = PL(
                trim=mtrim, fill=int(INT32_MAX), widths=w_t,
                sentinel=int(INT32_MAX) if w_t is not None else None)
        st['rst_time'] = PL(trim=tuple(range(rk)) if rk < R else None,
                            widths=w_t)
        if cfg.opcode_histogram:
            cnt = np.stack([np.sum(kind == kk, axis=1)
                            for kk in range(isa.N_KINDS)], axis=-1)
            st['op_hist'] = PL(widths=tuple(
                _bl(x) for x in cnt.reshape(-1).tolist()))
        if cfg.record_pulses and n_pt < cfg.max_pulses:
            P, keep = cfg.max_pulses, max(n_pt, 1)
            st['rec'] = PL(trim=tuple(
                fi * P + p for fi in range(len(_REC_FIELDS))
                for p in range(keep)))
        if fused:
            # measure-in-megastep: the demodulated bit and its physics
            # window parameters ride the carry as STATE (docs/PERF.md
            # "fused epoch"); widths are the pulse-param masks, slots
            # trim to the same static measurement bound
            st['meas_bits'] = PL(trim=mtrim, widths=1)
            st['meas_valid'] = PL(trim=mtrim, widths=1)
            st['phys_wait'] = PL(widths=1)
            st['meas_state'] = PL(trim=mtrim, widths=1)
            for key, w in (('meas_env', 24), ('meas_phase', 17),
                           ('meas_freq', 9), ('meas_amp', 16)):
                st[key] = PL(trim=mtrim, widths=w)
            st['meas_gtime'] = PL(trim=mtrim, widths=w_t)
            if cfg.x90_amp > 0:
                dq = (2 * int(_PMASKS[3]) + cfg.x90_amp) \
                    // (2 * cfg.x90_amp)
                st['qturns'] = PL(widths=_bl(2 + n_pt * dq))
        elif mtrim is not None:
            # injected-bits consts: values are caller-arbitrary int32
            # (never width-packed) but slots past the static bound are
            # never selected by the fproc read
            co['meas_bits'] = PL(trim=mtrim)
    else:
        # block mode loops, so only execution-count-independent fields
        # pack; the lane-activity const is a boolean mask
        co['act'] = PL(widths=1)

    clean = lambda d: {k: v for k, v in d.items()
                       if v[0] is not None or v[2] is not None}
    st, co = clean(st), clean(co)
    if not st and not co:
        return None
    enc = lambda d: tuple(sorted((k,) + v for k, v in d.items()))
    return (enc(st), enc(co))


def _packspec_decode(pack):
    """Static-tuple -> ``{'state'|'consts': {key: PackLeaf}}`` (the
    form ``ops.exec_pallas.span_call`` consumes)."""
    if pack is None:
        return None
    from ..ops.exec_pallas import PackLeaf
    mk = lambda e: {k: PackLeaf(t, f, w, s) for (k, t, f, w, s) in e}
    return {'state': mk(pack[0]), 'consts': mk(pack[1])}


def carry_stream_bytes(mp, cfg: InterpreterConfig, fused: bool = False):
    """``(unpacked, packed)`` modeled per-shot bytes of the megastep
    kernel's HBM-crossing streams for ``(mp, cfg)`` — the quantity the
    ``2 x carry x steps`` exec-phase HBM model prices
    (tools/exec_profile.py, bench utilization rows)."""
    from ..ops import exec_pallas
    C, M = mp.n_cores, cfg.max_meas
    st = dict(jax.eval_shape(lambda: _init_state(1, C, cfg)))
    i32 = jax.ShapeDtypeStruct((1, C, M), jnp.int32)
    if fused:
        st['meas_bits'] = i32
        st['meas_valid'] = jax.ShapeDtypeStruct((1, C, M), jnp.bool_)
        consts = {}
    else:
        consts = {'meas_bits': i32}
    pack = carry_packspec(mp, cfg, fused=fused)
    su, cu = exec_pallas.span_stream_bytes(st, consts)
    sp, cp = exec_pallas.span_stream_bytes(st, consts,
                                           _packspec_decode(pack))
    return su + cu, sp + cp


def _exec_span_pallas(st0: dict, soa_np, spc, interp, meas_bits,
                      cfg: InterpreterConfig, interpret,
                      pack=None) -> dict:
    """The megastep span executor: the ENTIRE forward-jump-only program
    as one Pallas call (docs/PERF.md "megastep").

    Semantically :func:`_exec_straightline` with every injected bit
    valid — the same :func:`_sl_apply_instr` per-instruction bodies,
    traced INSIDE the kernel over shot tiles, so the per-shot carry
    (regs / clocks / pulse params / slots / fault word) is loaded into
    VMEM once, K instructions retire in-register, and the carry is
    stored once: the generic engine's per-step fixed cost ``a`` (the
    decomposition in docs/PERF.md) collapses to one launch.
    """
    from ..ops import exec_pallas
    N = soa_np.shape[1]
    rows = [{name: np.asarray(soa_np[:, i, _F[name]])
             for name in _FIELDS}
            for i in range(N)]
    st = dict(st0)
    steps = st.pop('_steps')

    def body(stt, cc, hh):
        # injected-bits path: every bit valid, no lane ever stalls
        mv = jnp.ones(cc['meas_bits'].shape, bool)
        stalled = jnp.zeros(stt['pc'].shape, bool)
        for i, f in enumerate(rows):
            stt, stalled = _sl_apply_instr(
                stt, stalled, i, N, f, hh['spc'], hh['interp'],
                cc['meas_bits'], mv, cfg)
        return stt

    out = exec_pallas.span_call(st, {'meas_bits': meas_bits},
                                {'spc': spc, 'interp': interp}, body,
                                interpret=interpret,
                                packspec=_packspec_decode(pack))
    out['_steps'] = steps + N
    return out


def _fused_window_energy(fused, pp, nsamp, env_len):
    """Window energy ``amp^2 * sum_s e^2(s) * [s < count]`` of the
    measurement pulse latched in ``pp`` — the scale of the sigma=0
    matched-filter accumulation (the carrier's unit magnitude drops
    out, physics ``_resolve_analytic``).

    Computed against the static per-address DAC-resolution envelope
    energy rows (``fused['e2']``, ops/resolve_pallas
    ``build_energy_tables``): an address-equality row select over the
    statically-enumerated envelope addresses plus an iota-vs-count
    mask, chunked so the ``[B, C, chunk]`` intermediate bounds VMEM —
    no gathers, so the same code lowers inside the megastep kernel."""
    e2 = fused['e2']                                     # [C, R, Wp] f32
    Wp = e2.shape[2]
    # CW windows (length nibble 0xfff) demodulate over cw_samp=0 under
    # this engine's eligibility (cw_horizon == 0) — energy 0, like the
    # epoch resolver's _window_scalars
    count = jnp.where(env_len == 0xfff, 0,
                      jnp.minimum(nsamp, fused['w']))    # [B, C]
    addr = (pp[..., 0] & 0xfff) * 4
    chunk = min(int(fused.get('chunk') or Wp), Wp)
    tot = jnp.zeros(addr.shape, jnp.float32)
    for r, a in enumerate(fused['addrs']):
        acc = jnp.zeros(addr.shape, jnp.float32)
        for s0 in range(0, Wp, chunk):
            blk = e2[:, r, s0:s0 + chunk]                # [C, L]
            m = (s0 + jnp.arange(blk.shape[1]))[None, None, :] \
                < count[..., None]
            acc = acc + jnp.sum(jnp.where(m, blk[None], 0.0), axis=-1)
        tot = tot + jnp.where(addr == a, acc, 0.0)
    amp = pp[..., 3].astype(jnp.float32) / fused['amp_scale']
    return amp * amp * tot


def _fused_discriminate(fused, energy, state_bit):
    """2-class threshold of the sigma=0 accumulation ``gs * E``: the
    same projection onto the |0>-|1> axis as physics
    ``_discriminate_acc``.  At sigma=0 the accumulation is EXACTLY the
    state's clean response scaled by the (nonnegative) energy, so the
    projection's sign depends only on which response scaled it — the
    in-kernel bit and the epoch resolver's bit agree for every float
    realization of E, which is what makes the fused engine
    bit-identical to the generic engine by construction."""
    g0b, g1b = fused['g0'][None], fused['g1'][None]      # [1, C, 2]
    gs = jnp.where(state_bit[..., None] == 1, g1b, g0b)  # [B, C, 2]
    acc_i = gs[..., 0] * energy
    acc_q = gs[..., 1] * energy
    a0_i, a0_q = g0b[..., 0] * energy, g0b[..., 1] * energy
    a1_i, a1_q = g1b[..., 0] * energy, g1b[..., 1] * energy
    proj = (acc_i - (a0_i + a1_i) / 2) * (a1_i - a0_i) \
        + (acc_q - (a0_q + a1_q) / 2) * (a1_q - a0_q)
    return (proj > 0).astype(jnp.int32)


# VMEM chunk (DAC samples) of the fused engine's in-kernel energy mask
# — bounds the [tile, C, chunk] f32 intermediate the masked sum builds
_FUSED_ENERGY_CHUNK = 512


def _exec_span_pallas_fused(st0: dict, soa_np, spc, interp, meas_bits,
                            meas_valid, cfg: InterpreterConfig,
                            interpret, fargs, pack=None):
    """The measure-in-megastep span executor (``engine='fused'``): the
    whole forward-jump-only PHYSICS program as one Pallas call, with
    each measurement window demodulated inside the kernel the moment
    its trigger retires (docs/PERF.md "fused epoch").

    Semantically one epoch of :func:`_exec_straightline` plus the
    resolver, collapsed: ``meas_bits`` / ``meas_valid`` ride the carry
    as STATE, the :func:`_sl_apply_instr` bodies run with the
    ``fused`` directive so the bit lands in the slot at the trigger,
    and a later fproc read of that slot is served in-kernel — a
    branch-on-measurement program retires in ONE pass where the epoch
    loop needed an exec -> resolve -> inject round-trip per
    measurement layer.  ``fargs``: energy tables + responses from
    ``sim.physics`` (``e2`` [C, R, Wp] f32, ``g0``/``g1`` [C, 2] f32,
    static ``addrs``/``w``/``amp_scale``).  Returns
    ``(st, meas_bits, meas_valid)``.
    """
    from ..ops import exec_pallas
    counter_inc('pallas_trace')   # runs at trace time of the outer jit:
    # the fused path shares the pallas retrace contract (<= 1 per
    # program content)
    N = soa_np.shape[1]
    rows = [{name: np.asarray(soa_np[:, i, _F[name]])
             for name in _FIELDS}
            for i in range(N)]
    st = dict(st0)
    steps = st.pop('_steps')
    paused = st.pop('paused', None)   # [B] epoch flag, caller-managed
    st['meas_bits'] = meas_bits
    st['meas_valid'] = meas_valid
    addrs, W = fargs['addrs'], fargs['w']
    amp_scale = fargs['amp_scale']
    chunk = min(_FUSED_ENERGY_CHUNK, int(fargs['e2'].shape[2]))
    C = st['pc'].shape[1]

    def body(stt, cc, hh):
        stalled = jnp.zeros(stt['pc'].shape, bool)
        fus = {'e2': hh['e2'], 'g0': hh['g0'], 'g1': hh['g1'],
               'addrs': addrs, 'w': W, 'amp_scale': amp_scale,
               'chunk': chunk}
        for i, f in enumerate(rows):
            stt, stalled = _sl_apply_instr(
                stt, stalled, i, N, f, hh['spc'], hh['interp'],
                stt['meas_bits'], stt['meas_valid'], cfg, dev=None,
                fused=fus)
        # in-kernel bits are valid the instant they fire, so no lane
        # ever stalls on its own slot — phys_wait stays all-False and
        # the epoch loop exits after this single pass
        stt['phys_wait'] = stalled
        return stt

    out = exec_pallas.span_call(
        st, {},
        {'spc': spc, 'interp': interp, 'e2': fargs['e2'],
         'g0': fargs['g0'], 'g1': fargs['g1']},
        body, interpret=interpret, packspec=_packspec_decode(pack),
        shot_slack=8 * C * chunk)
    out['_steps'] = steps + N
    if paused is not None:
        out['paused'] = paused
    bits = out.pop('meas_bits')
    valid = out.pop('meas_valid')
    return out, bits, valid


def _exec_block_body_pallas(st: dict, act, rows_np, spc, interp,
                            cfg: InterpreterConfig, interpret,
                            packspec=None) -> dict:
    """Pallas form of :func:`_exec_block_body`: one superinstruction's
    ``[C, L, F]`` run as ONE kernel call over shot tiles, applying the
    same :func:`_blk_apply_row` bodies in VMEM.  ``act`` rides along
    as a tiled const (lane-activity mask from the block dispatcher)."""
    from ..ops import exec_pallas
    rows = [{name: np.asarray(rows_np[:, off, _F[name]])
             for name in _FIELDS}
            for off in range(rows_np.shape[1])]

    def body(stt, cc, hh):
        a = cc['act'] != 0
        for f in rows:
            stt = _blk_apply_row(stt, a, f, hh['spc'], hh['interp'], cfg)
        return stt

    return exec_pallas.span_call(st, {'act': act},
                                 {'spc': spc, 'interp': interp}, body,
                                 interpret=interpret, packspec=packspec)


def _exec_blocks(st0: dict, blk: tuple, spc, interp, sync_part, meas_bits,
                 meas_valid, cfg: InterpreterConfig, dev=None,
                 pallas_interpret=None, pallas_pack=None) -> dict:
    """The block-compiled engine: an outer while_loop over CFG blocks.

    Per iteration, each core either (a) takes ONE generic :func:`_step`
    — it is at a terminator (branch / fproc / sync / non-block
    position), where dynamic dispatch, fproc serves, sync exchange,
    and physics pause must happen — or (b) retires an ENTIRE deduped
    straight-line block via its specialized superinstruction.  The
    boundary step runs first (cores already parked at a block start
    are suppressed by reverting their per-core state slices — sound
    because every ``_step`` write is a per-core select, and cross-core
    fproc/sync reads only consume the iteration-START state either
    way); block ids are then recomputed so a core the boundary step
    just advanced onto a block start retires that block in the SAME
    iteration, and each deduped body runs masked by its id.  Masked
    application over the deduped body set is the vectorized form of a
    per-core ``lax.switch``: lanes diverge per (shot, core), so a
    scalar switch cannot dispatch them.

    ``_steps`` counts OUTER ITERATIONS here (each retires up to a full
    block per core), so ``stats['steps']`` is the engine's dispatch
    count — the quantity the engine ladder exists to shrink — and
    ``cfg.max_steps`` bounds iterations, never binding earlier than
    the generic engine's per-instruction budget.  Quiescence, deadlock
    flagging, physics pause, and the exactness select mirror
    :func:`_exec_loop` one-for-one.
    """
    soa_np = _soa_from_static(blk)
    bid_at, bodies = _block_plan(blk)
    traits = _soa_traits(soa_np)
    B, C = st0['pc'].shape
    N = soa_np.shape[1]
    soa = jnp.asarray(soa_np)
    # +1-encoded lookup so any out-of-range pc decodes to "no block"
    bid_tab = jnp.asarray(np.asarray(bid_at) + 1)

    def block_id(pc):
        if N <= _FETCH_ONEHOT_MAX:
            oh = (pc[..., None] == jnp.arange(N, dtype=jnp.int32)) \
                .astype(jnp.int32)
            return jnp.sum(bid_tab[None, None, :] * oh, axis=-1) - 1
        b = bid_tab[jnp.clip(pc, 0, N - 1)]
        return jnp.where((pc >= 0) & (pc < N), b, 0) - 1

    def cond(st):
        settled = jnp.all(st['done'], axis=-1)
        if cfg.physics:
            settled = settled | st['paused']
        return (~jnp.all(settled)) & (st['_steps'] < cfg.max_steps)

    def body(st):
        steps = st.pop('_steps')
        paused = st.pop('paused') if cfg.physics else None
        st_in = st
        # (1) boundary step, suppressed for cores parked at a block
        # start (they retire the whole block below instead)
        sup = block_id(st['pc']) >= 0
        st2 = _step(st, steps, soa, spc, interp, sync_part, meas_bits,
                    meas_valid, cfg, dev, traits)
        # transient: popped before the keep()/exactness dict sweeps
        # (st_in has no such key); suppressed cores were not really at
        # their instruction this iteration, so their flag is masked
        stall_sync = st2.pop('_stall_sync') & ~sup

        def keep(old, new):
            m = sup.reshape(sup.shape + (1,) * (new.ndim - 2))
            return jnp.where(m, old, new)
        st2 = {k: (keep(st_in[k], v)
                   if getattr(v, 'ndim', 0) >= 2 and v.shape[:2] == (B, C)
                   else v)
               for k, v in st2.items()}
        # (2) superinstructions: suppressed cores + cores the boundary
        # step just advanced onto a block start (bid fixed up front, so
        # a body that ends on another block's start waits an iteration)
        bid = block_id(st2['pc'])
        for k, (s, L) in enumerate(bodies):
            bact = (bid == jnp.int32(k)) & ~st2['done']
            if pallas_interpret is None:
                st2 = _exec_block_body(st2, bact, soa_np[:, s:s + L, :],
                                       spc, interp, cfg, dev)
            else:
                # pallas rung, block mode: same rows, bodies lowered to
                # one VMEM-resident kernel call each (_blk_apply_row is
                # shared, so the paths are bit-identical)
                st2 = _exec_block_body_pallas(
                    st2, bact, soa_np[:, s:s + L, :], spc, interp, cfg,
                    pallas_interpret, _packspec_decode(pallas_pack))
        # (3) quiescence / pause / deadlock / exactness per _exec_loop
        same = jnp.all((st2['pc'] == st_in['pc'])
                       & (st2['time'] == st_in['time'])
                       & (st2['done'] == st_in['done']), axis=-1)
        if cfg.physics:
            pending = jnp.any(st2['phys_wait'] & ~st2['done'], axis=-1)
            st2['paused'] = paused | (same & pending)
            hard = same & ~pending
        else:
            hard = same
        undone = hard[:, None] & ~st2['done']
        st2['err'] = jnp.where(undone, st2['err'] | ERR_FPROC_DEADLOCK,
                               st2['err'])
        st2['fault'] = st2['fault'] \
            | jnp.where(undone & stall_sync, FAULT_SYNC_DEADLOCK, 0) \
            | jnp.where(undone & ~stall_sync, FAULT_FPROC_STARVED, 0)
        st2['done'] = st2['done'] | hard[:, None]
        settled_in = jnp.all(st_in['done'], axis=-1)
        if cfg.physics:
            st_in = dict(st_in, paused=paused)
            settled_in = settled_in | paused
        ok = (steps < cfg.max_steps) & ~jnp.all(settled_in)
        st2 = {k: jnp.where(ok, v, st_in[k]) for k, v in st2.items()}
        st2['_steps'] = jnp.where(ok, steps + 1, steps)
        return st2

    return jax.lax.while_loop(cond, body, st0)


def _finalize(st: dict, cfg: InterpreterConfig) -> dict:
    steps = st.pop('_steps')
    if cfg.record_pulses:
        st.update(_split_records(st.pop('rec')))
    if 'op_hist' in st:
        # [B, C, N_KINDS] carry -> one [N_KINDS] retired-instruction
        # histogram per batch (engine-invariant; see opcode_histogram)
        st['op_hist'] = jnp.sum(st['op_hist'], axis=(0, 1))
    st['qclk'] = st['time'] - st['offset']
    st['steps'] = steps
    st['incomplete'] = ~jnp.all(st['done'])
    # a lane still live after every engine/epoch loop has returned ran
    # out of execution budget (max_steps, or the physics epoch cap) —
    # the one trap no step body can see locally
    st['fault'] = st['fault'] | jnp.where(~st['done'],
                                          FAULT_BUDGET_EXHAUSTED, 0)
    return st


def _check_fabric(cfg: InterpreterConfig, n_cores: int):
    if cfg.fabric == 'lut' and (len(cfg.lut_mask) != n_cores
                                or not cfg.lut_table):
        raise ValueError("fabric='lut' needs lut_mask (len n_cores) and "
                         "lut_table in the InterpreterConfig")


def _run_batch(soa, spc, interp, sync_part, meas_bits, cfg: InterpreterConfig,
               n_cores: int, init_regs=None, traits=None) -> dict:
    """Execute a shot batch: meas_bits ``[B, n_cores, max_meas]``
    (injected a priori and all valid — the cocotb-style path)."""
    # under shard_map n_cores is the LOCAL shard width; the lut fabric
    # validates against the full core axis, which sync_part (replicated,
    # full-width) still carries
    _check_fabric(cfg, n_cores if cfg.cores_axis is None
                  else int(sync_part.shape[0]))
    B = meas_bits.shape[0]
    st0 = _init_state(B, n_cores, cfg, init_regs)
    st0['_steps'] = jnp.int32(0)
    if cfg.physics:
        st0['paused'] = jnp.zeros((B,), bool)
    meas_valid = jnp.ones(meas_bits.shape, bool)
    st = _exec_loop(st0, soa, spc, interp, sync_part, meas_bits, meas_valid,
                    cfg, traits=traits)
    st.pop('paused', None)
    # engine-independent output schema: the straight-line executor pops
    # its internal stall carry too (_run_batch_sl_jit) — with every bit
    # injected valid a lane can never wait, so the key carries no
    # information on this path either way
    st.pop('phys_wait', None)
    return _finalize(st, cfg)


def _run_batch_engine(soa, spc, interp, sync_part, meas_bits,
                      cfg: InterpreterConfig, n_cores: int, init_regs=None,
                      traits=None, engine: str = 'generic',
                      prog: tuple = None, pack=None) -> dict:
    """Engine-dispatched :func:`_run_batch` for callers that build their
    own jit boundary (the shard_map locals in ``parallel.sweep``):
    ``engine`` is a RESOLVED engine name (:func:`resolve_engine`) and
    ``prog`` the :func:`_soa_static` tuple the specialized engines
    trace against (must be a host constant at trace time).  ``pack``
    is the optional :func:`carry_packspec` tuple for the pallas rung
    (host-static too — it is derived from the program)."""
    if engine == 'generic':
        return _run_batch(soa, spc, interp, sync_part, meas_bits, cfg,
                          n_cores, init_regs, traits)
    _check_fabric(cfg, n_cores)
    B = meas_bits.shape[0]
    st0 = _init_state(B, n_cores, cfg, init_regs)
    st0['_steps'] = jnp.int32(0)
    meas_valid = jnp.ones(meas_bits.shape, bool)
    if engine == 'straightline':
        st = _exec_straightline(st0, _soa_from_static(prog), spc, interp,
                                meas_bits, meas_valid, cfg)
    elif engine == 'block':
        if cfg.physics:
            st0['paused'] = jnp.zeros((B,), bool)
        st = _exec_blocks(st0, prog, spc, interp, sync_part, meas_bits,
                          meas_valid, cfg)
        st.pop('paused', None)
    elif engine == 'pallas':
        # physics/trace are pallas-ineligible (resolve_engine), so the
        # state carry is pure int32/bool and fits the kernel boundary
        itp = cfg.pallas_interpret
        if itp is None:
            itp = _default_pallas_interpret()
        if _pallas_mode(prog, cfg) == 'span':
            st = _exec_span_pallas(st0, _soa_from_static(prog), spc,
                                   interp, meas_bits, cfg, itp,
                                   pack=pack)
        else:
            st = _exec_blocks(st0, prog, spc, interp, sync_part,
                              meas_bits, meas_valid, cfg,
                              pallas_interpret=itp, pallas_pack=pack)
    else:
        raise ValueError(f'unresolved engine {engine!r}')
    st.pop('phys_wait', None)
    return _finalize(st, cfg)


def _run(soa, spc, interp, sync_part, meas_bits, cfg: InterpreterConfig,
         n_cores: int, init_regs=None, traits=None) -> dict:
    """Single-shot wrapper: meas_bits ``[n_cores, max_meas]``."""
    if init_regs is not None:
        init_regs = jnp.asarray(init_regs, jnp.int32)[None]
    out = _run_batch(soa, spc, interp, sync_part, meas_bits[None], cfg,
                     n_cores, init_regs, traits)
    return {k: (v if k in ('steps', 'incomplete', 'op_hist') else v[0])
            for k, v in out.items()}


@functools.partial(jax.jit, static_argnames=('cfg', 'n_cores', 'traits'))
def _run_jit(soa, spc, interp, sync_part, meas_bits, cfg, n_cores, init_regs,
             traits=None):
    return _run(soa, spc, interp, sync_part, meas_bits, cfg, n_cores,
                init_regs, traits)


@functools.partial(jax.jit, static_argnames=('cfg', 'n_cores', 'traits'))
def _run_batch_jit(soa, spc, interp, sync_part, meas_bits, cfg, n_cores,
                   init_regs, traits=None):
    return _run_batch(soa, spc, interp, sync_part, meas_bits, cfg, n_cores,
                      init_regs, traits)


@functools.partial(jax.jit, static_argnames=('cfg', 'n_cores', 'sl'))
def _run_batch_sl_jit(spc, interp, meas_bits, cfg, n_cores, init_regs,
                      sl=None):
    """Injected-bits batch on the straight-line executor (one pass —
    with every bit valid a lane can never stall)."""
    return _run_batch_engine(None, spc, interp, None, meas_bits, cfg,
                             n_cores, init_regs, engine='straightline',
                             prog=sl)


@functools.partial(jax.jit, static_argnames=('cfg', 'n_cores', 'blk'))
def _run_batch_blk_jit(spc, interp, sync_part, meas_bits, cfg, n_cores,
                       init_regs, blk=None):
    """Injected-bits batch on the block-compiled engine.  ``blk`` is the
    content-keyed static program (:func:`_soa_static`), so identical
    programs share one cache entry and the block table / superinstruction
    specialization happen at trace time."""
    counter_inc('block_trace')
    return _run_batch_engine(None, spc, interp, sync_part, meas_bits, cfg,
                             n_cores, init_regs, engine='block', prog=blk)


@functools.partial(jax.jit,
                   static_argnames=('cfg', 'n_cores', 'pal', 'pack'))
def _run_batch_pal_jit(spc, interp, sync_part, meas_bits, cfg, n_cores,
                       init_regs, pal=None, pack=None):
    """Injected-bits batch on the Pallas megastep engine.  ``pal`` is
    the content-keyed static program (:func:`_soa_static`) — identical
    programs share one cache entry, and the span/block mode pick plus
    the in-kernel instruction specialization happen at trace time.
    ``pack`` is the optional :func:`carry_packspec` static tuple."""
    counter_inc('pallas_trace')
    return _run_batch_engine(None, spc, interp, sync_part, meas_bits, cfg,
                             n_cores, init_regs, engine='pallas', prog=pal,
                             pack=pack)


def pallas_trace_count() -> int:
    """How many times the pallas-engine executor has been traced in
    this process (named counter ``'pallas_trace'`` — utils.profiling):
    the retrace contract allows at most one per (bucket, engine) pair."""
    return counter_get('pallas_trace')


def block_trace_count() -> int:
    """How many times the block-engine executor has been traced in this
    process (named counter ``'block_trace'`` — utils.profiling): the
    retrace contract allows at most one per (bucket, engine) pair."""
    return counter_get('block_trace')


def cores_trace_count() -> int:
    """How many times the sharded-cores executor has been traced in
    this process (named counter ``'cores_trace'`` — utils.profiling):
    the retrace contract allows at most one per mesh shape
    (``parallel.sweep`` caches the executor per (mesh, cfg, traits))."""
    return counter_get('cores_trace')


def multi_trace_count() -> int:
    """How many times the multi-program executor has been traced in
    this process — a second same-shape ensemble must not move it.
    (Named counter ``'multi_trace'`` in the utils.profiling registry.)"""
    return counter_get('multi_trace')


@functools.partial(jax.jit, static_argnames=('cfg', 'n_cores', 'traits'))
def _run_multi_batch_jit(soa, spc, interp, sync_part, meas_bits, cfg,
                         n_cores, init_regs, traits=None):
    """Program-as-data ensemble execution: vmap the generic engine over
    a leading program axis inside ONE jit.

    ``soa`` ``[n_progs, n_cores, n_instr, F]`` and ``sync_part`` /
    ``meas_bits`` / ``init_regs`` carry the program axis; ``spc`` /
    ``interp`` are ensemble-shared per-core constants.  The program
    tensor is a TRACED argument, so the jit cache keys on its SHAPE
    (the bucket), not its content — an entire RB ensemble compiles
    once, and fresh random sequences of the same shape are free.
    ``traits`` must be the UNION over the ensemble
    (:func:`program_traits` of the stacked program) so the shared step
    body covers every member.
    """
    counter_inc('multi_trace')

    def one_program(s, sy, mb, ir):
        return _run_batch(s, spc, interp, sy, mb, cfg, n_cores, ir,
                          traits)

    return jax.vmap(one_program)(soa, sync_part, meas_bits, init_regs)


# ---------------------------------------------------------------------------
# AOT bucket precompilation (docs/SERVING.md "cold start & warmup")
#
# jit dispatch populates its cache lazily — the first real request in a
# shape bucket pays the full XLA compile inside its latency budget.
# ``aot_compile_batch`` pays that compile AHEAD of traffic from a bare
# shape description (no program needed): it lowers
# ``_run_multi_batch_jit`` against abstract ``ShapeDtypeStruct`` inputs
# and holds the resulting ``Compiled`` executable in an explicit
# process-level cache.  The explicit cache is load-bearing:
# ``lower().compile()`` does NOT seed jit's own dispatch cache (they
# are separate tables), so ``simulate_multi_batch`` consults this one
# first and calls the precompiled executable directly on a hit.  A
# ``Compiled`` is shape/dtype-exact — exactly the bound-BucketSpec
# contract (serve/bucketspec.py) — and produces bit-identical results
# to the lazy path (tests/test_aot_warmup.py pins this per stat,
# fault word included).

_AOT_LOCK = threading.Lock()
# _aot_cache_key(...) -> jax.stages.Compiled, least-recently-USED
# first.  Bounded: a long-lived replica serving diverse traffic would
# otherwise pin every executable it ever compiled (each holds device
# buffers for its constants) — evictions cost a recompile on the next
# dispatch of that bucket, never correctness.  The named counter
# 'aot_evictions' counts them (aot_eviction_count()).
_AOT_CACHE: collections.OrderedDict = collections.OrderedDict()
_AOT_CACHE_CAP = int(os.environ.get('DPROC_AOT_CACHE_CAP', '256'))


def set_aot_cache_cap(cap: int) -> int:
    """Set the AOT executable cache bound (``DPROC_AOT_CACHE_CAP``
    gives the process default); returns the previous cap.  Lowering the
    cap evicts immediately, oldest-used first."""
    global _AOT_CACHE_CAP
    if cap < 1:
        raise ValueError('aot cache cap must be >= 1')
    with _AOT_LOCK:
        old, _AOT_CACHE_CAP = _AOT_CACHE_CAP, cap
        _evict_aot_locked()
    return old


def _evict_aot_locked() -> None:
    while len(_AOT_CACHE) > _AOT_CACHE_CAP:
        _AOT_CACHE.popitem(last=False)
        counter_inc('aot_evictions')


def _aot_cache_key(P, B, C, N, E, max_meas, cfg, traits, device):
    dev = None if device is None else (device.platform, device.id)
    return (int(P), int(B), int(C), int(N), int(E), int(max_meas),
            cfg, traits, dev)


def aot_compile_batch(spec, jax_device=None) -> float:
    """Ahead-of-time compile the multi-program executable a bound
    :class:`~..serve.bucketspec.BucketSpec` describes, pinned to
    ``jax_device`` (None = the default device).

    ``spec`` is duck-typed (``n_programs``/``n_shots``/``n_cores``/
    ``n_instr_bucket``/``max_elems``/``cfg``/``traits``) so this module
    needs no serve import.  ``spec.cfg`` must be jit-normalized the
    same way the dispatch path normalizes (the service's
    ``_normalize_cfg`` output already is; raw cfgs are re-normalized
    here defensively so both paths land on one cache key).

    Returns wall-clock compile seconds, or 0.0 when the executable was
    already cached (idempotent — safe to replay a catalog on every
    restart; JAX's persistent compilation cache makes the replays cheap
    across processes).
    """
    P, B = spec.n_programs, spec.n_shots
    if P is None or B is None:
        raise ValueError('aot_compile_batch needs a BOUND spec '
                         '(n_programs/n_shots set — BucketSpec.bind)')
    cfg = spec.cfg
    if cfg.straightline or cfg.engine in ('straightline', 'block',
                                          'pallas', 'fused'):
        raise ValueError('AOT precompilation covers the generic '
                         'multi-program engine only (content-keyed '
                         'engines have no shape-only executable)')
    if cfg.straightline is None or cfg.engine is not None:
        cfg = replace(cfg, straightline=False, engine=None)
    cfg, _ = _fault_policy(cfg)
    C, N, E = spec.n_cores, spec.n_instr_bucket, spec.max_elems
    key = _aot_cache_key(P, B, C, N, E, cfg.max_meas, cfg, spec.traits,
                         jax_device)
    with _AOT_LOCK:
        if key in _AOT_CACHE:
            _AOT_CACHE.move_to_end(key)
            return 0.0
    sds = jax.ShapeDtypeStruct
    soa = sds((P, C, N, len(_FIELDS)), jnp.int32)
    spc = sds((C, E), jnp.int32)
    interp = sds((C, E), jnp.int32)
    sync_part = sds((P, C), jnp.bool_)
    meas_bits = sds((P, B, C, cfg.max_meas), jnp.int32)
    init_regs = sds((P, B, C, isa.N_REGS), jnp.int32)
    ctx = jax.default_device(jax_device) if jax_device is not None \
        else contextlib.nullcontext()
    t0 = time.perf_counter()
    with ctx:
        compiled = _run_multi_batch_jit.lower(
            soa, spc, interp, sync_part, meas_bits, cfg, C, init_regs,
            spec.traits).compile()
    dt = time.perf_counter() - t0
    with _AOT_LOCK:
        # keep the first on a race — callers treat dt as "work done"
        _AOT_CACHE.setdefault(key, compiled)
        _AOT_CACHE.move_to_end(key)
        _evict_aot_locked()
    counter_inc('aot_compile')
    return dt


def _aot_lookup(P, B, C, N, E, max_meas, cfg, traits, device):
    with _AOT_LOCK:
        key = _aot_cache_key(P, B, C, N, E, max_meas, cfg, traits,
                             device)
        compiled = _AOT_CACHE.get(key)
        if compiled is not None:
            _AOT_CACHE.move_to_end(key)
        return compiled


def aot_batch_cached(spec, jax_device=None) -> bool:
    """Dispatch-classification hook: would a multi-batch dispatch of
    this bound spec hit a precompiled AOT executable on ``jax_device``
    right now?  Pure lookup — compiles nothing, never raises on an
    unbound or non-generic spec (returns False).  The serving tier's
    request tracing uses this to label dispatch spans cold / warm /
    aot (docs/OBSERVABILITY.md)."""
    P, B = spec.n_programs, spec.n_shots
    if P is None or B is None:
        return False
    cfg = spec.cfg
    if cfg.straightline or cfg.engine in ('straightline', 'block',
                                          'pallas', 'fused'):
        return False
    if cfg.straightline is None or cfg.engine is not None:
        cfg = replace(cfg, straightline=False, engine=None)
    cfg, _ = _fault_policy(cfg)
    return _aot_lookup(P, B, spec.n_cores, spec.n_instr_bucket,
                       spec.max_elems, cfg.max_meas, cfg, spec.traits,
                       jax_device) is not None


def aot_cache_size() -> int:
    with _AOT_LOCK:
        return len(_AOT_CACHE)


def clear_aot_cache() -> int:
    """Drop every precompiled executable (tests/conftest.py calls this
    at module boundaries alongside ``jax.clear_caches()`` so the
    per-process compiler footprint stays bounded).  Returns the number
    of entries dropped."""
    with _AOT_LOCK:
        n = len(_AOT_CACHE)
        _AOT_CACHE.clear()
    return n


def aot_compile_count() -> int:
    """How many AOT executables this process has compiled (named
    counter ``'aot_compile'``); ``'aot_hit'`` counts dispatches served
    by one."""
    return counter_get('aot_compile')


def aot_eviction_count() -> int:
    """How many executables the LRU bound has evicted in this process
    (named counter ``'aot_evictions'``)."""
    return counter_get('aot_evictions')


def span_trace_count() -> int:
    """How many times any span runner has been traced in this process —
    a sweep whose span divides its batch count must move it by one.
    (Named counter ``'span_trace'`` in the utils.profiling registry.)"""
    return counter_get('span_trace')


def make_span_runner(step):
    """Wrap a per-batch stats step (``key -> pytree of sums``) into a
    span runner: ONE dispatch executes ``span`` consecutive batches
    inside a ``lax.scan`` whose body derives each batch's key from the
    batch INDEX (``fold_in(key, start + j)`` computed in-carry — the
    same per-index stream as the host loop, so spanning and resuming
    reproduce it bit for bit) and folds the per-batch sums into an
    on-device carry.  Only the folded sums reach the host: one dispatch
    and one transfer per span instead of per batch.

    The carry argument is DONATED: the runner writes its output into
    the caller-provided stats buffers, so the accumulator never
    reallocates across spans — callers ping-pong two buffers,
    re-donating each only after fetching it to host
    (``parallel.sweep.run_spanned``).  Its VALUES are ignored (the scan
    starts from zeros built at trace time); only shapes/dtypes/sharding
    matter.  Never read a buffer after donating it: CPU tolerates that,
    TPU does not.

    ``span`` is static, so every full span of a sweep shares one
    compiled executable (``span_trace_count`` probes this) and a
    partial span at a resume point or the tail costs at most one trace
    each.  Bit-identity with the per-batch host loop holds because
    every accumulated stat is int32, whose addition is associative.
    """
    @functools.partial(jax.jit, static_argnames=('span',),
                       donate_argnums=(0,))
    def run_span(carry_in, key, start, span: int):
        counter_inc('span_trace')

        def body(carry, i):
            stats = step(jax.random.fold_in(key, i))
            return jax.tree.map(jnp.add, carry, stats), None

        init = jax.tree.map(jnp.zeros_like, carry_in)
        out, _ = jax.lax.scan(
            body, init, start + jnp.arange(span, dtype=jnp.int32))
        return out

    return run_span


def simulate_multi_batch(mps, meas_bits, init_regs=None,
                         cfg: InterpreterConfig = None, pad_to: int = None,
                         jax_device=None, _aot_device=None, **kw) -> dict:
    """Execute N programs x B shots in one compiled call.

    ``jax_device`` pins the dispatch to one accelerator device (inputs
    here are uncommitted host arrays, so ``jax.default_device`` decides
    placement) — and because pjit cache entries are per-device, each
    device pinned this way grows its own independent warm cache.  The
    multi-device serving tier (serve/service.py) gives every executor
    a hot cache exactly this way.  NOT ``cfg.device``, which selects
    the physics co-state model.

    ``mps``: a list of :class:`~..decoder.MachineProgram` (stacked here
    with shape-bucketed DONE padding — see ``decoder.
    stack_machine_programs``) or an already-stacked
    ``MultiMachineProgram``.  ``meas_bits``: ``[n_progs, n_shots,
    n_cores, n_meas]``, or ``[n_shots, n_cores, n_meas]`` broadcast to
    every program.  ``init_regs``: ``None``, ``[n_cores, 16]`` (shared),
    ``[n_progs, n_cores, 16]`` (per program), or the full
    ``[n_progs, n_shots, n_cores, 16]``.

    When ``cfg`` is omitted, the execution budget derives from the
    BUCKET shape (``max_steps = 2 * n_instr + 64``, ``max_pulses =
    n_instr + 2``), never from per-program content — content-derived
    budgets would retrace on every new ensemble and defeat the
    amortization this path exists for.

    Returns the :func:`simulate_batch` pytree with a leading program
    axis on every leaf (``steps`` and ``incomplete`` become ``[n_progs]``).
    Runs the generic engine only: the straight-line executor specializes
    on program content, which is exactly the compile-per-sequence cost
    being amortized away (``straightline=True`` raises).
    """
    if jax_device is not None:
        # recurse under the placement context; remember the device so
        # the AOT-cache lookup below keys on it (an executable compiled
        # for one device must not serve a dispatch pinned to another)
        with jax.default_device(jax_device):
            return simulate_multi_batch(mps, meas_bits, init_regs,
                                        cfg=cfg, pad_to=pad_to,
                                        _aot_device=jax_device, **kw)
    from ..decoder import MultiMachineProgram, stack_machine_programs
    mmp = mps if isinstance(mps, MultiMachineProgram) \
        else stack_machine_programs(mps, pad_to=pad_to)
    if cfg is None:
        kw.setdefault('max_steps', 2 * mmp.n_instr + 64)
        kw.setdefault('max_pulses', mmp.n_instr + 2)
        cfg = InterpreterConfig(**kw)
    else:
        cfg = replace(cfg, **kw)
    if cfg.straightline or cfg.engine in ('straightline', 'block',
                                          'pallas', 'fused'):
        raise ValueError(
            'simulate_multi_batch runs the generic engine only: the '
            'straight-line, block, and pallas executors key their '
            'caches on program content, the per-sequence compile this '
            'path amortizes away')
    _check_single_round(cfg)
    if cfg.straightline is None or cfg.engine is not None:
        # normalize 'auto'/'generic' to the one legacy cache key
        cfg = replace(cfg, straightline=False, engine=None)
    cfg, strict = _fault_policy(cfg)
    # _program_constants/program_traits consume the soa/tables attribute
    # surface, which MultiMachineProgram mirrors with a program axis;
    # traits become the UNION of instruction kinds over the ensemble
    soa, spc, interp, sync_part = _program_constants(mmp, cfg)
    P, C = mmp.n_progs, mmp.n_cores
    meas_bits = _pad_meas(meas_bits, cfg.max_meas)
    if meas_bits.ndim == 3:
        meas_bits = jnp.broadcast_to(meas_bits[None],
                                     (P,) + tuple(meas_bits.shape))
    if meas_bits.ndim != 4 or meas_bits.shape[0] != P \
            or meas_bits.shape[2] != C:
        raise ValueError(
            f'meas_bits must be [n_progs={P}, n_shots, n_cores={C}, '
            f'n_meas]; got {tuple(meas_bits.shape)}')
    B = meas_bits.shape[1]
    if init_regs is None:
        init_regs = jnp.zeros((P, B, C, isa.N_REGS), jnp.int32)
    else:
        init_regs = jnp.asarray(init_regs, jnp.int32)
        if init_regs.ndim == 2:          # [C, R] shared by everything
            init_regs = jnp.broadcast_to(init_regs[None, None],
                                         (P, B) + tuple(init_regs.shape))
        elif init_regs.ndim == 3:        # [P, C, R] per program
            if init_regs.shape[0] != P:
                raise ValueError(
                    f'3-D init_regs must be [n_progs={P}, n_cores, '
                    f'n_regs] (per-shot registers need the full 4-D '
                    f'form); got {tuple(init_regs.shape)}')
            init_regs = jnp.broadcast_to(
                init_regs[:, None], (P, B) + tuple(init_regs.shape[1:]))
    traits = program_traits(mmp)
    # AOT front door: a precompiled executable for this exact shape
    # bucket (and device pin) serves the dispatch with zero compile
    # risk; otherwise fall through to jit's lazy dispatch cache.
    fn = _aot_lookup(P, B, C, soa.shape[2], spc.shape[1], cfg.max_meas,
                     cfg, traits, _aot_device)
    if fn is not None:
        counter_inc('aot_hit')
        out = fn(soa, spc, interp, sync_part, meas_bits, init_regs)
    else:
        out = _run_multi_batch_jit(soa, spc, interp, sync_part,
                                   meas_bits, cfg, C, init_regs, traits)
    return _check_strict(out, strict)


# per-program scalars of the simulate_multi_batch result: every other
# leaf carries a shot axis after the program axis is sliced away
_MULTI_SCALAR_KEYS = ('steps', 'incomplete', 'op_hist')


def demux_multi_batch(out: dict, prog: int, n_shots: int = None) -> dict:
    """Per-program view of a :func:`simulate_multi_batch` result.

    Slices program ``prog`` off the leading axis of every leaf,
    restoring the exact :func:`simulate_batch` schema (``steps`` /
    ``incomplete`` become scalars again).  ``n_shots`` additionally
    trims the shot axis to the first ``n_shots`` lanes — the serving
    runtime pads short requests up to the coalesced batch's shot count
    by REPLICATING their own rows (execution is deterministic per lane,
    so replica lanes change nothing observable), and this is where the
    padding comes back off.  ``op_hist`` is the one aggregate a shot
    slice cannot demux (it is summed over lanes inside the jit); it is
    passed through per program, replica lanes included.
    """
    res = {}
    for k, v in out.items():
        vi = v[prog]
        if n_shots is not None and k not in _MULTI_SCALAR_KEYS:
            vi = vi[:n_shots]
        res[k] = vi
    return res


def _fault_policy(cfg: InterpreterConfig):
    """Split ``cfg.fault_mode`` into (jit cfg, strict flag).

    'strict' is purely a HOST-side policy — the cfg that reaches a jit
    is normalized to 'count' so both modes share one compiled
    executable (fault_mode is a static field; leaving it would split
    the cache for identical machine code)."""
    if cfg.fault_mode not in ('count', 'strict'):
        raise ValueError(
            f"fault_mode must be 'count' or 'strict'; got "
            f"{cfg.fault_mode!r}")
    if cfg.fault_mode == 'strict':
        return replace(cfg, fault_mode='count'), True
    return cfg, False


def _check_strict(out: dict, strict: bool) -> dict:
    """Raise :class:`FaultError` when strict and any lane trapped.
    Blocks on the device result — fail-fast trades away dispatch
    pipelining, which is why 'count' is the default."""
    if strict:
        counts = np.asarray(fault_shot_counts(out['fault']))
        if counts.any():
            raise FaultError(counts)
    return out


def _check_no_cores_axis(cfg: InterpreterConfig):
    """The single-device entry points trace no ``shard_map``, so a set
    ``cores_axis`` would reach an unbound mesh axis deep inside the
    step body — reject it typed at the front door instead."""
    if cfg.cores_axis is not None:
        raise ValueError(
            f'cores_axis={cfg.cores_axis!r} names a shard_map mesh '
            f'axis the single-device entry points cannot bind — run '
            f'via parallel.sweep.sharded_cores_simulate (or clear '
            f'cores_axis for single-device execution)')


def _check_single_round(cfg: InterpreterConfig):
    """The single-round entry points execute exactly one round per
    dispatch; a streaming config (``rounds > 1``) reaching them would
    silently serve one round of an R-round request — reject typed."""
    if cfg.rounds != 1:
        raise ValueError(
            f'cfg.rounds={cfg.rounds} is a streaming round count; the '
            f'single-round entry points execute one round per dispatch '
            f'— run via simulate_rounds (or clear rounds)')


def _pad_meas(meas_bits, max_meas: int):
    meas_bits = jnp.asarray(meas_bits, jnp.int32)
    if meas_bits.shape[-1] > max_meas:
        meas_bits = meas_bits[..., :max_meas]
    elif meas_bits.shape[-1] < max_meas:
        pad = [(0, 0)] * (meas_bits.ndim - 1) \
            + [(0, max_meas - meas_bits.shape[-1])]
        meas_bits = jnp.pad(meas_bits, pad)
    return meas_bits


def simulate(mp, meas_bits=None, init_regs=None,
             cfg: InterpreterConfig = None, **kw) -> dict:
    """Execute a decoded :class:`~..decoder.MachineProgram` on one shot.

    ``init_regs``: optional ``[n_cores, 16]`` initial register file — the
    batched sweep hook (register-parameterized pulses make amplitude /
    phase / time sweeps pure data, no recompilation).

    Returns the final machine state: pulse records (``rec_*`` arrays of
    shape ``[n_cores, max_pulses]`` valid up to ``n_pulses``), final
    registers, qclk values, per-core error bits, and completion flags.
    """
    cfg = replace(cfg, **kw) if cfg else InterpreterConfig(**kw)
    _check_no_cores_axis(cfg)
    _check_single_round(cfg)
    cfg, strict = _fault_policy(cfg)
    soa, spc, interp, sync_part = _program_constants(mp, cfg)
    if meas_bits is None:
        meas_bits = jnp.zeros((mp.n_cores, cfg.max_meas), jnp.int32)
    meas_bits = _pad_meas(meas_bits, cfg.max_meas)
    trim_regs = init_regs is None
    if init_regs is None:
        init_regs = jnp.zeros((mp.n_cores, isa.N_REGS), jnp.int32)
    init_regs = jnp.asarray(init_regs, jnp.int32)
    eng = resolve_engine(mp, cfg)
    if eng == 'fused':
        raise ValueError(
            "engine='fused' demodulates measurement windows in-kernel; "
            'the injected-bits entry points have no window — run via '
            'sim.physics.run_physics_batch')
    if eng == 'straightline':
        out = _run_batch_sl_jit(spc, interp, meas_bits[None], cfg,
                                mp.n_cores, init_regs[None],
                                sl=_soa_static(mp))
    elif eng == 'block':
        out = _run_batch_blk_jit(spc, interp, sync_part, meas_bits[None],
                                 cfg, mp.n_cores, init_regs[None],
                                 blk=_soa_static(mp))
    elif eng == 'pallas':
        pack = carry_packspec(mp, cfg, trim_regs=trim_regs) \
            if use_packed_carry(cfg) else None
        out = _run_batch_pal_jit(spc, interp, sync_part, meas_bits[None],
                                 cfg, mp.n_cores, init_regs[None],
                                 pal=_soa_static(mp), pack=pack)
    else:
        return _check_strict(
            _run_jit(soa, spc, interp, sync_part, meas_bits, cfg,
                     mp.n_cores, init_regs, program_traits(mp)), strict)
    return _check_strict(
        {k: (v if k in ('steps', 'incomplete', 'op_hist') else v[0])
         for k, v in out.items()}, strict)


def simulate_batch(mp, meas_bits, init_regs=None,
                   cfg: InterpreterConfig = None, jax_device=None,
                   **kw) -> dict:
    """Batch :func:`simulate` over a leading shot axis of ``meas_bits``
    (``[n_shots, n_cores, n_meas]``) — the reference re-runs shots on the
    host; here shots are the leading axis of every state array on the
    accelerator.  ``init_regs`` may also carry the shot/sweep-point axis.
    ``jax_device`` pins dispatch (and the jit cache entry) to one device
    — see :func:`simulate_multi_batch`."""
    if jax_device is not None:
        with jax.default_device(jax_device):
            return simulate_batch(mp, meas_bits, init_regs, cfg=cfg,
                                  **kw)
    cfg = replace(cfg, **kw) if cfg else InterpreterConfig(**kw)
    _check_no_cores_axis(cfg)
    _check_single_round(cfg)
    cfg, strict = _fault_policy(cfg)
    soa, spc, interp, sync_part = _program_constants(mp, cfg)
    meas_bits = _pad_meas(meas_bits, cfg.max_meas)
    trim_regs = init_regs is None
    init_regs = jnp.zeros((mp.n_cores, isa.N_REGS), jnp.int32) \
        if init_regs is None else jnp.asarray(init_regs, jnp.int32)
    if init_regs.ndim == 2:
        init_regs = jnp.broadcast_to(
            init_regs[None],
            (meas_bits.shape[0],) + tuple(init_regs.shape))
    eng = resolve_engine(mp, cfg)
    if eng == 'fused':
        raise ValueError(
            "engine='fused' demodulates measurement windows in-kernel; "
            'the injected-bits entry points have no window — run via '
            'sim.physics.run_physics_batch')
    if eng == 'straightline':
        return _check_strict(
            _run_batch_sl_jit(spc, interp, meas_bits, cfg, mp.n_cores,
                              init_regs, sl=_soa_static(mp)), strict)
    if eng == 'block':
        return _check_strict(
            _run_batch_blk_jit(spc, interp, sync_part, meas_bits, cfg,
                               mp.n_cores, init_regs,
                               blk=_soa_static(mp)), strict)
    if eng == 'pallas':
        pack = carry_packspec(mp, cfg, trim_regs=trim_regs) \
            if use_packed_carry(cfg) else None
        return _check_strict(
            _run_batch_pal_jit(spc, interp, sync_part, meas_bits, cfg,
                               mp.n_cores, init_regs,
                               pal=_soa_static(mp), pack=pack), strict)
    return _check_strict(
        _run_batch_jit(soa, spc, interp, sync_part, meas_bits, cfg,
                       mp.n_cores, init_regs, program_traits(mp)), strict)


@functools.partial(jax.jit,
                   static_argnames=('cfg', 'n_cores', 'traits', 'engine',
                                    'prog', 'pack', 'decode'))
def _run_rounds_jit(soa, spc, interp, sync_part, meas_bits, cfg, n_cores,
                    init_regs, traits=None, engine='generic', prog=None,
                    pack=None, decode=None):
    """R-round device-resident scan: one ``lax.scan`` over the leading
    round axis of ``meas_bits`` ``[R, B, C, M]``, each iteration the
    SAME engine body a single-round dispatch runs
    (:func:`_run_batch_engine` — bit-identity per round is by
    construction), each round from a fresh init state with that
    round's injected bits.  Outputs stack with a leading round axis
    (``steps``/``incomplete`` become ``[R]``); with ``decode`` set
    (a :class:`~..ops.decode.DecodeSpec`), the syndrome history is
    extracted and decoded INSIDE the same jit, so R rounds + the
    logical decode are one dispatch."""
    counter_inc('rounds_trace')

    def body(carry, mb):
        out = _run_batch_engine(soa, spc, interp, sync_part, mb, cfg,
                                n_cores, init_regs, traits=traits,
                                engine=engine, prog=prog, pack=pack)
        return carry, out

    _, st = jax.lax.scan(body, jnp.int32(0), meas_bits)
    if decode is not None:
        cores_idx = jnp.asarray(decode.cores, jnp.int32)
        hist = jnp.transpose(
            meas_bits[:, :, cores_idx, decode.slot], (1, 0, 2))
        st['syndrome_hist'] = hist
        st['decoded'] = decode_history(hist, decode.scheme)
    return st


def rounds_trace_count() -> int:
    """How many times the rounds-scan executor has been traced in this
    process (named counter ``'rounds_trace'`` — utils.profiling): the
    retrace contract allows at most one per (bucket, engine, rounds)
    triple."""
    return counter_get('rounds_trace')


def simulate_rounds(mp, meas_bits, init_regs=None,
                    cfg: InterpreterConfig = None, jax_device=None,
                    decode=None, **kw) -> dict:
    """Execute R syndrome rounds of one program in ONE dispatch
    (docs/PERF.md "Streaming QEC"): ``meas_bits`` is ``[rounds,
    n_shots, n_cores, n_meas]`` and a ``lax.scan`` over the round axis
    runs the resolved engine's batch body once per round — each round
    from a fresh init state with that round's injected bits, exactly
    what R sequential :func:`simulate_batch` dispatches compute, minus
    R-1 dispatch floors (the amortization the ``qec_streaming`` bench
    row measures).  Composes with the engine ladder: ``cfg.engine``
    picks generic/straightline/block/pallas per the usual eligibility
    rules ('fused' is rejected like every injected-bits entry).

    Returns the :func:`simulate_batch` pytree with a leading round
    axis on every leaf (``steps`` and ``incomplete`` become
    ``[rounds]``).  ``decode`` (a :class:`~..ops.decode.DecodeSpec`,
    tuple, or dict — see :func:`~..ops.decode.as_decode_spec`) adds
    ``syndrome_hist`` ``[n_shots, rounds, K]`` (the named cores'
    injected bits at the named slot) and ``decoded`` (the
    scheme-decoded correction) computed inside the same jit.

    ``cfg.rounds`` may pre-declare the round count (the serve tier's
    bucket identity does); it must then match the meas_bits round
    axis.  ``init_regs`` is shared across rounds (``[n_cores, 16]`` or
    ``[n_shots, n_cores, 16]``)."""
    if jax_device is not None:
        with jax.default_device(jax_device):
            return simulate_rounds(mp, meas_bits, init_regs, cfg=cfg,
                                   decode=decode, **kw)
    cfg = replace(cfg, **kw) if cfg else InterpreterConfig(**kw)
    _check_no_cores_axis(cfg)
    cfg, strict = _fault_policy(cfg)
    meas_bits = jnp.asarray(meas_bits, jnp.int32)
    if meas_bits.ndim != 4 or meas_bits.shape[2] != mp.n_cores:
        raise ValueError(
            f'meas_bits must be [rounds, n_shots, n_cores='
            f'{mp.n_cores}, n_meas]; got {tuple(meas_bits.shape)}')
    R = int(meas_bits.shape[0])
    if R < 1:
        raise ValueError('meas_bits must carry >= 1 round')
    if cfg.rounds != 1 and cfg.rounds != R:
        raise ValueError(
            f'cfg.rounds={cfg.rounds} contradicts the meas_bits round '
            f'axis {R}')
    cfg = replace(cfg, rounds=R)
    if decode is not None:
        from ..ops.decode import as_decode_spec
        decode = as_decode_spec(decode)
        bad = [c for c in decode.cores if not 0 <= c < mp.n_cores]
        if bad:
            raise ValueError(
                f'decode.cores {bad} out of range for n_cores='
                f'{mp.n_cores}')
        if not 0 <= decode.slot < cfg.max_meas:
            raise ValueError(
                f'decode.slot={decode.slot} out of range for '
                f'max_meas={cfg.max_meas}')
    soa, spc, interp, sync_part = _program_constants(mp, cfg)
    meas_bits = _pad_meas(meas_bits, cfg.max_meas)
    trim_regs = init_regs is None
    init_regs = jnp.zeros((mp.n_cores, isa.N_REGS), jnp.int32) \
        if init_regs is None else jnp.asarray(init_regs, jnp.int32)
    if init_regs.ndim == 2:
        init_regs = jnp.broadcast_to(
            init_regs[None],
            (meas_bits.shape[1],) + tuple(init_regs.shape))
    eng = resolve_engine(mp, cfg)
    if eng == 'fused':
        raise ValueError(
            "engine='fused' demodulates measurement windows in-kernel; "
            'the injected-bits entry points have no window — run via '
            'sim.physics.run_physics_batch')
    traits = prog = pack = None
    if eng == 'generic':
        traits = program_traits(mp)
    else:
        prog = _soa_static(mp)
        soa = None
        if eng == 'pallas' and use_packed_carry(cfg):
            pack = carry_packspec(mp, cfg, trim_regs=trim_regs)
    return _check_strict(
        _run_rounds_jit(soa, spc, interp, sync_part, meas_bits, cfg,
                        mp.n_cores, init_regs, traits=traits,
                        engine=eng, prog=prog, pack=pack,
                        decode=decode), strict)
