"""Vectorised JAX interpreter for the distributed-processor ISA.

This is the TPU-native replacement for the reference's per-qubit RTL
cores (reference: hdl/proc.sv + hdl/ctrl.v): instead of N soft CPUs
stepping an FSM, every core of every shot advances one *instruction* per
``lax.while_loop`` iteration, with all per-core state held in int32
arrays shaped ``[n_cores, ...]`` (``vmap`` adds the shot axis).  Cross-
core coupling — the sync barrier and the measurement (fproc) fabric — is
computed with masked reductions over the core axis each step, which is
the lockstep-convergence equivalent of the reference's `sync_iface` /
`fproc_iface` wiring (reference: hdl/sync_iface.sv, hdl/fproc_meas.sv,
hdl/core_state_mgr.sv).

Timing semantics match :mod:`.oracle` (the scalar golden model) exactly;
see that module's docstring for the contract.  The instruction-cost
model is the Schedule pass's (`ir/passes.py _TimedPass`), so any program
the compiler schedules executes without trigger misses by construction;
a program that *would* stall the hardware issue pipeline sets an error
bit instead of silently sliding the pulse (the runtime analog of
LintSchedule — reference: python/distproc/ir/passes.py:785-791).

Measurement bits are injected per (shot, core, measurement-index) —
exactly the strategy the reference's cocotb testbench uses to stand in
for the readout chain (reference: cocotb/proc/test_proc.py:441-446,
sim_modules/toplevel_sim.sv:16-18).  The DSP path (ops/) produces these
bits from demodulated waveforms when physics-in-the-loop is wanted.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, replace

import numpy as np
import jax
import jax.numpy as jnp

from .. import isa
from ..hwconfig import FPGAConfig
from .oracle import INIT_TIME, QCLK_RST_DELAY, MEAS_LATENCY

INT32_MAX = np.int32(2**31 - 1)

# error bits (per core)
ERR_MISSED_TRIG = 1      # pulse/idle trigger time already passed at issue
ERR_PULSE_OVERFLOW = 2   # more pulses than the static record buffer
ERR_MEAS_OVERFLOW = 4    # more measurements than meas_bits provides
ERR_FPROC_DEADLOCK = 8   # fproc read with producer halted and no data
ERR_SYNC_DONE = 16       # barrier released with a participant already done
ERR_FPROC_ID = 32        # fproc func_id out of range

_PMASKS = np.array([0xffffff, 0x1ffff, 0x1ff, 0xffff, 0xf], dtype=np.int32)
# field order matches isa.PULSE_PARAM_ORDER = (env, phase, freq, amp, cfg)


@dataclass(frozen=True)
class InterpreterConfig:
    """Static execution parameters (all shape-determining or trace-constant)."""
    max_steps: int = 4096
    max_pulses: int = 256
    max_meas: int = 64
    max_resets: int = 8
    fabric: str = 'sticky'        # 'sticky' | 'fresh'
    meas_elem: int = 2            # element index whose pulses are readouts
    meas_latency: int = MEAS_LATENCY
    alu_instr_clks: int = 5
    jump_cond_clks: int = 5
    jump_fproc_clks: int = 8
    pulse_regwrite_clks: int = 3
    pulse_load_clks: int = 3

    @classmethod
    def from_fpga_config(cls, fpga_config: FPGAConfig, **kw) -> 'InterpreterConfig':
        return cls(alu_instr_clks=fpga_config.alu_instr_clks,
                   jump_cond_clks=fpga_config.jump_cond_clks,
                   jump_fproc_clks=fpga_config.jump_fproc_clks,
                   pulse_regwrite_clks=fpga_config.pulse_regwrite_clks,
                   pulse_load_clks=fpga_config.pulse_load_clks, **kw)


def _alu_vec(op, in0, in1):
    """Vectorised 8-op ALU on int32 lanes (reference: hdl/alu.v:31-51)."""
    return jnp.select(
        [op == 0, op == 1, op == 2, op == 3, op == 4, op == 5, op == 6],
        [in0, in0 + in1, in0 - in1,
         (in0 == in1).astype(jnp.int32), (in0 <= in1).astype(jnp.int32),
         (in0 >= in1).astype(jnp.int32), in1],
        jnp.zeros_like(in0))


def _program_constants(mp, cfg: InterpreterConfig):
    """Host-side: freeze the decoded program into device constants."""
    soa = {f: jnp.asarray(getattr(mp.soa, f)) for f in (
        'kind', 'alu_op', 'in0_is_reg', 'imm', 'in0_reg', 'in1_reg', 'out_reg',
        'jump_addr', 'func_id', 'cmd_time',
        'p_env', 'p_phase', 'p_freq', 'p_amp', 'p_cfg',
        'p_wen', 'p_regsel', 'p_reg')}
    n_cores = mp.n_cores
    max_elems = max((len(t.elem_cfgs) for t in mp.tables), default=0) or 1
    spc = np.ones((n_cores, max_elems), dtype=np.int32)
    interp = np.zeros((n_cores, max_elems), dtype=np.int32)
    for c, t in enumerate(mp.tables):
        for e, ec in enumerate(t.elem_cfgs):
            spc[c, e] = ec.samples_per_clk
            interp[c, e] = ec.interp_ratio
    return soa, jnp.asarray(spc), jnp.asarray(interp), \
        jnp.asarray(mp.sync_participants)


def _init_state(n_cores: int, cfg: InterpreterConfig,
                init_regs=None) -> dict:
    C, P, M, R = n_cores, cfg.max_pulses, cfg.max_meas, cfg.max_resets
    z = lambda *s: jnp.zeros(s, dtype=jnp.int32)
    regs = z(C, isa.N_REGS) if init_regs is None \
        else jnp.asarray(init_regs, jnp.int32)
    return dict(
        pc=z(C), regs=regs,
        time=jnp.full((C,), INIT_TIME, jnp.int32), offset=z(C),
        done=jnp.zeros((C,), bool), err=z(C), pp=z(C, 5),
        n_pulses=z(C),
        rec_qtime=z(C, P), rec_gtime=z(C, P), rec_env=z(C, P),
        rec_phase=z(C, P), rec_freq=z(C, P), rec_amp=z(C, P),
        rec_cfg=z(C, P), rec_elem=z(C, P), rec_dur=z(C, P),
        n_resets=z(C), rst_time=z(C, R),
        n_meas=z(C), meas_avail=jnp.full((C, M), INT32_MAX, jnp.int32),
    )


def _step(st: dict, soa: dict, spc, interp, sync_part, meas_bits,
          cfg: InterpreterConfig) -> dict:
    C = st['pc'].shape[0]
    cidx = jnp.arange(C)
    pc = jnp.clip(st['pc'], 0, soa['kind'].shape[1] - 1)
    g = lambda f: soa[f][cidx, pc]
    kind = g('kind')
    live = ~st['done']
    time, offset, regs = st['time'], st['offset'], st['regs']

    # ---- operand fetch -------------------------------------------------
    in0 = jnp.where(g('in0_is_reg') == 1, regs[cidx, g('in0_reg')], g('imm'))
    qclk = time - offset
    is_fproc = (kind == isa.K_ALU_FPROC) | (kind == isa.K_JUMP_FPROC)

    # ---- fproc fabric (reference: hdl/fproc_meas.sv / core_state_mgr.sv)
    fid = g('func_id')
    fid_bad = fid >= C
    prod = jnp.clip(fid, 0, C - 1)
    req = time
    mavail_p = st['meas_avail'][prod]                       # [C, M]
    nmeas_p = st['n_meas'][prod]
    prod_done = st['done'][prod]
    if cfg.fabric == 'sticky':
        # bit latched at read time; producer must have simulated past `req`
        f_ready = prod_done | (st['time'][prod] >= req)
        m_cnt = jnp.sum(mavail_p <= req[:, None], axis=1)
        f_data = jnp.where(m_cnt > 0,
                           meas_bits[prod, jnp.maximum(m_cnt - 1, 0)], 0)
        f_tready = req
        f_deadlock = jnp.zeros((C,), bool)
    else:
        # fresh: first measurement completing strictly after the request
        fresh = (mavail_p > req[:, None]) & \
            (jnp.arange(cfg.max_meas)[None, :] < nmeas_p[:, None])
        exists = jnp.any(fresh, axis=1)
        j = jnp.argmax(fresh, axis=1)
        f_data = jnp.where(exists, meas_bits[prod, j], 0)
        f_tready = jnp.where(exists, jnp.maximum(req, mavail_p[cidx, j]), req)
        f_deadlock = ~exists & prod_done
        f_ready = exists | f_deadlock
    f_ready = f_ready | fid_bad
    f_data = jnp.where(fid_bad, 0, f_data)

    # ---- ALU (in1 mux per reference: hdl/proc.sv:111) ------------------
    in1 = jnp.where(is_fproc, f_data,
                    jnp.where(kind == isa.K_INC_QCLK, qclk,
                              regs[cidx, g('in1_reg')]))
    alu_res = _alu_vec(g('alu_op'), in0, in1)

    # ---- sync barrier (reference: ctrl.v:510-552 + qclk reset) ---------
    at_sync = live & (kind == isa.K_SYNC)
    live_part = sync_part & live
    sync_ready = jnp.any(at_sync) & jnp.all(~live_part | at_sync)
    release = jnp.max(jnp.where(at_sync, time, -INT32_MAX)) + QCLK_RST_DELAY
    sync_adv = at_sync & sync_ready
    sync_err = sync_ready & jnp.any(sync_part & st['done'])

    # ---- stall mask ----------------------------------------------------
    stalled = (is_fproc & ~f_ready) | (at_sync & ~sync_ready)
    adv = live & ~stalled                     # cores executing this step

    # ---- pulse-register latch + trigger --------------------------------
    is_pw = kind == isa.K_PULSE_WRITE
    is_pt = kind == isa.K_PULSE_TRIG
    is_pulse = (is_pw | is_pt) & adv
    imm_vals = jnp.stack([g('p_env'), g('p_phase'), g('p_freq'),
                          g('p_amp'), g('p_cfg')], axis=1)       # [C, 5]
    wen = (g('p_wen')[:, None] >> jnp.arange(5)[None, :]) & 1
    rsel = (g('p_regsel')[:, None] >> jnp.arange(5)[None, :]) & 1
    regval = regs[cidx, g('p_reg')]
    cand = jnp.where(rsel == 1, regval[:, None], imm_vals) & _PMASKS[None, :]
    pp = jnp.where(is_pulse[:, None] & (wen == 1), cand, st['pp'])

    cmd_time = g('cmd_time')                  # uint32 bit pattern
    trig = offset + cmd_time
    missed_trig = is_pt & adv & (trig < time)
    trig = jnp.maximum(trig, time)
    elem = pp[:, 4] & 0b11
    elem_c = jnp.minimum(elem, spc.shape[1] - 1)
    envw = pp[:, 0]
    env_len = (envw >> 12) & 0xfff
    nsamp = env_len * 4 * interp[cidx, elem_c]
    dur = jnp.where(env_len == 0xfff, 0,
                    (nsamp + spc[cidx, elem_c] - 1) // spc[cidx, elem_c])

    fire = is_pt & adv
    slot = jnp.minimum(st['n_pulses'], cfg.max_pulses - 1)
    rec_of = jnp.where(fire & (st['n_pulses'] >= cfg.max_pulses),
                       ERR_PULSE_OVERFLOW, 0)
    new_rec = {}
    for name, val in (('qtime', cmd_time), ('gtime', trig),
                      ('env', pp[:, 0]), ('phase', pp[:, 1]),
                      ('freq', pp[:, 2]), ('amp', pp[:, 3]),
                      ('cfg', pp[:, 4]), ('elem', elem), ('dur', dur)):
        arr = st['rec_' + name]
        new_rec['rec_' + name] = arr.at[cidx, slot].set(
            jnp.where(fire, val, arr[cidx, slot]))
    n_pulses = st['n_pulses'] + fire.astype(jnp.int32)

    is_meas_pulse = fire & (elem == cfg.meas_elem)
    mslot = jnp.minimum(st['n_meas'], cfg.max_meas - 1)
    meas_of = jnp.where(is_meas_pulse & (st['n_meas'] >= cfg.max_meas),
                        ERR_MEAS_OVERFLOW, 0)
    meas_avail = st['meas_avail'].at[cidx, mslot].set(
        jnp.where(is_meas_pulse, trig + dur + cfg.meas_latency,
                  st['meas_avail'][cidx, mslot]))
    n_meas = st['n_meas'] + is_meas_pulse.astype(jnp.int32)

    # ---- phase reset record --------------------------------------------
    is_rst = (kind == isa.K_PULSE_RESET) & adv
    rslot = jnp.minimum(st['n_resets'], cfg.max_resets - 1)
    rst_time = st['rst_time'].at[cidx, rslot].set(
        jnp.where(is_rst, time, st['rst_time'][cidx, rslot]))
    n_resets = st['n_resets'] + is_rst.astype(jnp.int32)

    # ---- idle ----------------------------------------------------------
    is_idle = (kind == isa.K_IDLE) & adv
    idle_end = offset + cmd_time
    missed_idle = is_idle & (time > idle_end)
    idle_end = jnp.maximum(idle_end, time)

    # ---- register writeback --------------------------------------------
    wr_reg = ((kind == isa.K_REG_ALU) | (kind == isa.K_ALU_FPROC)) & adv
    out_reg = g('out_reg')
    regs = regs.at[cidx, out_reg].set(
        jnp.where(wr_reg, alu_res, regs[cidx, out_reg]))

    # ---- next pc -------------------------------------------------------
    branch_taken = (alu_res & 1) == 1
    pc_next = jnp.select(
        [kind == isa.K_JUMP_I,
         (kind == isa.K_JUMP_COND) | (kind == isa.K_JUMP_FPROC)],
        [g('jump_addr'),
         jnp.where(branch_taken, g('jump_addr'), st['pc'] + 1)],
        st['pc'] + 1)
    pc_next = jnp.where(sync_adv, st['pc'] + 1, pc_next)
    is_done = (kind == isa.K_DONE) & adv
    pc_next = jnp.where(adv & ~is_done, pc_next, st['pc'])

    # ---- next time / qclk offset ---------------------------------------
    time_next = jnp.select(
        [is_pt, is_pw | is_rst, is_idle,
         (kind == isa.K_REG_ALU) | (kind == isa.K_INC_QCLK),
         (kind == isa.K_JUMP_I) | (kind == isa.K_JUMP_COND),
         is_fproc],
        [trig + cfg.pulse_load_clks,
         time + cfg.pulse_regwrite_clks,
         idle_end + cfg.pulse_load_clks,
         time + cfg.alu_instr_clks,
         time + cfg.jump_cond_clks,
         f_tready + cfg.jump_fproc_clks],
        time)
    time_next = jnp.where(sync_adv, release, time_next)
    time_next = jnp.where(adv, time_next, time)

    # inc_qclk loads qclk = alu_res (with hardware pipeline compensation,
    # reference: hdl/qclk.v:17); sync resets qclk to 0 at release
    offset_next = jnp.where((kind == isa.K_INC_QCLK) & adv,
                            time - alu_res, offset)
    offset_next = jnp.where(sync_adv, release, offset_next)

    err = st['err'] | rec_of | meas_of \
        | jnp.where(missed_trig | missed_idle, ERR_MISSED_TRIG, 0) \
        | jnp.where(is_fproc & adv & fid_bad, ERR_FPROC_ID, 0) \
        | jnp.where(is_fproc & adv & f_deadlock, ERR_FPROC_DEADLOCK, 0) \
        | jnp.where(sync_adv & sync_err, ERR_SYNC_DONE, 0)

    return dict(st, pc=pc_next, regs=regs, time=time_next, offset=offset_next,
                done=st['done'] | is_done, err=err, pp=pp, n_pulses=n_pulses,
                n_resets=n_resets, rst_time=rst_time,
                n_meas=n_meas, meas_avail=meas_avail, **new_rec)


def _run(soa, spc, interp, sync_part, meas_bits, cfg: InterpreterConfig,
         n_cores: int, init_regs=None) -> dict:
    st0 = _init_state(n_cores, cfg, init_regs)
    st0['_steps'] = jnp.int32(0)

    def cond(st):
        return (~jnp.all(st['done'])) & (st['_steps'] < cfg.max_steps)

    def body(st):
        steps = st.pop('_steps')
        # detect global deadlock: every live core stalled => no state change
        st2 = _step(st, soa, spc, interp, sync_part, meas_bits, cfg)
        same = jnp.all(jnp.array(
            [jnp.all(st2[k] == st[k]) for k in ('pc', 'time', 'done')]))
        st2['err'] = jnp.where(same & ~st2['done'],
                               st2['err'] | ERR_FPROC_DEADLOCK, st2['err'])
        st2['done'] = st2['done'] | same
        st2['_steps'] = steps + 1
        return st2

    st = jax.lax.while_loop(cond, body, st0)
    steps = st.pop('_steps')
    st['qclk'] = st['time'] - st['offset']
    st['steps'] = steps
    st['incomplete'] = ~jnp.all(st['done'])
    return st


@functools.partial(jax.jit, static_argnames=('cfg', 'n_cores'))
def _run_jit(soa, spc, interp, sync_part, meas_bits, cfg, n_cores, init_regs):
    return _run(soa, spc, interp, sync_part, meas_bits, cfg, n_cores,
                init_regs)


def _pad_meas(meas_bits, max_meas: int):
    meas_bits = jnp.asarray(meas_bits, jnp.int32)
    if meas_bits.shape[-1] > max_meas:
        meas_bits = meas_bits[..., :max_meas]
    elif meas_bits.shape[-1] < max_meas:
        pad = [(0, 0)] * (meas_bits.ndim - 1) \
            + [(0, max_meas - meas_bits.shape[-1])]
        meas_bits = jnp.pad(meas_bits, pad)
    return meas_bits


def simulate(mp, meas_bits=None, init_regs=None,
             cfg: InterpreterConfig = None, **kw) -> dict:
    """Execute a decoded :class:`~..decoder.MachineProgram` on one shot.

    ``init_regs``: optional ``[n_cores, 16]`` initial register file — the
    batched sweep hook (register-parameterized pulses make amplitude /
    phase / time sweeps pure data, no recompilation).

    Returns the final machine state: pulse records (``rec_*`` arrays of
    shape ``[n_cores, max_pulses]`` valid up to ``n_pulses``), final
    registers, qclk values, per-core error bits, and completion flags.
    """
    cfg = replace(cfg, **kw) if cfg else InterpreterConfig(**kw)
    soa, spc, interp, sync_part = _program_constants(mp, cfg)
    if meas_bits is None:
        meas_bits = jnp.zeros((mp.n_cores, cfg.max_meas), jnp.int32)
    meas_bits = _pad_meas(meas_bits, cfg.max_meas)
    if init_regs is None:
        init_regs = jnp.zeros((mp.n_cores, isa.N_REGS), jnp.int32)
    init_regs = jnp.asarray(init_regs, jnp.int32)
    return _run_jit(soa, spc, interp, sync_part, meas_bits, cfg, mp.n_cores,
                    init_regs)


def simulate_batch(mp, meas_bits, init_regs=None,
                   cfg: InterpreterConfig = None, **kw) -> dict:
    """vmap :func:`simulate` over a leading shot axis of ``meas_bits``
    (``[n_shots, n_cores, n_meas]``) — the reference re-runs shots on the
    host; here shots are a vectorised batch axis on the accelerator.
    ``init_regs`` may also carry a leading shot/sweep-point axis."""
    cfg = replace(cfg, **kw) if cfg else InterpreterConfig(**kw)
    soa, spc, interp, sync_part = _program_constants(mp, cfg)
    meas_bits = _pad_meas(meas_bits, cfg.max_meas)
    if init_regs is None:
        fn = jax.jit(jax.vmap(lambda mb: _run(
            soa, spc, interp, sync_part, mb, cfg, mp.n_cores)))
        return fn(meas_bits)
    init_regs = jnp.asarray(init_regs, jnp.int32)
    fn = jax.jit(jax.vmap(lambda mb, ir: _run(
        soa, spc, interp, sync_part, mb, cfg, mp.n_cores, ir)))
    return fn(meas_bits, init_regs)
