"""Differentiable physics: gradients through the readout/drive chain.

The forward models in :mod:`sim.physics` are already pure JAX, but two
points in the chain are non-differentiable by construction: the
measurement *branch* (traffic-dependent control flow on fproc bits) and
the discrimination threshold (:func:`~.physics._acc_to_bit` — a hard
``proj > 0``).  This module provides the calibration service
(:mod:`..calib`) with a differentiable mirror of the
pulse -> envelope -> window-synthesis -> demod -> discrimination path
plus explicit estimator choices at the discrete points
(docs/CALIBRATION.md "Estimators at branch points"):

* **smooth observables** — everything upstream of the threshold
  (matched-filter projection, window energy, assignment-error
  probability via the Gaussian error function) differentiates exactly;
  finite-difference agreement is pinned in tests/test_calib.py.
* **straight-through** (:func:`st_threshold`) — forward pass is the
  exact hard bit, backward pass substitutes a sigmoid surrogate
  (``custom_vjp``); the hard threshold itself has an exactly-zero
  gradient (also pinned).
* **score function** (:func:`score_function_grad`) — REINFORCE for
  losses of *sampled* bits where the branch taken depends on traffic:
  unbiased, needs no path derivative through the branch at all.

Everything here is float32 (the interpreter's native dtype); the
envelope mirrors :func:`~..envelopes.drag` numerically, and the
discriminator mirrors :func:`~.physics._discriminate_acc` term for
term, so a gradient taken here linearizes the same arithmetic the
serving tier executes.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

# the interpreter's amplitude word scale: gate amp a in [0, 1] compiles
# to round(a * AMP_SCALE) (isa amp_word); executed rec_amp words map
# back through the same constant
AMP_SCALE = float(2 ** 16 - 1)


# ---------------------------------------------------------------------------
# differentiable envelope synthesis (mirror of envelopes.drag)
# ---------------------------------------------------------------------------

def drag_envelope(amp, alpha, *, twidth: float = 24e-9,
                  sigmas: float = 3.0, delta: float = -270e6,
                  sample_rate: float = 1e9):
    """Complex DRAG envelope, differentiable in ``amp`` and ``alpha``.

    Numerically mirrors :func:`~..envelopes.drag` (gaussian I with
    edge lift, Q = alpha * dI/dt / (2 pi delta), peak renorm when the
    peak exceeds 1) with jnp ops so ``jax.grad`` flows through both
    the amplitude and the DRAG coefficient.  Returns ``(env_i, env_q)``
    float32 arrays of ``round(twidth * sample_rate)`` samples.
    """
    n = int(round(twidth * sample_rate))
    sigma = twidth / sigmas
    t = (jnp.arange(n, dtype=jnp.float32) + 0.5) / sample_rate \
        - twidth / 2
    env_i = jnp.exp(-t ** 2 / (2 * sigma ** 2))
    edge = jnp.exp(-(twidth / 2) ** 2 / (2 * sigma ** 2))
    env_i = (env_i - edge) / (1 - edge)
    d_env = -(t / sigma ** 2) * jnp.exp(-t ** 2 / (2 * sigma ** 2)) \
        / (1 - edge)
    env_q = alpha * d_env / (2 * jnp.pi * delta)
    peak = jnp.sqrt(jnp.max(env_i ** 2 + env_q ** 2))
    renorm = jnp.maximum(peak, 1.0)
    scale = amp / renorm
    return (scale * env_i).astype(jnp.float32), \
        (scale * env_q).astype(jnp.float32)


def drag_leakage(alpha, *, twidth: float = 24e-9, sigmas: float = 3.0,
                 delta: float = -270e6, sample_rate: float = 1e9):
    """Spectral leakage proxy for the DRAG knob: the envelope's power
    at the anharmonic transition's detuning ``delta``.

    ``|sum_t (I(t) + iQ(t)) exp(-2 pi i delta t)|^2``, normalized by
    the zero-detuning power so the loss is O(1).  To first order the
    derivative quadrature cancels the gaussian's spectral weight at
    ``delta``, so the minimum sits near alpha = 1 (the discrete
    sampling and edge lift shift it slightly); gradient descent on
    this loss is the DRAG-coefficient calibration loop's inner model.
    """
    env_i, env_q = drag_envelope(1.0, alpha, twidth=twidth,
                                 sigmas=sigmas, delta=delta,
                                 sample_rate=sample_rate)
    n = env_i.shape[0]
    t = (jnp.arange(n, dtype=jnp.float32) + 0.5) / sample_rate
    ph = -2 * jnp.pi * delta * t
    c, s = jnp.cos(ph), jnp.sin(ph)
    # (I + iQ) * (cos + i sin), accumulated
    re = jnp.sum(env_i * c - env_q * s)
    im = jnp.sum(env_i * s + env_q * c)
    norm = jnp.sum(env_i) ** 2 + jnp.sum(env_q) ** 2
    return (re ** 2 + im ** 2) / norm


# ---------------------------------------------------------------------------
# differentiable drive response (amplitude knob)
# ---------------------------------------------------------------------------

def bloch_p1(amp, x90_amp):
    """Excited-state population after one drive at ``amp``: the Bloch
    rotation model the statevec device implements — a drive is a
    rotation by ``theta = (pi/2) * amp / x90_amp`` about X, so
    ``p1 = sin^2(theta / 2)``.  Smooth in ``amp``; the amplitude
    calibration loss ``(p1 - 1/2)^2`` has its minimum exactly at the
    device's true X90 amplitude."""
    theta = (jnp.pi / 2) * amp / x90_amp
    return jnp.sin(theta / 2) ** 2


# ---------------------------------------------------------------------------
# differentiable readout window (placement knob)
# ---------------------------------------------------------------------------

def window_mask(start, width, horizon: int, *, edge: float = 4.0):
    """Soft-edged integration window over ``horizon`` ADC samples:
    ``sigmoid((s - start)/edge) - sigmoid((s - start - width)/edge)``.
    Differentiable in ``start`` (the placement knob); samples past the
    horizon simply do not exist, which is what makes the placement
    optimum interior (see :func:`window_snr`)."""
    s = jnp.arange(horizon, dtype=jnp.float32)
    return jax.nn.sigmoid((s - start) / edge) \
        - jax.nn.sigmoid((s - start - width) / edge)


def window_snr(start, *, width: float = 192.0, horizon: int = 512,
               ring_tau: float = 96.0, edge: float = 4.0):
    """Matched-filter SNR of a soft window placed at ``start`` over a
    resonator ring-up ``r(s) = 1 - exp(-(s+1)/ring_tau)`` (the same
    weighting :func:`~.physics._resolve` applies to the signal path).

    ``snr = (sum m r)^2 / sum m`` — signal integrates the rung-up
    transmission, noise variance integrates the window (white ADC
    noise).  Opening the window later trades low-amplitude early
    samples for rung-up ones until the window starts falling off the
    ``horizon``-sample record: the optimum is interior, which is what
    the readout-window placement loop descends to."""
    m = window_mask(start, width, horizon, edge=edge)
    s = jnp.arange(horizon, dtype=jnp.float32)
    r = 1.0 - jnp.exp(-(s + 1.0) / ring_tau)
    sig = jnp.sum(m * r)
    noise = jnp.sum(m) + 1e-6
    return sig ** 2 / noise


# ---------------------------------------------------------------------------
# demod + discrimination (mirror of physics._discriminate_acc)
# ---------------------------------------------------------------------------

def matched_filter_projection(acc_i, acc_q, energy, g0, g1):
    """The |0>-|1> axis projection of a matched-filter accumulation —
    term-for-term the pre-threshold arithmetic of
    :func:`~.physics._discriminate_acc` (clean responses
    ``a_s = g_s * E``), without the trailing ``> 0``.  Smooth in every
    input; the hard bit is ``proj > 0``."""
    a0_i, a0_q = g0[0] * energy, g0[1] * energy
    a1_i, a1_q = g1[0] * energy, g1[1] * energy
    return (acc_i - (a0_i + a1_i) / 2) * (a1_i - a0_i) \
        + (acc_q - (a0_q + a1_q) / 2) * (a1_q - a0_q)


def assignment_error_prob(energy, g0, g1, sigma):
    """Smooth readout assignment-error probability.

    Under the analytic matched-filter model
    (:func:`~.physics._resolve_analytic`:
    ``acc = g_s E + sigma sqrt(E) xi``, ``xi ~ N(0, I2)``) the
    projection is Gaussian with mean ``+-|g1-g0|^2 E^2 / 2`` and
    std ``sigma sqrt(E) |g1-g0| E``, so
    ``p_err = 0.5 erfc(|g1 - g0| sqrt(E) / (2 sqrt(2) sigma))``.
    Differentiable in ``energy`` — and through it in window placement
    and drive amplitude — unlike the empirical error *rate*, which is
    a mean of hard bits."""
    dg = jnp.sqrt((g1[0] - g0[0]) ** 2 + (g1[1] - g0[1]) ** 2)
    z = dg * jnp.sqrt(energy) / (2 * jnp.sqrt(2.0) * sigma)
    return 0.5 * jax.lax.erfc(z)


def hard_threshold(proj):
    """The exact discrimination bit, ``(proj > 0)`` as float32.  Its
    gradient is identically ZERO everywhere (the comparison is
    piecewise constant) — pinned in tests/test_calib.py as the
    documented behavior at the discrimination boundary; use
    :func:`st_threshold` when a surrogate gradient is wanted."""
    return (proj > 0).astype(jnp.float32)


@jax.custom_vjp
def st_threshold(proj, temp=1.0):
    """Straight-through discrimination bit: forward is the exact hard
    bit ``(proj > 0)``, backward substitutes the sigmoid surrogate
    ``d/dproj sigmoid(proj / temp)`` (``custom_vjp``).  ``temp`` sets
    the surrogate's sharpness; its own gradient is defined as zero
    (it is an estimator knob, not a physical parameter)."""
    return (proj > 0).astype(jnp.float32)


def _st_fwd(proj, temp=1.0):
    return st_threshold(proj, temp), (proj, temp)


def _st_bwd(res, g):
    proj, temp = res
    sg = jax.nn.sigmoid(proj / temp)
    return (g * sg * (1 - sg) / temp, jnp.zeros_like(temp))


st_threshold.defvjp(_st_fwd, _st_bwd)


def score_function_grad(p, bits, f_vals):
    """REINFORCE estimator for traffic-dependent branches: an unbiased
    estimate of ``d/dp E_{b~Bern(p)}[f(b)]`` from sampled bits.

    ``grad = mean(f(b) * d log P(b) / dp)
          = mean(f * (b/p - (1-b)/(1-p)))`` — no derivative ever flows
    through the branch itself, so this is the estimator of record when
    the simulated traffic BRANCHES on the measured bit (active reset,
    QEC feedback) and the pathwise surrogate of :func:`st_threshold`
    has no path to follow.  Exact expectation is ``f(1) - f(0)``
    (pinned statistically in tests/test_calib.py)."""
    p = jnp.clip(p, 1e-6, 1 - 1e-6)
    bits = jnp.asarray(bits, jnp.float32)
    score = bits / p - (1.0 - bits) / (1.0 - p)
    return jnp.mean(jnp.asarray(f_vals, jnp.float32) * score)


# ---------------------------------------------------------------------------
# the calibration losses + grad_loss front door
# ---------------------------------------------------------------------------

KNOBS = ('amplitude', 'drag', 'readout_window')


@dataclass(frozen=True)
class LossSpec:
    """Static description of one calibration loss (hashable: jit/vmap
    close over it as a constant).

    ``knob`` picks the loss; the remaining fields parameterize the
    forward model — ``x90_amp`` is the DEVICE-TRUTH quarter-turn
    amplitude the amplitude loop estimates (the nominal calibration
    may have drifted from it; that drift is what calibration
    corrects), ``target_p1`` the drive setpoint (1/2 for an X90),
    the ``window_*``/``ring_tau`` fields the readout-window SNR
    model, and the ``drag_*`` fields the leakage model."""
    knob: str = 'amplitude'
    # amplitude knob
    x90_amp: float = 0.48
    target_p1: float = 0.5
    # readout-window knob (units: ADC samples)
    window_width: float = 192.0
    window_horizon: int = 512
    ring_tau: float = 96.0
    window_edge: float = 4.0
    # drag knob
    drag_twidth: float = 24e-9
    drag_sigmas: float = 3.0
    drag_delta: float = -270e6
    sample_rate: float = 1e9

    def __post_init__(self):
        if self.knob not in KNOBS:
            raise ValueError(
                f'unknown knob {self.knob!r}; one of {KNOBS}')


# per-knob parameter name inside the pulse_params dict
PARAM_NAME = {'amplitude': 'amp', 'drag': 'alpha',
              'readout_window': 'window_start'}


def loss_fn(pulse_params, spec: LossSpec):
    """Scalar calibration loss for ``spec.knob`` at ``pulse_params``
    (a dict holding at least the knob's parameter, see
    :data:`PARAM_NAME`).  Smooth by construction: each knob's loss is
    built from the smooth observables above, so its gradient is exact
    (no estimator involved)."""
    if spec.knob == 'amplitude':
        p1 = bloch_p1(pulse_params['amp'], spec.x90_amp)
        return (p1 - spec.target_p1) ** 2
    if spec.knob == 'drag':
        return drag_leakage(pulse_params['alpha'],
                            twidth=spec.drag_twidth,
                            sigmas=spec.drag_sigmas,
                            delta=spec.drag_delta,
                            sample_rate=spec.sample_rate)
    # readout_window: maximize SNR == descend its negation (scaled to
    # O(1) so one learning rate serves every knob)
    snr = window_snr(pulse_params['window_start'],
                     width=spec.window_width,
                     horizon=spec.window_horizon,
                     ring_tau=spec.ring_tau,
                     edge=spec.window_edge)
    return -snr / spec.window_width


def grad_loss(pulse_params, spec: LossSpec = LossSpec()):
    """``(loss, grads)`` of the calibration loss at ``pulse_params``
    — the subsystem's front door (ISSUE 20 tentpole (a)).  ``grads``
    mirrors the ``pulse_params`` dict pytree; finite-difference
    agreement is pinned in tests/test_calib.py.
    """
    params = {k: jnp.asarray(v, jnp.float32)
              for k, v in pulse_params.items()}
    return jax.value_and_grad(lambda p: loss_fn(p, spec))(params)


def grad_loss_batch(pulse_params, spec: LossSpec = LossSpec()):
    """vmap-over-candidates batching of :func:`grad_loss`: each leaf
    of ``pulse_params`` carries a leading candidate axis.  Bit-identity
    with the sequential per-candidate path is pinned in
    tests/test_calib.py (the calibration burst evaluates its whole
    candidate population in one dispatch)."""
    params = {k: jnp.atleast_1d(jnp.asarray(v, jnp.float32))
              for k, v in pulse_params.items()}
    return jax.vmap(lambda p: jax.value_and_grad(
        lambda q: loss_fn(q, spec))(p))(params)
