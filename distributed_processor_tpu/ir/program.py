"""IR program container: a control-flow graph of basic blocks, plus the
frequency / variable / loop registries and JSON (de)serialisation.

Structure parity with the reference (python/distproc/ir/ir.py): nodes are
basic blocks carrying ``instructions`` (list), ``scope`` (set of channels)
and ``ind`` (source order); edges are possible control-flow paths added by
the GenerateCFG pass (loop back-edges excluded so the graph stays a DAG for
topological scheduling).
"""

from __future__ import annotations

import json
from abc import ABC, abstractmethod
from dataclasses import dataclass

import networkx as nx
import numpy as np

from . import instructions as iri
from ..utils import match_pattern

DEFAULT_QUBIT_GROUPING = ('{qubit}.qdrv', '{qubit}.rdrv', '{qubit}.rdlo')
DEFAULT_PROC_GROUPING = [('{qubit}.qdrv', '{qubit}.rdrv', '{qubit}.rdlo')]


@dataclass
class _Frequency:
    freq: float
    zphase: float
    scope: set = None


@dataclass
class _Variable:
    name: str
    scope: set
    dtype: str = 'int'   # 'int', 'phase', or 'amp'

    def to_dict(self):
        return {'scope': sorted(self.scope) if self.scope else [],
                'dtype': self.dtype}


@dataclass
class _Loop:
    name: str
    scope: set
    start_time: int
    delta_t: int = None

    def to_dict(self):
        return {'scope': sorted(self.scope) if self.scope else [],
                'start_time': self.start_time, 'delta_t': self.delta_t}


class _JSONEncoder(json.JSONEncoder):
    def default(self, obj):
        if isinstance(obj, set):
            return sorted(obj)
        if isinstance(obj, np.ndarray):
            return obj.tolist()
        if isinstance(obj, (np.integer,)):
            return int(obj)
        if isinstance(obj, (np.floating,)):
            return float(obj)
        return super().default(obj)


class IRProgram:
    """An IR program: CFG of basic blocks + freq/var/loop registries.

    Accepts a list of instructions (dicts or instruction objects), a dict
    with a ``program`` field (list or {blockname: instrs}) plus optional
    metadata, or a JSON string of the same.
    """

    def __init__(self, source):
        self._freqs: dict = {}
        self._vars: dict[str, _Variable] = {}
        self._hw_zphase_bindings: dict[str, str] = {}
        self.loops: dict[str, _Loop] = {}
        self.fpga_config = None
        self.control_flow_graph = nx.DiGraph()

        if isinstance(source, str):
            source = json.loads(source)
        if isinstance(source, list):
            self._blocks_from_list(source)
        elif isinstance(source, dict):
            prog = source['program']
            if isinstance(prog, list):
                self._blocks_from_list(prog)
            else:
                for i, (blockname, instrs) in enumerate(prog.items()):
                    self.control_flow_graph.add_node(
                        blockname, instructions=iri.program_from_dicts(instrs), ind=i)
            for varname, vd in source.get('vars', {}).items():
                self.register_var(varname, vd['scope'], vd['dtype'])
            for freqname, freq in source.get('freqs', {}).items():
                self.register_freq(freqname, freq)
            for loopname, ld in source.get('loops', {}).items():
                self.register_loop(loopname, ld['scope'], ld['start_time'],
                                   ld.get('delta_t'))
            for freq, var in source.get('hw_zphase_bindings', {}).items():
                self.register_phase_binding(freq, var)
            for node, targets in source.get('control_flow_graph', {}).items():
                for target in targets:
                    self.control_flow_graph.add_edge(node, target)
            for blockname, scope in source.get('scope', {}).items():
                self.control_flow_graph.nodes[blockname]['scope'] = set(scope)
        else:
            raise TypeError(f'invalid program source: {type(source)}')

    def _blocks_from_list(self, instr_list):
        self.control_flow_graph.add_node(
            'block_0', instructions=iri.program_from_dicts(instr_list), ind=0)

    # -- accessors --------------------------------------------------------

    @property
    def blocks(self):
        return self.control_flow_graph.nodes

    @property
    def blocknames_by_ind(self) -> list[str]:
        return sorted(self.control_flow_graph.nodes,
                      key=lambda n: self.control_flow_graph.nodes[n]['ind'])

    @property
    def freqs(self) -> dict:
        return self._freqs

    @property
    def vars(self) -> dict:
        return self._vars

    @property
    def bound_zphase_freqs(self) -> list:
        return list(self._hw_zphase_bindings.keys())

    @property
    def scope(self) -> set:
        return set().union(*(self.blocks[n]['scope'] for n in self.blocks))

    def get_zphase_var(self, freq) -> str:
        return self._hw_zphase_bindings[freq]

    # -- registries -------------------------------------------------------

    def register_freq(self, key, freq):
        if key in self._freqs and self._freqs[key] != freq:
            raise ValueError(
                f'frequency {key} already registered as {self._freqs[key]}, '
                f'conflicting value {freq}')
        self._freqs[key] = freq

    def register_var(self, varname, scope, dtype):
        if varname in self._vars:
            raise ValueError(f'variable {varname} already declared')
        self._vars[varname] = _Variable(varname, set(scope), dtype)

    def register_loop(self, name, scope, start_time, delta_t=None):
        self.loops[name] = _Loop(name, set(scope), start_time, delta_t)

    def register_phase_binding(self, freq, varname):
        if varname not in self._vars:
            raise ValueError(f'bind_phase var {varname} must be declared first')
        if self._vars[varname].dtype != 'phase':
            raise ValueError(f'bind_phase var {varname} must have phase dtype')
        if freq in self._hw_zphase_bindings:
            raise ValueError(
                f'frequency {freq} already bound to {self._hw_zphase_bindings[freq]}')
        self._hw_zphase_bindings[freq] = varname

    # -- serialization ----------------------------------------------------

    def serialize(self) -> str:
        out: dict = {'program': {
            name: [i.to_dict() for i in self.blocks[name]['instructions']]
            for name in self.blocknames_by_ind}}
        if self._vars:
            out['vars'] = {n: v.to_dict() for n, v in self._vars.items()}
        if self._freqs:
            out['freqs'] = dict(self._freqs)
        if self.loops:
            out['loops'] = {n: l.to_dict() for n, l in self.loops.items()}
        if self._hw_zphase_bindings:
            out['hw_zphase_bindings'] = dict(self._hw_zphase_bindings)
        if 'scope' in self.blocks[self.blocknames_by_ind[0]]:
            out['scope'] = {n: self.blocks[n]['scope']
                            for n in self.blocknames_by_ind}
        out['control_flow_graph'] = {
            n: list(self.control_flow_graph.successors(n)) for n in self.blocks}
        return json.dumps(out, indent=4, cls=_JSONEncoder)


class Pass(ABC):
    """A compiler pass: transforms an IRProgram in place."""

    @abstractmethod
    def run_pass(self, ir_prog: IRProgram):
        ...


class QubitScoper:
    """Maps qubits to their channel scope.

    A gate on Q1 is scoped to all Q1.* channels so nothing else can be
    scheduled on that qubit concurrently.  Inputs that already name a
    channel (match one of the grouping patterns) pass through unchanged.
    """

    def __init__(self, mapping=DEFAULT_QUBIT_GROUPING):
        self._mapping = tuple(mapping)

    def get_scope(self, qubits) -> set:
        if isinstance(qubits, str):
            qubits = [qubits]
        channels = set()
        for qubit in qubits:
            if any(match_pattern(pat, qubit) is not None for pat in self._mapping):
                channels.add(qubit)
            else:
                channels.update(pat.format(qubit=qubit) for pat in self._mapping)
        return channels


class CoreScoper:
    """Groups destination channels into processor cores.

    Cores are named by the tuple of channels they drive, e.g.
    ``('Q0.qdrv', 'Q0.rdrv', 'Q0.rdlo')``.
    """

    def __init__(self, dest_channels, proc_grouping=None):
        if proc_grouping is None:
            proc_grouping = DEFAULT_PROC_GROUPING
        self.proc_groupings: dict[str, tuple] = {}
        for dest in dest_channels:
            for group in proc_grouping:
                for pattern in group:
                    fields = match_pattern(pattern, dest)
                    if fields is not None:
                        self.proc_groupings[dest] = tuple(
                            p.format(**fields) for p in group)
        self.proc_groupings_flat = set(self.proc_groupings.values())

    def get_groups_bydest(self, dests) -> set:
        return {self.proc_groupings[dest] for dest in dests}
