"""The compilation pass pipeline.

Pass order and semantics maintain parity with the reference pipeline
(python/distproc/ir/passes.py; canonical order in
python/distproc/compiler.py:139-174):

FlattenProgram → MakeBasicBlocks → ScopeProgram → RegisterVarsAndFreqs →
ResolveGates → GenerateCFG → ResolveHWVirtualZ → ResolveVirtualZ →
ResolveFreqs → ResolveFPROCChannels → RescopeVars → Schedule|LintSchedule

The scheduler tracks two clock families per basic block (parity with
reference passes.py:596-742, the timing contract in BASELINE.md):

* ``cur_t[dest]`` — the pulse-end time per destination channel;
* ``last_instr_end_t[core]`` — the instruction-issue-pipeline time per
  processor core, advanced by the FPGAConfig per-instruction costs.

Loops are scheduled once: the loop body's schedule is referenced to the
loop start, and a negative ``inc_qclk`` (delta_t) emitted at loop end
rewinds the hardware clock so every iteration reuses the same offsets.
"""

from __future__ import annotations

import copy
import logging

import numpy as np
import networkx as nx

from . import instructions as iri
from .program import IRProgram, Pass, QubitScoper, CoreScoper

logger = logging.getLogger(__name__)


class FlattenProgram(Pass):
    """Lower nested control flow (branch_fproc/branch_var/loop) to jumps.

    A branch becomes ``jump → [false block] → jump_i end → true: [true
    block] → end``; a loop becomes ``label; barrier; body; loop_end;
    jump_cond(label, jump_type='loopctrl')``.
    """

    def run_pass(self, ir_prog: IRProgram):
        assert len(ir_prog.control_flow_graph.nodes) == 1
        blockname = next(iter(ir_prog.control_flow_graph.nodes))
        instrs = ir_prog.blocks[blockname]['instructions']
        self._used_labels = set()
        ir_prog.blocks[blockname]['instructions'] = self._flatten(instrs)

    def _unique(self, label: str) -> str:
        """Sibling bodies flattened in separate recursive calls restart
        their local index, so generated names can collide (e.g. two
        sequential branch-wrapped loops both yielding
        ``true_loop_0_loopctrl``); MakeBasicBlocks would then silently
        overwrite the first block.  First occurrence keeps the
        reference-compatible name; collisions get a ``_u<n>`` suffix."""
        out, n = label, 0
        while out in self._used_labels:
            n += 1
            out = f'{label}_u{n}'
        self._used_labels.add(out)
        return out

    def _flatten(self, program, label_prefix=''):
        out = []
        branchind = 0
        for statement in program:
            statement = copy.deepcopy(statement)
            if statement.name in ('branch_fproc', 'branch_var'):
                flat_true = self._flatten(statement.true, 'true_' + label_prefix)
                flat_false = self._flatten(statement.false, 'false_' + label_prefix)
                label_false = self._unique(f'{label_prefix}false_{branchind}')
                label_end = self._unique(f'{label_prefix}end_{branchind}')

                if statement.name == 'branch_fproc':
                    jump = iri.JumpFproc(alu_cond=statement.alu_cond,
                                         cond_lhs=statement.cond_lhs,
                                         func_id=statement.func_id,
                                         scope=statement.scope, jump_label=None)
                else:
                    jump = iri.JumpCond(alu_cond=statement.alu_cond,
                                        cond_lhs=statement.cond_lhs,
                                        cond_rhs=statement.cond_rhs,
                                        scope=statement.scope, jump_label=None)
                label_true = self._unique(f'{label_prefix}true_{branchind}')
                jump.jump_label = label_true if flat_true else label_end
                out.append(jump)

                out.append(iri.JumpLabel(label=label_false, scope=statement.scope))
                out.extend(flat_false)
                out.append(iri.JumpI(jump_label=label_end, scope=statement.scope))
                if flat_true:
                    out.append(iri.JumpLabel(label=label_true, scope=statement.scope))
                    out.extend(flat_true)
                out.append(iri.JumpLabel(label=label_end, scope=statement.scope))
                branchind += 1

            elif statement.name == 'loop':
                flat_body = self._flatten(statement.body, 'loop_body_' + label_prefix)
                # loopctrl suffix is load-bearing (block naming): keep it
                # terminal when disambiguating
                base = f'{label_prefix}loop_{branchind}'
                out_base, n = base, 0
                while f'{out_base}_loopctrl' in self._used_labels:
                    n += 1
                    out_base = f'{base}_u{n}'
                loop_label = f'{out_base}_loopctrl'
                self._used_labels.add(loop_label)
                out.append(iri.JumpLabel(label=loop_label, scope=statement.scope))
                out.append(iri.Barrier(qubit=statement.scope))
                out.extend(flat_body)
                out.append(iri.LoopEnd(loop_label=loop_label, scope=statement.scope))
                out.append(iri.JumpCond(cond_lhs=statement.cond_lhs,
                                        cond_rhs=statement.cond_rhs,
                                        alu_cond=statement.alu_cond,
                                        jump_label=loop_label,
                                        scope=statement.scope,
                                        jump_type='loopctrl'))
                branchind += 1
            else:
                out.append(statement)
        return out


class MakeBasicBlocks(Pass):
    """Split the flattened program into basic blocks at jumps and labels.

    Jump instructions are placed in their own control block (named
    ``<label>_ctrl`` for loop-control jumps, ``<block>_ctrl`` otherwise);
    labelled positions start a new block named after the label.
    """

    def run_pass(self, ir_prog: IRProgram):
        assert len(ir_prog.control_flow_graph.nodes) == 1
        g = ir_prog.control_flow_graph
        cur_blockname = next(iter(g.nodes))
        full_program = g.nodes[cur_blockname]['instructions']
        g.nodes[cur_blockname]['instructions'] = []

        blockname_ind = 1
        block_ind = 0
        cur_block: list = []
        for statement in full_program:
            if statement.name in ('jump_fproc', 'jump_cond', 'jump_i'):
                g.add_node(cur_blockname, instructions=cur_block, ind=block_ind)
                block_ind += 1
                if statement.jump_label.split('_')[-1] == 'loopctrl':
                    ctrl_blockname = f'{statement.jump_label}_ctrl'
                else:
                    ctrl_blockname = f'{cur_blockname}_ctrl'
                # networkx add_node REPLACES a same-named node: a branch
                # jump inside a loop body would otherwise collide with
                # (and be overwritten by) the loop back-edge's
                # '<label>_ctrl' block, silently dropping the branch
                base, n = ctrl_blockname, 0
                while ctrl_blockname in g:
                    n += 1
                    ctrl_blockname = f'{base}_u{n}'
                g.add_node(ctrl_blockname, instructions=[statement], ind=block_ind)
                block_ind += 1
                cur_blockname = f'block_{blockname_ind}'
                blockname_ind += 1
                cur_block = []
            elif statement.name == 'jump_label':
                g.add_node(cur_blockname, instructions=cur_block, ind=block_ind)
                block_ind += 1
                cur_block = [statement]
                cur_blockname = statement.label
            elif statement.name in ('branch_fproc', 'branch_var', 'loop'):
                raise ValueError(
                    f'{statement.name} found: flatten control flow before '
                    'forming basic blocks')
            else:
                cur_block.append(statement)

        g.add_node(cur_blockname, instructions=cur_block, ind=block_ind)
        for node in tuple(g.nodes):
            if g.nodes[node]['instructions'] == []:
                g.remove_node(node)


class ScopeProgram(Pass):
    """Resolve instruction and block scopes to sets of channels.

    Unscoped barriers/delays/idles are widened to the whole program scope.
    """

    def __init__(self, qubit_grouping: tuple, rescope_barriers_and_delays=True):
        self._scoper = QubitScoper(qubit_grouping)
        self._rescope = rescope_barriers_and_delays

    def run_pass(self, ir_prog: IRProgram):
        for node in ir_prog.blocks:
            scope = set()
            for instr in ir_prog.blocks[node]['instructions']:
                if getattr(instr, 'scope', None) is not None:
                    instr.scope = self._scoper.get_scope(instr.scope)
                    scope |= instr.scope
                elif getattr(instr, 'qubit', None) is not None:
                    instr.scope = self._scoper.get_scope(instr.qubit)
                    scope |= instr.scope
                elif hasattr(instr, 'dest'):
                    scope |= self._scoper.get_scope(instr.dest)
            ir_prog.blocks[node]['scope'] = scope

        if self._rescope:
            prog_scope = ir_prog.scope
            for node in ir_prog.blocks:
                for instr in ir_prog.blocks[node]['instructions']:
                    if instr.name in ('barrier', 'delay', 'idle') and instr.scope is None:
                        instr.scope = prog_scope


class RegisterVarsAndFreqs(Pass):
    """Register declared frequencies/variables; scope var-using ALU ops.

    Pulse frequencies referenced by name resolve through the QChip if one
    is provided (gate frequencies are registered by ResolveGates instead).
    """

    def __init__(self, qchip=None):
        self._qchip = qchip

    def run_pass(self, ir_prog: IRProgram):
        for node in ir_prog.blocks:
            for instr in ir_prog.blocks[node]['instructions']:
                if instr.name == 'declare_freq':
                    freqname = instr.freqname if instr.freqname is not None else instr.freq
                    ir_prog.register_freq(freqname, instr.freq)
                elif instr.name == 'declare':
                    ir_prog.register_var(instr.var, instr.scope, instr.dtype)
                elif instr.name == 'pulse':
                    if instr.freq not in ir_prog.freqs:
                        if isinstance(instr.freq, str):
                            if self._qchip is None:
                                raise ValueError(
                                    f'undefined frequency {instr.freq} and no QChip provided')
                            ir_prog.register_freq(
                                instr.freq, self._qchip.get_qubit_freq(instr.freq))
                        else:
                            ir_prog.register_freq(instr.freq, instr.freq)
                elif instr.name == 'alu':
                    if isinstance(instr.lhs, str):
                        instr.scope = ir_prog.vars[instr.rhs].scope \
                            | ir_prog.vars[instr.lhs].scope
                    else:
                        instr.scope = set(ir_prog.vars[instr.rhs].scope)
                    if not ir_prog.vars[instr.out].scope.issubset(instr.scope):
                        raise ValueError(
                            f'alu output {instr.out} scope exceeds operand scope')
                elif instr.name in ('set_var', 'read_fproc'):
                    instr.scope = set(ir_prog.vars[instr.var].scope)
                elif instr.name == 'alu_fproc':
                    # note: reference scopes this via a nonexistent rhs attr
                    # (passes.py:281-283, latent bug); we use the lhs var scope
                    if isinstance(instr.lhs, str):
                        instr.scope = set(ir_prog.vars[instr.lhs].scope)

        # widen block scopes with the var-derived instruction scopes: a
        # block whose only instructions are var-scoped (e.g. a bare
        # set_var between two hardware loops) otherwise has an empty
        # scope, gets no sequential CFG edge, and the scheduler never
        # seeds its clocks (KeyError in Schedule)
        for node in ir_prog.blocks:
            blk = ir_prog.blocks[node]
            for instr in blk['instructions']:
                sc = getattr(instr, 'scope', None)
                if sc:
                    blk['scope'] = set(blk['scope']) | set(sc)


class ResolveGates(Pass):
    """Expand Gate instructions into Barrier + Pulse/VirtualZ sequences
    using the QChip gate library.  Named gate frequencies are registered
    and pulses keep the name (resolved later by ResolveFreqs)."""

    def __init__(self, qchip, qubit_grouping):
        self._qchip = qchip
        self._scoper = QubitScoper(qubit_grouping)

    def run_pass(self, ir_prog: IRProgram):
        for node in ir_prog.blocks:
            block = ir_prog.blocks[node]['instructions']
            i = 0
            while i < len(block):
                if not isinstance(block[i], iri.Gate):
                    i += 1
                    continue
                instr = block.pop(i)
                gatename = ''.join(instr.qubit) + instr.name
                gate = self._qchip.get_gate(gatename, instr.modi)

                block.insert(i, iri.Barrier(scope=self._scoper.get_scope(instr.qubit)))
                i += 1
                for pulse in gate.get_pulses():
                    if hasattr(pulse, 'global_freqname'):   # virtual-z entry
                        block.insert(i, iri.VirtualZ(
                            freq=pulse.global_freqname, phase=pulse.phase))
                        i += 1
                        continue
                    if pulse.freqname is not None:
                        if pulse.freqname not in ir_prog.freqs:
                            ir_prog.register_freq(pulse.freqname, pulse.freq)
                        elif pulse.freq != ir_prog.freqs[pulse.freqname]:
                            logger.warning(
                                '%s = %s differs from qchip value %s',
                                pulse.freqname, ir_prog.freqs[pulse.freqname],
                                pulse.freq)
                        freq = pulse.freqname
                    else:
                        if pulse.freq not in ir_prog.freqs:
                            ir_prog.register_freq(pulse.freq, pulse.freq)
                        freq = pulse.freq
                    if pulse.t0 != 0:
                        block.insert(i, iri.Delay(t=pulse.t0, scope={pulse.dest}))
                        i += 1
                    block.insert(i, iri.Pulse(
                        freq=freq, phase=pulse.phase, amp=pulse.amp,
                        env=pulse.env, twidth=pulse.twidth, dest=pulse.dest))
                    i += 1


class GenerateCFG(Pass):
    """Add control-flow edges between basic blocks.

    Sequential edges follow the last block that touched each destination
    channel; jump edges go to the target label's block.  Loop-control
    back-edges are *excluded* so the CFG remains a DAG for scheduling.
    """

    def run_pass(self, ir_prog: IRProgram):
        lastblock = {dest: None for dest in ir_prog.scope}
        for blockname in ir_prog.blocknames_by_ind:
            block = ir_prog.blocks[blockname]
            for dest in block['scope']:
                if lastblock[dest] is not None:
                    ir_prog.control_flow_graph.add_edge(lastblock[dest], blockname)

            last_instr = block['instructions'][-1]
            if last_instr.name in ('jump_fproc', 'jump_cond'):
                if last_instr.jump_type != 'loopctrl':
                    ir_prog.control_flow_graph.add_edge(
                        blockname, last_instr.jump_label)
                for dest in block['scope']:
                    lastblock[dest] = blockname
            elif last_instr.name == 'jump_i':
                ir_prog.control_flow_graph.add_edge(blockname, last_instr.jump_label)
                for dest in block['scope']:
                    lastblock[dest] = None
            else:
                for dest in block['scope']:
                    lastblock[dest] = blockname


class ResolveHWVirtualZ(Pass):
    """Apply bind_phase: virtual-z on bound frequencies becomes runtime
    register arithmetic, and pulses on those frequencies take their phase
    from the bound register.  Run before ResolveVirtualZ."""

    def run_pass(self, ir_prog: IRProgram):
        for nodename in nx.topological_sort(ir_prog.control_flow_graph):
            instructions = ir_prog.blocks[nodename]['instructions']
            i = 0
            while i < len(instructions):
                instr = instructions[i]
                if instr.name == 'bind_phase':
                    ir_prog.register_phase_binding(instr.freq, instr.var)
                    instructions[i] = iri.SetVar(
                        value=0, var=instr.var,
                        scope=ir_prog.vars[instr.var].scope)
                elif isinstance(instr, iri.VirtualZ):
                    if instr.freq in ir_prog.bound_zphase_freqs:
                        var = ir_prog.get_zphase_var(instr.freq)
                        if instr.scope is not None and \
                                not set(instr.scope).issubset(ir_prog.vars[var].scope):
                            raise ValueError(
                                f'virtual-z scope exceeds bound var scope for {instr.freq}')
                        instructions[i] = iri.Alu(
                            op='add', lhs=instr.phase, rhs=var, out=var,
                            scope=ir_prog.vars[var].scope)
                elif instr.name == 'pulse':
                    if instr.freq in ir_prog.bound_zphase_freqs:
                        instr.phase = ir_prog.get_zphase_var(instr.freq)
                elif isinstance(instr, iri.Gate):
                    raise ValueError('resolve Gates before ResolveHWVirtualZ')
                i += 1


class ResolveVirtualZ(Pass):
    """Software virtual-z: accumulate z-phases per frequency along the CFG
    and fold them into downstream pulse phases.  Phase accumulators must
    agree across CFG predecessors (otherwise the z-phase must be bound to
    a hardware register with bind_phase)."""

    def run_pass(self, ir_prog: IRProgram):
        for nodename in nx.topological_sort(ir_prog.control_flow_graph):
            zphase_acc: dict = {}
            for pred in ir_prog.control_flow_graph.predecessors(nodename):
                for freqname, phase in ir_prog.blocks[pred]['ending_zphases'].items():
                    if freqname in zphase_acc:
                        if phase != zphase_acc[freqname]:
                            raise ValueError(
                                f'z-phase mismatch on {freqname} entering {nodename} '
                                f'from {pred} ({phase} rad)')
                    else:
                        zphase_acc[freqname] = phase

            instructions = ir_prog.blocks[nodename]['instructions']
            i = 0
            while i < len(instructions):
                instr = instructions[i]
                if isinstance(instr, iri.Pulse):
                    if instr.freq in zphase_acc:
                        instr.phase += zphase_acc[instr.freq]
                elif isinstance(instr, iri.VirtualZ):
                    if instr.freq not in ir_prog.freqs:
                        logger.warning('virtual-z on unused frequency: %s', instr.freq)
                    instructions.pop(i)
                    i -= 1
                    zphase_acc[instr.freq] = zphase_acc.get(instr.freq, 0) + instr.phase
                elif isinstance(instr, iri.Gate):
                    raise ValueError('resolve Gates before ResolveVirtualZ')
                elif isinstance(instr, iri.JumpCond) and instr.jump_type == 'loopctrl':
                    logger.warning('z-phase resolution inside loops is unsupported')
                i += 1

            ir_prog.blocks[nodename]['ending_zphases'] = zphase_acc


class ResolveFreqs(Pass):
    """Resolve named pulse frequencies to Hz (var-parameterised ones stay)."""

    def run_pass(self, ir_prog: IRProgram):
        for nodename in nx.topological_sort(ir_prog.control_flow_graph):
            for instr in ir_prog.blocks[nodename]['instructions']:
                if instr.name == 'pulse' and isinstance(instr.freq, str):
                    if instr.freq in ir_prog.vars:
                        if instr.dest not in ir_prog.vars[instr.freq].scope:
                            raise ValueError(
                                f'pulse dest {instr.dest} outside freq var scope')
                    else:
                        instr.freq = ir_prog.freqs[instr.freq]


class ResolveFPROCChannels(Pass):
    """Lower named fproc channels to hardware ids and insert Hold
    instructions so fproc reads land after the referenced measurement."""

    def __init__(self, fpga_config):
        self._fpga_config = fpga_config

    def run_pass(self, ir_prog: IRProgram):
        for nodename in nx.topological_sort(ir_prog.control_flow_graph):
            instructions = ir_prog.blocks[nodename]['instructions']
            i = 0
            while i < len(instructions):
                instr = instructions[i]
                if isinstance(instr, (iri.ReadFproc, iri.JumpFproc, iri.AluFproc)):
                    if instr.func_id in self._fpga_config.fproc_channels:
                        chan = self._fpga_config.fproc_channels[instr.func_id]
                        instructions.insert(i, iri.Hold(
                            nclks=chan.hold_nclks,
                            ref_chans=chan.hold_after_chans,
                            scope=instr.scope))
                        i += 1
                        instr.func_id = chan.id
                    elif not isinstance(instr.func_id, (int, tuple)):
                        raise ValueError(f'unresolvable fproc channel {instr.func_id}')
                i += 1


class RescopeVars(Pass):
    """Widen variable scopes to wherever the variables are used."""

    def run_pass(self, ir_prog: IRProgram):
        for nodename in nx.topological_sort(ir_prog.control_flow_graph):
            instructions = ir_prog.blocks[nodename]['instructions']
            rescope_block = False
            for instr in instructions:
                if instr.name == 'pulse':
                    if instr.phase in ir_prog.vars and \
                            instr.dest not in ir_prog.vars[instr.phase].scope:
                        ir_prog.vars[instr.phase].scope.add(instr.dest)
                        rescope_block = True
                elif instr.name in ('jump_cond', 'jump_fproc'):
                    if instr.cond_lhs in ir_prog.vars and \
                            not instr.scope.issubset(ir_prog.vars[instr.cond_lhs].scope):
                        ir_prog.vars[instr.cond_lhs].scope |= instr.scope
                        rescope_block = True
                    if instr.name == 'jump_cond' and \
                            not instr.scope.issubset(ir_prog.vars[instr.cond_rhs].scope):
                        ir_prog.vars[instr.cond_rhs].scope |= instr.scope
                        rescope_block = True
            if rescope_block:
                for instr in instructions:
                    if instr.name in ('declare', 'set_var'):
                        instr.scope = set(ir_prog.vars[instr.var].scope)
                    elif instr.name == 'alu':
                        instr.scope = set(ir_prog.vars[instr.out].scope)


START_NCLKS = 5   # schedule origin: first possible pulse issue


class _TimedPass(Pass):
    """Shared per-instruction clock accounting for Schedule/LintSchedule."""

    def __init__(self, fpga_config, proc_grouping: list):
        self._fpga_config = fpga_config
        self._proc_grouping = proc_grouping
        self._start_nclks = START_NCLKS

    def _pulse_nclks(self, length_secs: float) -> int:
        return int(np.ceil(length_secs / self._fpga_config.fpga_clk_period))

    def _instr_cost(self, name: str) -> int:
        cfg = self._fpga_config
        return {'alu': cfg.alu_instr_clks, 'set_var': cfg.alu_instr_clks,
                'loop_end': cfg.alu_instr_clks,
                'jump_fproc': cfg.jump_fproc_clks,
                'read_fproc': cfg.jump_fproc_clks,
                'alu_fproc': cfg.jump_fproc_clks,
                'jump_i': cfg.jump_cond_clks,
                'jump_cond': cfg.jump_cond_clks}[name]


class Schedule(_TimedPass):
    """Assign start times to pulses, resolve Hold→Idle, drop
    Barrier/Delay, and compute loop delta_t (see module docstring)."""

    def run_pass(self, ir_prog: IRProgram):
        self._core_scoper = CoreScoper(ir_prog.scope, self._proc_grouping)
        for nodename in nx.topological_sort(ir_prog.control_flow_graph):
            cur_t = {dest: self._start_nclks for dest in ir_prog.scope}
            last_instr_end_t = {
                grp: self._start_nclks for grp in
                self._core_scoper.get_groups_bydest(ir_prog.blocks[nodename]['scope'])}

            for pred in ir_prog.control_flow_graph.predecessors(nodename):
                pred_block = ir_prog.blocks[pred]
                for dest in cur_t:
                    if dest in pred_block['scope']:
                        cur_t[dest] = max(cur_t[dest], pred_block['block_end_t'][dest])
                for grp in last_instr_end_t:
                    if grp in pred_block['last_instr_end_t']:
                        last_instr_end_t[grp] = max(
                            last_instr_end_t[grp], pred_block['last_instr_end_t'][grp])

            if nodename.split('_')[-1] == 'loopctrl':
                ir_prog.register_loop(nodename, ir_prog.blocks[nodename]['scope'],
                                      max(cur_t.values()))

            self._schedule_block(
                ir_prog.blocks[nodename]['instructions'], cur_t, last_instr_end_t,
                ir_prog)

            last_instr = ir_prog.blocks[nodename]['instructions'][-1] \
                if ir_prog.blocks[nodename]['instructions'] else None
            if isinstance(last_instr, iri.JumpCond) and last_instr.jump_type == 'loopctrl':
                loop = ir_prog.loops[last_instr.jump_label]
                ir_prog.blocks[nodename]['block_end_t'] = {
                    dest: loop.start_time for dest in ir_prog.blocks[nodename]['scope']}
                ir_prog.blocks[nodename]['last_instr_end_t'] = {
                    grp: loop.start_time for grp in
                    self._core_scoper.get_groups_bydest(ir_prog.blocks[nodename]['scope'])}
                loop.delta_t = max(max(last_instr_end_t.values()),
                                   max(cur_t.values())) - loop.start_time
            else:
                ir_prog.blocks[nodename]['block_end_t'] = cur_t
                ir_prog.blocks[nodename]['last_instr_end_t'] = last_instr_end_t

        ir_prog.fpga_config = self._fpga_config

    def _schedule_block(self, instructions, cur_t, last_instr_end_t, ir_prog):
        groupings = self._core_scoper.proc_groupings
        i = 0
        while i < len(instructions):
            instr = instructions[i]
            if instr.name == 'pulse':
                grp = groupings[instr.dest]
                instr.start_time = max(last_instr_end_t[grp], cur_t[instr.dest])
                last_instr_end_t[grp] = instr.start_time \
                    + self._fpga_config.pulse_load_clks
                cur_t[instr.dest] = instr.start_time + self._pulse_nclks(instr.twidth)

            elif instr.name == 'barrier':
                max_t = max(max(cur_t[dest] for dest in instr.scope),
                            max(last_instr_end_t[groupings[dest]]
                                for dest in instr.scope))
                for dest in instr.scope:
                    cur_t[dest] = max_t
                instructions.pop(i)
                i -= 1

            elif instr.name == 'delay':
                for dest in instr.scope:
                    cur_t[dest] += self._pulse_nclks(instr.t)
                instructions.pop(i)
                i -= 1

            elif instr.name == 'hold':
                idle_end_t = max(cur_t[dest] for dest in instr.ref_chans) + instr.nclks
                idle_scope = set()
                for grp in self._core_scoper.get_groups_bydest(instr.scope):
                    if last_instr_end_t[grp] >= idle_end_t:
                        logger.info('skipping hold on core %s: timestamp exceeded', grp)
                    else:
                        idle_scope |= set(grp)
                        last_instr_end_t[grp] = idle_end_t \
                            + self._fpga_config.pulse_load_clks
                if idle_scope:
                    instructions[i] = iri.Idle(end_time=idle_end_t, scope=idle_scope)
                else:
                    instructions.pop(i)
                    i -= 1

            elif instr.name in ('alu', 'set_var', 'jump_fproc', 'read_fproc',
                                'alu_fproc', 'jump_i', 'jump_cond', 'loop_end'):
                cost = self._instr_cost(instr.name)
                for grp in self._core_scoper.get_groups_bydest(instr.scope):
                    last_instr_end_t[grp] += cost

            elif isinstance(instr, iri.Gate):
                raise ValueError('resolve Gates before scheduling')

            i += 1


class LintSchedule(_TimedPass):
    """Check user-provided start times against the issue-pipeline model;
    raises if a pulse or idle would stall the core."""

    def run_pass(self, ir_prog: IRProgram):
        self._core_scoper = CoreScoper(ir_prog.scope, self._proc_grouping)
        for nodename in nx.topological_sort(ir_prog.control_flow_graph):
            last_instr_end_t = {
                grp: self._start_nclks for grp in
                self._core_scoper.get_groups_bydest(ir_prog.blocks[nodename]['scope'])}
            for pred in ir_prog.control_flow_graph.predecessors(nodename):
                for grp in last_instr_end_t:
                    if grp in ir_prog.blocks[pred]['last_instr_end_t']:
                        last_instr_end_t[grp] = max(
                            last_instr_end_t[grp],
                            ir_prog.blocks[pred]['last_instr_end_t'][grp])

            self._lint_block(ir_prog.blocks[nodename]['instructions'], last_instr_end_t)

            last_instr = ir_prog.blocks[nodename]['instructions'][-1] \
                if ir_prog.blocks[nodename]['instructions'] else None
            if isinstance(last_instr, iri.JumpCond) and last_instr.jump_type == 'loopctrl':
                loop = ir_prog.loops[last_instr.jump_label]
                ir_prog.blocks[nodename]['last_instr_end_t'] = {
                    grp: loop.start_time for grp in
                    self._core_scoper.get_groups_bydest(ir_prog.blocks[nodename]['scope'])}
            else:
                ir_prog.blocks[nodename]['last_instr_end_t'] = last_instr_end_t

        ir_prog.fpga_config = self._fpga_config

    def _lint_block(self, instructions, last_instr_end_t):
        groupings = self._core_scoper.proc_groupings
        for i, instr in enumerate(instructions):
            if instr.name == 'pulse':
                grp = groupings[instr.dest]
                if instr.start_time < last_instr_end_t[grp]:
                    raise ValueError(
                        f'instruction {i}: {instr}: start time too early; '
                        f'must be >= {last_instr_end_t[grp]}')
                last_instr_end_t[grp] = instr.start_time \
                    + self._fpga_config.pulse_load_clks
            elif instr.name == 'idle':
                for grp in self._core_scoper.get_groups_bydest(instr.scope):
                    if instr.end_time < last_instr_end_t[grp]:
                        raise ValueError(
                            f'instruction {i}: {instr}: end time too early; '
                            f'must be >= {last_instr_end_t[grp]}')
                    last_instr_end_t[grp] = instr.end_time \
                        + self._fpga_config.pulse_load_clks
            elif instr.name in ('alu', 'set_var', 'jump_fproc', 'read_fproc',
                                'alu_fproc', 'jump_i', 'jump_cond', 'loop_end'):
                cost = self._instr_cost(instr.name)
                for grp in self._core_scoper.get_groups_bydest(instr.scope):
                    last_instr_end_t[grp] += cost
            elif isinstance(instr, iri.Gate):
                raise ValueError('resolve Gates before scheduling')
