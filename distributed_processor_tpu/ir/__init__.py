from .program import IRProgram, Pass, QubitScoper, CoreScoper
from . import instructions
from . import passes
