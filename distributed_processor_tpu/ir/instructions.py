"""IR instruction set.

Each instruction is a small dataclass with a fixed ``name`` tag; the
program-input format is a list of dicts with matching field names
(documented in :mod:`distributed_processor_tpu.compiler`; parity with the
reference circuit format, python/distproc/compiler.py:1-106).  Dicts are
resolved through an explicit registry (:func:`from_dict`) — unknown names
are treated as :class:`Gate` instructions.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field as dfield
from typing import Any

import numpy as np


def _as_scope(scope):
    """Normalise a scope spec (list/tuple/set of channels or qubits) to a set."""
    return set(scope) if scope is not None else None


def resolve_freqname(qubit, freq) -> str | float | None:
    """Phase-tracker name resolution for virtual-z / bind_phase.

    * only ``freq`` given → ``freq`` (name or numeric);
    * only ``qubit`` given → ``'{qubit}.freq'``;
    * both given and freq is a name → ``'{qubit}.{freq}'``.
    """
    if isinstance(qubit, (list, tuple)):
        if len(qubit) != 1:
            raise ValueError('virtual-z instructions address exactly one qubit')
        qubit = qubit[0]
    if qubit is None:
        return freq
    if freq is None:
        return f'{qubit}.freq'
    if isinstance(freq, str):
        return f'{qubit}.{freq}'
    return freq


class Instruction:
    """Base: every IR instruction has a ``name`` and an optional ``scope``."""

    def to_dict(self) -> dict:
        out = {'name': self.name}
        for f in dataclasses.fields(self):
            if f.name in ('name',):
                continue
            val = getattr(self, f.name)
            if val is None:
                continue
            if isinstance(val, set):
                val = sorted(val)
            elif isinstance(val, np.ndarray):
                val = list(val)
            elif isinstance(val, list):
                val = [v.to_dict() if isinstance(v, Instruction) else v
                       for v in val]
            out[f.name] = val
        return out


@dataclass
class Gate(Instruction):
    """A named gate on one or more qubits, resolved via the QChip library."""
    name: str
    qubit: list
    modi: dict = None
    start_time: int = None
    scope: set = None

    def __post_init__(self):
        if isinstance(self.qubit, (str,)):
            self.qubit = [self.qubit]
        elif isinstance(self.qubit, tuple):
            self.qubit = list(self.qubit)
        self.scope = _as_scope(self.scope)

    def to_dict(self) -> dict:
        out = {'name': self.name, 'qubit': self.qubit}
        if self.modi is not None:
            out['modi'] = self.modi
        if self.start_time is not None:
            out['start_time'] = self.start_time
        if self.scope is not None:
            out['scope'] = sorted(self.scope)
        return out


@dataclass
class Pulse(Instruction):
    freq: Any = None            # Hz, freq name, or register name
    twidth: float = None
    env: Any = None             # ndarray of samples, paradict, or list of paradicts
    dest: str = None
    phase: Any = 0
    amp: Any = 1
    start_time: int = None
    tag: str = None
    name: str = dfield(default='pulse', init=False)

    def to_dict(self) -> dict:
        out = {'name': 'pulse', 'freq': self.freq, 'twidth': self.twidth,
               'dest': self.dest, 'phase': self.phase, 'amp': self.amp}
        out['env'] = list(self.env) if isinstance(self.env, np.ndarray) else self.env
        if self.tag is not None:
            out['tag'] = self.tag
        if self.start_time is not None:
            out['start_time'] = self.start_time
        return out


@dataclass
class VirtualZ(Instruction):
    phase: float = None
    qubit: Any = None
    freq: Any = None
    scope: set = None
    name: str = dfield(default='virtual_z', init=False)

    def __post_init__(self):
        self.freq = resolve_freqname(self.qubit, self.freq)
        if isinstance(self.qubit, (list, tuple)):
            self.qubit = self.qubit[0]
        self.scope = _as_scope(self.scope)

    def to_dict(self) -> dict:
        out = {'name': 'virtual_z', 'phase': self.phase, 'freq': self.freq}
        if self.scope is not None:
            out['scope'] = sorted(self.scope)
        return out


@dataclass
class DeclareFreq(Instruction):
    freq: float = None
    scope: set = None
    freqname: str = None
    freq_ind: int = None
    name: str = dfield(default='declare_freq', init=False)

    def __post_init__(self):
        self.scope = _as_scope(self.scope)


@dataclass
class BindPhase(Instruction):
    """Bind a frequency's z-phase to a processor register (hardware virtual-z)."""
    var: str = None
    qubit: Any = None
    freq: Any = None
    scope: set = None
    name: str = dfield(default='bind_phase', init=False)

    def __post_init__(self):
        self.freq = resolve_freqname(self.qubit, self.freq)
        if isinstance(self.qubit, (list, tuple)):
            self.qubit = self.qubit[0]
        self.scope = _as_scope(self.scope)

    def to_dict(self) -> dict:
        out = {'name': 'bind_phase', 'var': self.var, 'freq': self.freq}
        if self.scope is not None:
            out['scope'] = sorted(self.scope)
        return out


@dataclass
class Barrier(Instruction):
    qubit: list = None
    scope: set = None
    name: str = dfield(default='barrier', init=False)


@dataclass
class Delay(Instruction):
    t: float = None
    qubit: list = None
    scope: set = None
    name: str = dfield(default='delay', init=False)


@dataclass
class Idle(Instruction):
    """Stall the core until qclk reaches ``end_time``."""
    end_time: int = None
    qubit: list = None
    scope: set = None
    name: str = dfield(default='idle', init=False)


@dataclass
class Hold(Instruction):
    """Wait until ``nclks`` after the end of the last pulse on ``ref_chans``.

    Resolved into :class:`Idle` by the scheduler.
    """
    nclks: int = None
    ref_chans: Any = None
    qubit: list = None
    scope: set = None
    name: str = dfield(default='hold', init=False)


@dataclass
class Loop(Instruction):
    cond_lhs: Any = None
    alu_cond: str = None
    cond_rhs: str = None
    scope: set = None
    body: list = None
    name: str = dfield(default='loop', init=False)

    def __post_init__(self):
        self.scope = _as_scope(self.scope)


@dataclass
class JumpFproc(Instruction):
    alu_cond: str = None
    cond_lhs: Any = None
    func_id: Any = None
    scope: set = None
    jump_label: str = None
    jump_type: str = None
    name: str = dfield(default='jump_fproc', init=False)

    def __post_init__(self):
        if isinstance(self.func_id, list):
            self.func_id = tuple(self.func_id)
        self.scope = _as_scope(self.scope)


@dataclass
class BranchFproc(Instruction):
    alu_cond: str = None
    cond_lhs: Any = None
    func_id: Any = None
    scope: set = None
    true: list = None
    false: list = None
    name: str = dfield(default='branch_fproc', init=False)

    def __post_init__(self):
        if isinstance(self.func_id, list):
            self.func_id = tuple(self.func_id)
        self.scope = _as_scope(self.scope)


@dataclass
class ReadFproc(Instruction):
    func_id: Any = None
    var: str = None
    scope: set = None
    name: str = dfield(default='read_fproc', init=False)

    def __post_init__(self):
        if isinstance(self.func_id, list):
            self.func_id = tuple(self.func_id)
        self.scope = _as_scope(self.scope)


@dataclass
class AluFproc(Instruction):
    func_id: Any = None
    lhs: Any = None
    op: str = None
    out: str = None
    scope: set = None
    name: str = dfield(default='alu_fproc', init=False)

    def __post_init__(self):
        if isinstance(self.func_id, list):
            self.func_id = tuple(self.func_id)
        self.scope = _as_scope(self.scope)


@dataclass
class JumpLabel(Instruction):
    label: str = None
    scope: set = None
    name: str = dfield(default='jump_label', init=False)

    def __post_init__(self):
        self.scope = _as_scope(self.scope)


@dataclass
class JumpCond(Instruction):
    cond_lhs: Any = None
    alu_cond: str = None
    cond_rhs: str = None
    scope: set = None
    jump_label: str = None
    jump_type: str = None
    name: str = dfield(default='jump_cond', init=False)

    def __post_init__(self):
        self.scope = _as_scope(self.scope)


@dataclass
class BranchVar(Instruction):
    cond_lhs: Any = None
    alu_cond: str = None
    cond_rhs: str = None
    scope: set = None
    true: list = None
    false: list = None
    name: str = dfield(default='branch_var', init=False)

    def __post_init__(self):
        self.scope = _as_scope(self.scope)


@dataclass
class JumpI(Instruction):
    scope: set = None
    jump_label: str = None
    jump_type: str = None
    name: str = dfield(default='jump_i', init=False)

    def __post_init__(self):
        self.scope = _as_scope(self.scope)


@dataclass
class Declare(Instruction):
    var: str = None
    scope: set = None
    dtype: str = 'int'      # 'int', 'phase', or 'amp'
    name: str = dfield(default='declare', init=False)

    def __post_init__(self):
        self.scope = _as_scope(self.scope)


@dataclass
class LoopEnd(Instruction):
    scope: set = None
    loop_label: str = None
    name: str = dfield(default='loop_end', init=False)

    def __post_init__(self):
        self.scope = _as_scope(self.scope)


@dataclass
class Alu(Instruction):
    op: str = None
    lhs: Any = None
    rhs: str = None
    out: str = None
    scope: set = None
    name: str = dfield(default='alu', init=False)

    def __post_init__(self):
        self.scope = _as_scope(self.scope)


@dataclass
class SetVar(Instruction):
    value: Any = None
    var: str = None
    scope: set = None
    name: str = dfield(default='set_var', init=False)

    def __post_init__(self):
        self.scope = _as_scope(self.scope)


# name → class registry (explicit; no eval/reflection)
INSTRUCTION_CLASSES = {
    'pulse': Pulse,
    'virtual_z': VirtualZ,
    'virtualz': VirtualZ,
    'declare_freq': DeclareFreq,
    'bind_phase': BindPhase,
    'barrier': Barrier,
    'delay': Delay,
    'idle': Idle,
    'hold': Hold,
    'loop': Loop,
    'jump_fproc': JumpFproc,
    'branch_fproc': BranchFproc,
    'read_fproc': ReadFproc,
    'alu_fproc': AluFproc,
    'jump_label': JumpLabel,
    'jump_cond': JumpCond,
    'branch_var': BranchVar,
    'jump_i': JumpI,
    'declare': Declare,
    'loop_end': LoopEnd,
    'alu': Alu,
    'set_var': SetVar,
}


def from_dict(instr: dict) -> Instruction:
    """Resolve an instruction dict to its dataclass; unknown names → Gate."""
    instr = dict(instr)
    name = instr.pop('name')
    cls = INSTRUCTION_CLASSES.get(name)
    if cls is None:
        obj = Gate(name=name, **instr)
    else:
        obj = cls(**instr)
    # recursively resolve nested control-flow bodies
    for attr in ('true', 'false', 'body'):
        sub = getattr(obj, attr, None)
        if sub is not None and sub and isinstance(sub[0], dict):
            setattr(obj, attr, [from_dict(s) for s in sub])
    return obj


def program_from_dicts(instrs: list) -> list:
    return [from_dict(i) if isinstance(i, dict) else i for i in instrs]
